# Build / test / bench entry points (reference analogue: makefile +
# build/build-*.sh; engine choice is a runtime flag here, not a build tag).

SHELL := /bin/bash  # test-tier1 needs pipefail

.PHONY: all native test bench bench-all bench-smoke bench-cluster \
        bench-multichip bench-write bench-compact bench-fanout run clean \
        protos lint typecheck check test-tier1

all: native

# Static analysis: the kblint syntactic rules (KB101-KB111) over all
# Python PLUS the interprocedural tier (--deep: call graph over
# kubebrain_tpu/ + tools/ + bench.py, rules KB112-KB115, baseline.json),
# then the native lint pass. The deep run is held to a 60s wall-clock
# budget (exceeded = failure) and is incremental via .kblint_cache/
# (content-hash keyed; KBLINT_CACHE=0 disables). docs/static_analysis.md.
lint:
	python -m tools.kblint kubebrain_tpu tools tests --deep --budget 60
	$(MAKE) -C native lint

# mypy over the typed core when installed; compileall fallback otherwise
# (this container must not pip install anything).
typecheck:
	python tools/typecheck.py

# The ROADMAP.md tier-1 verify command, the ONE definition CI and
# tools/ci.sh both invoke (the flags and timeout must not drift apart).
test-tier1:
	set -o pipefail; rm -f /tmp/_t1.log; \
	timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
	    -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
	    -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; \
	rc=$$?; \
	echo "DOTS_PASSED=$$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$$' /tmp/_t1.log | tr -cd . | wc -c)"; \
	exit $$rc

# Everything CI runs: lint + typecheck + the tier-1 suite (tools/ci.sh).
check:
	tools/ci.sh

native:
	$(MAKE) -C native

protos:
	cd kubebrain_tpu/proto && protoc --python_out=. kv.proto rpc.proto brain.proto health.proto

test: native
	python -m pytest tests/ -q

bench: native
	python bench.py

bench-all: native
	python bench.py
	KB_BENCH_METRIC=fanout python bench.py
	KB_BENCH_METRIC=compact python bench.py
	KB_BENCH_METRIC=insert python bench.py

# Scheduler microbench on a tiny dataset (CPU, no native build needed):
# asserts scheduled == unscheduled byte-identically, reports coalescing
# and shed counters. Fast enough for CI smoke.
bench-smoke:
	JAX_PLATFORMS=cpu KB_BENCH_METRIC=sched KB_BENCH_KEYS=2000 \
	    KB_BENCH_OPS=200 python bench.py

# Cluster-scale workload replay (kubebrain_tpu/workload): deterministic
# kube-apiserver traffic for an N-node simulated cluster through the real
# gRPC front — pod churn + controller list/watch + node lease keepalives +
# compaction in one run. Emits WORKLOAD_rNN.json (docs/workloads.md).
# Same seed => byte-identical op trace (self-checked every run).
# MESH_PART/SCAN_PARTS drive a part-sharded server (STORAGE=tpu required;
# docs/multichip.md), e.g.: make bench-cluster N=1000 STORAGE=tpu MESH_PART=8
# SCENARIO=churn_heavy skews the trace to pod churn + a keepalive storm
# (write-group commit exercised + asserted; docs/writes.md).
# SCENARIO=watch_heavy skews to multi-controller fan-in (many watchers per
# namespace prefix, thin writes) and spawns every server — leader and
# followers — with the block-batched device fan-out matcher; with
# REPLICAS=2 the whole watcher population rides the followers
# (docs/watch.md). MESH_WAT=<n> additionally shards the watcher table
# over n (simulated) devices, any scenario.
# FAULTS=<preset> (smoke|storage|watch|merge|full) arms chaos mode
# (docs/faults.md): churn_heavy replayed against a fault-injected server,
# judged by the acknowledged-write consistency check; emits CHAOS_rNN.json.
# COMPACT_S overrides the spec's compaction cadence in SIMULATED seconds
# (0 = scenario default), e.g. the 5-min-compaction scenario of the
# ROADMAP: make bench-cluster N=1000 DURATION=900 COMPACT_S=300.
# REPLICAS=<n> spawns n follower replicas next to the leader
# (docs/replication.md): controller list+watch traffic routes to the
# followers (bounded-staleness local serving + local watch fan-out),
# writes/leases round-robin and forward; emits REPLICA_rNN.json with the
# per-replica served/forwarded/lag section. FAULTS=replica REPLICAS=2
# arms the follower chaos kinds (replication reset, leader-unreachable,
# fence timeout) and judges by the same acked-write consistency check.
N ?= 1000
STORAGE ?= memkv
MESH_PART ?= 0
SCAN_PARTS ?= 0
SCENARIO ?= cluster
FAULTS ?= none
FAULT_SEED ?= 0
COMPACT_S ?= 0
REPLICAS ?= 0
MESH_WAT ?= 0
bench-cluster:
	JAX_PLATFORMS=cpu KB_BENCH_METRIC=cluster KB_BENCH_NODES=$(N) \
	    KB_WORKLOAD_STORAGE=$(STORAGE) KB_WORKLOAD_MESH_PART=$(MESH_PART) \
	    KB_WORKLOAD_SCAN_PARTITIONS=$(SCAN_PARTS) \
	    KB_WORKLOAD_SCENARIO=$(SCENARIO) KB_WORKLOAD_FAULTS=$(FAULTS) \
	    KB_WORKLOAD_FAULT_SEED=$(FAULT_SEED) \
	    KB_WORKLOAD_COMPACT_S=$(COMPACT_S) \
	    KB_WORKLOAD_REPLICAS=$(REPLICAS) \
	    KB_WORKLOAD_MESH_WAT=$(MESH_WAT) python bench.py

# Watch fan-out bench (docs/watch.md): block-batched device matching at
# 10k+ watchers — watch_fanout_events_per_sec, delivery masks asserted
# byte-identical to the host segment-index oracle, batched path >= 2x the
# per-batch device path on CPU-sim (TPU bar pending_tpu off-TPU). Emits
# the kubebrain-fanout/v1 report to KB_FANOUT_OUT (FANOUT_rNN.json).
bench-fanout:
	JAX_PLATFORMS=cpu KB_BENCH_METRIC=fanout python bench.py

# Multichip sharded serving curve (docs/multichip.md): the scan workload
# served through the scheduler at mesh sizes 1..8, byte-identical across
# sizes; KB_MULTICHIP_OUT=MULTICHIP_rNN.json writes the schema'd report.
bench-multichip:
	JAX_PLATFORMS=cpu KB_BENCH_METRIC=multichip python bench.py

# Write-path group commit (docs/writes.md): write_txns_per_sec serial vs
# grouped at 8-writer concurrency (grouped >= 1.5x asserted on CPU,
# byte-identity vs the sequential oracle), plus the TPU-engine steady
# state proving the incremental delta merge never takes a full rebuild.
bench-write:
	JAX_PLATFORMS=cpu KB_BENCH_METRIC=write python bench.py

# Device-side compaction (docs/compaction.md): the stored-domain pipeline
# vs the engine-generic host compactor over one ~1M-row store with a
# realistic victim mix — byte-identity vs the sequential oracle asserted,
# zero full rebuilds / re-dictionary encodes asserted, >= 2x host asserted
# at acceptance size (CPU-sim; TPU bar pending_tpu off-TPU). Emits the
# kubebrain-compact/v1 report to KB_COMPACT_OUT (COMPACT_rNN.json).
bench-compact:
	JAX_PLATFORMS=cpu KB_BENCH_METRIC=compact python bench.py

run: native
	python -m kubebrain_tpu.cli --single-node --storage=tpu --inner-storage=native

clean:
	$(MAKE) -C native clean
