# Build / test / bench entry points (reference analogue: makefile +
# build/build-*.sh; engine choice is a runtime flag here, not a build tag).

.PHONY: all native test bench bench-all run clean protos

all: native

native:
	$(MAKE) -C native

protos:
	cd kubebrain_tpu/proto && protoc --python_out=. kv.proto rpc.proto brain.proto health.proto

test: native
	python -m pytest tests/ -q

bench: native
	python bench.py

bench-all: native
	python bench.py
	KB_BENCH_METRIC=fanout python bench.py
	KB_BENCH_METRIC=compact python bench.py
	KB_BENCH_METRIC=insert python bench.py

run: native
	python -m kubebrain_tpu.cli --single-node --storage=tpu --inner-storage=native

clean:
	$(MAKE) -C native clean
