"""North-star benchmark: MVCC range-scan rate over a 1M-key x 100-revision
class dataset (BASELINE.json config: "range-scan keys/sec").

Measures the device visibility kernel (prefix-match + revision filter +
last-version select + tombstone suppression — the single pass the reference
does row-by-row in scanner worker.run, scanner.go:389-516) over HBM-resident
packed blocks, against a vectorized numpy CPU implementation of the *same*
algorithm (a much stronger baseline than the reference's per-row LSM
iteration).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

Env knobs: KB_BENCH_KEYS (default 200000), KB_BENCH_REVS (default 100),
KB_BENCH_PLATFORM (force "cpu"), KB_BENCH_ITERS.
"""

from __future__ import annotations

import functools
import json
import os
import subprocess
import sys
import time

import numpy as np

WIDTH = 64  # bytes per packed key; registry bench keys are ~36B
CHUNKS = WIDTH // 4


def platform_info() -> dict:
    """Platform/device stamp carried by EVERY emitted bench JSON: acceptance
    bars differ by device class (the PR 5 batched bar is TPU-only), so each
    record must say where it ran instead of leaving that to stderr logs.
    Never *initializes* a jax backend just for the stamp — jax.devices() on
    a merely-imported jax would pay seconds of XLA startup on host-only
    benches, and in this container could attach the wedge-prone axon tunnel
    the bench only ever probes from a throwaway subprocess."""
    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            backends = getattr(
                getattr(jax, "_src", None), "xla_bridge", None)
            if backends is not None and getattr(backends, "_backends", None):
                dev = jax.devices()[0]  # backend already live: this is cheap
                return {"platform": dev.platform, "device": str(dev)}
        except Exception:
            pass
    return {"platform": os.environ.get("JAX_PLATFORMS") or "host",
            "device": "host(jax backend not initialized)"}


def _probe_tpu_alive(timeout: float = 90.0) -> bool:
    """The axon tunnel serializes one client and can wedge; probe it in a
    throwaway subprocess so a dead tunnel can't hang the bench."""
    code = "import jax, jax.numpy as jnp; jnp.arange(4).sum().block_until_ready(); print('ok')"
    try:
        out = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, timeout=timeout
        )
        return b"ok" in out.stdout
    except (subprocess.TimeoutExpired, OSError):
        return False


def _force_cpu() -> None:
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        # virtual 8-device mesh so the sharded paths mean something on CPU
        os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")


def build_dataset(n_keys: int, revs_per_key: int):
    """Vectorized construction of sorted (key, rev) rows: fixed-format keys
    '/registry/pods/default/pod-%08d' x revs_per_key ascending revisions,
    last version tombstoned for 10% of keys."""
    prefix = b"/registry/pods/default/pod-"
    plen = len(prefix)
    n = n_keys * revs_per_key

    from kubebrain_tpu.ops import keys as keyops

    digits = np.zeros((n_keys, 8), np.uint8)
    x = np.arange(n_keys, dtype=np.int64)
    for d in range(7, -1, -1):
        digits[:, d] = (x % 10) + ord("0")
        x //= 10
    key_bytes = np.zeros((n_keys, WIDTH), np.uint8)
    key_bytes[:, :plen] = np.frombuffer(prefix, np.uint8)
    key_bytes[:, plen : plen + 8] = digits

    chunks = keyops.bytes_to_chunks(np.repeat(key_bytes, revs_per_key, axis=0))

    revs = np.arange(1, n + 1, dtype=np.uint64)
    rh = (revs >> np.uint64(32)).astype(np.uint32)
    rl = (revs & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    tomb = np.zeros(n, dtype=bool)
    tomb[revs_per_key - 1 :: 10 * revs_per_key] = True  # last version of every 10th key
    return chunks, rh, rl, tomb


def pack_bound(key: bytes) -> np.ndarray:
    from kubebrain_tpu.ops import keys as keyops

    return keyops.pack_one(key, WIDTH)


def key_encoding_info(chunks: np.ndarray, sample: int = 200_000) -> dict:
    """Schema-stamped mirror-compression stats for a (sorted) packed-key
    dataset: what the serving mirror would store per row under the
    order-preserving prefix/dictionary encoding (docs/compression.md) —
    the capacity-unlock fields BENCH/MULTICHIP JSONs track across rounds."""
    from kubebrain_tpu.ops import keys as keyops
    from kubebrain_tpu.storage.tpu.encode import build_encoding

    stride = max(1, len(chunks) // sample)
    u8 = keyops.chunks_to_u8(np.asarray(chunks[::stride]))
    w = u8.shape[1]
    nz = (u8[:, ::-1] != 0).argmax(axis=1)
    lens = np.where((u8 != 0).any(axis=1), w - nz, 0).astype(np.int64)
    enc = build_encoding(u8, lens, raw_width=w)
    enc_w = enc.width if enc is not None else w
    # per-row device bytes: key column + rev hi/lo (8B) + tomb/ttl flags (2B)
    return {
        "schema": "kubebrain-keyenc/v1",
        "raw_key_bytes_per_row": w,
        "encoded_key_bytes_per_row": enc_w,
        "mirror_bytes_per_row": enc_w + 10,
        "raw_mirror_bytes_per_row": w + 10,
        "key_compression_ratio": round(w / enc_w, 3),
        "dict_entries": len(enc.boundaries) if enc is not None else 0,
    }


def cpu_scan(chunks, rh, rl, tomb, start, end, qhi, qlo) -> int:
    """The same visibility algorithm, vectorized numpy (CPU baseline)."""
    def lex_less(keys, bound):
        eq = keys == bound
        neq = ~eq
        has_diff = neq.any(axis=1)
        first = neq.argmax(axis=1)
        lt_first = np.take_along_axis(keys < bound, first[:, None], axis=1)[:, 0]
        return has_diff & lt_first

    in_range = ~lex_less(chunks, start) & lex_less(chunks, end)
    rev_le = (rh < qhi) | ((rh == qhi) & (rl <= qlo))
    cand = in_range & rev_le
    same_next = np.zeros(len(chunks), dtype=bool)
    same_next[:-1] = (chunks[1:] == chunks[:-1]).all(axis=1)
    cand_next = np.zeros_like(cand)
    cand_next[:-1] = cand[1:]
    visible = cand & ~(same_next & cand_next) & ~tomb
    return int(visible.sum())


def _fanout_population(n_watchers: int, n_broad: int, rng):
    """Kube-realistic watcher specs ``[(wid, start, end, min_rev)]``:
    namespace/kind prefix ranges (the informer shape), ~2% single-key
    watches whose end bound carries a NUL (``key + b"\\0"``), and
    ``n_broad`` broad unbounded watches over the whole registry. The broad
    cohort makes the hub's ``_RangeIndex`` go DENSE, which is exactly the
    population class that routes ``stream`` to the device block path even
    on CPU backends."""
    kinds = (b"pods", b"leases", b"endpoints", b"configmaps")
    namespaces = [b"ns-%03d" % i for i in range(40)]
    specs = []
    for w in range(n_watchers - n_broad):
        ns = namespaces[rng.randint(len(namespaces))]
        kind = kinds[rng.randint(len(kinds))]
        if rng.rand() < 0.02:
            # single-key watch: end = key + NUL (etcd single-key range)
            key = b"/registry/%s/%s/obj-%05d" % (kind, ns, rng.randint(4096))
            specs.append((w, key, key + b"\x00", int(rng.randint(0, 256))))
        else:
            start = b"/registry/%s/%s/" % (kind, ns)
            end = start[:-1] + bytes([start[-1] + 1])
            specs.append((w, start, end, int(rng.randint(0, 256))))
    for b in range(n_broad):
        specs.append((n_watchers - n_broad + b, b"/registry/", b"", 0))
    return specs


def _fanout_events(n_events: int, rev0: int, rng, ts: float = 0.0):
    from kubebrain_tpu.backend.common import WatchEvent

    kinds = (b"pods", b"leases", b"endpoints", b"configmaps")
    namespaces = [b"ns-%03d" % i for i in range(40)]
    return [
        WatchEvent(
            revision=rev0 + i,
            key=b"/registry/%s/%s/obj-%05d" % (
                kinds[rng.randint(len(kinds))],
                namespaces[rng.randint(len(namespaces))],
                rng.randint(4096)),
            value=b"v",
            ts=ts,
        )
        for i in range(n_events)
    ]


def _next_fanout_path(root: str) -> str:
    import re

    pat = re.compile(r"FANOUT_r(\d+)\.json$")
    rounds = [int(m.group(1)) for f in os.listdir(root) if (m := pat.match(f))]
    return os.path.join(root, "FANOUT_r%02d.json" % (max(rounds, default=0) + 1))


def bench_fanout() -> None:
    """Watch fan-out bench (make bench-fanout; docs/watch.md): block-batched
    device matching at 10k watchers, three legs —

    - **identity**: the device matcher's delivery masks byte-identical to
      the brute-force raw-bytes oracle (full W, leading events) AND its
      block deliveries identical to the host segment-index
      (``_RangeIndex``) oracle over the index-buildable sub-population;
    - **throughput**: one block dispatch for the whole drain
      (``DeviceFanout.deliver``) vs the per-batch legacy device path
      (EVENT_BATCH-chunked ``FanoutMatcher`` masks + hub-style column
      demux) — the batched path must be >= 2x on CPU-sim; the TPU bar is
      the same 2x asserted on-TPU and stamped pending_tpu off it;
    - **lag**: the same population subscribed on a REAL WatcherHub with
      PrometheusMetrics armed; drain blocks stream through the hub's
      device block route and p99 of ``kb_watch_lag_seconds{point=queue}``
      must land under KB_FANOUT_LAG_BOUND_S.

    Report: FANOUT_rNN.json (kubebrain-fanout/v1) in the repo root, or
    KB_FANOUT_OUT. Perf bars are asserted AFTER the report is emitted."""
    import time as _time

    import jax

    from kubebrain_tpu.backend.watcherhub import WatcherHub, _RangeIndex
    from kubebrain_tpu.fanout.matcher import DeviceFanout, match_oracle
    from kubebrain_tpu.metrics.prom import PrometheusMetrics
    from kubebrain_tpu.ops.fanout import FanoutMatcher
    from kubebrain_tpu.workload import slo

    n_watchers = int(os.environ.get("KB_BENCH_WATCHERS", 10_000))
    n_events = int(os.environ.get("KB_BENCH_EVENTS", 512))
    n_broad = int(os.environ.get("KB_BENCH_BROAD", 100))
    iters = int(os.environ.get("KB_BENCH_ITERS", 3))
    rounds = int(os.environ.get("KB_BENCH_ROUNDS", 4))
    lag_bound = float(os.environ.get("KB_FANOUT_LAG_BOUND_S", 5.0))
    rng = np.random.RandomState(0)

    specs = _fanout_population(n_watchers, n_broad, rng)
    events = _fanout_events(n_events, rev0=300, rng=rng)

    # ---- leg 1: identity ------------------------------------------------
    matcher = DeviceFanout()
    # brute-force raw-bytes oracle over the FULL watcher population on the
    # leading events (bounded: the oracle is O(E*W) Python)
    n_oracle_ev = min(n_events, 64)
    mask_dev = matcher(events[:n_oracle_ev], specs, version=1)
    mask_brute = match_oracle(events[:n_oracle_ev], specs)
    assert (mask_dev == mask_brute).all(), "device mask diverged from oracle"
    # segment-index oracle over the index-buildable (bounded) population,
    # against the BLOCK protocol's demuxed deliveries, all events
    narrow = [s for s in specs if s[2]]
    filters = {wid: (s, e, r) for wid, s, e, r in narrow}
    index = _RangeIndex(filters)
    assert not index.dense, "bounded sub-population unexpectedly dense"
    per_seg: dict[int, list] = {}
    for ev in events:
        for wid in index.lookup(ev.key):
            if ev.revision >= filters[wid][2]:
                per_seg.setdefault(wid, []).append(ev)
    per_dev = DeviceFanout().deliver(events, narrow, version=1)
    assert per_dev == per_seg, "block deliveries diverged from segment index"

    # ---- leg 2: block vs per-batch throughput ---------------------------
    from kubebrain_tpu.backend.backend import EVENT_BATCH

    legacy = FanoutMatcher()

    def run_block():
        return matcher.deliver(events, specs, version=2)

    def run_per_batch():
        # the pre-block hub pipeline: EVENT_BATCH-chunked legacy masks +
        # per-column demux (watcherhub.stream's legacy device branch)
        out: dict[int, list] = {}
        for i in range(0, n_events, EVENT_BATCH):
            chunk = events[i:i + EVENT_BATCH]
            mask = legacy(chunk, specs, version=2)
            for w in np.nonzero(mask.any(axis=0))[0]:
                wid = specs[int(w)][0]
                rows = np.nonzero(mask[:, w])[0]
                out.setdefault(wid, []).extend(chunk[int(e)] for e in rows)
        return out

    block_delivery = run_block()  # warm (pays jit compiles)
    per_batch_delivery = run_per_batch()
    assert block_delivery == per_batch_delivery, \
        "block deliveries diverged from per-batch path"
    deliveries = sum(len(v) for v in block_delivery.values())

    block_dt = min(_timeit(run_block) for _ in range(iters))
    per_batch_dt = min(_timeit(run_per_batch) for _ in range(iters))
    speedup = per_batch_dt / block_dt
    events_per_sec = n_events / block_dt

    # ---- leg 3: hub lag through the device block route ------------------
    metrics = PrometheusMetrics()
    hub_matcher = DeviceFanout()
    hub = WatcherHub(fanout_matcher=hub_matcher)
    hub.set_metrics(metrics)
    hub_matcher.set_metrics(metrics)
    for _wid, s, e, r in specs:
        hub.add_watcher(s, e, r)
    rev = 300 + n_events
    for _ in range(rounds):
        batch = _fanout_events(n_events, rev0=rev, rng=rng,
                               ts=_time.monotonic())
        hub.stream(batch)
        rev += n_events
    assert hub_matcher.stats["blocks"] == rounds, (
        "hub did not route stream() through the device block path",
        hub_matcher.stats)
    snap = slo.parse_prom(metrics.http_handler()()[1].decode())
    lag_p99 = slo.hist_quantile(snap, "kb_watch_lag_seconds", 0.99,
                                point="queue")
    hub.close()

    dev = jax.devices()[0]
    on_tpu = dev.platform in ("tpu", "axon")
    report = {
        "schema": "kubebrain-fanout/v1",
        "platform": platform_info(),
        "watchers": n_watchers,
        "broad_watchers": n_broad,
        "events_per_block": n_events,
        "rounds": rounds,
        "deliveries_per_block": deliveries,
        "watch_fanout_events_per_sec": round(events_per_sec),
        "block_seconds": round(block_dt, 4),
        "per_batch_seconds": round(per_batch_dt, 4),
        "speedup_vs_per_batch": round(speedup, 3),
        "mask_identical_to_brute_oracle": True,
        "deliveries_identical_to_segment_index": True,
        "hub_routed_blocks": hub_matcher.stats["blocks"],
        "dispatches": matcher.stats["dispatches"],
        "redispatches": matcher.stats["redispatches"],
        "table": matcher.table.stats(),
        "lag_p99_s": lag_p99,
        "lag_bound_s": lag_bound,
        "acceptance_lag_p99": ("pass" if lag_p99 is not None
                               and lag_p99 <= lag_bound else "fail"),
        "acceptance_2x_cpu": "pass" if speedup >= 2.0 else "fail",
        "acceptance_2x_tpu": ("pass" if on_tpu and speedup >= 2.0
                              else "pending_tpu"),
    }
    out_path = os.environ.get("KB_FANOUT_OUT") or _next_fanout_path(
        os.path.dirname(os.path.abspath(__file__)))
    with open(out_path, "w") as f:
        json.dump(report, f, indent=2, sort_keys=True)
        f.write("\n")
    print(json.dumps({
        "metric": "watch fan-out events/sec at %dk watchers" % (n_watchers // 1000),
        "value": round(events_per_sec),
        "unit": "events/sec",
        "vs_baseline": round(speedup, 3),
        "platform": report["platform"],
        "detail": {k: v for k, v in report.items()
                   if k not in ("schema", "platform")},
    }))
    # asserted AFTER the report is emitted so a failing run still leaves
    # the timings on record (the nonzero exit fails CI either way)
    assert speedup >= 2.0, (
        f"block path {block_dt:.3f}s not >= 2x per-batch {per_batch_dt:.3f}s")
    assert lag_p99 is not None and lag_p99 <= lag_bound, (
        f"kb_watch_lag_seconds p99 {lag_p99} over bound {lag_bound}")


def _timeit(fn) -> float:
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


def _compact_dataset(n_keys: int, seed: int = 7):
    """Deterministic pre-compaction store content with a realistic victim
    mix (docs/compaction.md): superseded version chains, tombstoned chains
    (fully doomed incl. the rev record), TTL-expired ``/events/`` rows, and
    clean singleton survivors. Returns ``(rows, ttl_boundary_rev,
    compact_rev, n_version_rows)`` where ``rows`` is a list of
    ``(internal_key, value)`` pairs ready to batch-put into ANY engine —
    the oracle and device stores load byte-identical content."""
    import random as _random

    from kubebrain_tpu import coder
    from kubebrain_tpu.backend.common import TOMBSTONE

    rng = _random.Random(seed)
    rows: list[tuple[bytes, bytes]] = []
    rev = 0
    n_version_rows = 0
    # kube-realistic object payloads (pods serialize to KBs, not tens of
    # bytes): deterministic sizes in [256, 2048) sliced from one pattern
    # buffer — content doesn't matter to compaction, footprint does
    payload = bytes(range(256)) * 8

    def body(i):
        return payload[: rng.randrange(256, 2048)] + b"#%d" % i

    def version(uk, value):
        nonlocal rev, n_version_rows
        rev += 1
        n_version_rows += 1
        rows.append((coder.encode_object_key(uk, rev), value))
        return rev

    def rev_record(uk, latest, deleted):
        rows.append((coder.encode_revision_key(uk),
                     coder.encode_rev_value(latest, deleted=deleted)))

    # phase 1: expired /events/ rows — everything at or below this boundary
    # revision is TTL-expired (the seeded compact history ages it past the
    # EVENTS_TTL cutoff)
    n_events = n_keys // 4
    for i in range(n_events):
        uk = b"/events/ns%02d/ev-%06d" % (i % 20, i)
        r = version(uk, body(i))
        rev_record(uk, r, False)
    ttl_boundary_rev = rev

    # phase 2: registry churn — chains, tombstones, singletons
    for i in range(n_keys - n_events):
        ns = i % 32
        uk = b"/registry/pods/ns%02d/pod-%06d" % (ns, i)
        shape = i % 3
        if shape == 0:  # superseded chain: 2-4 doomed + 1 surviving version
            r = version(uk, body(i))
            for j in range(2 + rng.randrange(3)):
                r = version(uk, body(i + j))
            rev_record(uk, r, False)
        elif shape == 1:  # tombstoned: the whole chain compacts away
            version(uk, body(i))
            r = version(uk, TOMBSTONE)
            rev_record(uk, r, True)
        else:  # clean singleton survivor
            r = version(uk, body(i))
            rev_record(uk, r, False)
    # load in sorted key order: engines keeping a sorted key index (memkv's
    # insort, LSM memtables) then pay O(1) tail appends instead of O(n)
    # mid-list inserts — bulk loads are sorted in any real migration, and
    # both engines load the identical sequence either way
    rows.sort(key=lambda kv: kv[0])
    return rows, ttl_boundary_rev, rev, n_version_rows


def _load_store(store, rows, batch: int = 1024) -> None:
    for b0 in range(0, len(rows), batch):
        bw = store.begin_batch_write()
        for k, v in rows[b0 : b0 + batch]:
            bw.put(k, v)
        bw.commit()


def _dump_store(store) -> list:
    from kubebrain_tpu import coder

    lo, hi = coder.internal_range(b"", b"")
    return list(store.iter(lo, hi))


def bench_compact() -> None:
    """Engine-level compaction bench (make bench-compact; docs/compaction.md):
    three compactors over byte-identical store content with a realistic
    victim mix —

    - **device**: the stored-domain pipeline (victim kernel → shard-local
      index pull → victim-only decode GC → survivor gather + k-way merge,
      dirty shards only);
    - **host path**: the CURRENT-until-this-PR mirror half — identical
      marking + GC, but the mirror absorbs the compaction through the
      decode-everything → re-dictionary → re-partition full rebuild
      (`compact_force_full`, preserved as the fallback rung);
    - **oracle**: the engine-generic sequential compactor
      (backend/scanner.py) — the semantic ground truth.

    Gates: post-compact store state byte-identical across ALL three,
    serving results identical, ZERO full rebuilds / re-dictionary encodes
    on the device path, and (at the >= 1M-row acceptance size, on the
    native engine) compact_rows_per_sec >= 2x the host path on CPU-sim —
    the TPU bar is the same 2x asserted on-TPU and stamped pending_tpu
    off it. The inner engine is the NATIVE store when its library loads
    (KB_COMPACT_ENGINE=auto|native|memkv): that is the production
    configuration — compaction GC rides the C `bulk_gc`/`prune` fast
    paths in all three compactors, so the measured difference is the
    mirror half this PR moved into the stored domain, not Python store
    mutation (the memkv fallback still runs every identity gate, plus the
    TTL-expiry class the native engine handles natively). One untimed
    warm-up pass pays every jit compile before either timed pass (the
    shapes are identical — same dataset). Report: COMPACT_rNN.json
    (kubebrain-compact/v1) via KB_COMPACT_OUT."""
    import time as _time

    import jax

    from kubebrain_tpu import coder
    from kubebrain_tpu.backend.scanner import CompactHistory, Scanner
    from kubebrain_tpu.storage import new_storage

    # default sizes the acceptance shape: ~2 version rows per key on
    # average, so 520k keys ≈ 1.04M version rows (>= the 1M-row bar)
    n_keys = int(os.environ.get("KB_BENCH_KEYS", 520_000))
    seed = int(os.environ.get("KB_BENCH_SEED", 7))
    engine = os.environ.get("KB_COMPACT_ENGINE", "auto")
    if engine == "auto":
        try:
            probe = new_storage("native")
            probe.close()
            engine = "native"
        except Exception:
            engine = "memkv"
    inner_kw = {} if engine == "native" else {"ttl_supported": False}
    rows, ttl_rev, compact_rev, n_version_rows = _compact_dataset(n_keys, seed)
    lo, hi = coder.internal_range(b"", b"")
    aged = _time.time() - 7200  # compact-history entry older than EVENTS_TTL

    def tpu_scanner():
        store = new_storage("tpu", inner=engine, **inner_kw)
        _load_store(store, rows)
        hist = CompactHistory()
        hist.log(ttl_rev, now=aged)
        sc = store.make_scanner(
            get_compact_revision=lambda *_a: 0, compact_history=hist)
        sc.publish()  # mirror build off the clock (boot cost, not compact)
        return store, sc

    def run_tpu_path(force_full):
        store, sc = tpu_scanner()
        sc.compact_force_full = force_full
        enc_before = sc._mirror.encoding
        t0 = _time.time()
        stats = sc.compact(lo, hi, compact_rev)
        return store, sc, stats, _time.time() - t0, enc_before

    # ---- warm-up: pays every jit compile off BOTH clocks (the legacy
    # path shares the marking kernels; its full rebuild is numpy-only)
    w_store, w_sc, _w_stats, _, _ = run_tpu_path(False)
    w_sc.close()
    w_store.close()

    # ---- device path: the stored-domain pipeline ------------------------
    dev_store, dev_sc, dev_stats, dev_dt, encoding_before = run_tpu_path(False)
    dev_rate = n_version_rows / dev_dt

    # ---- host path: identical marking + GC, legacy mirror rebuild -------
    leg_store, leg_sc, leg_stats, leg_dt, _enc = run_tpu_path(True)
    leg_rate = n_version_rows / leg_dt
    assert leg_stats.mirror_path == "full_rebuild", leg_stats.mirror_path

    # ---- oracle: the engine-generic sequential compactor ----------------
    orc_store = new_storage(engine, **inner_kw)
    _load_store(orc_store, rows)
    hist = CompactHistory()
    hist.log(ttl_rev, now=aged)
    orc_sc = Scanner(orc_store, lambda *_a: 0, compact_history=hist)
    t0 = _time.time()
    orc_stats = orc_sc.compact(lo, hi, compact_rev)
    orc_dt = _time.time() - t0
    orc_rate = n_version_rows / orc_dt

    # ---- gates ----------------------------------------------------------
    # 1. post-compact store state byte-identical across all three
    orc_dump = _dump_store(orc_store)
    dev_dump = _dump_store(dev_store._inner)
    leg_dump = _dump_store(leg_store._inner)
    assert orc_dump == dev_dump, (
        f"device store diverged from oracle: {len(orc_dump)} vs "
        f"{len(dev_dump)} rows")
    assert orc_dump == leg_dump, "legacy store diverged from oracle"
    # 2. serving results identical (mirrors vs oracle host scan)
    orc_kvs = [(kv.key, kv.value, kv.revision)
               for kv in orc_sc.range_(b"", b"", compact_rev)[0]]
    for sc in (dev_sc, leg_sc):
        got = [(kv.key, kv.value, kv.revision)
               for kv in sc.range_(b"", b"", compact_rev)[0]]
        assert got == orc_kvs, "post-compact serving results diverged"
    # 3. steady state: no full rebuild, no re-dictionary, stored path
    assert dev_sc.full_rebuild_total == 0, \
        f"device compact took {dev_sc.full_rebuild_total} full rebuild(s)"
    assert dev_sc._mirror.encoding is encoding_before, \
        "device compact re-dictionaried the mirror"
    assert dev_stats.mirror_path == "stored_incremental", dev_stats.mirror_path
    # 4. victim classification equal to the oracle's
    for f in ("deleted_versions", "deleted_tombstones", "deleted_rev_records",
              "expired_ttl"):
        assert getattr(dev_stats, f) == getattr(orc_stats, f), (
            f, getattr(dev_stats, f), getattr(orc_stats, f))
        assert getattr(leg_stats, f) == getattr(orc_stats, f), f

    dev = jax.devices()[0]
    on_tpu = dev.platform in ("tpu", "axon")
    speedup = dev_rate / leg_rate
    # the CPU-sim acceptance bar holds at the >= 1M-row size on the
    # production (native) engine — small smoke runs are identity gates
    # only (fixed dispatch cost dominates them), and the memkv fallback
    # measures Python store mutation, not the mirror pipeline; the TPU
    # bar is the same 2x, asserted on-TPU, pending_tpu off it
    at_acceptance_size = n_version_rows >= 1_000_000 and engine == "native"
    acceptance_cpu = ("pass" if speedup >= 2.0 and engine == "native" else
                      ("fail" if at_acceptance_size else
                       ("memkv_fallback" if engine != "native" else "small_n")))

    report = {
        "schema": "kubebrain-compact/v1",
        "platform": platform_info(),
        "keys": n_keys,
        "rows": n_version_rows,
        "compact_rows_per_sec": round(dev_rate),
        "host_rows_per_sec": round(leg_rate),
        "oracle_rows_per_sec": round(orc_rate),
        "speedup_vs_host": round(speedup, 3),
        "compact_seconds": round(dev_dt, 3),
        "host_seconds": round(leg_dt, 3),
        "oracle_seconds": round(orc_dt, 3),
        "victims": {
            "superseded": dev_stats.deleted_versions,
            "tombstone": dev_stats.deleted_tombstones,
            "ttl_expired": dev_stats.expired_ttl,
            "rev_record": dev_stats.deleted_rev_records,
        },
        "survivor_rows": dev_stats.survivor_rows,
        "dirty_partitions": dev_stats.dirty_partitions,
        "mirror_path": dev_stats.mirror_path,
        "phase_seconds": {k: round(v, 4)
                          for k, v in dev_stats.phase_seconds.items()},
        "host_phase_seconds": {k: round(v, 4)
                               for k, v in leg_stats.phase_seconds.items()},
        "byte_identical_store": True,
        "byte_identical_serving": True,
        "full_rebuild_total": dev_sc.full_rebuild_total,
        "re_dictionary": dev_sc._mirror.encoding is not encoding_before,
        "kernel": dev_sc._scan_kernel,
        "engine": engine,
        # one untimed warm-up pass paid every jit compile before either
        # timed pass (identical shapes — same dataset)
        "warmed": True,
        "acceptance_2x_cpu": acceptance_cpu,
        "acceptance_2x_tpu": ("pass" if on_tpu and speedup >= 2.0
                              else "pending_tpu"),
    }
    out_path = os.environ.get("KB_COMPACT_OUT")
    if out_path:
        with open(out_path, "w") as f:
            json.dump(report, f, indent=2, sort_keys=True)
            f.write("\n")
    print(json.dumps({
        "metric": "compaction rows/sec",
        "value": round(dev_rate),
        "unit": "rows/sec",
        "vs_baseline": round(speedup, 3),
        "platform": report["platform"],
        "detail": {k: v for k, v in report.items()
                   if k not in ("schema", "platform")},
    }))

    for sc in (dev_sc, leg_sc, orc_sc):
        sc.close()
    for st in (dev_store, leg_store, orc_store):
        st.close()
    # asserted AFTER the report is emitted so a failing run still leaves
    # the phase breakdown on record (the nonzero exit fails CI either way)
    if at_acceptance_size:
        assert speedup >= 2.0, (
            f"device compact {dev_rate:.0f} rows/s < 2x host path "
            f"{leg_rate:.0f} rows/s at acceptance size")


def bench_insert() -> None:
    """Reference headline: insert throughput + insert→event delivery latency
    through the full MVCC write path (BASELINE.md: KubeBrain/TiKV 28.6k
    ops/s, event latency avg 11.9-13.5ms p99 23-41ms) over the C++ engine."""
    import queue as _q
    import threading

    from kubebrain_tpu.backend import Backend, BackendConfig
    from kubebrain_tpu.storage import new_storage

    n_ops = int(os.environ.get("KB_BENCH_OPS", 20_000))
    n_threads = int(os.environ.get("KB_BENCH_THREADS", 8))
    store = new_storage("native")
    backend = Backend(store, BackendConfig(event_ring_capacity=200_000))
    value = b"x" * 512  # reference workload: 512B values
    per = n_ops // n_threads

    # a watcher measuring write→event delivery latency (reference's "insert
    # event" rows): writers stamp send time in the value
    _, wq = backend.watch(b"/registry/pods/")
    ev_lat: list[float] = []
    stop_watch = threading.Event()

    def watcher():
        while not stop_watch.is_set():
            try:
                batch = wq.get(timeout=0.2)
            except _q.Empty:
                continue
            if batch is None:
                return
            now = time.time()
            for ev in batch:
                sent = float(ev.value[:20])
                ev_lat.append(now - sent)

    wt = threading.Thread(target=watcher, daemon=True)
    wt.start()

    def writer(w):
        for i in range(per):
            stamped = (b"%020.6f" % time.time()) + value
            backend.create(b"/registry/pods/bench-%02d-%06d" % (w, i), stamped)

    threads = [threading.Thread(target=writer, args=(w,)) for w in range(n_threads)]
    t0 = time.time()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    dt = time.time() - t0
    rate = per * n_threads / dt
    time.sleep(0.5)
    stop_watch.set()
    backend.close()
    store.close()
    lat_sorted = sorted(ev_lat) or [0.0]
    print(json.dumps({
        "metric": "insert ops/sec",
        "value": round(rate),
        "unit": "ops/sec",
        "vs_baseline": round(rate / 28_644, 3),  # reference KubeBrain/TiKV insert
        "platform": platform_info(),
        "detail": {
            "ops": per * n_threads, "threads": n_threads,
            "value_bytes": 512, "engine": "native(C++)",
            "events_delivered": len(ev_lat),
            "event_latency_avg_ms": round(sum(lat_sorted) / len(lat_sorted) * 1e3, 2),
            "event_latency_p99_ms": round(lat_sorted[int(len(lat_sorted) * 0.99) - 1] * 1e3, 2),
            "reference_event_latency": "avg 11.9-13.5ms p99 23-41ms",
        },
    }))


def bench_delete() -> None:
    """The reference's documented weakness: delete throughput (published
    4,847-5,028 ops/s vs etcd's 10.8k; read-before-delete + CAS,
    benchmark.md:56-61). Here the whole sequence is one native call."""
    import threading

    from kubebrain_tpu.backend import Backend, BackendConfig
    from kubebrain_tpu.storage import new_storage

    n_ops = int(os.environ.get("KB_BENCH_OPS", 20_000))
    n_threads = int(os.environ.get("KB_BENCH_THREADS", 8))
    store = new_storage("native")
    backend = Backend(store, BackendConfig(event_ring_capacity=300_000))
    value = b"x" * 512
    per = n_ops // n_threads
    for w in range(n_threads):
        for i in range(per):
            backend.create(b"/registry/pods/del-%02d-%06d" % (w, i), value)

    def deleter(w):
        for i in range(per):
            backend.delete(b"/registry/pods/del-%02d-%06d" % (w, i))

    threads = [threading.Thread(target=deleter, args=(w,)) for w in range(n_threads)]
    t0 = time.time()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    dt = time.time() - t0
    rate = per * n_threads / dt
    backend.close()
    store.close()
    print(json.dumps({
        "metric": "delete ops/sec",
        "value": round(rate),
        "unit": "ops/sec",
        "vs_baseline": round(rate / 5_028, 3),  # reference's published delete
        "platform": platform_info(),
        "detail": {"ops": per * n_threads, "threads": n_threads,
                   "engine": "native(C++)", "reference": "4.8-5.0k (KubeBrain), 10.8-11.2k (etcd)"},
    }))


def bench_grpc_list() -> None:
    """BASELINE config 1: etcd3 Range over 10k /registry/pods/* keys through
    the live gRPC surface. Measured through BOTH listeners of one server —
    the native frontend (kbfront, the production path) and the sync Python
    endpoint (round-2's recorded 208ms-p50 path) — so the ratio is the
    native front's win on the read path (VERDICT r2 next #6; reference read
    bar avg 7.9-11.9ms, docs/data/benchmark_rw.csv)."""
    import socket
    import subprocess

    from kubebrain_tpu.client import EtcdCompatClient

    def free_port():
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        p = s.getsockname()[1]
        s.close()
        return p

    n_keys = int(os.environ.get("KB_BENCH_KEYS", 10_000))
    iters = int(os.environ.get("KB_BENCH_ITERS", 10))
    repo = os.path.dirname(os.path.abspath(__file__))
    py_port, front_port = free_port(), free_port()
    have_front = os.path.exists(os.path.join(repo, "native", "front", "kbfront"))
    args = [sys.executable, "-m", "kubebrain_tpu.cli", "--single-node",
            "--storage", "native", "--host", "127.0.0.1",
            "--client-port", str(py_port),
            "--peer-port", str(free_port()), "--info-port", str(free_port())]
    if have_front:
        args += ["--front-port", str(front_port)]
    server = subprocess.Popen(args, cwd=repo, stderr=subprocess.DEVNULL)
    c = EtcdCompatClient(f"127.0.0.1:{py_port}")
    deadline = time.time() + 30
    while time.time() < deadline:
        try:
            c.count(b"/x", b"/y")
            break
        except Exception:
            time.sleep(0.2)
    value = b"x" * 512
    for i in range(n_keys):
        c.create(b"/registry/pods/default/pod-%06d" % i, value)

    def measure(client):
        lat = []
        for _ in range(iters):
            t0 = time.time()
            kvs, _ = client.list(b"/registry/pods/", b"/registry/pods0", page=1000)
            lat.append(time.time() - t0)
            assert len(kvs) == n_keys
        return sorted(lat)[len(lat) // 2]

    py_p50 = measure(c)
    c.close()
    if have_front:
        cf = EtcdCompatClient(f"127.0.0.1:{front_port}")
        front_p50 = measure(cf)
        cf.close()
    else:
        front_p50 = py_p50
    server.terminate()
    server.wait(timeout=10)
    p50 = front_p50
    rate = n_keys / p50
    print(json.dumps({
        "metric": "grpc list keys/sec",
        "value": round(rate),
        "unit": "keys/sec",
        "vs_baseline": round(py_p50 / front_p50, 3),
        "platform": platform_info(),
        "detail": {"keys": n_keys, "list_p50_ms": round(p50 * 1e3, 2),
                   "py_endpoint_p50_ms": round(py_p50 * 1e3, 2),
                   "value_bytes": 512, "paged": 1000,
                   "transport": "etcd3 gRPC (kbfront)" if have_front
                                else "etcd3 gRPC (sync py)",
                   "baseline": "same list through the sync python endpoint"},
    }))


def bench_grpc_insert() -> None:
    """Over-the-wire insert throughput against the native frontend
    (kbfront), driven by the native load generator — the reference's
    methodology (an external Go benchmark tool, 300 concurrent etcd
    clients, 512B values, docs/benchmark.md:34-37). A Python grpcio load
    generator saturates a 2-vCPU box at ~2k ops/s of CLIENT-side
    interpreter cost; kbloadgen plays the Go tool's role at native speed
    so the measurement exercises the server, not the client.

    KB_BENCH_PYCLIENT=1 falls back to the round-1 methodology (32 Python
    grpcio client threads against the sync endpoint) for comparison.
    """
    import socket
    import threading

    from kubebrain_tpu.client import EtcdCompatClient

    def free_port():
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        p = s.getsockname()[1]
        s.close()
        return p

    n_ops = int(os.environ.get("KB_BENCH_OPS", 50_000))
    use_pyclient = bool(os.environ.get("KB_BENCH_PYCLIENT"))
    repo = os.path.dirname(os.path.abspath(__file__))
    loadgen = os.path.join(repo, "native", "front", "kbloadgen")
    front_bin = os.path.join(repo, "native", "front", "kbfront")
    if not use_pyclient and not (os.path.exists(loadgen) and os.path.exists(front_bin)):
        use_pyclient = True

    port = free_port()
    args = [sys.executable, "-m", "kubebrain_tpu.cli", "--single-node",
            "--storage", "native", "--host", "127.0.0.1",
            "--client-port", str(free_port() if not use_pyclient else port),
            "--peer-port", str(free_port()), "--info-port", str(free_port())]
    if not use_pyclient:
        args += ["--front-port", str(port)]
    use_tls = bool(os.environ.get("KB_BENCH_TLS")) and not use_pyclient
    tls_dir = None
    if use_tls:
        import tempfile

        from kubebrain_tpu.util.selfsigned import gen_self_signed

        tls_dir = tempfile.mkdtemp(prefix="kb-bench-tls-")
        cert_file, key_file = gen_self_signed(tls_dir, "kb-bench", (), ("127.0.0.1",))
        args += ["--cert-file", cert_file, "--key-file", key_file]
    server = subprocess.Popen(args, cwd=repo, stderr=subprocess.DEVNULL)
    value = b"x" * 512
    probe = EtcdCompatClient(f"127.0.0.1:{port}")
    deadline = time.time() + 30
    while time.time() < deadline:
        try:
            probe.count(b"/x", b"/y")
            break
        except Exception:
            time.sleep(0.2)
    probe.close()

    try:
        if use_pyclient:
            n_clients = int(os.environ.get("KB_BENCH_CLIENTS", 32))
            n_ops = int(os.environ.get("KB_BENCH_OPS", 10_000))
            per = n_ops // n_clients

            def client_writer(w):
                c = EtcdCompatClient(f"127.0.0.1:{port}")
                for i in range(per):
                    c.create(b"/registry/pods/g-%03d-%06d" % (w, i), value)
                c.close()

            threads = [threading.Thread(target=client_writer, args=(w,))
                       for w in range(n_clients)]
            t0 = time.time()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            dt = time.time() - t0
            rate = per * n_clients / dt
            detail = {"ops": per * n_clients, "clients": n_clients,
                      "value_bytes": 512, "transport": "etcd3 gRPC (sync, py client)"}
        else:
            n_conns = int(os.environ.get("KB_BENCH_CLIENTS", 8))
            inflight = int(os.environ.get("KB_BENCH_INFLIGHT", 16))
            lg_args = [loadgen, "127.0.0.1", str(port), str(n_ops),
                       str(n_conns), str(inflight), "512"]
            if use_tls:
                lg_args.append("--tls")
            out = subprocess.run(
                lg_args, capture_output=True, text=True, timeout=300,
            )
            if out.returncode != 0 or not out.stdout.strip():
                raise RuntimeError(
                    f"kbloadgen failed rc={out.returncode}: {out.stderr[-500:]}")
            res = json.loads(out.stdout.strip().splitlines()[-1])
            assert res["failed"] == 0, res
            rate = res["rate"]
            detail = {"ops": res["ops"], "conns": n_conns, "inflight": inflight,
                      "value_bytes": 512,
                      "transport": "etcd3 gRPC (kbfront%s)" % (
                          " TLS" if use_tls else ""),
                      "avg_ms": round(res["avg_us"] / 1e3, 2),
                      "p50_ms": round(res["p50_us"] / 1e3, 2),
                      "p99_ms": round(res["p99_us"] / 1e3, 2)}
    finally:
        server.terminate()
        server.wait(timeout=10)
        if tls_dir is not None:
            import shutil

            shutil.rmtree(tls_dir, ignore_errors=True)  # unencrypted key
    print(json.dumps({
        "metric": "grpc insert ops/sec",
        "value": round(rate),
        "unit": "ops/sec",
        "vs_baseline": round(rate / 28_644, 3),
        "platform": platform_info(),
        "detail": detail,
    }))


def bench_rebuild() -> None:
    """TPU-mirror rebuild over the remote tier (the composed production
    topology, --storage=tpu --inner-storage=remote): bulk OP_EXPORT vs the
    per-row iter+decode path, both over a real kbstored subprocess.
    Reference analogue: the TiKV adapter feeding the scanner's partition
    map (tikv.go:38-153). KB_BENCH_KEYS keys x 2 revisions."""
    import socket

    _force_cpu()
    from kubebrain_tpu import coder
    from kubebrain_tpu.parallel.mesh import make_mesh
    from kubebrain_tpu.storage import new_storage
    from kubebrain_tpu.storage.remote import RemoteKvStorage

    n_keys = int(os.environ.get("KB_BENCH_KEYS", 100_000))
    rows = n_keys * 2

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    stored = subprocess.Popen(
        [os.path.join(os.path.dirname(__file__), "native", "kvrpc", "kbstored"),
         str(port)],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
    )
    try:
        assert b"READY" in stored.stdout.readline(), "kbstored failed to start"

        remote = new_storage("remote", address=f"127.0.0.1:{port}", pool=4)
        t0 = time.time()
        rev = 0
        for base in range(0, n_keys, 2000):
            b = remote.begin_batch_write()
            for i in range(base, min(base + 2000, n_keys)):
                k = b"/registry/pods/p%07d" % i
                for _ in range(2):
                    rev += 1
                    b.put(coder.encode_object_key(k, rev), b"v" * 64)
            b.commit()
        print(f"[bench] loaded {rows} rows into kbstored in {time.time()-t0:.1f}s",
              file=sys.stderr)

        store = new_storage("tpu", inner="remote", mesh=make_mesh(),
                            address=f"127.0.0.1:{port}", pool=4)
        scanner = store.make_scanner(get_compact_revision=lambda: 0)

        def timed_rebuild():
            scanner.mark_uncertain()
            t = time.time()
            scanner.publish()
            return time.time() - t

        fast = min(timed_rebuild() for _ in range(3))

        # hide the bulk export: the rebuild falls to per-row iter + decode
        orig = RemoteKvStorage.export_mvcc
        del RemoteKvStorage.export_mvcc
        try:
            slow = timed_rebuild()
        finally:
            RemoteKvStorage.export_mvcc = orig

        rate = rows / fast
        print(f"[bench] rebuild fast {fast*1e3:.0f}ms slow {slow*1e3:.0f}ms "
              f"({slow/fast:.1f}x)", file=sys.stderr)
        print(json.dumps({
            "metric": "mirror-rebuild rows/sec (over kbstored)",
            "value": int(rate),
            "unit": "rows/sec",
            "vs_baseline": round(slow / fast, 3),
            "platform": platform_info(),
            "detail": {
                "rows": rows,
                "bulk_export_ms": round(fast * 1e3, 1),
                "per_row_ms": round(slow * 1e3, 1),
                "baseline": "per-row iter+decode rebuild over the same wire",
            },
        }))
        store.close()
    finally:
        stored.terminate()
        stored.wait(timeout=5)


def bench_sim() -> None:
    """BASELINE config 5: kube-apiserver informer simulation OVER THE WIRE —
    N long-lived etcd Watch streams (default 10k) through the native
    frontend (kbfront), then a create load into the watched namespaces;
    watcher-side event-delivery latency measured end to end by the native
    load generator. Reference bar: insert event latency avg 11.9-13.5ms,
    p99 23-41ms on 3x12 cores (docs/data/benchmark_insert.csv).

    KB_BENCH_INPROC=1 falls back to the round-1 in-process variant."""
    if not os.environ.get("KB_BENCH_INPROC"):
        return _bench_sim_wire()
    import threading

    from kubebrain_tpu.backend import Backend, BackendConfig
    from kubebrain_tpu.ops.fanout import FanoutMatcher
    from kubebrain_tpu.storage import new_storage

    n_watchers = int(os.environ.get("KB_BENCH_WATCHERS", 1_000))
    n_ops = int(os.environ.get("KB_BENCH_OPS", 10_000))
    n_threads = int(os.environ.get("KB_BENCH_THREADS", 4))
    n_ns = 50

    store = new_storage("native")
    backend = Backend(store, BackendConfig(
        event_ring_capacity=max(200_000, n_ops * 2),
        fanout_matcher=FanoutMatcher(),
    ))
    watch_queues = []
    for i in range(n_watchers):
        _, q = backend.watch(b"/registry/pods/ns-%03d/" % (i % n_ns))
        watch_queues.append(q)

    delivered = [0]
    stop = False

    def drain():
        while not stop:
            for q in watch_queues:
                try:
                    while True:
                        batch = q.get_nowait()
                        if batch:
                            delivered[0] += len(batch)
                except Exception:
                    pass
            time.sleep(0.01)

    drainer = threading.Thread(target=drain, daemon=True)
    drainer.start()

    per = n_ops // n_threads
    value = b"x" * 512

    def writer(w):
        for i in range(per):
            key = b"/registry/pods/ns-%03d/pod-%02d-%06d" % (i % n_ns, w, i)
            rev = backend.create(key, value)
            if i % 10 == 0:
                backend.list_(b"/registry/pods/ns-%03d/" % (i % n_ns),
                              b"/registry/pods/ns-%03d0" % (i % n_ns), limit=100)

    threads = [threading.Thread(target=writer, args=(w,)) for w in range(n_threads)]
    t0 = time.time()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    dt = time.time() - t0
    time.sleep(0.5)
    stop = True
    rate = per * n_threads / dt
    backend.close()
    store.close()
    print(json.dumps({
        "metric": "apiserver-sim write ops/sec",
        "value": round(rate),
        "unit": "ops/sec",
        "vs_baseline": round(rate / 14_801, 3),  # reference mixed-RW insert low bound
        "platform": platform_info(),
        "detail": {
            "watchers": n_watchers, "ops": per * n_threads,
            "events_delivered": delivered[0],
            "lists_interleaved": per * n_threads // 10,
            "threads": n_threads, "engine": "native(C++)",
        },
    }))


def _bench_sim_wire() -> None:
    import socket

    from kubebrain_tpu.client import EtcdCompatClient

    def free_port():
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        p = s.getsockname()[1]
        s.close()
        return p

    n_watchers = int(os.environ.get("KB_BENCH_WATCHERS", 10_000))
    n_ns = int(os.environ.get("KB_BENCH_NS", 500))
    n_ops = int(os.environ.get("KB_BENCH_OPS", 10_000))
    # throughput saturates by ~16 in-flight; deeper pipelines only add
    # queueing delay to the reported event latency
    n_conns = int(os.environ.get("KB_BENCH_CLIENTS", 4))
    inflight = int(os.environ.get("KB_BENCH_INFLIGHT", 4))
    repo = os.path.dirname(os.path.abspath(__file__))
    loadgen = os.path.join(repo, "native", "front", "kbloadgen")
    front_bin = os.path.join(repo, "native", "front", "kbfront")
    if not (os.path.exists(loadgen) and os.path.exists(front_bin)):
        raise RuntimeError("build native first: make -C native")

    port = free_port()
    args = [sys.executable, "-m", "kubebrain_tpu.cli", "--single-node",
            "--storage", "native", "--host", "127.0.0.1",
            "--client-port", str(free_port()), "--peer-port", str(free_port()),
            "--info-port", str(free_port()), "--front-port", str(port),
            "--tpu-fanout", "--grpc-workers", "8"]
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # --tpu-fanout touches jax at startup; a wedged axon tunnel would
        # hang the child without the in-process override (see cli --jax-platform)
        args += ["--jax-platform", "cpu"]
    server = subprocess.Popen(args, cwd=repo, stderr=subprocess.DEVNULL)
    try:
        probe = EtcdCompatClient(f"127.0.0.1:{port}")
        deadline = time.time() + 60
        while time.time() < deadline:
            try:
                probe.count(b"/x", b"/y")
                break
            except Exception:
                time.sleep(0.3)
        probe.close()
        out = subprocess.run(
            [loadgen, "127.0.0.1", str(port), str(n_ops), str(n_conns),
             str(inflight), "512", "--watchers", str(n_watchers),
             "--ns", str(n_ns)],
            capture_output=True, text=True, timeout=900,
        )
        if out.returncode != 0 or not out.stdout.strip():
            raise RuntimeError(
                f"kbloadgen failed rc={out.returncode}: {out.stderr[-500:]}")
        res = json.loads(out.stdout.strip().splitlines()[-1])
        assert res["failed"] == 0, res
        assert res["deliveries"] == res["expected_deliveries"], res
    finally:
        server.terminate()
        server.wait(timeout=10)
    print(json.dumps({
        "metric": "apiserver-sim write ops/sec",
        "value": round(res["rate"]),
        "unit": "ops/sec",
        "vs_baseline": round(res["rate"] / 14_801, 3),
        "platform": platform_info(),
        "detail": {
            "watchers": n_watchers, "namespaces": n_ns, "ops": res["ops"],
            "events_delivered": res["deliveries"],
            "event_latency_avg_ms": res["ev_avg_ms"],
            "event_latency_p50_ms": res["ev_p50_ms"],
            "event_latency_p99_ms": res["ev_p99_ms"],
            "insert_p50_ms": round(res["p50_us"] / 1e3, 1),
            "conns": n_conns, "inflight": inflight,
            "transport": "etcd3 gRPC (kbfront), native watch streams",
            "reference_event_latency": "avg 11.9-13.5ms p99 23-41ms (3x12 cores)",
        },
    }))


def bench_sched() -> None:
    """Scheduler microbench (make bench-smoke): randomized Range workloads
    over a real backend, scheduled (concurrent, coalesced, depth-bounded)
    vs unscheduled sequential. On the CPU fallback the two paths must be
    byte-identical per request — the scheduler is a throughput/fairness
    layer, never a semantics layer. Small by default (KB_BENCH_KEYS=2000)
    so it runs as a smoke check anywhere."""
    import random
    import threading

    from kubebrain_tpu.backend import Backend, BackendConfig
    from kubebrain_tpu.sched import SchedConfig, ensure_scheduler
    from kubebrain_tpu.storage import new_storage

    n_keys = int(os.environ.get("KB_BENCH_KEYS", 2_000))
    n_req = int(os.environ.get("KB_BENCH_OPS", 200))
    depth = int(os.environ.get("KB_SCHED_DEPTH", 4))
    rng = random.Random(0)

    store = new_storage("memkv")
    backend = Backend(store, BackendConfig(event_ring_capacity=max(8192, n_keys * 2)))
    sched = ensure_scheduler(backend, SchedConfig(depth=depth))
    for i in range(n_keys):
        backend.create(b"/registry/pods/ns-%02d/pod-%06d" % (i % 20, i), b"x" * 64)
    rev = backend.current_revision()

    workloads = []
    for _ in range(n_req):
        ns = rng.randrange(20)
        workloads.append((
            b"/registry/pods/ns-%02d/" % ns, b"/registry/pods/ns-%02d0" % ns,
            rng.choice([0, rev]), rng.choice([0, 50]),
        ))

    def fingerprint(res):
        out = [b"%d|%d|%d" % (res.revision, res.count, int(res.more))]
        for kv in res.kvs:
            out.append(kv.key + b"\x00" + kv.value + b"\x00%d" % kv.revision)
        return b"\xff".join(out)

    # unscheduled sequential baseline
    t0 = time.time()
    expect = [fingerprint(backend.list_(*w)) for w in workloads]
    seq_dt = time.time() - t0

    # scheduled, concurrent (8 client threads sharing the queue)
    results: list = [None] * n_req
    idx = iter(range(n_req))
    lock = threading.Lock()

    def worker():
        while True:
            with lock:
                try:
                    i = next(idx)
                except StopIteration:
                    return
            results[i] = fingerprint(sched.list_(*workloads[i], client="w"))

    threads = [threading.Thread(target=worker) for _ in range(8)]
    t0 = time.time()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    sched_dt = time.time() - t0

    mismatches = sum(1 for a, b in zip(results, expect) if a != b)
    assert mismatches == 0, f"{mismatches}/{n_req} scheduled results diverged"

    # deterministic batch-formation check (ISSUE 5): plug the single slot of
    # a fresh scheduler, queue 8 distinct ranges + counts, release — they
    # must ride ONE backend batch and match sequential results byte for byte
    from kubebrain_tpu.sched import Lane

    store2 = new_storage("memkv")
    backend2 = Backend(store2, BackendConfig(event_ring_capacity=8192))
    sched2 = ensure_scheduler(backend2, SchedConfig(depth=1, batch=8))
    for i in range(200):
        backend2.create(b"/registry/pods/ns-%02d/p-%04d" % (i % 8, i), b"x" * 32)
    release = threading.Event()
    sched2.submit_async(release.wait, Lane.SYSTEM)
    time.sleep(0.1)
    outs: dict = {}

    def one_batched(i):
        ns = i % 8
        a, b = b"/registry/pods/ns-%02d/" % ns, b"/registry/pods/ns-%02d0" % ns
        if i % 3 == 2:
            outs[i] = ("count", sched2.count(a, b, client="w"))
        else:
            outs[i] = ("list", fingerprint(sched2.list_(a, b, 0, 0, client="w")))
    bthreads = [threading.Thread(target=one_batched, args=(i,)) for i in range(8)]
    for t in bthreads:
        t.start()
    time.sleep(0.3)
    release.set()
    for t in bthreads:
        t.join(30.0)
    assert sched2.batched > 0, "plugged slot formed no batch"
    batched_mismatches = 0
    for i in range(8):
        ns = i % 8
        a, b = b"/registry/pods/ns-%02d/" % ns, b"/registry/pods/ns-%02d0" % ns
        if i % 3 == 2:
            want = ("count", backend2.count(a, b))
        else:
            want = ("list", fingerprint(backend2.list_(a, b, 0, 0)))
        batched_mismatches += outs[i] != want
    assert batched_mismatches == 0, f"{batched_mismatches}/8 batched diverged"
    backend2.close()
    store2.close()

    print(json.dumps({
        "metric": "scheduled range reqs/sec",
        "value": round(n_req / sched_dt),
        "unit": "requests/sec",
        "vs_baseline": round(seq_dt / sched_dt, 3),
        "platform": platform_info(),
        "detail": {
            "requests": n_req, "keys": n_keys, "depth": depth,
            "byte_identical": True,
            "coalesced": sched.coalesced,
            "batched_riders": sched2.batched,
            "batched_byte_identical": True,
            "shed": {l.name.lower(): c for l, c in sched.shed_counts.items()},
            "sequential_reqs_per_sec": round(n_req / seq_dt),
            "baseline": "unscheduled sequential backend.list_",
        },
    }))
    backend.close()
    store.close()


def bench_write() -> None:
    """Write-path group commit bench (KB_BENCH_METRIC=write; BENCH_r06):
    ``write_txns_per_sec`` serial vs grouped — the SAME mixed
    create/update/delete workload at 8-writer concurrency through the
    scheduler, once with group commit off (``write_batch=1``) and once on
    (``write_batch=8``). Disjoint per-writer keyspaces make the runs
    commute, so final (key, value) state must be identical; exact
    byte-identity INCLUDING revisions is asserted separately with a
    deterministic plugged-slot group vs a sequential oracle (the same
    construction proof tests/test_write_batch.py pins).

    The second half runs grouped writes over the TPU engine (CPU-sim jnp
    kernel) with a concurrent reader crossing the merge threshold, and
    asserts the steady state NEVER takes the full host rebuild:
    ``full_rebuild_total == 0`` and ``merge_rows_total`` accounts every
    delta row that left the overlay (merged + still-pending == committed
    version rows since the initial publish).

    Bars: grouped >= 1.5x serial is asserted ON CPU (the win is dispatch
    and commit-path amortization, not device time); the TPU-engine merge
    numbers carry a ``pending_tpu`` stamp off-TPU like the other phases."""
    import random
    import threading

    from kubebrain_tpu.backend import Backend, BackendConfig
    from kubebrain_tpu.sched import Lane, SchedConfig, ensure_scheduler
    from kubebrain_tpu.storage import new_storage

    writers = int(os.environ.get("KB_BENCH_WRITERS", 8))
    ops_per_writer = int(os.environ.get("KB_BENCH_OPS", 400))
    depth = int(os.environ.get("KB_SCHED_DEPTH", 1))
    wbatch = int(os.environ.get("KB_SCHED_WRITE_BATCH", 8))

    def writer_stream(w: int):
        """Deterministic mixed stream for writer ``w`` over its own keys:
        create -> update -> update -> delete -> recreate ... (4:2:1 mix)."""
        rng = random.Random(1000 + w)
        live: dict[bytes, int] = {}
        ops = []
        for step in range(ops_per_writer):
            k = b"/registry/pods/w-%02d/p-%03d" % (w, rng.randrange(40))
            if k not in live:
                ops.append(("create", k, b"c%04d" % step))
            elif rng.random() < 0.6:
                ops.append(("update", k, b"u%04d" % step))
            else:
                ops.append(("delete", k))
            # liveness tracking only; revisions resolve at run time
            if ops[-1][0] == "delete":
                live.pop(k)
            else:
                live[k] = 1
        return ops

    streams = [writer_stream(w) for w in range(writers)]

    def run(write_batch: int):
        store = new_storage("memkv")
        backend = Backend(store, BackendConfig(event_ring_capacity=65536))
        sched = ensure_scheduler(backend, SchedConfig(
            depth=depth, write_batch=write_batch))
        errs: list = []

        def w_run(w: int):
            try:
                live: dict[bytes, int] = {}
                for op in streams[w]:
                    if op[0] == "create":
                        live[op[1]] = sched.create(op[1], op[2],
                                                   client=f"w{w}")
                    elif op[0] == "update":
                        live[op[1]] = sched.update(op[1], op[2],
                                                   live[op[1]],
                                                   client=f"w{w}")
                    else:
                        sched.delete(op[1], live.pop(op[1]),
                                     client=f"w{w}")
            except BaseException as e:  # pragma: no cover
                errs.append(e)

        threads = [threading.Thread(target=w_run, args=(w,))
                   for w in range(writers)]
        t0 = time.time()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        dt = time.time() - t0
        assert not errs, errs[0]
        state = sorted(
            (kv.key, kv.value) for kv in
            backend.list_(b"/registry/", b"/registry0", 0, 0).kvs)
        riders = sched.write_batched
        backend.close()
        store.close()
        return dt, state, riders

    total_ops = writers * ops_per_writer
    # warm up both paths (allocator/thread pools), then interleave
    # serial/grouped rounds and take best-of-3 each: the 2-vCPU CI box's
    # load swings dwarf the effect under test
    run(1)
    run(wbatch)
    rounds = [(run(1), run(wbatch)) for _ in range(3)]
    serial_dt, serial_state, _ = min(
        (s for s, _ in rounds), key=lambda r: r[0])
    grouped_dt, grouped_state, riders = min(
        (g for _, g in rounds), key=lambda r: r[0])
    assert grouped_state == serial_state, \
        "grouped and serial runs must converge to the same (key,value) state"
    assert riders > 0, "no write group ever formed at 8-writer concurrency"
    serial_rate = total_ops / serial_dt
    grouped_rate = total_ops / grouped_dt
    speedup = grouped_rate / serial_rate
    assert speedup >= 1.5, (
        f"group commit {speedup:.2f}x serial is under the 1.5x bar "
        f"({grouped_rate:.0f} vs {serial_rate:.0f} txns/s)")

    # --- deterministic formation: byte-identity incl. revisions ----------
    store = new_storage("memkv")
    backend = Backend(store, BackendConfig(event_ring_capacity=8192))
    sched = ensure_scheduler(backend, SchedConfig(depth=1, write_batch=8))
    o_store = new_storage("memkv")
    oracle = Backend(o_store, BackendConfig(event_ring_capacity=8192))
    release = threading.Event()
    sched.submit_async(release.wait, Lane.SYSTEM)
    time.sleep(0.1)
    keys = [b"/registry/pods/det/p-%d" % i for i in range(8)]
    outs: dict = {}
    det_errs: list = []

    def det_create(i: int) -> None:
        try:
            outs[i] = sched.create(keys[i], b"v%d" % i, client=f"c{i}")
        except BaseException as e:  # pragma: no cover
            det_errs.append(e)

    gthreads = [threading.Thread(target=det_create, args=(i,))
                for i in range(8)]
    for t in gthreads:
        t.start()
    time.sleep(0.3)
    release.set()
    for t in gthreads:
        t.join(30)
    assert not det_errs, det_errs[0]
    assert sched.write_batched > 0, "plugged slot formed no write group"
    for i in range(8):
        oracle.create(keys[i], b"v%d" % i)
    det_got = sorted(
        (kv.key, kv.value) for kv in
        backend.list_(b"/registry/pods/det/", b"/registry/pods/det0", 0, 0).kvs)
    det_want = sorted(
        (kv.key, kv.value) for kv in
        oracle.list_(b"/registry/pods/det/", b"/registry/pods/det0", 0, 0).kvs)
    # the dealt revision block is contiguous like the oracle's sequence
    det_identical = det_got == det_want and \
        sorted(outs.values()) == list(range(min(outs.values()),
                                            min(outs.values()) + 8))
    assert det_identical, "deterministic group diverged from the oracle"
    backend.close()
    store.close()
    oracle.close()
    o_store.close()

    # --- TPU-engine steady state: incremental merge, no full rebuild -----
    import jax  # noqa: F401  (forces backend init for platform_info)

    t_store = new_storage("tpu", inner="memkv")
    t_backend = Backend(t_store, BackendConfig(event_ring_capacity=65536))
    t_sched = ensure_scheduler(t_backend, SchedConfig(
        depth=depth, write_batch=wbatch))
    sc = t_backend.scanner
    sc._merge_threshold = 256
    rng = random.Random(17)
    seeded: dict[bytes, int] = {}
    for w in range(writers):
        for i in range(0, 40, 2):
            k = b"/registry/pods/w-%02d/p-%03d" % (w, i)
            seeded[k] = t_backend.create(k, b"seed")
    sc.publish()
    base_rows = len(sc._delta)  # 0 after publish
    stop_reader = threading.Event()

    def reader():
        while not stop_reader.is_set():
            t_backend.count(b"/registry/pods/", b"/registry/pods0")
            time.sleep(0.005)

    rt = threading.Thread(target=reader)
    rt.start()
    errs2: list = []

    def t_writer(w: int):
        try:
            live = {k: r for k, r in seeded.items()
                    if k.startswith(b"/registry/pods/w-%02d/" % w)}
            lrng = random.Random(2000 + w)
            for step in range(ops_per_writer):
                k = b"/registry/pods/w-%02d/p-%03d" % (w, lrng.randrange(40))
                if k not in live:
                    live[k] = t_sched.create(k, b"c%04d" % step,
                                             client=f"w{w}")
                elif lrng.random() < 0.6:
                    live[k] = t_sched.update(k, b"u%04d" % step, live[k],
                                             client=f"w{w}")
                else:
                    t_sched.delete(k, live.pop(k), client=f"w{w}")
        except BaseException as e:  # pragma: no cover
            errs2.append(e)

    tthreads = [threading.Thread(target=t_writer, args=(w,))
                for w in range(writers)]
    t0 = time.time()
    for t in tthreads:
        t.start()
    for t in tthreads:
        t.join()
    tpu_dt = time.time() - t0
    stop_reader.set()
    rt.join(10)
    assert not errs2, errs2[0]
    # quiesce before sampling: publish() enters the merge path and blocks
    # on the merge lock, so any in-flight write-kicked background merge
    # finishes (and its counters land) before we read them; it also
    # sweeps the delta tail, so pending is 0 and the accounting is exact
    sc.publish()
    merged = sc.merge_rows_total
    pending = len(sc._delta)
    full_rebuilds = sc.full_rebuild_total
    assert sc.merge_bg_errors == 0, sc._merge_bg_last_error
    assert full_rebuilds == 0, (
        f"steady-state churn took {full_rebuilds} full host rebuilds — "
        "the incremental merge must carry it")
    assert sc.merge_count > 0, "writes never crossed the merge threshold"
    assert merged + pending == total_ops - base_rows, (
        f"merge accounting leak: {merged} merged + {pending} pending != "
        f"{total_ops} committed rows")
    on_tpu = jax.devices()[0].platform in ("tpu", "axon")
    t_backend.close()
    t_store.close()

    print(json.dumps({
        "metric": "write_txns_per_sec",
        "value": round(grouped_rate),
        "unit": "txns/sec",
        "vs_baseline": round(speedup, 3),
        "platform": platform_info(),
        "detail": {
            "writers": writers, "ops": total_ops, "depth": depth,
            "write_batch": wbatch,
            "serial_txns_per_sec": round(serial_rate),
            "grouped_txns_per_sec": round(grouped_rate),
            "grouped_riders": riders,
            "state_identical": True,
            "deterministic_group_byte_identical": det_identical,
            "grouped_acceptance_1_5x": "pass",  # asserted above, on CPU
            "mix": "create/update/delete ~40/36/24",
            "tpu_engine_merge": {
                "write_txns_per_sec": round((total_ops) / tpu_dt),
                "merges": sc.merge_count,
                "merge_rows_total": merged,
                "delta_rows_pending": pending,
                "full_rebuild_total": full_rebuilds,
                "accounting_exact": True,
                "merge_acceptance_tpu": "pass" if on_tpu else "pending_tpu",
            },
        },
    }))


def bench_cluster() -> None:
    """Cluster-scale workload replay (make bench-cluster N=...): the
    deterministic kube-apiserver traffic generator driven through the real
    gRPC front — pod churn + per-controller list/watch + node lease
    keepalives + compaction in ONE run — reporting per-lane p50/p99, shed
    rates, watch queue->wire lag, and lease counts reconciled against
    /metrics. Full report: WORKLOAD_rNN.json (docs/workloads.md).

    Env knobs: KB_BENCH_NODES (or N), KB_WORKLOAD_SEED, KB_WORKLOAD_DURATION
    (simulated seconds), KB_WORKLOAD_SCALE (sim seconds per real second),
    KB_WORKLOAD_STORAGE, KB_WORKLOAD_OUT (report path),
    KB_WORKLOAD_MESH_PART / KB_WORKLOAD_SCAN_PARTITIONS (sharded server,
    requires KB_WORKLOAD_STORAGE=tpu; docs/multichip.md),
    KB_WORKLOAD_COMPACT_S (compaction cadence in simulated seconds —
    the 5-min-compaction scenario; docs/compaction.md)."""
    from kubebrain_tpu.workload.runner import run_workload
    from kubebrain_tpu.workload.spec import WorkloadSpec

    nodes = int(os.environ.get("KB_BENCH_NODES", os.environ.get("N", 1000)))
    scenario = os.environ.get("KB_WORKLOAD_SCENARIO", "cluster")
    faults = os.environ.get("KB_WORKLOAD_FAULTS", "none")
    common = dict(
        seed=int(os.environ.get("KB_WORKLOAD_SEED", 0)),
        duration_s=float(os.environ.get("KB_WORKLOAD_DURATION", 30.0)),
        time_scale=float(os.environ.get("KB_WORKLOAD_SCALE", 5.0)),
        storage=os.environ.get("KB_WORKLOAD_STORAGE", "memkv"),
        mesh_part=int(os.environ.get("KB_WORKLOAD_MESH_PART", 0)),
        scan_partitions=int(os.environ.get("KB_WORKLOAD_SCAN_PARTITIONS", 0)),
        # read scale-out (docs/replication.md): spawn follower replicas;
        # the report then lands in REPLICA_rNN.json with a schema'd
        # `replica` section (make bench-cluster REPLICAS=2)
        replicas=int(os.environ.get("KB_WORKLOAD_REPLICAS", 0)),
    )
    # compaction-cadence knob (SIMULATED seconds; 0 = scenario default) —
    # `make bench-cluster COMPACT_S=300` drives the 5-min-compaction
    # scenario with serving-lane SLOs judged while compactions run
    compact_s = float(os.environ.get("KB_WORKLOAD_COMPACT_S", 0) or 0)
    if compact_s > 0:
        common["compact_interval_s"] = compact_s
    # watch fan-out offload (docs/watch.md): MESH_WAT=N shards the spawned
    # servers' watcher table over N (simulated) devices; the watch_heavy
    # scenario arms --tpu-fanout by itself, MESH_WAT works with any scenario
    mesh_wat = int(os.environ.get("KB_WORKLOAD_MESH_WAT", 0))
    if mesh_wat:
        common["tpu_fanout"] = True
        common["mesh_wat"] = mesh_wat
    if faults and faults != "none":
        # chaos mode (docs/faults.md): churn_heavy traffic under an armed
        # fault schedule; judged by the acknowledged-write consistency
        # check + per-kind injection reconcile; report -> CHAOS_rNN.json
        spec = WorkloadSpec.for_chaos(
            nodes, preset=faults,
            fault_seed=int(os.environ.get("KB_WORKLOAD_FAULT_SEED", 0)),
            **common)
    else:
        factory = {"cluster": WorkloadSpec.for_cluster,
                   "churn_heavy": WorkloadSpec.for_churn_heavy,
                   "churn-heavy": WorkloadSpec.for_churn_heavy,
                   "watch_heavy": WorkloadSpec.for_watch_heavy,
                   "watch-heavy": WorkloadSpec.for_watch_heavy}[scenario]
        spec = factory(nodes, **common)
    report = run_workload(spec, out_path=os.environ.get("KB_WORKLOAD_OUT") or None)
    lanes = {lane: {"p50_ms": s["p50_ms"], "p99_ms": s["p99_ms"],
                    "count": s["count"], "shed": s["shed"]}
             for lane, s in report["lanes"].items()}
    print(json.dumps({
        "metric": "cluster-replay ops/sec",
        "value": report["replay"]["ops_per_sec"],
        "unit": "ops/sec",
        "vs_baseline": 1.0 if report["slo"]["pass"] else 0.0,
        "platform": platform_info(),
        "detail": {
            "nodes": spec.nodes,
            "seed": spec.seed,
            "trace_sha256": report["trace"]["sha256"],
            "slo_pass": report["slo"]["pass"],
            "violations": report["slo"]["violations"],
            "lanes": lanes,
            "watchers": report["watch"]["watchers"],
            "watch_events": report["watch"]["events"],
            "watch_wire_lag_p99_s": report["watch"]["lag_wire_p99_s"],
            "keepalives_acked": report["leases"]["keepalives_acked"],
            "lease_expiries": report["leases"]["metrics"]["expired_delta"],
            "batched_requests": report["sched"]["batched_requests"],
            "reconcile_ok": report["reconcile"]["ok"],
            "replica": ({
                "replicas": spec.replicas,
                "rows_per_sec": report["replica"]["rows_per_sec"],
                "fence_probes": report["replica"]["fence_probes"],
                "endpoint_failovers": report["replica"]["endpoint_failovers"],
                "reconcile_ok": report["replica"]["reconcile"]["ok"],
            } if spec.replicas else None),
            "faults": ({
                "preset": spec.faults,
                "sha256": report["faults"]["schedule"]["sha256"],
                "injected": report["faults"]["injected"],
                "consistency_ok": report["faults"]["consistency"]["ok"],
                "degraded_p99_ms": report["faults"]["degraded"]["p99_ms"],
            } if report["faults"]["armed"] else {"preset": "none"}),
        },
    }))


#: timed serve passes per measurement point in multichip_phase — the
#: fastest pass is reported (least cross-process interference on shared
#: CPU boxes; on a quiet TPU host the passes agree within noise)
_SERVE_PASSES = 3


def _serve_best(serve_fn, sched):
    """Best-of-N timed serves: every pass must return identical results
    (asserted — a best-of measurement must not hide a divergence)."""
    best = None
    for _ in range(_SERVE_PASSES):
        results, rows, dt = serve_fn(sched)
        if best is not None:
            assert results == best[0], "serve passes diverged"
        if best is None or dt < best[2]:
            best = (results, rows, dt)
    return best


def multichip_phase(mesh_sizes, n_keys=20_000, n_req=64, depth=4, batch=8,
                    partitions=0, use_pallas=None, threads=8):
    """Serve the SAME scan workload through the request scheduler over the
    TPU engine at each mesh size and report the scaling curve — the
    promoted multichip path (the MULTICHIP dry runs never served a
    request). One host store is preloaded once; each mesh size wraps it in
    a fresh ``TpuKvStorage`` whose mirror shards over ``part`` across that
    many devices, then 8 distinct per-namespace Range/Count requests x
    ``n_req`` are pushed through the scheduler concurrently (composing
    with PR 2 lanes/pipelining and PR 5 query batching). Results are
    fingerprinted against the unscheduled sequential oracle AND across
    mesh sizes — byte identity is asserted, not sampled.

    Shared by ``bench_multichip`` (KB_BENCH_METRIC=multichip) and
    ``__graft_entry__.dryrun_multichip`` (the driver contract)."""
    import threading

    from kubebrain_tpu.backend import Backend, BackendConfig
    from kubebrain_tpu.parallel.mesh import make_mesh
    from kubebrain_tpu.sched import SchedConfig, ensure_scheduler
    from kubebrain_tpu.storage import new_storage
    from kubebrain_tpu.storage.tpu.engine import TRANSFER_METER, TpuKvStorage

    NS = 8
    inner = new_storage("memkv")
    loader = Backend(inner, BackendConfig(
        event_ring_capacity=max(8192, n_keys * 2)))
    for i in range(n_keys):
        loader.create(b"/registry/pods/ns-%02d/pod-%07d" % (i % NS, i),
                      b"x" * 64)
    loader.close()

    # request mix: per-namespace Range (3 of 4) and Count (1 of 4) — the
    # distinct-prefix shape that forms PR 5 query batches
    reqs = []
    for i in range(n_req):
        ns = i % NS
        bounds = (b"/registry/pods/ns-%02d/" % ns,
                  b"/registry/pods/ns-%02d0" % ns)
        reqs.append(("count" if i % 4 == 3 else "list", *bounds))

    def fingerprint(kind, res):
        if kind == "count":
            return b"count|%d|%d" % res
        out = [b"%d|%d|%d" % (res.revision, res.count, int(res.more))]
        for kv in res.kvs:
            out.append(kv.key + b"\x00" + kv.value + b"\x00%d" % kv.revision)
        return b"\xff".join(out)

    report = {
        "mesh_sizes": list(mesh_sizes),
        "rows_per_sec": {},
        "scaling_vs_1dev": {},
        "byte_identical": True,
        "batched_riders": {},
        "mirror_partitions": {},
        "host_transfer_bytes_per_req": {},
        "requests": n_req,
        "sched": {"depth": depth, "batch": batch, "threads": threads},
        "dataset": {"keys": n_keys, "namespaces": NS},
    }
    baseline_fps = None
    kernel = None

    def _serve(sched):
        results: list = [None] * n_req
        rows = [0] * n_req
        pending = iter(range(n_req))
        lock = threading.Lock()

        def worker():
            while True:
                with lock:
                    try:
                        i = next(pending)
                    except StopIteration:
                        return
                kind, s, e = reqs[i]
                if kind == "count":
                    res = sched.count(s, e, client=f"c{i % 4}")
                    rows[i] = res[0]
                else:
                    res = sched.list_(s, e, 0, 0, client=f"c{i % 4}")
                    rows[i] = len(res.kvs)
                results[i] = fingerprint(kind, res)

        pool = [threading.Thread(target=worker) for _ in range(threads)]
        t0 = time.monotonic()
        for t in pool:
            t.start()
        for t in pool:
            t.join()
        return results, rows, time.monotonic() - t0

    try:
        for ndev in mesh_sizes:
            mesh = make_mesh(n_devices=ndev)
            kw = {} if use_pallas is None else {"use_pallas": use_pallas}
            store = TpuKvStorage(inner, mesh=mesh, partitions=partitions, **kw)
            backend = Backend(store, BackendConfig(event_ring_capacity=8192))
            sched = ensure_scheduler(
                backend, SchedConfig(depth=depth, batch=batch))
            kernel = backend.scanner._scan_kernel
            # sequential unscheduled oracle; also publishes the mirror and
            # compiles this mesh size's kernels off the clock
            expect = []
            for kind, s, e in reqs:
                if kind == "count":
                    expect.append(fingerprint(kind, backend.count(s, e)))
                else:
                    expect.append(fingerprint(kind, backend.list_(s, e)))
            report["mirror_partitions"][str(ndev)] = \
                backend.scanner._mirror.partitions
            # mirror-compression capacity unlock (kubebrain-keyenc/v1):
            # identical at every mesh size — one dictionary, sharded rows
            report["key_encoding"] = {
                "schema": "kubebrain-keyenc/v1",
                **backend.scanner.encoding_stats()}
            report["mirror_bytes_per_row"] = \
                report["key_encoding"].get("mirror_bytes_per_row", 0.0)
            report["key_compression_ratio"] = \
                report["key_encoding"].get("key_compression_ratio", 1.0)

            # warm serve off the clock: the timed pass must not pay the
            # Q-gridded batch kernel's first compile (the sequential oracle
            # above never launches it — it only warms the single-query path)
            _serve(sched)
            batched0 = sched.batched  # cumulative — report the timed delta
            b0, _ = TRANSFER_METER.snapshot()
            results, rows, dt = _serve_best(_serve, sched)
            b1, _ = TRANSFER_METER.snapshot()

            mism = sum(1 for a, b in zip(results, expect) if a != b)
            assert mism == 0, (
                f"{mism}/{n_req} scheduled results diverged from the "
                f"sequential oracle at mesh={ndev}")
            if baseline_fps is None:
                baseline_fps = expect
            elif expect != baseline_fps:
                report["byte_identical"] = False
            report["rows_per_sec"][str(ndev)] = round(sum(rows) / dt)
            report["batched_riders"][str(ndev)] = round(
                (sched.batched - batched0) / _SERVE_PASSES)
            report["host_transfer_bytes_per_req"][str(ndev)] = round(
                (b1 - b0) / n_req / _SERVE_PASSES)
            backend.close()

        # RAW-mirror control at the smallest mesh: the prefix-encoded scan
        # must serve at equal-or-better p50 than the raw layout it
        # replaces (byte-identity asserted against the same oracle)
        if report["key_encoding"].get("encoded"):
            mesh = make_mesh(n_devices=mesh_sizes[0])
            kw = {} if use_pallas is None else {"use_pallas": use_pallas}
            store = TpuKvStorage(inner, mesh=mesh, partitions=partitions,
                                 encode_keys=False, **kw)
            backend = Backend(store, BackendConfig(event_ring_capacity=8192))
            sched = ensure_scheduler(
                backend, SchedConfig(depth=depth, batch=batch))
            for kind, s, e in reqs:  # publish + compile off the clock
                backend.count(s, e) if kind == "count" else backend.list_(s, e)
            _serve(sched)  # warm the batched path off the clock (as above)
            results, rows, dt = _serve_best(_serve, sched)
            assert results == baseline_fps, \
                "raw-control results diverged from the encoded mirror"
            report["rows_per_sec_raw_control"] = round(sum(rows) / dt)
            report["encoded_vs_raw"] = round(
                report["rows_per_sec"][str(mesh_sizes[0])]
                / max(1, report["rows_per_sec_raw_control"]), 3)
            backend.close()
    finally:
        inner.close()
    assert report["byte_identical"], "mesh sizes disagreed byte-for-byte"
    base = report["rows_per_sec"].get(str(mesh_sizes[0]), 0) or 1
    for k, v in report["rows_per_sec"].items():
        report["scaling_vs_1dev"][k] = round(v / base, 3)
    report["kernel"] = kernel
    return report


def bench_multichip() -> None:
    """Multichip sharded serving (the promoted MULTICHIP phase): the scan
    workload served through the scheduler at mesh sizes 1→8, byte-identical
    across sizes, reported as ``multichip_rows_per_sec`` plus a schema'd
    report (kubebrain-multichip/v1; KB_MULTICHIP_OUT=path writes it —
    MULTICHIP_rNN.json replaces the bare ``dryrun ok`` tail of r01–r05).

    Bars: on real TPU, near-linear scaling (>= 0.6x ideal at the largest
    mesh) is asserted; on CPU simulation the devices share the same
    sockets, so the bar is byte-identity plus no pathological slowdown
    (largest mesh >= 0.5x of 1-device) with the TPU bar recorded
    ``pending_tpu``."""
    if os.environ.get("JAX_PLATFORMS", "") == "cpu" or \
            os.environ.get("KB_BENCH_PLATFORM") == "cpu":
        _force_cpu()  # 8 virtual host devices so the mesh sizes exist
    import jax

    n_keys = int(os.environ.get("KB_BENCH_KEYS", 20_000))
    n_req = int(os.environ.get("KB_BENCH_OPS", 64))
    depth = int(os.environ.get("KB_SCHED_DEPTH", 4))
    batch = int(os.environ.get("KB_SCHED_BATCH", 8))
    partitions = int(os.environ.get("KB_SCAN_PARTITIONS", 0))
    n_dev = len(jax.devices())
    mesh_sizes = [k for k in (1, 2, 4, 8) if k <= n_dev]
    on_tpu = jax.devices()[0].platform in ("tpu", "axon")

    phase = multichip_phase(
        mesh_sizes, n_keys=n_keys, n_req=n_req, depth=depth, batch=batch,
        partitions=partitions)
    top = str(mesh_sizes[-1])
    rate = phase["rows_per_sec"][top]
    base = phase["rows_per_sec"][str(mesh_sizes[0])]
    scaling = phase["scaling_vs_1dev"][top]
    if on_tpu:
        assert scaling >= 0.6 * mesh_sizes[-1], (
            f"multichip scaling {scaling:.2f}x at {top} devices is not "
            f"near-linear (bar: >= {0.6 * mesh_sizes[-1]:.1f}x)")
        acceptance = "pass"
    else:
        assert scaling >= 0.5, (
            f"CPU-sim multichip serving collapsed: {scaling:.2f}x of the "
            "1-device rate at the largest mesh")
        acceptance = "pending_tpu"

    report = {
        "schema": "kubebrain-multichip/v1",
        "metric": "multichip_rows_per_sec",
        "platform": platform_info(),
        "served_through_scheduler": True,
        "acceptance_near_linear_tpu": acceptance,
        **phase,
    }
    out_path = os.environ.get("KB_MULTICHIP_OUT")
    if out_path:
        with open(out_path, "w") as f:
            json.dump(report, f, indent=1)
        print(f"[bench] wrote {out_path}", file=sys.stderr)
    print(json.dumps({
        "metric": "multichip_rows_per_sec",
        "value": rate,
        "unit": "rows/sec",
        "vs_baseline": round(rate / base, 3),
        "platform": platform_info(),
        "detail": {k: v for k, v in report.items() if k != "platform"},
    }))


def bench_watcurve() -> None:
    """Scan QPS vs the ``wat`` (read-replica) mesh axis — SURVEY P6.

    Blocks are sharded over ``part`` and REPLICATED over ``wat``; a batch of
    Q concurrent scan queries is sharded over ``wat`` so each replica group
    serves its own query subset. Reports the QPS curve for wat in {1,2,4,8}
    on the available mesh (8 virtual CPU devices in CI — the curve's SHAPE
    is the deliverable there; real chips give it real slope).
    Reference analogue: follower read replicas (README.md:21-24)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from kubebrain_tpu.ops import keys as keyops
    from kubebrain_tpu.ops.scan import visibility_mask
    from kubebrain_tpu.parallel.mesh import make_mesh

    n_keys = int(os.environ.get("KB_BENCH_KEYS", 50_000))
    revs = int(os.environ.get("KB_BENCH_REVS", 20))
    iters = int(os.environ.get("KB_BENCH_ITERS", 7))
    n_q = int(os.environ.get("KB_BENCH_QUERIES", 8))
    n_dev = len(jax.devices())

    chunks, rh, rl, tomb = build_dataset(n_keys, revs)
    n = len(chunks)
    # distinct per-query bounds: staggered sub-ranges of the key space
    starts, ends, qrevs = [], [], []
    for qi in range(n_q):
        lo = b"/registry/pods/default/pod-%08d" % (qi * (n_keys // n_q))
        hi = b"/registry/pods/default/pod-%08d" % ((qi + 1) * (n_keys // n_q))
        starts.append(pack_bound(lo))
        ends.append(pack_bound(hi))
        qrevs.append(n * (qi + 2) // (n_q + 2))
    s_q = np.stack(starts)
    e_q = np.stack(ends)
    qhi, qlo = keyops.split_revs(np.array(qrevs, dtype=np.uint64))

    curve = {}
    for wat in (1, 2, 4, 8):
        if n_dev % wat or wat > n_dev or n_q % wat:
            continue
        part = n_dev // wat
        mesh = make_mesh(axes=("part", "wat"), shape=(part, wat))
        rows_per = (n // part) // 8 * 8
        usable = rows_per * part
        P3, P1 = P("part", None, None), P("part", None)
        sh = lambda a, spec: jax.device_put(
            a, jax.sharding.NamedSharding(mesh, spec))
        keys_s = sh(chunks[:usable].reshape(part, rows_per, CHUNKS), P3)
        rh_s = sh(rh[:usable].reshape(part, rows_per), P1)
        rl_s = sh(rl[:usable].reshape(part, rows_per), P1)
        tomb_s = sh(tomb[:usable].reshape(part, rows_per), P1)
        nv_s = sh(np.full(part, rows_per, np.int32), P("part"))
        sq = sh(s_q, P("wat", None))
        eq = sh(e_q, P("wat", None))
        hq = sh(qhi, P("wat"))
        lq = sh(qlo, P("wat"))

        @partial_shard_map_scan(mesh)
        def scan_batch(keys, a, b, t, nv, ss, ee, hh, ll):
            def one_query(s1, e1, h1, l1):
                vis = jax.vmap(
                    lambda k, x, y, z, m: visibility_mask(
                        k, x, y, z, m, s1, e1, jnp.asarray(False), h1, l1)
                )(keys, a, b, t, nv)
                return jax.lax.psum(jnp.sum(vis, dtype=jnp.int32), "part")
            return jax.vmap(one_query)(ss, ee, hh, ll)

        out = scan_batch(keys_s, rh_s, rl_s, tomb_s, nv_s, sq, eq, hq, lq)
        jax.block_until_ready(out)
        lat = []
        for _ in range(iters):
            t0 = time.time()
            jax.block_until_ready(
                scan_batch(keys_s, rh_s, rl_s, tomb_s, nv_s, sq, eq, hq, lq))
            lat.append(time.time() - t0)
        p50 = sorted(lat)[len(lat) // 2]
        curve[wat] = round(n_q / p50, 1)

    base = curve.get(1) or 1.0
    best_wat = max(curve, key=curve.get)
    print(json.dumps({
        "metric": "scan QPS vs wat (read-replica axis)",
        "value": curve[best_wat],
        "unit": "queries/sec",
        "vs_baseline": round(curve[best_wat] / base, 3),
        "platform": platform_info(),
        "detail": {
            "curve_qps": {str(k): v for k, v in curve.items()},
            "queries": n_q, "rows": n, "devices": n_dev,
            "best_wat": best_wat,
            "note": "blocks replicated over wat, queries sharded over wat",
        },
    }))


def partial_shard_map_scan(mesh):
    """shard_map decorator for the wat-curve scan (part x wat mesh)."""
    import jax
    from jax.sharding import PartitionSpec as P

    def deco(f):
        shard_map = getattr(jax, "shard_map", None)
        kw = {}
        if shard_map is None:
            from jax.experimental.shard_map import shard_map

            kw["check_rep"] = False
        specs = dict(
            mesh=mesh,
            in_specs=(P("part", None, None), P("part", None), P("part", None),
                      P("part", None), P("part"),
                      P("wat", None), P("wat", None), P("wat"), P("wat")),
            out_specs=P("wat"),
        )
        return jax.jit(shard_map(f, **specs, **kw))

    return deco


def main() -> None:
    n_keys = int(os.environ.get("KB_BENCH_KEYS", 200_000))
    revs = int(os.environ.get("KB_BENCH_REVS", 100))
    iters = int(os.environ.get("KB_BENCH_ITERS", 10))
    platform = os.environ.get("KB_BENCH_PLATFORM", "")

    if platform == "cpu" or (
        os.environ.get("PALLAS_AXON_POOL_IPS") and not _probe_tpu_alive()
    ):
        print("[bench] TPU tunnel unavailable -> CPU fallback", file=sys.stderr)
        _force_cpu()

    metric = os.environ.get("KB_BENCH_METRIC", "scan")
    if metric == "fanout":
        return bench_fanout()
    if metric == "compact":
        return bench_compact()
    if metric == "insert":
        return bench_insert()
    if metric == "delete":
        return bench_delete()
    if metric == "grpc-insert":
        return bench_grpc_insert()
    if metric == "grpc-list":
        return bench_grpc_list()
    if metric == "sim":
        return bench_sim()
    if metric == "rebuild":
        return bench_rebuild()
    if metric == "sched":
        return bench_sched()
    if metric == "write":
        return bench_write()
    if metric == "cluster":
        return bench_cluster()
    if metric == "multichip":
        return bench_multichip()
    if metric == "watcurve":
        return bench_watcurve()

    import jax
    import jax.numpy as jnp

    from kubebrain_tpu.ops.scan import visibility_mask

    dev = jax.devices()[0]
    print(f"[bench] device: {dev}", file=sys.stderr)

    t0 = time.time()
    chunks, rh, rl, tomb = build_dataset(n_keys, revs)
    n = len(chunks)
    start = pack_bound(b"/registry/pods/")
    end = pack_bound(b"/registry/pods0")
    read_rev = np.uint64(n * 3 // 4)  # mid-history snapshot read
    qhi = np.uint32(read_rev >> np.uint64(32))
    qlo = np.uint32(read_rev & np.uint64(0xFFFFFFFF))
    print(f"[bench] dataset: {n_keys} keys x {revs} revs = {n} rows "
          f"({chunks.nbytes/1e9:.2f} GB keys) in {time.time()-t0:.1f}s", file=sys.stderr)
    keyenc_info = key_encoding_info(chunks)
    print(f"[bench] key encoding: {keyenc_info['encoded_key_bytes_per_row']}B/row "
          f"vs {keyenc_info['raw_key_bytes_per_row']}B raw = "
          f"{keyenc_info['key_compression_ratio']}x", file=sys.stderr)

    # ---- CPU baseline (vectorized numpy, same algorithm)
    t0 = time.time()
    cpu_visible = cpu_scan(chunks, rh, rl, tomb, start, end, qhi, qlo)
    cpu_dt = time.time() - t0
    cpu_rate = n / cpu_dt
    print(f"[bench] CPU numpy: {cpu_dt:.2f}s = {cpu_rate/1e6:.1f}M rows/s "
          f"(visible {cpu_visible})", file=sys.stderr)

    # ---- device kernel (jnp/XLA by default; KB_BENCH_PALLAS=1 for the
    # explicit chunk-major Pallas kernel; KB_BENCH_SHARDED=1 shards rows
    # over the full device mesh — BASELINE config 4's mesh-sharded scan)
    use_sharded = os.environ.get("KB_BENCH_SHARDED") == "1"
    if use_sharded:
        from kubebrain_tpu.ops.scan import visibility_mask as _vis
        from kubebrain_tpu.parallel.mesh import make_mesh, replicate, shard_rows

        mesh = make_mesh()
        n_dev = len(mesh.devices.reshape(-1))
        rows_per = (n // n_dev) // 8 * 8
        usable = rows_per * n_dev
        part = lambda a: shard_rows(mesh, a[:usable].reshape(n_dev, rows_per))
        keys_s = shard_rows(mesh, chunks[:usable].reshape(n_dev, rows_per, CHUNKS))
        rh_s, rl_s, tomb_s = part(rh), part(rl), part(tomb)
        nv = jax.device_put(
            np.full(n_dev, rows_per, np.int32),
            jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec("part")),
        )
        s_r, e_r = replicate(mesh, start), replicate(mesh, end)

        @jax.jit
        def sharded_count(k, a, b, t, num):
            f = lambda kk, aa, bb, tt, nn: _vis(
                kk, aa, bb, tt, nn, s_r, e_r, jnp.asarray(False), qhi, qlo
            )
            return jnp.sum(jax.vmap(f)(k, a, b, t, num), dtype=jnp.int32)

        out = sharded_count(keys_s, rh_s, rl_s, tomb_s, nv)
        out.block_until_ready()
        lat = []
        for _ in range(iters):
            t0 = time.time()
            sharded_count(keys_s, rh_s, rl_s, tomb_s, nv).block_until_ready()
            lat.append(time.time() - t0)
        p50 = sorted(lat)[len(lat) // 2]
        rate = usable / p50
        print(json.dumps({
            "metric": "sharded range-scan keys/sec",
            "value": round(rate),
            "unit": "rows/sec",
            "vs_baseline": round(rate / cpu_rate, 3),
            "platform": platform_info(),
            "detail": {"rows": usable, "devices": n_dev,
                       "scan_p50_ms": round(p50 * 1e3, 2),
                       "cpu_numpy_rows_per_sec": round(cpu_rate)},
        }))
        return

    # On a real TPU the Mosaic-lowered Pallas kernel is the production scan
    # path (8.5x the jnp kernel on v5e); default to it there, keep the jnp
    # kernel as the off-TPU / opt-out (KB_BENCH_PALLAS=0) path.
    on_tpu = dev.platform in ("tpu", "axon")
    env_pallas = os.environ.get("KB_BENCH_PALLAS")
    use_pallas = on_tpu if env_pallas is None else env_pallas == "1"
    if use_pallas:
        from kubebrain_tpu.ops import scan_pallas as sp

        revs_u64 = ((rh.astype(np.uint64) << np.uint64(32)) | rl.astype(np.uint64))
        keys_t, rh31, rl31, tomb8, n_real = sp.prepare_blocks(chunks, revs_u64, tomb)
        qhi31, qlo31 = sp.split_revs31(np.array([int(read_rev)], dtype=np.uint64))
        s_f = sp.pack_bound_flipped(start)
        e_f = sp.pack_bound_flipped(end)
        p_args = [jax.device_put(jnp.asarray(x), dev) for x in (keys_t, rh31, rl31, tomb8)]
        p_bounds = [jax.device_put(jnp.asarray(x), dev) for x in (s_f, e_f)]

        interp = not on_tpu  # pallas needs interpret mode off-TPU

        @jax.jit
        def scan_count_pallas_sum(kt, a, b, t, s, e):
            mask = sp.scan_mask_pallas(
                kt, a, b, t, np.int32(n_real), s, e,
                np.int32(0), np.int32(qhi31[0]), np.int32(qlo31[0]),
                interpret=interp,
            )
            return jnp.sum(mask, dtype=jnp.int32)

        def scan_count(*_ignored):
            return scan_count_pallas_sum(*p_args, *p_bounds)

    else:
        @jax.jit
        def scan_count(keys, a, b, t, nv, s, e, hi, lo):
            mask = visibility_mask(keys, a, b, t, nv, s, e, jnp.asarray(False), hi, lo)
            return jnp.sum(mask, dtype=jnp.int32)

    if use_pallas:
        # the pallas closure ignores these; don't ship a second ~1.3GB
        # row-major copy of the dataset to HBM alongside the pallas layout
        d_args = [None] * 4
        s_dev = e_dev = nv = None
    else:
        d_args = [jax.device_put(x, dev) for x in (chunks, rh, rl, tomb)]
        s_dev, e_dev = jax.device_put(start, dev), jax.device_put(end, dev)
        nv = jnp.asarray(np.int32(min(n, 2**31 - 1)))
    t0 = time.time()
    out = scan_count(d_args[0], d_args[1], d_args[2], d_args[3], nv, s_dev, e_dev, qhi, qlo)
    out.block_until_ready()
    compile_dt = time.time() - t0
    tpu_visible = int(out)
    print(f"[bench] device first call (incl compile): {compile_dt:.1f}s, "
          f"visible {tpu_visible}", file=sys.stderr)
    assert tpu_visible == cpu_visible, f"device {tpu_visible} != cpu {cpu_visible}"

    lat = []
    for _ in range(iters):
        t0 = time.time()
        scan_count(d_args[0], d_args[1], d_args[2], d_args[3], nv, s_dev, e_dev, qhi, qlo).block_until_ready()
        lat.append(time.time() - t0)
    best = min(lat)
    p50 = sorted(lat)[len(lat) // 2]
    rate = n / p50
    print(f"[bench] device: best {best*1e3:.1f}ms p50 {p50*1e3:.1f}ms "
          f"= {rate/1e6:.1f}M rows/s", file=sys.stderr)

    # KB_TRACE=1: rerun the same scan under full span/stage tracing and
    # bound the tracer's cost on the north-star metric. Compared on
    # best-of-iters (noise-robust); the tracer's per-span cost is a few
    # monotonic() reads + list appends, so >5% means a regression in the
    # trace hot path, not machine jitter.
    trace_on = os.environ.get("KB_TRACE") == "1"
    trace_overhead = None
    if trace_on:
        from kubebrain_tpu.trace import TRACER

        TRACER.reset()

        # IDENTICAL work to the untraced loop (dispatch + block) — an extra
        # host pull here would measure a device-link round trip as "tracer
        # overhead" and fail the <5% assert spuriously over the axon tunnel
        def traced_scan():
            with TRACER.span("bench.scan"):
                with TRACER.stage("device_dispatch", device=True):
                    out = scan_count(d_args[0], d_args[1], d_args[2],
                                     d_args[3], nv, s_dev, e_dev, qhi, qlo)
                with TRACER.stage("device_compute", device=True):
                    jax.block_until_ready(out)

        lat_tr = []
        for _ in range(iters):
            t0 = time.time()
            traced_scan()
            lat_tr.append(time.time() - t0)
        trace_overhead = min(lat_tr) / best - 1
        print(f"[bench] traced: best {min(lat_tr)*1e3:.1f}ms "
              f"(overhead {trace_overhead:+.2%})", file=sys.stderr)
        assert trace_overhead < 0.05, (
            f"tracing overhead {trace_overhead:.1%} >= 5% "
            f"(traced best {min(lat_tr)*1e3:.2f}ms vs {best*1e3:.2f}ms)")

    # sustained throughput: jax dispatch is async, so issuing a burst and
    # blocking once amortizes the per-dispatch transport RTT (over the axon
    # tunnel that RTT dominates single-query p50; with locally-attached
    # chips the two numbers converge). This is the concurrent-scan shape of
    # the production scanner (many Range queries in flight).
    BURST = 8
    t0 = time.time()
    outs = [scan_count(d_args[0], d_args[1], d_args[2], d_args[3], nv,
                       s_dev, e_dev, qhi, qlo) for _ in range(BURST)]
    jax.block_until_ready(outs)
    pipelined = n * BURST / (time.time() - t0)
    print(f"[bench] device pipelined x{BURST}: {pipelined/1e6:.1f}M rows/s",
          file=sys.stderr)

    # THE SERVING-PATH number: the same dispatches routed through the
    # request scheduler (kubebrain_tpu/sched) at bounded depth — what a
    # Range flood actually gets end to end. Each worker blocks on its own
    # result, so up to `depth` kernels are in flight (the pipelined shape
    # above), while admission, lanes, and coalescing stay on.
    from kubebrain_tpu.sched import RequestScheduler, SchedConfig

    depth = int(os.environ.get("KB_SCHED_DEPTH", 4))
    n_req = max(16, 2 * depth)
    sched = RequestScheduler(None, SchedConfig(depth=depth))
    try:
        def one_scan(i):
            return lambda: jax.block_until_ready(
                scan_count(d_args[0], d_args[1], d_args[2], d_args[3], nv,
                           s_dev, e_dev, qhi, qlo))
        # warm the scheduler threads once
        sched.submit(one_scan(-1))
        t0 = time.time()
        reqs = [sched.submit_async(one_scan(i), client=f"c{i % 4}")
                for i in range(n_req)]
        for r in reqs:
            r.wait(300.0)
        scheduled = n * n_req / (time.time() - t0)
    finally:
        sched.close()
    print(f"[bench] scheduled x{n_req} depth {depth}: "
          f"{scheduled/1e6:.1f}M rows/s", file=sys.stderr)

    # QUERY-BATCHED dispatch (ISSUE 5): the same scheduler concurrency over
    # 8 DISTINCT prefix ranges, but a freed dispatch slot drains every
    # compatible ready request and launches ONE query-batched kernel for
    # the whole set — the kernel-launch amortization the scheduler's
    # pipelining alone can't buy (each pipelined request still pays its own
    # launch). Acceptance on TPU: >= 1.5x the scheduled rate at the same
    # concurrency, byte-identical per-query results; on the CPU dry run:
    # byte-identical and within 10% of sequential.
    NQ = 8
    # distinct bounds: the dataset's key-space octile borders (real rows)
    q_rows = [(n * i) // NQ for i in range(NQ)]
    if use_pallas:
        q_starts = np.stack([sp.pack_bound_flipped(chunks[r]) for r in q_rows])
        q_ends = np.stack(
            [sp.pack_bound_flipped(chunks[(n * (i + 1)) // NQ - 1])
             for i in range(NQ - 1)] + [q_starts[0]])
        q_unb = np.array([0] * (NQ - 1) + [1], dtype=np.int32)
        q_his = np.full(NQ, np.int32(qhi31[0]), dtype=np.int32)
        q_los = np.full(NQ, np.int32(qlo31[0]), dtype=np.int32)

        @jax.jit
        def count_one_q(kt, a, b, t, s_, e_, u_):
            mask = sp.scan_mask_pallas(
                kt, a, b, t, np.int32(n_real), s_, e_, u_,
                np.int32(qhi31[0]), np.int32(qlo31[0]), interpret=interp)
            return jnp.sum(mask, dtype=jnp.int32)

        @jax.jit
        def count_many_q(kt, a, b, t, ss, ee, uu, hh, ll):
            mask = sp.scan_mask_pallas_q(
                kt, a, b, t, np.int32(n_real), ss, ee, uu, hh, ll,
                interpret=interp)
            return jnp.sum(mask, axis=1, dtype=jnp.int32)

        def one_count(k):
            return count_one_q(*p_args, jnp.asarray(q_starts[k]),
                               jnp.asarray(q_ends[k]), np.int32(q_unb[k]))

        def many_counts(ks):
            return count_many_q(
                *p_args, jnp.asarray(q_starts[ks]), jnp.asarray(q_ends[ks]),
                jnp.asarray(q_unb[ks]), jnp.asarray(q_his[ks]),
                jnp.asarray(q_los[ks]))
    else:
        from kubebrain_tpu.ops.scan import visibility_mask_queries

        q_starts = np.stack([chunks[r] for r in q_rows])
        q_ends = np.stack([chunks[(n * (i + 1)) // NQ - 1]
                           for i in range(NQ - 1)] + [q_starts[0]])
        q_unb = np.array([False] * (NQ - 1) + [True])
        q_his = np.full(NQ, qhi, dtype=np.uint32)
        q_los = np.full(NQ, qlo, dtype=np.uint32)

        @jax.jit
        def count_one_q(keys, a, b, t, num, s_, e_, u_):
            mask = visibility_mask(keys, a, b, t, num, s_, e_, u_, qhi, qlo)
            return jnp.sum(mask, dtype=jnp.int32)

        @jax.jit
        def count_many_q(keys, a, b, t, num, ss, ee, uu, hh, ll):
            masks = visibility_mask_queries(
                keys, a, b, t, num, ss, ee, uu, hh, ll)
            return jnp.sum(masks, axis=1, dtype=jnp.int32)

        def one_count(k):
            return count_one_q(d_args[0], d_args[1], d_args[2], d_args[3], nv,
                               jnp.asarray(q_starts[k]),
                               jnp.asarray(q_ends[k]), jnp.asarray(bool(q_unb[k])))

        def many_counts(ks):
            return count_many_q(
                d_args[0], d_args[1], d_args[2], d_args[3], nv,
                jnp.asarray(q_starts[ks]), jnp.asarray(q_ends[ks]),
                jnp.asarray(q_unb[ks]), jnp.asarray(q_his[ks]),
                jnp.asarray(q_los[ks]))

    def batch_exec(descs):
        """Scheduler batch executor: range indices -> per-query counts from
        ONE kernel launch (pow2-padded like TpuScanner._dev_mask_batch)."""
        ks = list(descs)
        qp = 1
        while qp < len(ks):
            qp *= 2
        counts = np.asarray(many_counts(np.array(ks + [ks[0]] * (qp - len(ks)))))
        return [int(counts[j]) for j in range(len(ks))]

    # warm + per-query oracle (sequential single dispatches)
    expect_q = [int(one_count(k)) for k in range(NQ)]
    batch_exec(list(range(NQ)))  # compile the Q=8 shape off the clock
    t0 = time.time()
    for i in range(n_req):
        int(one_count(i % NQ))
    seq_q_dt = time.time() - t0

    # distinct ranges through the scheduler, one dispatch each (baseline)
    sched = RequestScheduler(None, SchedConfig(depth=depth, batch=1))
    try:
        sched.submit(lambda: int(one_count(0)))  # warm the worker threads
        t0 = time.time()
        reqs = [sched.submit_async(
            lambda k=i % NQ: int(one_count(k)), client=f"c{i % 4}")
            for i in range(n_req)]
        got_sched = [r.wait(300.0) for r in reqs]
        sched_q_dt = time.time() - t0
    finally:
        sched.close()
    scheduled_q = n * n_req / sched_q_dt
    assert all(got_sched[i] == expect_q[i % NQ] for i in range(n_req))

    # the same requests with batch formation on: slots plugged so every
    # ready request queues, then one release -> n_req/NQ batched launches
    sched = RequestScheduler(None, SchedConfig(depth=depth, batch=NQ))
    try:
        import threading as _threading
        release = _threading.Event()
        for _ in range(depth):
            sched.submit_async(release.wait)
        time.sleep(0.05)
        reqs = [sched.submit_async(
            lambda k=i % NQ: batch_exec([k])[0], client=f"c{i % 4}",
            bargs=i % NQ, bexec=batch_exec) for i in range(n_req)]
        t0 = time.time()
        release.set()
        got_batched = [r.wait(300.0) for r in reqs]
        batched_dt = time.time() - t0
    finally:
        sched.close()
    batched = n * n_req / batched_dt
    mism = sum(1 for i in range(n_req) if got_batched[i] != expect_q[i % NQ])
    assert mism == 0, f"{mism}/{n_req} batched results diverged"
    print(f"[bench] batched x{n_req} ({NQ} distinct ranges/launch): "
          f"{batched/1e6:.1f}M rows/s ({batched/scheduled_q:.2f}x scheduled, "
          f"batched riders {sched.batched})", file=sys.stderr)
    if on_tpu:
        assert batched >= 1.5 * scheduled_q, (
            f"batched {batched/1e6:.1f}M rows/s < 1.5x scheduled "
            f"{scheduled_q/1e6:.1f}M rows/s at {NQ} distinct ranges")
    else:
        # CPU dry run: the batched path must cost ~the same total compute
        tol = float(os.environ.get("KB_BENCH_BATCH_TOL", "1.10"))
        assert batched_dt <= seq_q_dt * tol, (
            f"CPU batched path {batched_dt:.3f}s vs sequential "
            f"{seq_q_dt:.3f}s (> {tol:.0%})")

    # per-stage time fractions from the tracer's EWMAs: device stages from
    # the traced single-dispatch run, queue_wait from the scheduled run
    # (the scheduler records it for every request)
    stage_breakdown = None
    if trace_on:
        from kubebrain_tpu.trace import TRACER

        ew = {
            "queue_wait": TRACER.ewma("queue_wait") or 0.0,
            "dispatch": TRACER.ewma("device_dispatch") or 0.0,
            "device": TRACER.ewma("device_compute") or 0.0,
            "host_copy": TRACER.ewma("host_copy") or 0.0,
        }
        total_ew = sum(ew.values()) or 1.0
        stage_breakdown = {k: round(v / total_ew, 4) for k, v in ew.items()}

    print(json.dumps({
        "metric": "range-scan keys/sec",
        "value": round(rate),
        "unit": "rows/sec",
        "vs_baseline": round(rate / cpu_rate, 3),
        "platform": platform_info(),
        "detail": {
            "rows": n, "visible": tpu_visible,
            "scan_p50_ms": round(p50 * 1e3, 2),
            "pipelined_rows_per_sec": round(pipelined),
            "pipelined_depth": BURST,
            "scheduled_rows_per_sec": round(scheduled),
            "scheduled_depth": depth,
            "scheduled_vs_single_dispatch": round(scheduled / rate, 3),
            "scheduled_distinct_rows_per_sec": round(scheduled_q),
            "batched_rows_per_sec": round(batched),
            "batched_queries_per_launch": NQ,
            "batched_vs_scheduled": round(batched / scheduled_q, 3),
            "batched_byte_identical": True,
            # the PR 5 acceptance bar (>= 1.5x scheduled at 8 distinct
            # prefixes) is a TPU bar: on CPU dispatch isn't the bottleneck,
            # so the run only proves byte-identity + cost parity and the
            # bar stays machine-visibly pending until a real-TPU round
            "batched_acceptance_1_5x": "pass" if on_tpu else "pending_tpu",
            "cpu_numpy_rows_per_sec": round(cpu_rate),
            "device": str(dev),
            "kernel": "pallas" if use_pallas else "jnp",
            # mirror-compression capacity unlock on this dataset's keyspace
            # (kubebrain-keyenc/v1; tracked across BENCH rounds)
            "mirror_bytes_per_row": keyenc_info["mirror_bytes_per_row"],
            "key_compression_ratio": keyenc_info["key_compression_ratio"],
            "key_encoding": keyenc_info,
            **({"stage_breakdown": stage_breakdown,
                "trace_overhead": round(trace_overhead, 4)}
               if trace_on else {}),
        },
    }))


if __name__ == "__main__":
    main()
