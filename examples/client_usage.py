"""Client library walkthrough (run a server first — see README.md)."""

from kubebrain_tpu.client import BrainClient, EtcdCompatClient

ENDPOINT = "127.0.0.1:2379"


def etcd_compat():
    c = EtcdCompatClient(ENDPOINT)
    ok, rev = c.create(b"/registry/demo/pod-1", b'{"spec": 1}')
    assert ok
    ok, rev = c.update(b"/registry/demo/pod-1", b'{"spec": 2}', rev)  # CAS on mod revision

    events, cancel = c.watch(b"/registry/demo/", b"/registry/demo0", prev_kv=True)
    c.create(b"/registry/demo/pod-2", b"{}")
    kind, kv, prev = next(events)
    print("watched:", kind, kv.key, kv.mod_revision)
    cancel()

    kvs, list_rev = c.list(b"/registry/demo/", b"/registry/demo0", page=500)
    print("list:", [(kv.key, kv.mod_revision) for kv in kvs], "at", list_rev)

    # huge ranges: one stream per storage partition, merged in key order
    for kv in c.parallel_list(b"/registry/demo/", b"/registry/demo0"):
        print("par:", kv.key)

    # leases: grant + background keepalive (jittered, watchdog-fenced),
    # attach a key, inspect, then revoke — the key is deleted as a normal
    # watch-visible MVCC tombstone
    h = c.lease(ttl=5)
    ok, rev = c.create(b"/registry/demo/leased", b'{"held": true}', lease=h.id)
    assert ok and h.alive
    ttl, granted, keys = c.lease_time_to_live(h.id, keys=True)
    print("lease:", h.id, "ttl:", ttl, "/", granted, "keys:", keys)
    h.revoke()  # stops the keepalive thread, deletes /registry/demo/leased
    assert c.get(b"/registry/demo/leased") is None
    c.close()


def native_protocol():
    b = BrainClient(ENDPOINT)
    ok, rev = b.create(b"/registry/demo/native", b"payload")
    print("brain create:", ok, rev)
    print("count:", b.count(b"/registry/demo/", b"/registry/demo0"))
    print("partitions:", b.list_partition(b"/registry/demo/", b"/registry/demo0"))
    b.close()


if __name__ == "__main__":
    etcd_compat()
    native_protocol()
