"""kubebrain_tpu — a TPU-native, etcd3-API-compatible MVCC metadata store.

A ground-up rebuild of the capabilities of kubewharf/kubebrain (reference:
/root/reference, a pure-Go stateless etcd3-compatible storage server for
Kubernetes) designed TPU-first:

- The MVCC hot loops (revision-encoded range scan, compaction/GC merge,
  watch-event fan-out) run as vectorized JAX/Pallas kernels over HBM-resident
  sorted key blocks, sharded across a ``jax.sharding.Mesh`` with shard_map
  (reference hot loop: pkg/backend/scanner/scanner.go:389-516).
- The control plane (gRPC servers, leader election, revision sync, event
  sequencing, uncertain-write retry) stays on host, mirroring the reference's
  top layers (pkg/endpoint, pkg/server, pkg/backend).
- The storage engine abstraction (reference pkg/storage/interface.go) is kept,
  with engines selected at runtime: ``memkv`` (in-memory, tests), ``native``
  (C++ host block manager), ``tpu`` (device-mirrored block store).
"""

__version__ = "0.1.0"
