"""MVCC backend core (reference pkg/backend)."""

from .backend import Backend, BackendConfig, wait_for_revision
from .common import TOMBSTONE, KeyValue, RangeResult, Verb, WatchEvent
from .errors import (
    BackendError,
    CASRevisionMismatchError,
    CompactedError,
    FutureRevisionError,
    KeyExistsError,
    NotLeaderError,
    WatchExpiredError,
)

__all__ = [
    "Backend",
    "BackendConfig",
    "wait_for_revision",
    "KeyValue",
    "RangeResult",
    "Verb",
    "WatchEvent",
    "TOMBSTONE",
    "BackendError",
    "CompactedError",
    "FutureRevisionError",
    "KeyExistsError",
    "CASRevisionMismatchError",
    "NotLeaderError",
    "WatchExpiredError",
]
