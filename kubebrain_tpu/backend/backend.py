"""The MVCC backend core — revision allocation, conditional writes, snapshot
reads, compaction, and the single-sequencer event pipeline.

Reference: pkg/backend/backend.go (Backend iface :44-84, NewBackend :145,
collectStorageWriteEvents :208), txn.go, range.go, watch.go, compact.go.

Threading model (mirrors the reference's goroutines, backend.go:178-183):

- any number of writer threads: deal a revision, run the engine batch, then
  post exactly one WatchEvent into the revision-indexed ring
  (``_notify``; reference txn.go:267-293). Every dealt revision is notified —
  valid, failed, or uncertain — or the sequencer would stall;
- ONE sequencer thread consumes ring slots strictly in revision order
  (``_collect_events``): commits the revision to the TSO, routes uncertain
  results to the async retry queue, and appends valid events to the watch
  cache + fan-out hub in batches of <= EVENT_BATCH;
- the async retry daemon repairs uncertain writes (retry.py);
- watch fan-out happens inline in the sequencer via WatcherHub.stream.
"""

from __future__ import annotations

import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Iterator

from .. import coder
from ..storage import CASFailedError, KvStorage, Partition, UncertainResultError
from ..storage.errors import KeyNotFoundError, RevisionDriftBackError
from ..trace import TRACER
from ..util.env import txn_log
from . import creator
from .common import (
    COMPACT_KEY,
    LAST_REV_KEY,
    TOMBSTONE,
    KeyValue,
    RangeResult,
    Verb,
    WatchEvent,
)
from .errors import (
    CASRevisionMismatchError,
    CompactedError,
    FutureRevisionError,
    KeyExistsError,
    WatchExpiredError,
)
from ..util import fieldcheck
from .retry import AsyncFifoRetry
from .ring import Ring
from .scanner import CompactHistory, Scanner
from .tso import TSO
from .watcherhub import WatcherHub

# Reference constants, backend.go:39-42
WATCH_CACHE_CAPACITY = 200_000
EVENT_RING_CAPACITY = 100_000
EVENT_BATCH = 300


@dataclass
class BackendConfig:
    prefix: bytes = b"/"
    skip_prefixes: list[bytes] = field(default_factory=list)
    watch_cache_capacity: int = WATCH_CACHE_CAPACITY
    event_ring_capacity: int = EVENT_RING_CAPACITY
    enable_etcd_compatibility: bool = True  # gates Count (reference range.go:188)
    fanout_matcher: object | None = None  # vectorized watch matcher (ops.fanout)
    scanner_workers: int = 8


@fieldcheck.track
class Backend:
    def __init__(self, store: KvStorage, config: BackendConfig | None = None):
        self.config = config or BackendConfig()
        self.store = store
        self.tso = TSO()
        self.watch_cache = Ring(self.config.watch_cache_capacity)
        self.watcher_hub = WatcherHub(fanout_matcher=self.config.fanout_matcher)
        # block-batched fan-out (docs/watch.md): a matcher that matches a
        # whole drain block in one device dispatch makes EVENT_BATCH
        # chunking pure overhead — hand the hub the full contiguous block
        self._hub_blocks = self.watcher_hub.prefers_blocks
        self.retry = AsyncFifoRetry(self._read_rev_record, self._retry_rewrite)
        scanner_kw = dict(
            get_compact_revision=lambda _snap: self._compact_revision_cached(),
            retry_min_revision=self.retry.min_revision,
            compact_history=CompactHistory(),
            max_workers=self.config.scanner_workers,
        )
        # engines with their own scan offload (tpu) supply the scanner
        self.scanner = store.make_scanner(**scanner_kw) or Scanner(store, **scanner_kw)
        # single-FFI-call write/delete fast paths when the engine provides them
        self._mvcc_write = getattr(store, "mvcc_write", None)
        self._mvcc_delete = getattr(store, "mvcc_delete", None)
        # grouped-commit engine executor (one engine round trip for a whole
        # write group, per-op demux) — engines without it fall back per-op
        self._engine_write_batch = getattr(store, "write_batch", None)
        # compact watermark cache: -1 unknown; refreshed at most once per
        # COMPACT_CACHE_TTL so hot reads don't pay an engine round-trip
        # (local compactions update it synchronously; the TTL bounds follower
        # staleness against a remote leader's compaction)
        self._compact_rev_cache = -1
        self._compact_cache_time = 0.0
        self._compact_lock = threading.Lock()
        # guards ONLY the two cache fields above — never held across
        # engine work. The TTL getter must not take _compact_lock itself:
        # compact() holds that across its whole GC pass, and every
        # Range/Count consults the getter (a convoy exactly like the PR 8
        # _rr_lock pool rebuild)
        self._compact_cache_lock = threading.Lock()

        # revision-indexed event ring (reference backend.go:111; txn.go:291)
        self._ring_cap = self.config.event_ring_capacity
        self._ring: list[WatchEvent | None] = [None] * self._ring_cap
        self._ring_cond = threading.Condition()
        self._next_rev = 1  # next revision the sequencer expects
        self._draining = False  # exactly one drainer sequences at a time
        self._closed = False

        # resume the revision sequence on restart over an existing store
        recovered = self.recover_revision()
        if recovered:
            self.tso.init(recovered)
            self._next_rev = recovered + 1

        from ..util.env import crash_guard

        self._seq_thread = threading.Thread(
            target=crash_guard(self._collect_events), name="kb-sequencer", daemon=True
        )
        self._seq_thread.start()
        self.retry.run()

    def recover_revision(self) -> int:
        """Highest revision any write batch ever committed (LAST_REV_KEY is
        written inside every write batch); 0 on a fresh store."""
        try:
            raw = self.store.get(LAST_REV_KEY)
            rev, _ = coder.decode_rev_value(raw)
            return rev
        except (KeyNotFoundError, coder.CodecError):
            return 0

    def _await_revealed(self, revision: int) -> None:
        """Fence a definite write failure behind the sequencer floor.

        A conflict/notfound reveals storage state that can be AHEAD of the
        contiguous committed floor: the conflicting write is already
        storage-committed but its event not yet sequenced, so the caller's
        NEXT read (served at the floor) would travel back in time — a real
        stale-read anomaly our linearizability soak caught (a create
        conflicted against rev 18, then the same client's get served rev
        15; tests/test_linearizability.py). Wait (bounded) until the floor
        passes the revealed revision before surfacing the failure.
        ``revision < 0`` means "something newer exists but its revision is
        unknown" (a delete that found a fresh tombstone): sync to the
        storage watermark instead. MUST be called only after this op's own
        event was notified — the floor cannot pass our own dealt revision
        until then (self-deadlock).
        """
        if revision < 0:
            try:
                revision = self.recover_revision()
            except Exception:
                return  # best-effort fence: never mask the original error
        if revision > self.tso.committed():
            self.tso.wait_committed(revision, timeout=5.0)

    # =================================================================== writes
    def _commit_write(
        self,
        user_key: bytes,
        revision: int,
        new_record: bytes,
        expected_record: bytes | None,
        obj_value: bytes,
        ttl: int,
    ) -> None:
        """Record + object row + watermark as one atomic engine write.
        expected_record None ⇒ put-if-not-exist on the revision record.
        Uses the engine's single-call fast path when available."""
        rev_key = coder.encode_revision_key(user_key)
        obj_key = coder.encode_object_key(user_key, revision)
        last_val = coder.encode_rev_value(revision)
        if self._mvcc_write is not None:
            self._mvcc_write(
                rev_key, new_record, expected_record, obj_key, obj_value,
                LAST_REV_KEY, last_val, ttl,
            )
            return
        batch = self.store.begin_batch_write()
        if expected_record is None:
            batch.put_if_not_exist(rev_key, new_record, ttl)
        else:
            batch.cas(rev_key, new_record, expected_record, ttl)
        batch.put(obj_key, obj_value, ttl)
        batch.put(LAST_REV_KEY, last_val)
        batch.commit()

    def create(self, user_key: bytes, value: bytes, ttl: int | None = None,
               lease: int = 0) -> int:
        """Insert; returns the new revision. KeyExistsError carries the live
        revision on conflict. Reference txn.go:33 + creator/naive.go:53.
        ``ttl`` overrides the key-pattern TTL; ``lease`` attaches the key to
        a lease (kubebrain_tpu/lease) — expiry then happens via the reaper's
        revision-stamped delete, NOT an engine TTL, so it always wins over
        both."""
        if lease:
            ttl = self._lease_ttl(lease)  # raises LeaseNotFoundError
        rev = self.tso.deal()
        event = WatchEvent(revision=rev, verb=Verb.CREATE, key=user_key, value=value, valid=False)
        revealed = 0
        try:
            creator.create(self._commit_write, user_key, value, rev, ttl=ttl)
            event.valid = True
            self._lease_attach(user_key, lease)
            return rev
        except KeyExistsError as e:
            revealed = e.revision or -1  # rev-0 conflicts still fence
            raise
        except FutureRevisionError as e:
            revealed = e.current
            raise
        except UncertainResultError as e:
            event.err = e
            raise
        finally:
            # ring first: _notify is the side that must survive anything
            # else in this finally raising (a dealt-but-unnotified revision
            # stalls the sequencer forever); the log line is best-effort
            self._notify(event)
            txn_log("create", user_key, rev, event.err or sys.exc_info()[1])
            self.tso.wait_committed(rev, timeout=5.0)
            if revealed:
                self._await_revealed(revealed)

    def update(
        self, user_key: bytes, value: bytes, expected_revision: int,
        ttl: int | None = None, lease: int = 0,
    ) -> int:
        """Conditional overwrite: CAS(revision_key, expected→new) + Put(object).
        Reference txn.go:193-265. On revision mismatch raises
        CASRevisionMismatchError carrying the latest (revision, value) —
        re-read via the conflict fast path (txn.go:225-241). ``lease``
        re-attaches the key (0 = detach, etcd put-without-lease)."""
        if lease:
            ttl = self._lease_ttl(lease)  # raises LeaseNotFoundError
        # resolve the TTL before dealing: ttl_for_key can raise, and no
        # fallible call belongs between a deal and its notify-protected try
        ttl_resolved = creator.ttl_for_key(user_key) if ttl is None else ttl
        rev = self.tso.deal()
        event = WatchEvent(
            revision=rev, verb=Verb.PUT, key=user_key, value=value,
            prev_revision=expected_revision, valid=False,
        )
        ttl = ttl_resolved
        revealed = 0
        try:
            if rev <= expected_revision:
                # drift-back anomaly (reference txn.go:171-175): the dealt
                # revision must exceed the record it supersedes
                raise FutureRevisionError(rev, expected_revision)
            self._commit_write(
                user_key, rev,
                coder.encode_rev_value(rev),
                coder.encode_rev_value(expected_revision),
                value, ttl,
            )
            event.valid = True
            self._lease_reattach(user_key, lease)
            return rev
        except CASFailedError as e:
            observed = e.conflict.value if e.conflict else None
            latest_rev, latest_val = 0, None
            if observed is not None:
                try:
                    latest_rev, deleted = coder.decode_rev_value(observed)
                    if not deleted:
                        latest_val = self._read_object(user_key, latest_rev)
                except coder.CodecError:
                    pass
            revealed = latest_rev or -1
            raise CASRevisionMismatchError(user_key, latest_rev, latest_val) from e
        except UncertainResultError as e:
            event.err = e
            raise
        finally:
            self._notify(event)
            txn_log("update", user_key, rev, event.err or sys.exc_info()[1])
            self.tso.wait_committed(rev, timeout=5.0)
            if revealed:
                self._await_revealed(revealed)

    def delete(self, user_key: bytes, expected_revision: int = 0) -> tuple[int, KeyValue]:
        """Tombstone write. The reference pays three engine round-trips here
        (read record, read previous value, CAS batch — its documented delete
        weakness, txn.go:79-190, benchmark.md:56-61); with a native engine the
        whole read-validate-tombstone sequence is one call.
        Returns (new_revision, previous KeyValue)."""
        if self._mvcc_delete is not None:
            return self._delete_fast(user_key, expected_revision)
        record = self._read_rev_record(user_key)
        if record is None or record[1]:
            # nothing dealt yet — fence directly when the miss reveals a
            # possibly-not-yet-sequenced tombstone (a truly absent record
            # reveals nothing newer; see _await_revealed)
            if record is not None:
                self._await_revealed(record[0])
            raise KeyNotFoundError(user_key)
        latest_rev, _ = record
        if expected_revision and latest_rev != expected_revision:
            val = self._read_object(user_key, latest_rev)
            self._await_revealed(latest_rev)
            raise CASRevisionMismatchError(user_key, latest_rev, val)
        prev_value = self._read_object(user_key, latest_rev)
        rev = self.tso.deal()
        event = WatchEvent(
            revision=rev, verb=Verb.DELETE, key=user_key,
            prev_revision=latest_rev, prev_value=prev_value, valid=False,
        )
        revealed = 0
        try:
            if rev <= latest_rev:
                # drift-back anomaly (txn.go:171-175) — raised inside the
                # notify-protected region so the dealt revision is still
                # sequenced and the pipeline never stalls
                revealed = latest_rev
                raise FutureRevisionError(rev, latest_rev)
            self._commit_write(
                user_key, rev,
                coder.encode_rev_value(rev, deleted=True),
                coder.encode_rev_value(latest_rev),
                TOMBSTONE, 0,
            )
            event.valid = True
            self._lease_detach(user_key)
            return rev, KeyValue(user_key, prev_value or b"", latest_rev)
        except CASFailedError as e:
            observed = e.conflict.value if e.conflict else None
            lr, lv = 0, None
            if observed is not None:
                try:
                    lr, deleted = coder.decode_rev_value(observed)
                    lv = None if deleted else self._read_object(user_key, lr)
                except coder.CodecError:
                    pass
            revealed = lr or -1
            raise CASRevisionMismatchError(user_key, lr, lv) from e
        except UncertainResultError as e:
            event.err = e
            raise
        finally:
            self._notify(event)
            txn_log("delete", user_key, rev, event.err or sys.exc_info()[1])
            self.tso.wait_committed(rev, timeout=5.0)
            if revealed:
                self._await_revealed(revealed)

    def _delete_fast(self, user_key: bytes, expected_revision: int) -> tuple[int, KeyValue]:
        """Single-call delete via the engine (read+validate+tombstone under
        one lock). Failed deletes consume a revision here (dealt up front) —
        etcd semantics allow revision gaps."""
        rev = self.tso.deal()
        event = WatchEvent(revision=rev, verb=Verb.DELETE, key=user_key, valid=False)
        revealed = 0
        try:
            outcome, prev, latest = self._mvcc_delete(
                coder.encode_revision_key(user_key),
                expected_revision, rev,
                coder.encode_rev_value(rev, deleted=True),
                TOMBSTONE, LAST_REV_KEY, coder.encode_rev_value(rev),
            )
            if outcome == "not_found":
                # latest = tombstone revision; 0 = truly absent (no fence)
                revealed = latest
                raise KeyNotFoundError(user_key)
            if outcome == "mismatch":
                revealed = latest or -1
                raise CASRevisionMismatchError(
                    user_key, latest, None if prev == TOMBSTONE else prev
                )
            event.prev_revision = latest
            event.prev_value = prev
            event.valid = True
            self._lease_detach(user_key)
            return rev, KeyValue(user_key, prev or b"", latest)
        except RevisionDriftBackError as e:
            # engine-level drift (a concurrent write drew >= our revision):
            # same fenced, retryable contract as the slow path
            revealed = e.latest or -1
            raise FutureRevisionError(rev, e.latest) from e
        except UncertainResultError as e:
            event.err = e
            raise
        finally:
            self._notify(event)
            txn_log("delete", user_key, rev, event.err or sys.exc_info()[1])
            self.tso.wait_committed(rev, timeout=5.0)
            if revealed:
                self._await_revealed(revealed)

    # ============================================================ group commit
    def write_batch(self, ops: list) -> list:
        """Group commit: execute a batch of write ops as ONE commit group —
        the scheduler's write-batch executor (the write twin of
        :meth:`list_batch`). ``ops`` is a list of

        - ``("create", key, value, ttl, lease)``
        - ``("update", key, value, expected_revision, ttl, lease)``
        - ``("delete", key, expected_revision)``

        and the return list is aligned with it: an ``int`` revision for
        create/update, ``(revision, KeyValue)`` for delete, or an Exception
        instance to raise to that op's waiter alone (per-op demux — a CAS
        conflict fails its op, never the group).

        Mechanics (docs/writes.md): lease TTLs resolve first (a bad lease
        fails its op without consuming a revision, like the sequential
        paths); the surviving ops deal ONE contiguous revision block
        (``TSO.deal_block``) in op order; the engine applies the group in a
        single ``write_batch`` round trip with per-op conditional demux —
        each op validates against the state as mutated by earlier ops in
        the SAME group, so same-key ops inside a group behave exactly as
        back-to-back sequential commits; every dealt revision is notified
        into the event ring (valid, failed, or uncertain — the sequencer
        contract), all in one ring pass. Failed ops consume their dealt
        revision (notified invalid) exactly like the engine fast paths
        (`_delete_fast`) — etcd semantics allow revision gaps. Engines
        without ``write_batch`` fall back to the per-op sequential methods
        with identical results."""
        out: list = [None] * len(ops)
        if self._engine_write_batch is None or len(ops) == 1:
            for i, op in enumerate(ops):
                try:
                    out[i] = self._apply_single(op)
                except BaseException as e:
                    out[i] = e
            return out

        # phase 1 — lease/TTL resolution; failures consume no revision
        pending: list[dict] = []
        for i, op in enumerate(ops):
            kind = op[0]
            try:
                if kind == "create":
                    _, key, value, ttl, lease = op
                    if lease:
                        ttl = self._lease_ttl(lease)
                    ttl = creator.ttl_for_key(key) if ttl is None else ttl
                    pending.append(dict(i=i, kind=kind, key=key, value=value,
                                        ttl=ttl, lease=lease, expected=0))
                elif kind == "update":
                    _, key, value, expected, ttl, lease = op
                    if lease:
                        ttl = self._lease_ttl(lease)
                    ttl = creator.ttl_for_key(key) if ttl is None else ttl
                    pending.append(dict(i=i, kind=kind, key=key, value=value,
                                        ttl=ttl, lease=lease, expected=expected))
                elif kind == "delete":
                    _, key, expected = op
                    pending.append(dict(i=i, kind=kind, key=key, value=b"",
                                        ttl=0, lease=0, expected=expected))
                else:
                    raise ValueError(f"unknown write op kind {kind!r}")
            except BaseException as e:
                out[i] = e
        if not pending:
            return out

        # phase 2 — one contiguous revision block, dealt in op order
        base = self.tso.deal_block(len(pending))
        engine_ops: list[tuple] = []
        runnable: list[dict] = []  # pending ops that reach the engine
        revealed_max = 0
        revealed_watermark = False
        try:
            for j, p in enumerate(pending):
                rev = base + j
                p["rev"] = rev
                kind, key = p["kind"], p["key"]
                if kind == "create":
                    p["event"] = WatchEvent(revision=rev, verb=Verb.CREATE,
                                            key=key, value=p["value"], valid=False)
                    op_t = ("create", coder.encode_revision_key(key), rev,
                            coder.encode_rev_value(rev),
                            coder.encode_object_key(key, rev), p["value"],
                            LAST_REV_KEY, coder.encode_rev_value(rev), p["ttl"])
                elif kind == "update":
                    p["event"] = WatchEvent(revision=rev, verb=Verb.PUT, key=key,
                                            value=p["value"],
                                            prev_revision=p["expected"], valid=False)
                    if rev <= p["expected"]:
                        # drift-back anomaly (txn.go:171-175): the dealt revision
                        # must exceed the record it supersedes; the revision is
                        # consumed and notified invalid, like the sequential path
                        p["fail"] = FutureRevisionError(rev, p["expected"])
                        continue
                    op_t = ("update", coder.encode_revision_key(key),
                            coder.encode_rev_value(rev),
                            coder.encode_rev_value(p["expected"]),
                            coder.encode_object_key(key, rev), p["value"],
                            LAST_REV_KEY, coder.encode_rev_value(rev), p["ttl"])
                else:  # delete
                    p["event"] = WatchEvent(revision=rev, verb=Verb.DELETE,
                                            key=key, valid=False)
                    op_t = ("delete", coder.encode_revision_key(key),
                            p["expected"], rev,
                            coder.encode_rev_value(rev, deleted=True), TOMBSTONE,
                            LAST_REV_KEY, coder.encode_rev_value(rev))
                engine_ops.append(op_t)
                runnable.append(p)

            # phase 3 — ONE engine round trip with per-op outcome demux
            if engine_ops:
                try:
                    results = self._engine_write_batch(engine_ops)
                    if len(results) != len(engine_ops):
                        raise RuntimeError(
                            f"engine write_batch returned {len(results)} "
                            f"outcomes for {len(engine_ops)} ops")
                except UncertainResultError as e:
                    # group-atomic uncertainty: every op maybe-applied
                    results = [("uncertain", e)] * len(engine_ops)
                except BaseException as e:
                    results = [("error", e)] * len(engine_ops)
            else:
                results = []

            # phase 4 — map outcomes, run lease hooks, collect fences
            by_id = {id(p): r for p, r in zip(runnable, results)}
            for p in pending:
                i, rev, key = p["i"], p["rev"], p["key"]
                fail = p.get("fail")
                if fail is not None:
                    out[i] = fail
                else:
                    try:
                        res, rvl = self._demux_write_outcome(p, by_id[id(p)])
                    except BaseException as e:
                        # demux/lease-hook failure (e.g. a transient
                        # _read_object error building a CAS conflict) fails
                        # ONLY this op; the event keeps whatever validity
                        # was set before the raise, so a committed engine
                        # op stays watch-visible
                        res, rvl = e, 0
                    out[i] = res
                    if rvl == -1:
                        revealed_watermark = True
                    elif rvl:
                        revealed_max = max(revealed_max, rvl)
                err = out[i] if isinstance(out[i], BaseException) else None
                txn_log(p["kind"], key, rev, p["event"].err or err)
        finally:
            # phase 5 — one ring pass for the whole block, then the write
            # fence. In a finally like every sequential path's notify: a
            # dealt revision MUST always reach the ring, else the sequencer
            # can never advance past it and every later write stalls. A
            # phase-2 encoding failure leaves later ops eventless — they
            # still consumed their revisions, so they get invalid events
            # here (dealt and notified must never diverge).
            verbs = {"create": Verb.CREATE, "update": Verb.PUT,
                     "delete": Verb.DELETE}
            for j, p in enumerate(pending):
                if "event" not in p:
                    p["event"] = WatchEvent(revision=base + j,
                                            verb=verbs[p["kind"]],
                                            key=p["key"], valid=False)
            self._notify_many([p["event"] for p in pending])
            self.tso.wait_committed(base + len(pending) - 1, timeout=5.0)
        if revealed_watermark:
            self._await_revealed(-1)
        elif revealed_max:
            self._await_revealed(revealed_max)
        return out

    def _demux_write_outcome(self, p: dict, outcome) -> tuple:
        """One engine outcome → (result-or-Exception, revealed_revision).
        The mappings replicate the sequential paths' conflict handling
        byte for byte (create/creator.py, update, _delete_fast)."""
        kind, key, rev = p["kind"], p["key"], p["rev"]
        event = p["event"]
        status = outcome[0]
        if status == "uncertain":
            event.err = outcome[1]
            return outcome[1], 0
        if status == "error":
            return outcome[1], 0
        if kind == "delete":
            if status == "ok":
                _, prev, latest = outcome
                event.prev_revision = latest
                event.prev_value = prev
                event.valid = True
                self._lease_detach(key)
                return (rev, KeyValue(key, prev or b"", latest)), 0
            if status == "not_found":
                # outcome[2] = tombstone revision; 0 = truly absent (no fence)
                return KeyNotFoundError(key), outcome[2]
            if status == "mismatch":
                _, prev, latest = outcome
                return (CASRevisionMismatchError(
                    key, latest, None if prev == TOMBSTONE else prev),
                    latest or -1)
            if status == "drift":
                return FutureRevisionError(rev, outcome[1]), outcome[1] or -1
        elif kind == "create":
            if status == "ok":
                event.valid = True
                self._lease_attach(key, p["lease"])
                return rev, 0
            if status == "drift":
                return FutureRevisionError(rev, outcome[1]), outcome[1] or -1
            if status == "conflict":
                observed = outcome[1]
                if observed is None:
                    return KeyExistsError(key, 0), -1
                try:
                    old_rev, deleted = coder.decode_rev_value(observed)
                except coder.CodecError:
                    return KeyExistsError(key, 0), -1
                if deleted:
                    # a correct engine resolves tombstones itself (convert or
                    # drift); an engine that surfaces one is mapped like the
                    # creator's lost-race branch
                    return FutureRevisionError(rev, old_rev), old_rev or -1
                return KeyExistsError(key, old_rev), old_rev or -1
        else:  # update
            if status == "ok":
                event.valid = True
                self._lease_reattach(key, p["lease"])
                return rev, 0
            if status == "conflict":
                observed = outcome[1]
                latest_rev, latest_val = 0, None
                if observed is not None:
                    try:
                        latest_rev, deleted = coder.decode_rev_value(observed)
                        if not deleted:
                            latest_val = self._read_object(key, latest_rev)
                    except coder.CodecError:
                        pass
                return (CASRevisionMismatchError(key, latest_rev, latest_val),
                        latest_rev or -1)
            if status == "drift":
                return FutureRevisionError(rev, outcome[1]), outcome[1] or -1
        return RuntimeError(
            f"engine write_batch outcome {outcome!r} for op kind {kind}"), 0

    def _apply_single(self, op: tuple):
        """Per-op fallback for engines without ``write_batch`` — the
        sequential methods, so semantics cannot drift."""
        kind = op[0]
        if kind == "create":
            return self.create(op[1], op[2], ttl=op[3], lease=op[4])
        if kind == "update":
            return self.update(op[1], op[2], op[3], ttl=op[4], lease=op[5])
        if kind == "delete":
            return self.delete(op[1], op[2])
        raise ValueError(f"unknown write op kind {kind!r}")

    # ==================================================================== reads
    def current_revision(self) -> int:
        return self.tso.committed()

    def set_current_revision(self, revision: int) -> None:
        """Seed revision state (leader start / follower sync).
        Reference: leader.go:96-107 → backend.SetCurrentRevision."""
        self.tso.init(revision)
        with self._ring_cond:
            if revision + 1 > self._next_rev:
                self._next_rev = revision + 1
                # drop events below the new term's floor — they would never
                # be drained and would poison the wrap check
                for i, ev in enumerate(self._ring):
                    if ev is not None and ev.revision < self._next_rev:
                        self._ring[i] = None
            self._ring_cond.notify_all()

    def ingest_replicated(self, events: list[WatchEvent], watermark: int) -> None:
        """Follower role (kubebrain_tpu/replica): adopt an already-sequenced
        replicated event block from the leader's stream — watch cache + hub
        fan-out + the committed revision floor, strictly DOWNSTREAM of the
        leader's sequencer. The local ring/TSO-deal path is never involved:
        followers deal nothing, so the block needs no re-sequencing — the
        stream's revision order IS the sequence. ``events`` may be empty
        (a progress mark crossing the leader's revision gaps); ``watermark``
        is the new applied floor (every leader event <= it has been applied
        to the local store before this call)."""
        now = time.monotonic()
        for e in events:
            e.ts = now
        if events:
            self._flush(events)
        if watermark > self.tso.committed():
            # commit (not init): fence waiters park on the TSO's committed
            # condition, and the watermark advance is their wake-up
            self.tso.commit(watermark)
            with self._ring_cond:
                if watermark + 1 > self._next_rev:
                    self._next_rev = watermark + 1

    def flushed_revision(self) -> int:
        """Highest revision guaranteed fully streamed into every hub
        subscriber queue (the sound floor for watch progress marks —
        ``WatcherHub.post_progress``). -1 while the pipeline is mid-drain
        or an event is pending at the floor (callers retry) — distinct
        from the legitimate floor 0 of a store that has served no writes.
        Gap revisions (failed/uncertain ops) count: every DEALT revision
        passes through the ring, so ``_next_rev - 1`` means "nothing
        below is owed"."""
        with self._ring_cond:
            if self._draining:
                return -1
            if self._ring[self._next_rev % self._ring_cap] is not None:
                return -1
            return self._next_rev - 1

    def get(self, user_key: bytes, revision: int = 0) -> KeyValue:
        """Point read at a snapshot: reverse-iterate the version chain from
        (key, read_rev) down, take the first row, reject tombstones.
        Reference range.go:34-121."""
        read_rev = self._read_revision_checked(revision)
        # reverse-iterate (key, read_rev) → (key, 0); highest version first,
        # the rev-0 record sorts last so a rev-0 first hit means "no versions"
        start = coder.encode_object_key(user_key, read_rev)
        end = coder.encode_revision_key(user_key)
        it = self.store.iter(start, end, snapshot_ts=self.store.get_timestamp_oracle(), limit=1)
        for ikey, value in it:
            _, rev = coder.decode(ikey)
            if rev == 0 or value == TOMBSTONE:
                break
            return KeyValue(user_key, value, rev)
        raise KeyNotFoundError(user_key)

    def list_(
        self, start: bytes, end: bytes, revision: int = 0, limit: int = 0
    ) -> RangeResult:
        """Range read at a snapshot; limit+1 detects More (range.go:124-171)."""
        read_rev = self._read_revision_checked(revision)
        kvs, more = self.scanner.range_(start, end, read_rev, limit)
        return RangeResult(kvs=kvs, revision=read_rev, more=more, count=len(kvs))

    def list_wire(self, start: bytes, end: bytes, revision: int = 0,
                  limit: int = 0):
        """Range read returning ready RangeResponse.kvs wire bytes when the
        engine scanner has a C wire encoder; None otherwise. Returns
        (kvs_blob, count, more, read_rev)."""
        fast = getattr(self.scanner, "list_wire", None)
        if fast is None:
            return None
        read_rev = self._read_revision_checked(revision)
        # one C call does scan + wire encode; attribute it as the engine
        # compute stage so the raw fast path still shows up in traces
        with TRACER.stage("device_compute"):
            blob, n, more = fast(start, end, read_rev, limit)
        return blob, n, more, read_rev

    def count(self, start: bytes, end: bytes, revision: int = 0) -> tuple[int, int]:
        read_rev = self._read_revision_checked(revision)
        return self.scanner.count(start, end, read_rev), read_rev

    def list_batch(self, queries: list) -> list:
        """Batched range reads — the scheduler's batch executor. ``queries``
        is a list of ``("list", start, end, revision, limit)`` /
        ``("count", start, end, revision)`` tuples; the return list is
        aligned with it, each element a RangeResult, a ``(count,
        read_rev)`` tuple, or an Exception instance to raise to that
        query's waiter alone (a compacted revision fails its query, not
        the batch). Read revisions resolve here, at execution start — the
        same point a sequential execution would resolve them, so rev-0
        batching preserves read-your-writes exactly like coalescing does.
        Engines with a query-batched scanner (``scan_batch``, the TPU
        mirror) answer every device-path query in ONE kernel dispatch;
        other engines fall back to per-query scans with identical results.
        """
        out: list = [None] * len(queries)
        resolved: list[tuple[int, tuple, int]] = []
        for i, q in enumerate(queries):
            try:
                resolved.append((i, q, self._read_revision_checked(q[3])))
            except Exception as e:
                out[i] = e
        scan_batch = getattr(self.scanner, "scan_batch", None)
        if scan_batch is not None and len(resolved) > 1:
            specs = [
                ("count", q[1], q[2], rr) if q[0] == "count"
                else ("range", q[1], q[2], rr, q[4])
                for _i, q, rr in resolved
            ]
            results = scan_batch(specs)
            for (i, q, rr), res in zip(resolved, results):
                if isinstance(res, BaseException):
                    out[i] = res
                elif q[0] == "count":
                    out[i] = (res, rr)
                else:
                    kvs, more = res
                    out[i] = RangeResult(kvs=kvs, revision=rr, more=more,
                                         count=len(kvs))
            return out
        for i, q, rr in resolved:  # engine-generic sequential fallback
            try:
                if q[0] == "count":
                    out[i] = (self.scanner.count(q[1], q[2], rr), rr)
                else:
                    kvs, more = self.scanner.range_(q[1], q[2], rr, q[4])
                    out[i] = RangeResult(kvs=kvs, revision=rr, more=more,
                                         count=len(kvs))
            except Exception as e:
                out[i] = e
        return out

    def list_by_stream(
        self, start: bytes, end: bytes, revision: int = 0
    ) -> tuple[int, Iterator[list[KeyValue]]]:
        read_rev = self._read_revision_checked(revision)
        return read_rev, self.scanner.range_stream(start, end, read_rev)

    def get_partitions(self, start: bytes, end: bytes) -> list[Partition]:
        """User-key partition borders for client-side partition-wise listing
        (reference range.go:208-244, magic revision 1888 in etcd/kv.go:33)."""
        lo, hi = coder.internal_range(start, end)
        parts = self.store.get_partitions(lo, hi)
        out: list[Partition] = []
        left = start
        for p in parts[:-1]:
            if coder.is_internal_key(p.right):
                user_key, _ = coder.decode(p.right)
            else:
                user_key = p.right
            if user_key <= left or (end and user_key >= end):
                continue
            out.append(Partition(left, user_key))
            left = user_key
        out.append(Partition(left, end))
        return out

    # ================================================================== compact
    def compact(self, revision: int) -> int:
        """Compact to min(requested, committed, min-uncertain − 1); persist the
        watermark (fences readers), then GC per border pair.
        Reference compact.go:31-126."""
        with self._compact_lock:
            target = min(revision, self.tso.committed())
            retry_min = self.retry.min_revision()
            if retry_min:
                target = min(target, retry_min - 1)
            current = self._compact_revision_at(None)
            if target <= current:
                return current
            self._persist_compact_floor_locked(target, current)
            for left, right in self._compact_borders():
                self.scanner.compact(left, right, target)
            return target

    def _persist_compact_floor_locked(self, target: int, current: int) -> None:
        """Persist + cache the compact watermark (callers hold
        ``_compact_lock``) — shared by :meth:`compact` and the follower's
        GC-free :meth:`set_compact_floor` so the record format and cache
        invalidation can never diverge between the two."""
        self._set_compact_record(target, current)
        with self._compact_cache_lock:
            self._compact_rev_cache = target
            self._compact_cache_time = time.monotonic()

    def set_compact_floor(self, revision: int) -> int:
        """Persist the compact watermark WITHOUT running GC borders — the
        follower bootstrap/resync case (kubebrain_tpu/replica): the local
        store was built from post-GC leader state, so there is nothing to
        collect, only history below ``revision`` to fence off (reads under
        it refuse as compacted — the honest etcd answer for a follower
        whose replicated history starts at its bootstrap revision)."""
        with self._compact_lock:
            current = self._compact_revision_at(None)
            if revision <= current:
                return current
            self._persist_compact_floor_locked(revision, current)
            return revision

    def _compact_borders(self) -> list[tuple[bytes, bytes]]:
        """Internal-key border pairs covering the configured prefix minus
        skip-prefixes (reference compact.go:107-126)."""
        prefix = self.config.prefix
        lo, hi = coder.internal_range(prefix, coder.prefix_end(prefix) if prefix else b"")
        borders: list[tuple[bytes, bytes]] = []
        left = lo
        for skip in sorted(self.config.skip_prefixes):
            s_lo = coder.encode_revision_key(skip)
            s_hi = coder.encode_revision_key(coder.prefix_end(skip))
            if s_lo > left:
                borders.append((left, s_lo))
            left = s_hi
        borders.append((left, hi))
        return borders

    def _set_compact_record(self, revision: int, old: int) -> None:
        batch = self.store.begin_batch_write()
        value = coder.encode_rev_value(revision)
        if old == 0:
            try:
                batch.put_if_not_exist(COMPACT_KEY, value)
                batch.commit()
                return
            except CASFailedError:
                batch = self.store.begin_batch_write()
                old = self._compact_revision_at(None)
        batch.cas(COMPACT_KEY, value, coder.encode_rev_value(old))
        batch.commit()

    def _compact_revision_at(self, snapshot: int | None) -> int:
        try:
            raw = self.store.get(COMPACT_KEY, snapshot_ts=snapshot)
        except KeyNotFoundError:
            return 0
        rev, _ = coder.decode_rev_value(raw)
        return rev

    def _compact_revision_cached(self) -> int:
        # cache fields ride their own tiny lock (kblint KB120: the
        # lock-free RMW raced _persist_compact_floor_locked's update); the
        # STORE read happens outside any hold, and the install is
        # monotonic — a refresh that raced a concurrent compaction can
        # only raise the floor, never resurrect a pre-compact one (the
        # watermark itself never decreases; -1 means invalidated)
        with self._compact_cache_lock:
            now = time.monotonic()
            cached = self._compact_rev_cache
            if cached >= 0 and now - self._compact_cache_time <= 1.0:
                return cached
        fetched = self._compact_revision_at(None)
        with self._compact_cache_lock:
            if fetched > self._compact_rev_cache:
                self._compact_rev_cache = fetched
            if now > self._compact_cache_time:
                self._compact_cache_time = now
            return self._compact_rev_cache

    def compact_revision(self) -> int:
        return self._compact_revision_at(None)

    # ==================================================================== watch
    def watch(self, prefix: bytes = b"", revision: int = 0, queue_factory=None):
        """Prefix-watch sugar over watch_range."""
        end = coder.prefix_end(prefix) if prefix else b""
        return self.watch_range(prefix, end, revision, queue_factory=queue_factory)

    def watch_range(self, start: bytes, end: bytes, revision: int = 0, queue_factory=None):
        """Subscribe-then-replay watch registration (reference watch.go:37-96):
        subscribe to the hub FIRST, then replay history from the cache for
        events in (revision, hub-subscription point]; raise WatchExpiredError
        when the requested revision pre-dates the cache so the client re-lists.
        Returns (watcher_id, queue) — the queue yields event batches and a
        None poison pill on close."""
        def validate() -> None:
            if not revision:
                return
            compacted = self._compact_revision_cached()
            if revision < compacted:
                # etcd semantics: watching below the compact watermark is
                # unservable history — cancel so the client re-lists
                raise WatchExpiredError(f"want {revision}, compacted {compacted}")
            oldest = self.watch_cache.oldest_revision()
            if len(self.watch_cache) == 0:
                if revision < self.tso.committed():
                    raise WatchExpiredError(f"cache empty, want {revision}")
            elif self.watch_cache.has_evicted():
                # once the ring has dropped events, oldest-1 may name a real
                # evicted event — match the reference's strict check
                # (ring.FindEvents "low" when revision < oldest, watch.go)
                if revision < oldest:
                    raise WatchExpiredError(f"want {revision}, cache oldest {oldest}")
            elif revision < oldest - 1:
                # never-full cache: oldest-1 is the pre-history revision the
                # first cached event was written against — replay is complete
                raise WatchExpiredError(f"want {revision}, cache oldest {oldest}")

        wid, q, _replayed = self.watcher_hub.add_watcher_with_replay(
            start, end, revision, self.watch_cache, validate=validate,
            queue_factory=queue_factory,
        )
        return wid, q

    def unwatch(self, wid: int) -> None:
        self.watcher_hub.delete_watcher(wid)

    # ========================================================== event pipeline
    def _notify(self, event: WatchEvent) -> None:
        """Post one event into the revision-indexed ring (txn.go:267-293) and
        opportunistically sequence it inline. Raises if the ring wraps — the
        invariant crash the reference keeps (panic "watch push buffer full",
        txn.go:287-290)."""
        idx = event.revision % self._ring_cap
        with self._ring_cond:
            if self._ring[idx] is not None:
                raise RuntimeError("event ring wrapped: sequencer too far behind")
            self._ring[idx] = event
            self._ring_cond.notify_all()
        # inline drain: in the common (uncontended) case the writer sequences
        # its own event synchronously, skipping a cross-thread wakeup —
        # functionally the reference's always-hot spin sequencer
        # (backend.go:212-224) without burning a core
        self._drain()

    def _notify_many(self, events: list[WatchEvent]) -> None:
        """Post a whole commit group's events into the ring under ONE lock
        acquisition, then drain once — the group-commit analogue of
        :meth:`_notify` (a group of G writes pays one ring pass and one
        sequencer wakeup instead of G)."""
        if not events:
            return
        with self._ring_cond:
            for event in events:
                idx = event.revision % self._ring_cap
                if self._ring[idx] is not None:
                    raise RuntimeError(
                        "event ring wrapped: sequencer too far behind")
                self._ring[idx] = event
            self._ring_cond.notify_all()
        self._drain()

    def _drain(self) -> None:
        """Consume contiguous ready revisions in order. Exactly one drainer
        runs at a time (ordering through cache + hub must match revision
        order); others return immediately — their events are picked up by
        the active drainer's re-check loop."""
        while True:
            with self._ring_cond:
                if self._draining or self._closed:
                    return
                ready: list[WatchEvent] = []
                while True:
                    idx = self._next_rev % self._ring_cap
                    ev = self._ring[idx]
                    if ev is None or ev.revision != self._next_rev:
                        break
                    self._ring[idx] = None
                    self._next_rev += 1
                    ready.append(ev)
                if not ready:
                    return
                self._draining = True
            try:
                batch: list[WatchEvent] = []
                for event in ready:
                    self.tso.commit(event.revision)
                    event.ts = time.monotonic()
                    if event.err is not None and isinstance(event.err, UncertainResultError):
                        self.retry.append(event)
                    elif event.valid:
                        batch.append(event)
                    if len(batch) >= EVENT_BATCH and not self._hub_blocks:
                        self._flush(batch)
                        batch = []
                self._flush(batch)
            finally:
                with self._ring_cond:
                    self._draining = False
            # loop: events may have landed while we processed

    def _collect_events(self) -> None:
        """Background drainer (reference collectStorageWriteEvents,
        backend.go:208-270): picks up whatever writers didn't sequence
        inline (e.g. events posted while another drainer was mid-flush)."""
        while True:
            with self._ring_cond:
                if self._closed:
                    return
                idx = self._next_rev % self._ring_cap
                if self._ring[idx] is None:
                    self._ring_cond.wait(timeout=0.2)
                    # wait() reacquired the condition: the post-wait close
                    # check rides the SAME hold — the bare re-read outside
                    # the lock had no guard in common with close()'s
                    # write (kblint KB120)
                    if self._closed:
                        return
            self._drain()

    def _flush(self, batch: list[WatchEvent]) -> None:
        if not batch:
            return
        for e in batch:
            self.watch_cache.add(e)
        self.watcher_hub.stream(batch)

    # ============================================================ lease hooks
    # (the lease subsystem attaches a registry as ``_kb_lease`` via
    # lease.ensure_lease; without one, PutRequest.lease degrades to the
    # legacy ID:=TTL interpretation for raw embedders)
    def _lease_ttl(self, lease: int) -> int:
        """Engine TTL for a write under ``lease``. With the registry armed
        the answer is always 0: expiry must be the reaper's revision-stamped
        MVCC delete, never a silent engine-level drop — an explicit lease
        beats every key-pattern TTL (creator.ttl_for_key precedence,
        docs/storage_engine.md)."""
        reg = getattr(self, "_kb_lease", None)
        if reg is None:
            return int(lease)  # legacy stub semantics: the lease id IS its TTL
        reg.require(lease)  # LeaseNotFoundError for unknown/expired leases
        return 0

    def _lease_attach(self, user_key: bytes, lease: int) -> None:
        reg = getattr(self, "_kb_lease", None)
        if reg is None or not lease:
            return
        try:
            reg.attach(lease, user_key)
        except Exception:
            # the lease was revoked between require() and commit: the write
            # stands (etcd's applier has the same window, serialized only
            # by raft ordering) and the next put/delete re-binds the key
            pass

    def _lease_reattach(self, user_key: bytes, lease: int) -> None:
        reg = getattr(self, "_kb_lease", None)
        if reg is None:
            return
        try:
            reg.reattach(user_key, lease)
        except Exception:
            pass  # same revoke race as _lease_attach

    def _lease_detach(self, user_key: bytes) -> None:
        reg = getattr(self, "_kb_lease", None)
        if reg is not None:
            reg.detach_key(user_key)

    # ============================================================ retry support
    def _read_rev_record(self, user_key: bytes) -> tuple[int, bool] | None:
        try:
            raw = self.store.get(coder.encode_revision_key(user_key))
        except KeyNotFoundError:
            return None
        try:
            return coder.decode_rev_value(raw)
        except coder.CodecError:
            return None

    def _read_object(self, user_key: bytes, revision: int) -> bytes | None:
        try:
            val = self.store.get(coder.encode_object_key(user_key, revision))
        except KeyNotFoundError:
            return None
        return None if val == TOMBSTONE else val

    def _retry_rewrite(self, event: WatchEvent, record: tuple[int, bool]) -> None:
        """Idempotent overwrite at a fresh revision (retry.go:222-264): the
        uncertain op DID land; emit a proper event via the normal write path."""
        old_rev, deleted = record
        rev = self.tso.deal()
        new_event = WatchEvent(
            revision=rev, verb=event.verb, key=event.key, value=event.value,
            prev_revision=old_rev, valid=False,
        )
        try:
            self._commit_write(
                event.key, rev,
                coder.encode_rev_value(rev, deleted=deleted),
                coder.encode_rev_value(old_rev, deleted=deleted),
                TOMBSTONE if deleted else event.value,
                creator.ttl_for_key(event.key),
            )
            new_event.valid = True
        except CASFailedError:
            pass  # superseded meanwhile: nothing to repair
        except UncertainResultError as e:
            new_event.err = e
        finally:
            self._notify(new_event)

    # ================================================================ lifecycle
    def reset_term(self) -> None:
        """Leadership lost: wipe the watch pipeline so no stale state is ever
        served. The reference panics the whole process for this ("simple and
        rude", leader.go:109-118); dropping every watcher (poison pills force
        clients to re-list/re-watch) and poisoning the scan mirror gives the
        same observable contract without the restart."""
        self.watcher_hub.close()
        if hasattr(self.scanner, "mark_uncertain"):
            self.scanner.mark_uncertain()
        with self._compact_cache_lock:
            self._compact_rev_cache = -1  # re-read the watermark from storage

    def _read_revision_checked(self, revision: int) -> int:
        committed = self.tso.committed()
        read_rev = revision or committed
        if revision > committed:
            raise FutureRevisionError(revision, committed)
        compacted = self._compact_revision_cached()
        if compacted and read_rev < compacted:
            raise CompactedError(read_rev, compacted)
        return read_rev

    def close(self) -> None:
        # the lease reaper issues deletes through this backend: stop it (and
        # checkpoint remaining TTLs) while the sequencer is still alive
        reaper = getattr(self, "_kb_lease_reaper", None)
        if reaper is not None:
            reaper.close()
        # the request scheduler (sched.ensure_scheduler attaches it here)
        # must unblock queued readers before the scan pipeline goes away
        sched = getattr(self, "_kb_scheduler", None)
        if sched is not None:
            sched.close()
        with self._ring_cond:
            self._closed = True
            self._ring_cond.notify_all()
        self._seq_thread.join(timeout=2.0)
        self.retry.close()
        self.watcher_hub.close()
        self.scanner.close()


def wait_for_revision(backend: Backend, revision: int, timeout: float = 5.0) -> bool:
    """Test helper: block until the sequencer has committed ``revision``
    (reference waitUntilRevisionEqualOrTimeout, backend_test.go:1437)."""
    import time

    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if backend.tso.committed() >= revision:
            return True
        time.sleep(0.002)
    return False
