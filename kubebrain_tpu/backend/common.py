"""Shared backend value types.

Reference: pkg/backend/common/common.go:18-29 (WatchEvent) and the proto Event
verbs used at pkg/backend/backend.go:240-262.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field


class Verb(enum.IntEnum):
    CREATE = 0
    PUT = 1
    DELETE = 2


@dataclass(slots=True)
class WatchEvent:
    """The record handed from the write path to the async event pipeline.

    One WatchEvent is posted for *every* allocated revision — valid or not —
    so the single sequencer can consume revisions contiguously
    (reference common.go:18-29; sequencing invariant at backend.go:208-270).
    Slotted: the history cache holds up to 200k of these.
    """

    revision: int
    verb: Verb = Verb.PUT
    key: bytes = b""
    value: bytes = b""
    prev_revision: int = 0
    prev_value: bytes | None = None
    valid: bool = True
    err: BaseException | None = None
    # monotonic commit time, stamped by the sequencer when this revision is
    # committed — the zero point of the watch-path delivery-lag histograms
    ts: float = 0.0


@dataclass
class KeyValue:
    key: bytes
    value: bytes
    revision: int


@dataclass
class RangeResult:
    kvs: list[KeyValue] = field(default_factory=list)
    revision: int = 0
    more: bool = False
    count: int = 0


# Engine-level tombstone marker written at the object key on delete
# (reference pkg/backend/util.go:28-42).
TOMBSTONE = b"\x00kb_tombstone\x00"

# Metadata keys live outside the MAGIC-prefixed MVCC keyspace so scans never
# observe them (reference stores compact_key/election under the user prefix,
# compact.go:70-105 / election/election.go:49; a disjoint namespace is cleaner).
META_PREFIX = b"!kb_meta/"
COMPACT_KEY = META_PREFIX + b"compact"
ELECTION_KEY = META_PREFIX + b"election"
# The lease registry's checkpoint row (kubebrain_tpu/lease): ids, granted
# TTLs, remaining-TTL-at-checkpoint, and key attachments, length-framed.
LEASE_STATE_KEY = META_PREFIX + b"lease_state"
# Highest successfully-committed revision, updated inside every write batch.
# A new leader seeds its sequencer from this + the election record clock so
# revision numbers are never re-dealt across terms (the reference gets this
# from TiKV's PD timestamp domain dominating revision counts; an embedded
# commit-counter clock needs the explicit watermark).
LAST_REV_KEY = META_PREFIX + b"last_rev"
