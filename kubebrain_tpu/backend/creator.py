"""Create (insert) path.

Reference: pkg/backend/creator/naive.go:53-98. A create is the atomic write

    PutIfNotExist(revision_key, rev_value(new_rev)) + Put(object_key, value)

On CAS conflict the engine hands back the observed revision record
(``Conflict.value``), which enables two conversions without extra reads:

- the record is a **tombstone with a lower revision** — the key was deleted;
  convert create→update by CAS-ing over the tombstone (naive.go:83-86);
- the record vanished between conflict and inspection (compacted-away
  delete) — retry the create once (naive.go:70-72).

A live record means the key exists: surface ``KeyExistsError`` with the
existing revision so the etcd shim can return txn-failed + current kv.

``commit_write(user_key, revision, new_record, expected_record, obj_value,
ttl)`` is the backend's atomic record+object+watermark writer
(Backend._commit_write) — batch-based or the engine's single-call fast path.
"""

from __future__ import annotations

from .. import coder
from ..storage import CASFailedError
from .errors import FutureRevisionError, KeyExistsError

EVENTS_TTL_PREFIX = b"/events/"
EVENTS_TTL_SECONDS = 3600

#: The reference's key-pattern TTL (util.go:28-42, lease.go) — demoted to a
#: flag-gated fallback now that real leases exist (kubebrain_tpu/lease).
#: Precedence (docs/storage_engine.md): an explicit ``PutRequest.lease``
#: always wins (Backend._lease_ttl returns 0 — reaper-owned expiry); the
#: pattern applies only to lease-less writes, and only while this flag is
#: on (``--legacy-ttl-patterns``, default on for kube-apiserver compat).
LEGACY_TTL_PATTERNS = True


def ttl_for_key(user_key: bytes) -> int:
    """Key-pattern TTL fallback for writes without an explicit lease."""
    if not LEGACY_TTL_PATTERNS:
        return 0
    return EVENTS_TTL_SECONDS if user_key.startswith(EVENTS_TTL_PREFIX) else 0


def create(commit_write, user_key: bytes, value: bytes, revision: int, ttl: int | None = None) -> None:
    """Insert ``user_key``=``value`` at ``revision``; raises KeyExistsError
    (with the live revision) or propagates engine errors (incl. uncertain).
    ``ttl`` (etcd lease attachment) overrides the key-pattern TTL."""
    ttl = ttl_for_key(user_key) if ttl is None else ttl
    new_record = coder.encode_rev_value(revision)
    for _attempt in range(2):
        try:
            commit_write(user_key, revision, new_record, None, value, ttl)
            return
        except CASFailedError as e:
            observed = e.conflict.value if e.conflict else None
            if observed is None:
                # record disappeared under us (compacted delete): retry create
                continue
            try:
                old_rev, deleted = coder.decode_rev_value(observed)
            except coder.CodecError:
                raise KeyExistsError(user_key, 0) from e
            if deleted:
                if old_rev < revision:
                    # deleted key: create becomes an update over the tombstone
                    try:
                        commit_write(user_key, revision, new_record, observed,
                                     value, ttl)
                        return
                    except CASFailedError as e2:
                        # two creates raced over the same tombstone and we
                        # lost: surface the WINNER's revision (the caller
                        # fences its read floor on it — the stale old_rev
                        # would make the fence a no-op and reopen the
                        # ahead-of-floor stale read); -1 = revealed state
                        # of unknown revision, fence to the watermark
                        observed2 = e2.conflict.value if e2.conflict else None
                        if observed2 is not None:
                            try:
                                rev2, del2 = coder.decode_rev_value(observed2)
                            except coder.CodecError:
                                raise KeyExistsError(user_key, 0) from e2
                            if not del2:
                                raise KeyExistsError(user_key, rev2) from e2
                            raise FutureRevisionError(revision, rev2) from e2
                        raise FutureRevisionError(revision, -1) from e2
                # Tombstone from a delete that RACED us and drew a HIGHER
                # revision than ours: the key does not exist, so KeyExists
                # would claim a state that never was (caught by the
                # linearizability soak, tests/test_linearizability.py), and
                # committing at our stale revision would break per-key
                # revision monotonicity. Same drift-back anomaly as
                # update/delete (reference txn.go:171-175): definite,
                # retryable failure — the caller re-deals a fresh revision.
                raise FutureRevisionError(revision, old_rev) from e
            raise KeyExistsError(user_key, old_rev) from e
    raise KeyExistsError(user_key, 0)
