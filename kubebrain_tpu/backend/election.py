"""Leader election anchored in the storage engine.

Reference: pkg/backend/election/election.go:49-188 + the campaign wrapper at
pkg/server/service/leader/leader.go:82-158. There is no peer consensus — the
KV engine is the source of truth: the lock is a record at a well-known key,
acquired/renewed with PutIfNotExist/CAS. The record carries the holder
identity, lease metadata, AND the storage logical clock observed at each lock
operation (reference Describe() returns "identity,tso") — the winner seeds its
revision sequencer from that clock so revisions stay monotonic across terms.

Timing mirrors the reference: lease 8s / renew every 5s / retry every 1s
(leader.go:87-91). On losing leadership the reference *panics* to clear dirty
watch state ("simple and rude", leader.go:109-118); here the campaign invokes
``on_stopped_leading`` and the server layer resets the backend term instead
(watch cache + watcher hub are wiped — same observable contract: watchers are
cancelled and clients must re-list).
"""

from __future__ import annotations

import json
import logging
import threading
import time
from dataclasses import dataclass
from typing import Callable

from ..storage import CASFailedError, KvStorage
from ..storage.errors import KeyNotFoundError
from ..util.env import crash_guard
from .common import ELECTION_KEY

logger = logging.getLogger("kubebrain")

LEASE_SECONDS = 8.0
RENEW_INTERVAL = 5.0
RETRY_INTERVAL = 1.0


@dataclass
class LockRecord:
    holder: str
    acquired_at: float
    renewed_at: float
    lease_seconds: float
    tso: int  # storage logical clock at the last lock op
    meta: dict | None = None  # holder-published metadata (e.g. client address)

    def to_bytes(self) -> bytes:
        return json.dumps(self.__dict__, sort_keys=True).encode()

    @classmethod
    def from_bytes(cls, raw: bytes) -> "LockRecord":
        payload = json.loads(raw.decode())
        payload.setdefault("meta", None)
        return cls(**payload)

    def expired(self, now: float) -> bool:
        return now - self.renewed_at > self.lease_seconds


class ResourceLock:
    """CAS lock record manager (reference NewResourceLockManager,
    election.go:49-188)."""

    def __init__(
        self,
        store: KvStorage,
        identity: str,
        key: bytes = ELECTION_KEY,
        meta: dict | None = None,
    ):
        self._store = store
        self.identity = identity
        self._key = key
        self.meta = meta or {}

    def get(self) -> LockRecord | None:
        try:
            return LockRecord.from_bytes(self._store.get(self._key))
        except KeyNotFoundError:
            return None

    def create(self, now: float | None = None, lease_seconds: float = LEASE_SECONDS) -> LockRecord:
        now = time.time() if now is None else now
        record = LockRecord(
            holder=self.identity, acquired_at=now, renewed_at=now,
            lease_seconds=lease_seconds, tso=self._store.get_timestamp_oracle(),
            meta=self.meta,
        )
        batch = self._store.begin_batch_write()
        batch.put_if_not_exist(self._key, record.to_bytes())
        batch.commit()
        return record

    def update(self, old: LockRecord, new: LockRecord) -> LockRecord:
        new.tso = max(self._store.get_timestamp_oracle(), old.tso)
        batch = self._store.begin_batch_write()
        batch.cas(self._key, new.to_bytes(), old.to_bytes())
        batch.commit()
        return new


class LeaderElection:
    """Campaign loop (reference leader.go:82-158 over k8s leaderelection)."""

    def __init__(
        self,
        lock: ResourceLock,
        on_started_leading: Callable[[int], None] | None = None,
        on_stopped_leading: Callable[[], None] | None = None,
        lease_seconds: float = LEASE_SECONDS,
        renew_interval: float = RENEW_INTERVAL,
        retry_interval: float = RETRY_INTERVAL,
    ):
        self._lock = lock
        self._on_started = on_started_leading
        self._on_stopped = on_stopped_leading
        self._lease = lease_seconds
        self._renew = renew_interval
        self._retry = retry_interval
        self._stop = threading.Event()
        self._is_leader = threading.Event()
        self._thread: threading.Thread | None = None
        self._current: LockRecord | None = None

    # ----------------------------------------------------------------- queries
    def is_leader(self) -> bool:
        return self._is_leader.is_set()

    def leader_identity(self) -> str | None:
        rec = self._lock.get()
        if rec is None:
            return None
        if rec.expired(time.time()):
            return None
        return rec.holder

    def wait_for_leadership(self, timeout: float) -> bool:
        return self._is_leader.wait(timeout)

    # ---------------------------------------------------------------- campaign
    def try_acquire_once(self, now: float | None = None) -> bool:
        """One acquire/renew attempt; True iff we hold the lock afterwards.

        Any storage error — CAS loss, uncertain result, engine/network
        failure, even a malformed lock record — means we could NOT prove we
        hold the lock, so we must report not-leader. Treating an error as
        anything else risks two concurrent leaders: the reference's
        leaderelection machinery likewise treats renew errors as lease loss
        (leader.go:109-118 panics on loss).
        """
        now = time.time() if now is None else now
        try:
            rec = self._lock.get()
            if rec is None:
                self._current = self._lock.create(now, lease_seconds=self._lease)
                return True
            if rec.holder == self._lock.identity:
                new = LockRecord(
                    holder=rec.holder, acquired_at=rec.acquired_at,
                    renewed_at=now, lease_seconds=self._lease, tso=rec.tso,
                    meta=self._lock.meta,
                )
                self._current = self._lock.update(rec, new)
                return True
            if rec.expired(now):
                new = LockRecord(
                    holder=self._lock.identity, acquired_at=now,
                    renewed_at=now, lease_seconds=self._lease, tso=rec.tso,
                    meta=self._lock.meta,
                )
                self._current = self._lock.update(rec, new)
                return True
            return False
        except CASFailedError:
            return False
        except Exception:
            logger.exception("lock op failed for %s; assuming not leader", self._lock.identity)
            return False

    def campaign(self) -> None:
        self._thread = threading.Thread(
            target=crash_guard(self._loop), name="kb-campaign", daemon=True
        )
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.is_set():
            if self.try_acquire_once():
                start_rev = self._current.tso if self._current else 0
                self._is_leader.set()
                if self._on_started:
                    self._on_started(start_rev)
                self._hold()
            else:
                self._stop.wait(self._retry)

    def _hold(self) -> None:
        while not self._stop.wait(self._renew):
            if not self.try_acquire_once():
                break
        self._is_leader.clear()
        if self._on_stopped and not self._stop.is_set():
            self._on_stopped()

    def resign(self) -> None:
        self._is_leader.clear()

    def close(self) -> None:
        self._stop.set()
        self._is_leader.clear()
        if self._thread is not None:
            self._thread.join(timeout=2.0)


class StubLeaderElection:
    """Always-leader stub for single-node servers and tests
    (reference pkg/server/service/leader/stub.go:19-39)."""

    def __init__(self, identity: str = "stub", leader: bool = True):
        self.identity = identity
        self._leader = leader

    def is_leader(self) -> bool:
        return self._leader

    def leader_identity(self) -> str | None:
        return self.identity if self._leader else None

    def wait_for_leadership(self, timeout: float) -> bool:
        return self._leader

    def campaign(self) -> None:
        pass

    def close(self) -> None:
        pass
