"""Backend-level error taxonomy (maps onto etcd3 error codes at the shim)."""

from __future__ import annotations


class BackendError(Exception):
    pass


class CompactedError(BackendError):
    """Requested revision is older than the compact watermark.

    Reference: scanner.go:594-626 (checkCompactRace) — readers at a revision
    below the persisted compact record must fail; etcd calls this
    ErrCompacted and clients respond by re-listing.
    """

    def __init__(self, requested: int, compacted: int):
        super().__init__(f"revision {requested} compacted at {compacted}")
        self.requested = requested
        self.compacted = compacted


class FutureRevisionError(BackendError):
    """Requested revision is ahead of the committed revision."""

    def __init__(self, requested: int, current: int):
        super().__init__(f"revision {requested} > current {current}")
        self.requested = requested
        self.current = current


class KeyExistsError(BackendError):
    """Create of a live key; carries the existing revision."""

    def __init__(self, key: bytes, revision: int):
        super().__init__(f"key exists: {key!r}@{revision}")
        self.key = key
        self.revision = revision


class CASRevisionMismatchError(BackendError):
    """Conditional update/delete lost; carries latest (revision, value)."""

    def __init__(self, key: bytes, revision: int, value: bytes | None):
        super().__init__(f"revision mismatch on {key!r}: latest {revision}")
        self.key = key
        self.revision = revision
        self.value = value


class NotLeaderError(BackendError):
    pass


class WatchExpiredError(BackendError):
    """Watch start revision fell out of the history cache; client must re-list
    (reference backend/watch.go:60-84)."""
