"""Async FIFO repair of uncertain write results.

Reference: pkg/backend/retry (queue.go:23-81, retry.go:142-264). When a
distributed engine's commit times out, the write *may or may not* have
landed (``UncertainResultError``). The write path reports failure to the
client but posts an invalid event; the sequencer appends it here. This loop
then, for every queued event older than ``probe_after`` seconds:

1. re-reads the key's revision record;
2. if the record's mod revision still equals the uncertain op's revision, the
   op **did** land — but no valid event was ever emitted, so watchers and
   readers would disagree with storage. Repair: idempotently rewrite the same
   value at a *fresh* revision via CAS (retry.go:222-264), which emits a
   proper event through the normal write path;
3. otherwise the op never landed (or was already superseded) — drop it.

``min_revision()`` (retry.go:123) lower-bounds compaction: compacting past an
unresolved uncertain write could garbage-collect the very record step 2 needs.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Callable

from .common import Verb, WatchEvent

logger = logging.getLogger("kubebrain")

# A head event whose resolution keeps failing (persistent engine fault on one
# key) must not wedge the FIFO and pin the compaction watermark forever: after
# this many failed attempts it is dropped with a loud log (the reference makes
# exactly one attempt per tick and drops on the first definitive answer).
MAX_RESOLVE_ATTEMPTS = 8


class AsyncFifoRetry:
    def __init__(
        self,
        read_rev_record: Callable[[bytes], tuple[int, bool] | None],
        rewrite: Callable[[WatchEvent, tuple[int, bool]], None],
        check_interval: float = 1.0,
        probe_after: float = 5.0,
        max_attempts: int = MAX_RESOLVE_ATTEMPTS,
    ):
        self._read_rev_record = read_rev_record
        self._rewrite = rewrite
        self._check_interval = check_interval
        self._probe_after = probe_after
        self._max_attempts = max_attempts
        self._lock = threading.Lock()
        self._queue: deque[list] = deque()  # [event, enqueued_at, attempts]
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._metrics = None

    def set_metrics(self, metrics) -> None:
        """Arm repair observability: ``kb_retry_queue_depth`` (scrape-time
        gauge) + ``kb_uncertain_repairs_total{outcome=}`` — under chaos the
        uncertain-write FIFO is a serving-path component and its progress
        must be scrape-visible (docs/faults.md)."""
        self._metrics = metrics
        if metrics is not None:
            metrics.register_gauge_fn("kb.retry.queue.depth",
                                      lambda: float(len(self)))

    def _count_outcome(self, outcome: str) -> None:
        if self._metrics is not None:
            self._metrics.emit_counter("kb.uncertain.repairs", 1,
                                       outcome=outcome)

    def append(self, event: WatchEvent) -> None:
        with self._lock:
            self._queue.append([event, time.monotonic(), 0])

    def min_revision(self) -> int:
        """Smallest unresolved uncertain revision; 0 when queue empty."""
        with self._lock:
            if not self._queue:
                return 0
            return min(entry[0].revision for entry in self._queue)

    def __len__(self) -> int:
        with self._lock:
            return len(self._queue)

    def process_ready(self, now: float | None = None) -> int:
        """Resolve every queued event old enough to probe; returns count.

        Split out of the loop for deterministic tests (the reference drives
        this via TestUncertainRewrite, backend_test.go:1268-1386).
        """
        now = time.monotonic() if now is None else now
        resolved = 0
        while True:
            with self._lock:
                if not self._queue:
                    return resolved
                entry = self._queue[0]
                event, enqueued, attempts = entry
                if now - enqueued < self._probe_after:
                    return resolved
            # resolve BEFORE popping: while the repair is in flight the event
            # must keep fencing compaction via min_revision() (the revision
            # record _resolve reads could otherwise be GC'd under us), and an
            # engine hiccup in _resolve must not drop the event — the
            # reference queue holds the item until handled (retry.go:161-220)
            try:
                self._resolve(event)
            except Exception:
                with self._lock:
                    entry[2] = attempts + 1
                    give_up = entry[2] >= self._max_attempts
                    if give_up and self._queue and self._queue[0] is entry:
                        self._queue.popleft()
                if give_up:
                    self._count_outcome("gave_up")
                    logger.exception(
                        "uncertain-write repair for key=%r rev=%d dropped after "
                        "%d failed attempts; storage may disagree with the "
                        "event stream for this key",
                        event.key, event.revision, entry[2],
                    )
                    continue
                logger.warning(
                    "uncertain-write repair for key=%r rev=%d failed "
                    "(attempt %d/%d); will retry",
                    event.key, event.revision, entry[2], self._max_attempts,
                    exc_info=True,
                )
                return resolved  # leave at head; retry next tick
            with self._lock:
                if self._queue and self._queue[0] is entry:
                    self._queue.popleft()
            resolved += 1

    def _resolve(self, event: WatchEvent) -> None:
        record = self._read_rev_record(event.key)
        if record is None:
            # key vanished entirely: op failed or was compacted away
            self._count_outcome("dropped")
            return
        rev, deleted = record
        if rev != event.revision:
            # op never landed, or a later write superseded it: drop
            self._count_outcome("dropped")
            return
        if deleted != (event.verb == Verb.DELETE):
            self._count_outcome("dropped")
            return
        self._rewrite(event, record)
        self._count_outcome("rewritten")

    # ----------------------------------------------------------------- daemon
    def run(self) -> None:
        self._thread = threading.Thread(
            target=self._loop, name="kb-async-retry", daemon=True
        )
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self._check_interval):
            try:
                self.process_ready()
            except Exception:  # keep the repair loop alive, but never silently
                logger.exception("uncertain-write repair tick failed")

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
