"""Fixed-size watch-history cache.

Reference: pkg/backend/ring.go:31-118 — a mutex-guarded circular buffer of
events ordered by revision; ``find_events(rev)`` binary-searches and copies
the suffix with revision >= rev. Watchers that ask for a revision older than
the oldest cached event must re-list (backend/watch.go:78-84).
"""

from __future__ import annotations

import bisect
import threading

from .common import WatchEvent


class RingOverflowError(Exception):
    pass


class Ring:
    def __init__(self, capacity: int):
        assert capacity > 0
        self._cap = capacity
        self._buf: list[WatchEvent] = []
        self._start = 0  # index of oldest
        self._lock = threading.Lock()

    def add(self, event: WatchEvent) -> None:
        with self._lock:
            if len(self._buf) < self._cap:
                self._buf.append(event)
            else:
                self._buf[self._start] = event
                self._start = (self._start + 1) % self._cap

    def _ordered(self) -> list[WatchEvent]:
        return self._buf[self._start :] + self._buf[: self._start]

    def oldest_revision(self) -> int:
        """0 when empty."""
        with self._lock:
            if not self._buf:
                return 0
            return self._buf[self._start].revision

    def latest_revision(self) -> int:
        with self._lock:
            if not self._buf:
                return 0
            return self._buf[(self._start - 1) % len(self._buf)].revision

    def find_events(self, revision: int) -> list[WatchEvent]:
        """All cached events with event.revision >= revision, in order.

        Reference ring.go:84-118 (sort.Search + suffix copy).
        """
        with self._lock:
            ordered = self._ordered()
            revs = [e.revision for e in ordered]
            idx = bisect.bisect_left(revs, revision)
            return ordered[idx:]

    def __len__(self) -> int:
        with self._lock:
            return len(self._buf)
