"""Fixed-size watch-history cache.

Reference: pkg/backend/ring.go:31-118 — a mutex-guarded circular buffer of
events ordered by revision; ``find_events(rev)`` binary-searches and copies
the suffix with revision >= rev. Watchers that ask for a revision older than
the oldest cached event must re-list (backend/watch.go:78-84).
"""

from __future__ import annotations

import threading

from .common import WatchEvent


class RingOverflowError(Exception):
    pass


class Ring:
    """Circular buffer of events in strictly increasing revision order (the
    single sequencer is the only writer). ``find_events`` binary-searches the
    rotated array in place — no O(cache) copy under the lock at 200k events
    (a watch registration holds the hub lock while replaying)."""

    def __init__(self, capacity: int):
        assert capacity > 0
        self._cap = capacity
        self._buf: list[WatchEvent] = []
        self._start = 0  # index of oldest
        self._evicted = False
        self._lock = threading.Lock()

    def add(self, event: WatchEvent) -> None:
        with self._lock:
            if len(self._buf) < self._cap:
                self._buf.append(event)
            else:
                self._buf[self._start] = event
                self._start = (self._start + 1) % self._cap
                self._evicted = True

    def has_evicted(self) -> bool:
        """True once any event has been dropped off the tail — after that,
        ``oldest_revision() - 1`` may correspond to a real, evicted event."""
        with self._lock:
            return self._evicted

    def _at(self, logical_index: int) -> WatchEvent:
        return self._buf[(self._start + logical_index) % len(self._buf)]

    def oldest_revision(self) -> int:
        """0 when empty."""
        with self._lock:
            return self._buf[self._start].revision if self._buf else 0

    def latest_revision(self) -> int:
        with self._lock:
            if not self._buf:
                return 0
            return self._buf[(self._start - 1) % len(self._buf)].revision

    def find_events(self, revision: int) -> list[WatchEvent]:
        """All cached events with event.revision >= revision, in order.

        Reference ring.go:84-118 (sort.Search + suffix copy) — binary search
        over the rotated array, copying out only the matching suffix.
        """
        with self._lock:
            n = len(self._buf)
            lo, hi = 0, n
            while lo < hi:
                mid = (lo + hi) // 2
                if self._at(mid).revision < revision:
                    lo = mid + 1
                else:
                    hi = mid
            return [self._at(i) for i in range(lo, n)]

    def __len__(self) -> int:
        with self._lock:
            return len(self._buf)
