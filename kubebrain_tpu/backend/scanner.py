"""Partition-parallel MVCC range scan + compaction over a generic engine.

Reference: pkg/backend/scanner/scanner.go — THE hot loop (worker.run
:389-516). One worker per storage partition iterates internal keys in order
and, in a single pass, implements:

- MVCC visibility: per user key, keep the *last* version <= read_revision
  (ascending (key, revision) order makes this a "next row differs" test);
- tombstone suppression for reads;
- in compact mode: GC of superseded versions, tombstone removal, deletion of
  flagged revision records (guarded against in-flight uncertain retries), and
  TTL expiry of ``/events/`` keys when the engine lacks native TTL.

This module is the *engine-generic* (iterator-based) implementation — the
correctness reference and CPU fallback. The TPU implementation
(``kubebrain_tpu.storage.tpu`` + ``kubebrain_tpu.ops.scan``) computes the same
single-pass visibility/GC decisions as a vectorized kernel over sorted key
blocks, sharded across the device mesh; both satisfy the same ``Scanner``
contract so the backend swaps them freely.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Callable, Iterator

from .. import coder
from ..storage import CASFailedError, KvStorage, Partition
from ..trace import TRACER
from .common import TOMBSTONE, KeyValue
from .errors import CompactedError

RANGE_STREAM_BATCH = 300  # reference scanner.go:44 (rangeStreamBatch)
WORKER_RETRIES = 3  # reference scanner.go:351-387 (exponential backoff x3)
EVENTS_TTL_PREFIX = b"/events/"  # reference util.go:28-42
EVENTS_TTL_SECONDS = 3600


@dataclass
class CompactStats:
    scanned: int = 0
    deleted_versions: int = 0
    deleted_tombstones: int = 0
    deleted_rev_records: int = 0
    expired_ttl: int = 0
    # device-mirror accounting (kubebrain_tpu.storage.tpu; the engine-generic
    # host path reports mirror_path="host" and leaves the rest zero):
    # how the mirror absorbed the compaction — "stored_incremental" is the
    # steady path (survivor gather + k-way stored-domain merge, dirty shards
    # only), "full_rebuild" the width-drift/dict-overflow fallback,
    # "superseded" a mirror swapped under the compaction (the fresher mirror
    # came from the post-GC store), "escalated" the bounded-retry give-up
    # (mirror quarantined, background rebuild recovering).
    mirror_path: str = "host"
    survivor_rows: int = 0
    dirty_partitions: int = 0
    #: wall seconds per pipeline phase (mark | gc | merge | publish) —
    #: the same split kb_compact_seconds{phase=} exports
    phase_seconds: dict = field(default_factory=dict)


@dataclass
class _PartitionResult:
    kvs: list[KeyValue] = field(default_factory=list)
    count: int = 0


class CompactHistory:
    """(compact revision, wall time) log used to derive the TTL cutoff
    revision when the engine lacks native TTL.

    Reference: scanner.go:147-177 (logCompactHistory + timeout revision).
    """

    def __init__(self, capacity: int = 128):
        self._entries: list[tuple[int, float]] = []
        self._cap = capacity
        self._lock = threading.Lock()

    def log(self, revision: int, now: float | None = None) -> None:
        with self._lock:
            self._entries.append((revision, time.time() if now is None else now))
            if len(self._entries) > self._cap:
                self._entries = self._entries[-self._cap :]

    def timeout_revision(self, ttl_seconds: float, now: float | None = None) -> int:
        """Largest revision whose compact-log time is older than the TTL —
        keys written at or below it are expired."""
        now = time.time() if now is None else now
        cutoff = now - ttl_seconds
        best = 0
        with self._lock:
            for rev, t in self._entries:
                if t <= cutoff and rev > best:
                    best = rev
        return best


def adjust_partition_borders(
    partitions: list[Partition], start: bytes, end: bytes
) -> list[Partition]:
    """Clamp engine partitions to [start, end) and snap interior borders to
    user-key boundaries so one key's version chain never straddles workers.

    Reference: scanner.go:202-225 (adjustPartitionsBorders) — tested against
    real region keys in scanner_test.go:27.
    """
    borders: list[bytes] = [start]
    for p in partitions:
        b = p.right
        if not b:
            continue
        if b <= start or (end and b >= end):
            continue
        if coder.is_internal_key(b):
            user_key, _ = coder.decode(b)
            b = coder.encode_revision_key(user_key)
            if b <= start or (end and b >= end):
                continue
        if b != borders[-1]:
            borders.append(b)
    borders.append(end)
    out = []
    for i in range(len(borders) - 1):
        left, right = borders[i], borders[i + 1]
        if not right or left < right:
            out.append(Partition(left, right))
    return out or [Partition(start, end)]


class Scanner:
    """Engine-generic scanner (reference Scanner iface, interface.go:23-37)."""

    def __init__(
        self,
        store: KvStorage,
        get_compact_revision: Callable[[int | None], int],
        retry_min_revision: Callable[[], int] = lambda: 0,
        compact_history: CompactHistory | None = None,
        max_workers: int = 8,
    ):
        self._store = store
        self._get_compact_revision = get_compact_revision
        self._retry_min_revision = retry_min_revision
        self.compact_history = compact_history or CompactHistory()
        self._pool = ThreadPoolExecutor(max_workers=max_workers, thread_name_prefix="kb-scan")

    def close(self) -> None:
        self._pool.shutdown(wait=False, cancel_futures=True)

    # ------------------------------------------------------------------ reads
    def range_(
        self, start: bytes, end: bytes, read_revision: int, limit: int = 0
    ) -> tuple[list[KeyValue], bool]:
        """Visible KVs of user-key range [start, end) at read_revision.

        Returns (kvs, more). With a limit, runs a single sequential worker and
        stops early (reference rangeWithLimit, scanner.go:96-119); otherwise
        fans out one worker per partition and merges in partition order
        (scanner.go:227-300).
        """
        lo, hi = coder.internal_range(start, end)
        snapshot = self._snapshot_checked(read_revision)
        # trace attribution: the engine scan is this scanner's "device"
        # (host iteration here; a kernel dispatch in the TPU scanner), the
        # result merge is the host copy — the same stage names both engines
        # report so /debug/traces reads identically across storage choices
        if limit > 0:
            kvs: list[KeyValue] = []
            with TRACER.stage("device_compute"):
                self._scan_partition(
                    Partition(lo, hi), snapshot, read_revision, kvs.append,
                    limit=limit + 1,
                )
            with TRACER.stage("host_copy"):
                more = len(kvs) > limit
                out = kvs[:limit]
            return out, more
        with TRACER.stage("device_compute"):
            results = self._parallel_scan(lo, hi, snapshot, read_revision)
        with TRACER.stage("host_copy"):
            merged: list[KeyValue] = []
            for r in results:
                merged.extend(r.kvs)
        return merged, False

    def count(self, start: bytes, end: bytes, read_revision: int) -> int:
        lo, hi = coder.internal_range(start, end)
        snapshot = self._snapshot_checked(read_revision)
        with TRACER.stage("device_compute"):
            results = self._parallel_scan(
                lo, hi, snapshot, read_revision, count_only=True)
        return sum(r.count for r in results)

    def range_stream(
        self,
        start: bytes,
        end: bytes,
        read_revision: int,
        batch_size: int = RANGE_STREAM_BATCH,
    ) -> Iterator[list[KeyValue]]:
        """Stream visible KVs in bounded batches so unbounded ranges never
        materialize (reference receiver.go:105-160)."""
        lo, hi = coder.internal_range(start, end)
        snapshot = self._snapshot_checked(read_revision)
        parts = adjust_partition_borders(self._store.get_partitions(lo, hi), lo, hi)
        batch: list[KeyValue] = []
        for part in parts:
            sink: list[KeyValue] = []
            self._scan_with_retry(part, snapshot, read_revision, sink.append)
            for kv in sink:
                batch.append(kv)
                if len(batch) >= batch_size:
                    yield batch
                    batch = []
        if batch:
            yield batch

    # ----------------------------------------------------------------- compact
    def compact(self, start: bytes, end: bytes, compact_revision: int) -> CompactStats:
        """GC every internal row made unreachable by compacting to
        compact_revision (reference scan(compact=true), scanner.go:195-232).

        Runs on an exclusive engine handle so bulk deletes don't contend with
        serving traffic (reference ExclusiveKvStorage, interface.go:28-31).
        """
        lo, hi = (start, end)
        store = self._store.exclusive_client()
        snapshot = store.get_timestamp_oracle()
        self.compact_history.log(compact_revision)
        ttl_cutoff_rev = 0
        if not store.support_ttl():
            ttl_cutoff_rev = self.compact_history.timeout_revision(EVENTS_TTL_SECONDS)
        parts = adjust_partition_borders(store.get_partitions(lo, hi), lo, hi)
        stats = CompactStats()
        futures = [
            self._pool.submit(
                self._compact_partition, store, p, snapshot, compact_revision, ttl_cutoff_rev
            )
            for p in parts
        ]
        for f in futures:
            s = f.result()
            stats.scanned += s.scanned
            stats.deleted_versions += s.deleted_versions
            stats.deleted_tombstones += s.deleted_tombstones
            stats.deleted_rev_records += s.deleted_rev_records
            stats.expired_ttl += s.expired_ttl
        return stats

    # --------------------------------------------------------------- internals
    def _snapshot_checked(self, read_revision: int) -> int:
        snapshot = self._store.get_timestamp_oracle()
        compacted = self._get_compact_revision(snapshot)
        if read_revision and compacted and read_revision < compacted:
            raise CompactedError(read_revision, compacted)
        return snapshot

    def _parallel_scan(
        self,
        lo: bytes,
        hi: bytes,
        snapshot: int,
        read_revision: int,
        count_only: bool = False,
    ) -> list[_PartitionResult]:
        parts = adjust_partition_borders(self._store.get_partitions(lo, hi), lo, hi)
        futures = [
            self._pool.submit(self._run_partition, p, snapshot, read_revision, count_only)
            for p in parts
        ]
        return [f.result() for f in futures]

    def _run_partition(
        self, part: Partition, snapshot: int, read_revision: int, count_only: bool
    ) -> _PartitionResult:
        result = _PartitionResult()
        if count_only:
            def emit(kv: KeyValue) -> None:
                result.count += 1
        else:
            def emit(kv: KeyValue) -> None:
                result.kvs.append(kv)
                result.count += 1
        self._scan_with_retry(part, snapshot, read_revision, emit)
        return result

    def _scan_with_retry(
        self,
        part: Partition,
        snapshot: int,
        read_revision: int,
        emit: Callable[[KeyValue], None],
        limit: int = 0,
    ) -> None:
        backoff = 0.01
        for attempt in range(WORKER_RETRIES):
            # buffer per attempt: a retry after a mid-scan failure must not
            # re-emit rows the failed attempt already produced
            buf: list[KeyValue] = []
            try:
                self._scan_partition(part, snapshot, read_revision, buf.append, limit)
            except Exception:
                if attempt == WORKER_RETRIES - 1:
                    raise
                time.sleep(backoff)
                backoff *= 2
                continue
            for kv in buf:
                emit(kv)
            return

    def _scan_partition(
        self,
        part: Partition,
        snapshot: int,
        read_revision: int,
        emit: Callable[[KeyValue], None],
        limit: int = 0,
    ) -> None:
        """The single-pass visibility loop (reference worker.run :389-516)."""
        emitted = 0
        cur_key: bytes | None = None
        candidate: KeyValue | None = None

        def flush() -> bool:
            nonlocal candidate, emitted
            if candidate is not None and candidate.value != TOMBSTONE:
                emit(candidate)
                emitted += 1
                candidate = None
                return bool(limit and emitted >= limit)
            candidate = None
            return False

        it = self._store.iter(part.left, part.right, snapshot_ts=snapshot)
        for ikey, value in it:
            user_key, rev = coder.decode(ikey)
            if user_key != cur_key:
                if flush():
                    return
                cur_key = user_key
            if rev == 0:
                continue  # revision record, not a version row
            if rev <= read_revision:
                # ascending revision order: later rows supersede
                candidate = KeyValue(user_key, value, rev)
        flush()

    def _compact_partition(
        self,
        store: KvStorage,
        part: Partition,
        snapshot: int,
        compact_revision: int,
        ttl_cutoff_rev: int,
    ) -> CompactStats:
        """One pass collecting GC victims, then batched engine deletes.

        Victim classes (reference worker.run :445-491,566-591):
        - version rows superseded by a newer version <= compact_revision;
        - tombstone version rows at <= compact_revision;
        - revision records whose latest write is a tombstone <= compact_revision
          (deleted via del_current, and only when no uncertain retry below
          that revision is in flight — scanner.go:477-491);
        - ``/events/`` rows whose revision is below the TTL cutoff revision.
        """
        stats = CompactStats()
        retry_min = self._retry_min_revision()
        plain_victims: list[bytes] = []
        guarded_victims: list[tuple[bytes, bytes]] = []  # (rev_key, expected_value)

        rows: list[tuple[bytes, int, bytes]] = []  # (user_key, rev, value)
        rev_record: tuple[bytes, bytes] | None = None  # (internal rev key, raw value)

        def flush_group() -> None:
            nonlocal rows, rev_record
            if not rows and rev_record is None:
                return
            user_key = rows[0][0] if rows else coder.decode(rev_record[0])[0]
            is_events = user_key.startswith(EVENTS_TTL_PREFIX)
            # last version <= compact_revision survives; older ones are victims
            last_visible = -1
            for i, (_k, rev, _v) in enumerate(rows):
                if rev <= compact_revision:
                    last_visible = i
            expired = bool(
                is_events
                and ttl_cutoff_rev
                and rows
                and rows[-1][1] <= ttl_cutoff_rev
            )
            for i, (_k, rev, value) in enumerate(rows):
                doomed = i < last_visible or expired
                if i == last_visible and value == TOMBSTONE:
                    doomed = True  # the visible version is a tombstone: gone
                    stats.deleted_tombstones += 1
                if doomed:
                    plain_victims.append(coder.encode_object_key(user_key, rev))
                    if i < last_visible:
                        stats.deleted_versions += 1
                    elif expired and value != TOMBSTONE:
                        stats.expired_ttl += 1
            # revision record GC: only when the key is fully gone
            if rev_record is not None:
                rev_key, raw = rev_record
                try:
                    latest_rev, deleted = coder.decode_rev_value(raw)
                except coder.CodecError:
                    latest_rev, deleted = 0, False
                fully_gone = (deleted and latest_rev <= compact_revision) or (
                    expired and latest_rev <= ttl_cutoff_rev
                )
                uncertain_inflight = retry_min and latest_rev >= retry_min
                if fully_gone and not uncertain_inflight:
                    guarded_victims.append((rev_key, raw))
            rows = []
            rev_record = None

        it = store.iter(part.left, part.right, snapshot_ts=snapshot)
        cur_key: bytes | None = None
        for ikey, value in it:
            user_key, rev = coder.decode(ikey)
            stats.scanned += 1
            if user_key != cur_key:
                flush_group()
                cur_key = user_key
            if rev == 0:
                rev_record = (ikey, value)
            else:
                rows.append((user_key, rev, value))
        flush_group()

        # batched deletes: unconditional for superseded rows, guarded
        # (delete-if-unchanged) for revision records. Each batch retries with
        # backoff like the scan workers (scanner.go:351-387) — deletes are
        # idempotent, so re-running a batch is safe.
        BATCH = 256
        for i in range(0, len(plain_victims), BATCH):
            chunk = plain_victims[i : i + BATCH]

            def commit_chunk() -> None:
                b = store.begin_batch_write()
                for k in chunk:
                    b.delete(k)
                b.commit()

            backoff = 0.01
            for attempt in range(WORKER_RETRIES):
                try:
                    commit_chunk()
                    break
                except CASFailedError:
                    raise
                except Exception:
                    if attempt == WORKER_RETRIES - 1:
                        raise
                    time.sleep(backoff)
                    backoff *= 2
        for rev_key, expected in guarded_victims:
            try:
                store.del_current(rev_key, expected)
                stats.deleted_rev_records += 1
            except CASFailedError:
                continue  # key was rewritten since the scan: skip
        # engine-level history pruning: logical deletes above only append
        # markers; physically free chains invisible to snapshots taken after
        # the GC (fresh clock — the pre-GC snapshot would spare the GC's own
        # markers). No-op for engines without the capability.
        pruner = getattr(store, "prune_versions", None)
        if pruner is not None:
            pruner(store.get_timestamp_oracle())
        return stats
