"""Revision allocator (timestamp oracle).

Reference: pkg/backend/tso/tso.go:21-80. Two counters:

- ``deal``    — the next revision to hand out; ``deal()`` atomically
  increments and returns a fresh, unique revision (tso.go:52).
- ``commit``  — the highest revision known to be *sequenced into the event
  stream*; everything <= commit is visible to readers (tso.go:57-71).

``init(rev)`` seeds both at leader election from the storage logical clock /
election record (tso.go:73; leader.go:96-107), and ``commit`` bumps ``deal``
forward on leader transfer so a new leader never re-deals old revisions.
"""

from __future__ import annotations

import threading
import time


class TSO:
    def __init__(self) -> None:
        self._cond = threading.Condition()
        self._lock = self._cond  # commit/deal share the condition's lock
        self._deal = 0
        self._commit = 0

    def deal(self) -> int:
        with self._lock:
            self._deal += 1
            return self._deal

    def deal_block(self, n: int) -> int:
        """Atomically reserve ``n`` consecutive revisions; returns the first.
        The group-commit write path (Backend.write_batch) deals one block
        per group so the whole group occupies a contiguous ring span and the
        sequencer drains it in one pass. Every revision of the block MUST be
        notified (valid, failed, or uncertain) or the sequencer stalls —
        the same contract as ``deal()``."""
        if n <= 0:
            raise ValueError(f"deal_block needs n >= 1, got {n}")
        with self._lock:
            first = self._deal + 1
            self._deal += n
            return first

    def commit(self, revision: int) -> None:
        with self._lock:
            if revision > self._commit:
                self._commit = revision
            if self._deal < self._commit:
                self._deal = self._commit
            self._cond.notify_all()

    def wait_committed(self, revision: int, timeout: float) -> bool:
        """Block until committed >= revision. Writers use this so a client
        that completed a write immediately reads its own write (the reference
        gets the same effect from its always-caught-up spin sequencer,
        backend.go:212-224)."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while self._commit < revision:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not self._cond.wait(remaining):
                    return self._commit >= revision
            return True

    def committed(self) -> int:
        with self._lock:
            return self._commit

    def dealt(self) -> int:
        with self._lock:
            return self._deal

    def init(self, revision: int) -> None:
        with self._lock:
            if revision > self._commit:
                self._commit = revision
            if revision > self._deal:
                self._deal = revision
