"""Watch-event fan-out hub.

Reference: pkg/backend/watcherhub.go:30-100 — a map of subscriber channels
(buffer 10000); every event batch is pushed to every subscriber with a
non-blocking send, and **slow consumers are dropped** (watcherhub.go:82-90):
a watcher that cannot keep up is removed and its stream ends, forcing the
client to re-watch (and possibly re-list). This bounds memory and protects
the pipeline — the same protocol etcd uses for its watch streams.

Filters are key *ranges* [start, end) + a minimum revision (etcd watch
semantics; a prefix watch is [p, prefix_end(p)), a single-key watch is
[k, k+\\0)). The hot part of fan-out — deciding which watchers match an
event batch — can be offloaded: ``kubebrain_tpu.ops.fanout`` computes the
(events × watchers) range-match mask on the mesh; the hub uses it when the
batch × watcher product is large (BASELINE config 3: 10k watchers × 1k ev/s).
"""

from __future__ import annotations

import queue
import threading
from typing import Callable

from .common import WatchEvent

SUBSCRIBER_BUFFER = 10000


def _in_range(key: bytes, start: bytes, end: bytes) -> bool:
    return key >= start and (not end or key < end)


class WatcherHub:
    def __init__(self, fanout_matcher: Callable | None = None):
        self._lock = threading.Lock()
        self._next_id = 0
        self._subs: dict[int, queue.Queue] = {}
        # id -> (start, end, min_revision); end == b"" means unbounded
        self._filters: dict[int, tuple[bytes, bytes, int]] = {}
        # Optional vectorized matcher:
        # (events, [(id, start, end, min_rev)]) -> bool[E][W]
        self._fanout_matcher = fanout_matcher

    def add_watcher(
        self, start: bytes = b"", end: bytes = b"", min_revision: int = 0,
        queue_factory=None,
    ) -> tuple[int, queue.Queue]:
        with self._lock:
            return self._add_locked(start, end, min_revision, queue_factory)

    def _add_locked(
        self, start: bytes, end: bytes, min_revision: int, queue_factory=None
    ) -> tuple[int, queue.Queue]:
        """``queue_factory(maxsize)`` may supply a custom subscriber queue
        (e.g. an asyncio bridge); it must provide queue.Queue's put_nowait /
        get_nowait / empty contract incl. raising queue.Full."""
        self._next_id += 1
        wid = self._next_id
        factory = queue_factory or (lambda maxsize: queue.Queue(maxsize=maxsize))
        q = factory(SUBSCRIBER_BUFFER)
        self._subs[wid] = q
        self._filters[wid] = (start, end, min_revision)
        return wid, q

    def add_watcher_with_replay(
        self,
        start: bytes,
        end: bytes,
        revision: int,
        cache,
        validate: Callable[[], None] | None = None,
        queue_factory=None,
    ) -> tuple[int, queue.Queue, int]:
        """Atomically subscribe AND replay history >= ``revision`` from the
        watch cache, then set the live filter to newest-replayed + 1.

        Registration and replay must be one critical section w.r.t.
        ``stream``: the sequencer adds events to the cache *before* streaming,
        so under the hub lock every event is either (a) already in the cache —
        delivered exactly once via replay and excluded from the live stream by
        the advanced filter — or (b) not yet streamed — delivered exactly once
        live. (The reference gets the same exactly-once property from
        subscribe-first + a lastRevision filter in the consumer goroutine,
        watch.go:102-160.)

        Returns (wid, queue, replayed_count).
        """
        with self._lock:
            if validate is not None:
                validate()  # fast-fail before paying for the replay
            catch_up = (
                [e for e in cache.find_events(revision) if _in_range(e.key, start, end)]
                if revision
                else []
            )
            if validate is not None and revision:
                # re-check AFTER the replay copy: the sequencer appends (and
                # evicts) cache entries outside the hub lock, so the cache's
                # oldest revision may have advanced past ``revision`` between
                # the first check and find_events — replay would then be
                # missing the evicted events. Eviction only moves oldest
                # forward, so if this second check passes, find_events ran
                # with oldest <= revision and the copy is complete.
                validate()
            next_rev = (catch_up[-1].revision + 1) if catch_up else revision
            wid, q = self._add_locked(start, end, next_rev, queue_factory)
            if catch_up:
                q.put_nowait(catch_up)
            return wid, q, len(catch_up)

    def delete_watcher(self, wid: int) -> None:
        with self._lock:
            q = self._subs.pop(wid, None)
            self._filters.pop(wid, None)
        if q is not None:
            # poison pill: stream closed. If the queue is full (that's why the
            # watcher is being dropped), evict one batch so the pill fits —
            # the consumer must learn the stream ended and re-watch.
            while True:
                try:
                    q.put_nowait(None)
                    break
                except queue.Full:
                    try:
                        q.get_nowait()
                    except queue.Empty:
                        pass

    def watcher_count(self) -> int:
        with self._lock:
            return len(self._subs)

    def stream(self, batch: list[WatchEvent]) -> None:
        """Push one batch to every matching subscriber; drop the slow.

        Reference watcherhub.go:78-100. Per-watcher filtering (range +
        min-revision) happens here rather than in each consumer thread so a
        vectorized matcher can compute the whole (E × W) mask at once.
        """
        if not batch:
            return
        with self._lock:
            subs = list(self._subs.items())
            filters = dict(self._filters)
        if not subs:
            return

        if self._fanout_matcher is not None and len(subs) * len(batch) >= 4096:
            import numpy as np

            watcher_specs = [(wid, *filters[wid]) for wid, _ in subs]
            mask = np.asarray(self._fanout_matcher(batch, watcher_specs))  # bool[E, W]
            # deliver ∝ matches, not E*W: most watchers match nothing in a
            # given batch, so only touch columns with hits
            col_hits = np.nonzero(mask.any(axis=0))[0]
            per_watcher = {}
            for w in col_hits:
                wid = subs[int(w)][0]
                rows = np.nonzero(mask[:, w])[0]
                per_watcher[wid] = [batch[int(e)] for e in rows]
        else:
            per_watcher = {}
            for wid, _q in subs:
                start, end, min_rev = filters[wid]
                per_watcher[wid] = [
                    ev
                    for ev in batch
                    if ev.revision >= min_rev and _in_range(ev.key, start, end)
                ]

        dead: list[int] = []
        for wid, q in subs:
            events = per_watcher.get(wid)
            if not events:
                continue
            try:
                q.put_nowait(events)
            except queue.Full:
                dead.append(wid)  # slow consumer: drop it
        for wid in dead:
            self.delete_watcher(wid)

    def close(self) -> None:
        with self._lock:
            wids = list(self._subs)
        for wid in wids:
            self.delete_watcher(wid)
