"""Watch-event fan-out hub.

Reference: pkg/backend/watcherhub.go:30-100 — a map of subscriber channels
(buffer 10000); every event batch is pushed to every subscriber with a
non-blocking send, and **slow consumers are dropped** (watcherhub.go:82-90):
a watcher that cannot keep up is removed and its stream ends, forcing the
client to re-watch (and possibly re-list). This bounds memory and protects
the pipeline — the same protocol etcd uses for its watch streams.

Filters are key *ranges* [start, end) + a minimum revision (etcd watch
semantics; a prefix watch is [p, prefix_end(p)), a single-key watch is
[k, k+\\0)). The hot part of fan-out — deciding which watchers match an
event batch — can be offloaded: ``kubebrain_tpu.ops.fanout`` computes the
(events × watchers) range-match mask on the mesh; the hub uses it when the
batch × watcher product is large (BASELINE config 3: 10k watchers × 1k ev/s).
"""

from __future__ import annotations

import bisect
import queue
import threading
import time
from typing import Callable

from ..util import fieldcheck
from .common import WatchEvent

SUBSCRIBER_BUFFER = 10000


class ProgressMarker:
    """A watch progress mark riding a subscriber queue IN ORDER with event
    batches: by the time a consumer pulls it, every event with revision <=
    ``revision`` has already been pulled (the poster guarantees all such
    events were enqueued first — Backend.flushed_revision). The follower
    replication stream uses these to advance its applied watermark across
    the leader's revision gaps (docs/replication.md)."""

    __slots__ = ("revision",)

    def __init__(self, revision: int):
        self.revision = revision


def _in_range(key: bytes, start: bytes, end: bytes) -> bool:
    return key >= start and (not end or key < end)


class _RangeIndex:
    """Sweep-line interval-stabbing index over watcher ranges.

    Kube watch populations are thousands of near-disjoint namespace prefixes
    (plus a few broad watches), so matching an event by scanning all W
    watchers — or dispatching a kernel per small batch — wastes almost all
    of its work. Coordinate-compress the range boundaries into elementary
    segments and precompute each segment's covering watcher list: a lookup
    is then bisect + list walk, O(log S + matches).

    Degenerate (heavily nested) populations could make the per-segment lists
    big; ``dense`` flags when average coverage explodes so the caller can
    fall back to the vectorized matcher.
    """

    __slots__ = ("_bounds", "_cover", "dense")

    # average covering-watchers-per-segment beyond which the index is worse
    # than vectorized matching; construction aborts early at this point so a
    # degenerate population (e.g. thousands of unbounded from-key watches)
    # never pays the O(W^2) segment-list materialization
    DENSE_COVER = 64

    def __init__(self, filters: dict[int, tuple[bytes, bytes, int]]):
        events = []  # (key, is_end, wid)
        for wid, (start, end, _minrev) in filters.items():
            events.append((start, 0, wid))
            # end == b"" means unbounded: never removed
            if end:
                events.append((end, 1, wid))
        events.sort(key=lambda t: (t[0], t[1]))
        bounds: list[bytes] = [b""]
        cover: list[tuple[int, ...]] = [()]
        active: set[int] = set()
        total_cover = 0
        self.dense = False
        i = 0
        n = len(events)
        while i < n:
            key = events[i][0]
            while i < n and events[i][0] == key:
                _, is_end, wid = events[i]
                (active.discard if is_end else active.add)(wid)
                i += 1
            if key == bounds[-1]:
                cover[-1] = tuple(active)
            else:
                bounds.append(key)
                cover.append(tuple(active))
            total_cover += len(active)
            if len(cover) >= 64 and total_cover > self.DENSE_COVER * len(cover):
                # too nested to index: abandon construction (lookup must not
                # be used — the hub falls back to matcher / linear filtering)
                self.dense = True
                break
        self._bounds = bounds
        self._cover = cover

    def lookup(self, key: bytes) -> tuple[int, ...]:
        """Watcher ids whose [start, end) contains ``key`` (min_revision NOT
        applied — the caller filters)."""
        idx = bisect.bisect_right(self._bounds, key) - 1
        return self._cover[idx]


@fieldcheck.track
class WatcherHub:
    def __init__(self, fanout_matcher: Callable | None = None):
        self._lock = threading.Lock()
        self._next_id = 0
        self._subs: dict[int, queue.Queue] = {}
        # id -> (start, end, min_revision); end == b"" means unbounded
        self._filters: dict[int, tuple[bytes, bytes, int]] = {}
        # Optional vectorized matcher:
        # (events, [(id, start, end, min_rev)]) -> bool[E][W]
        self._fanout_matcher = fanout_matcher
        # Block protocol (kubebrain_tpu.fanout.DeviceFanout): the matcher
        # demuxes on its own — deliver(batch, specs, version) -> {wid: evs}
        # — so the hub never materializes the [E, W] mask at all
        self._matcher_delivers = callable(getattr(fanout_matcher, "deliver",
                                                  None))
        # watcher-set version: lets the matcher cache its packed table with
        # an O(1) check instead of an O(W) spec-tuple compare per batch
        self._version = 0
        self._matcher_takes_version = False
        # lazily (re)built interval index for host-side matching
        self._index: _RangeIndex | None = None
        self._index_version = -1
        # optional metrics sink (set_metrics): commit->delivery lag histogram
        # + per-watcher backlog gauges
        self._metrics = None
        if fanout_matcher is not None:
            import inspect

            try:
                self._matcher_takes_version = (
                    "version" in inspect.signature(fanout_matcher).parameters
                )
            except (TypeError, ValueError):
                pass

    @property
    def prefers_blocks(self) -> bool:
        """True when the matcher wants WHOLE sequencer drain blocks: the
        backend then skips the EVENT_BATCH chunking in ``_drain`` so one
        contiguous revision block costs one device dispatch (docs/watch.md),
        not ceil(block / EVENT_BATCH)."""
        return bool(getattr(self._fanout_matcher, "prefers_blocks", False))

    def set_metrics(self, metrics) -> None:
        """Arm watch-path lag instrumentation: ``kb.watch.lag.seconds``
        (commit -> subscriber-queue delivery, emitted in ``stream``) and a
        ``kb.watch.backlog{watcher=}`` scrape-time gauge per live watcher.
        Dead watchers unregister themselves by raising LookupError at scrape
        (the callback-gauge collector drops them)."""
        self._metrics = metrics

    def _backlog_of(self, wid: int) -> float:
        q = self._subs.get(wid)
        if q is None:
            raise LookupError(wid)  # watcher gone: gauge self-unregisters
        qsize = getattr(q, "qsize", None)
        return float(qsize()) if callable(qsize) else 0.0

    def add_watcher(
        self, start: bytes = b"", end: bytes = b"", min_revision: int = 0,
        queue_factory=None,
    ) -> tuple[int, queue.Queue]:
        with self._lock:
            return self._add_locked(start, end, min_revision, queue_factory)

    def _add_locked(
        self, start: bytes, end: bytes, min_revision: int, queue_factory=None
    ) -> tuple[int, queue.Queue]:
        """``queue_factory(maxsize)`` may supply a custom subscriber queue
        (e.g. an asyncio bridge); it must provide queue.Queue's put_nowait /
        get_nowait / empty contract incl. raising queue.Full."""
        self._next_id += 1
        self._version += 1
        wid = self._next_id
        factory = queue_factory or (lambda maxsize: queue.Queue(maxsize=maxsize))
        q = factory(SUBSCRIBER_BUFFER)
        self._subs[wid] = q
        self._filters[wid] = (start, end, min_revision)
        if self._metrics is not None:
            self._metrics.register_gauge_fn(
                "kb.watch.backlog", lambda w=wid: self._backlog_of(w),
                watcher=str(wid),
            )
        return wid, q

    def add_watcher_with_replay(
        self,
        start: bytes,
        end: bytes,
        revision: int,
        cache,
        validate: Callable[[], None] | None = None,
        queue_factory=None,
    ) -> tuple[int, queue.Queue, int]:
        """Atomically subscribe AND replay history >= ``revision`` from the
        watch cache, then set the live filter to newest-replayed + 1.

        Registration and replay must be one critical section w.r.t.
        ``stream``: the sequencer adds events to the cache *before* streaming,
        so under the hub lock every event is either (a) already in the cache —
        delivered exactly once via replay and excluded from the live stream by
        the advanced filter — or (b) not yet streamed — delivered exactly once
        live. (The reference gets the same exactly-once property from
        subscribe-first + a lastRevision filter in the consumer goroutine,
        watch.go:102-160.)

        Returns (wid, queue, replayed_count).
        """
        with self._lock:
            if validate is not None:
                validate()  # fast-fail before paying for the replay
            catch_up = (
                [e for e in cache.find_events(revision) if _in_range(e.key, start, end)]
                if revision
                else []
            )
            if validate is not None and revision:
                # re-check AFTER the replay copy: the sequencer appends (and
                # evicts) cache entries outside the hub lock, so the cache's
                # oldest revision may have advanced past ``revision`` between
                # the first check and find_events — replay would then be
                # missing the evicted events. Eviction only moves oldest
                # forward, so if this second check passes, find_events ran
                # with oldest <= revision and the copy is complete.
                validate()
            next_rev = (catch_up[-1].revision + 1) if catch_up else revision
            wid, q = self._add_locked(start, end, next_rev, queue_factory)
            if catch_up:
                q.put_nowait(catch_up)
            return wid, q, len(catch_up)

    def delete_watcher(self, wid: int) -> None:
        with self._lock:
            q = self._subs.pop(wid, None)
            self._filters.pop(wid, None)
            self._version += 1
        if q is not None and self._metrics is not None:
            # eager unregistration (outside the hub lock): scrape-time
            # LookupError GC alone would leak one dead entry per watcher
            # on servers nothing ever scrapes
            self._metrics.unregister_gauge_fn("kb.watch.backlog",
                                              watcher=str(wid))
        if q is not None:
            # Drop protocol. Evicting buffered batches to fit the poison
            # pill would let the consumer deliver a NEWER batch after an
            # older one was discarded (the consumer races any eviction) —
            # an invisible gap whose resume watermark skips the evicted
            # events forever (docs/replication.md). Instead: flag the
            # queue dropped FIRST — consumers check the flag before every
            # delivery and truncate, so the delivered sequence stays a
            # strict prefix of the enqueued order — then make room for
            # the pill (the evictions are now provably undeliverable).
            # Structurally bounded: each pass evicts one batch from a
            # bounded queue until the pill fits.
            try:
                q.kb_dropped = True
            except AttributeError:
                pass  # exotic queue_factory without attribute support
            while True:  # kblint: disable=KB118 -- drains a bounded queue
                try:
                    q.put_nowait(None)
                    break
                except queue.Full:
                    try:
                        q.get_nowait()
                    except queue.Empty:
                        pass

    def post_progress(self, wid: int, revision: int) -> None:
        """Enqueue a ProgressMarker on watcher ``wid``'s own queue. The
        caller must have established that every event with revision <=
        ``revision`` was already enqueued (Backend.flushed_revision reads
        the sequencer floor while the drainer is idle); queue FIFO then
        carries the ordering to the wire. Best-effort: a full queue drops
        the mark (that watcher is about to be dropped as a slow consumer
        anyway), never an event."""
        with self._lock:
            q = self._subs.get(wid)
        if q is None:
            return
        try:
            q.put_nowait(ProgressMarker(revision))
        except queue.Full:
            pass

    def watcher_count(self) -> int:
        with self._lock:
            return len(self._subs)

    def watcher_ids(self) -> list[int]:
        """Live watcher ids (the fault plane's watch-reset injection picks
        its victims from this list)."""
        with self._lock:
            return list(self._subs)

    _on_tpu_cached: bool | None = None

    def _on_tpu(self) -> bool:
        if WatcherHub._on_tpu_cached is None:
            try:
                import jax

                WatcherHub._on_tpu_cached = jax.default_backend() == "tpu"
            except Exception:
                WatcherHub._on_tpu_cached = False
        return WatcherHub._on_tpu_cached

    def stream(self, batch: list[WatchEvent]) -> None:
        """Push one batch to every matching subscriber; drop the slow.

        Reference watcherhub.go:78-100. Per-watcher filtering (range +
        min-revision) happens here rather than in each consumer thread so a
        vectorized matcher can compute the whole (E × W) mask at once.
        """
        if not batch:
            return
        with self._lock:
            subs = list(self._subs.items())
            filters = dict(self._filters)
            version = self._version
        if not subs:
            return

        index = None
        if len(subs) >= 64:
            if self._index_version != version:
                self._index = _RangeIndex(filters)
                self._index_version = version
            index = self._index
            if index.dense and self._fanout_matcher is None:
                index = None  # aborted build, no kernel either: linear filter

        # the kernel beats the index only where a chip makes the (E x W) mask
        # ~free: big batches on a real TPU, or populations too nested for the
        # index. On CPU backends the index wins at every realistic batch.
        use_device = self._fanout_matcher is not None and (
            (self._on_tpu() and len(subs) * len(batch) >= 1_000_000)
            or (index is not None and index.dense)
            or (index is None and len(subs) * len(batch) >= 4096)
        )
        if use_device and self._matcher_delivers:
            # block protocol: sync + one dispatch + vectorized demux inside
            # the matcher; the hub only routes the per-watcher lists
            watcher_specs = [(wid, *filters[wid]) for wid, _ in subs]
            per_watcher = self._fanout_matcher.deliver(
                batch, watcher_specs, version=version)
        elif use_device:
            import numpy as np

            watcher_specs = [(wid, *filters[wid]) for wid, _ in subs]
            if self._matcher_takes_version:
                mask = np.asarray(
                    self._fanout_matcher(batch, watcher_specs, version=version)
                )  # bool[E, W]
            else:
                mask = np.asarray(self._fanout_matcher(batch, watcher_specs))
            # deliver ∝ matches, not E*W: most watchers match nothing in a
            # given batch, so only touch columns with hits
            col_hits = np.nonzero(mask.any(axis=0))[0]
            per_watcher = {}
            for w in col_hits:
                wid = subs[int(w)][0]
                rows = np.nonzero(mask[:, w])[0]
                per_watcher[wid] = [batch[int(e)] for e in rows]
        elif index is not None:
            # interval-stabbing: cost ∝ events x matches, independent of W.
            # Group by cover tuple first so the watchers of one namespace
            # SHARE one event-list object (20 watchers x N events used to
            # allocate 20 lists — pure GC pressure at informer scale).
            groups: dict[int, tuple[tuple[int, ...], list]] = {}
            for ev in batch:
                cover = index.lookup(ev.key)
                if not cover:
                    continue
                g = groups.get(id(cover))
                if g is None:
                    groups[id(cover)] = (cover, [ev])
                else:
                    g[1].append(ev)
            per_watcher = {}
            multi: dict[int, list[list]] = {}  # broad watchers: pieces to merge
            for cover, evs in groups.values():
                first_rev = evs[0].revision
                for wid in cover:
                    min_rev = filters[wid][2]
                    mine = (
                        evs if min_rev <= first_rev
                        else [e for e in evs if e.revision >= min_rev]
                    )
                    if not mine:
                        continue
                    if wid in multi:
                        multi[wid].append(mine)
                    elif wid in per_watcher:
                        multi[wid] = [per_watcher.pop(wid), mine]
                    else:
                        per_watcher[wid] = mine
            # a watcher spanning several cover segments merges its
            # revision-ordered pieces once, not per segment
            if multi:
                import heapq

                for wid, pieces in multi.items():
                    per_watcher[wid] = list(
                        heapq.merge(*pieces, key=lambda e: e.revision)
                    )
        else:
            per_watcher = {}
            for wid, _q in subs:
                start, end, min_rev = filters[wid]
                per_watcher[wid] = [
                    ev
                    for ev in batch
                    if ev.revision >= min_rev and _in_range(ev.key, start, end)
                ]

        dead: list[int] = []
        delivered = False
        for wid, q in subs:
            events = per_watcher.get(wid)
            if not events:
                continue
            try:
                q.put_nowait(events)
                delivered = True
            except queue.Full:
                dead.append(wid)  # slow consumer: drop it
        if delivered and self._metrics is not None and batch[0].ts:
            # commit-revision -> subscriber-queue delivery lag, one
            # observation per fan-out (the oldest event bounds the batch)
            self._metrics.emit_histogram(
                "kb.watch.lag.seconds", time.monotonic() - batch[0].ts,
                point="queue",
            )
        if dead and self._metrics is not None:
            # the documented backlog-bound drop (SUBSCRIBER_BUFFER): visible
            # on /metrics so the SLO report can count slow-consumer drops
            self._metrics.emit_counter("kb.watch.dropped", len(dead))
        for wid in dead:
            self.delete_watcher(wid)

    def close(self) -> None:
        with self._lock:
            wids = list(self._subs)
        for wid in wids:
            self.delete_watcher(wid)
