"""Process bootstrap: flags, wiring, graceful shutdown.

Reference: cmd/main.go (cobra root command, SIGINT/SIGTERM graceful exit
with a 3s force-kill watchdog, :35-97) and cmd/option/option.go (flags,
validation, dependency wiring — storage → metrics decorator → backend →
endpoint, :230-259). Engine choice is a runtime flag (--storage) instead of
the reference's compile-time Go build tags (option_badger.go:15).
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import threading

from . import __version__


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="kubebrain-tpu",
        description="TPU-native etcd3-compatible metadata store for Kubernetes",
    )
    p.add_argument("--storage", default="memkv",
                   choices=["memkv", "tpu", "native", "remote"],
                   help="storage engine (reference: build-tag selected TiKV/Badger; "
                        "'remote' = shared kbstored server, the TiKV role)")
    p.add_argument("--storage-address", default="127.0.0.1:2389",
                   help="kbstored address for --storage=remote; comma-"
                        "separated primary,follower,... enables failover()")
    p.add_argument("--tier-auto-failover", action="store_true",
                   help="probe the kbstored tier primary and auto-promote a "
                        "follower after 3 missed probes (split-brain-guarded "
                        "by the follower's stream-liveness check)")
    p.add_argument("--storage-read-followers", action="store_true",
                   help="route snapshot-pinned reads to kbstored followers "
                        "(tier-level read scaling; falls back to the "
                        "primary on replica lag)")
    p.add_argument("--storage-pool", type=int, default=8,
                   help="connection pool size to kbstored (reference keeps "
                        "200 round-robin TiKV clients, tikv.go:36-82)")
    p.add_argument("--inner-storage", default="memkv",
                   help="host engine backing the tpu mirror (tpu engine only)")
    p.add_argument("--use-pallas", action="store_true",
                   help="run range scans through the Pallas/Mosaic kernel "
                        "instead of the fused-jnp kernel (tpu engine only; "
                        "interpret-mode off-TPU; env KB_USE_PALLAS)")
    p.add_argument("--mesh-part", type=int, default=0,
                   help="devices on the scan mesh's `part` axis (tpu engine "
                        "only): the mirror's 20M-row keyspace shards across "
                        "this many chips so per-chip HBM bounds the dataset; "
                        "0 = every visible device (docs/multichip.md)")
    p.add_argument("--key-encoding", choices=("encoded", "raw"), default="",
                   help="mirror key layout (--storage=tpu): 'encoded' = "
                        "order-preserving prefix/dictionary compression of "
                        "the device key column (docs/compression.md), "
                        "'raw' = full-width packed keys; default follows "
                        "KB_ENCODE_KEYS (encoded)")
    p.add_argument("--merge-threshold", type=int, default=0,
                   help="TPU engine: delta rows that trigger an incremental "
                        "mirror merge (0 = engine default 4096). Chaos runs "
                        "lower it so merge-fault windows exercise the real "
                        "merge/retry/escalation machinery (docs/faults.md)")
    p.add_argument("--scan-partitions", type=int, default=0,
                   help="mirror partition count, decoupled from the mesh "
                        "size (must be a multiple of --mesh-part; each "
                        "device then holds P/N contiguous partitions); "
                        "0 = one partition per mesh device")
    p.add_argument("--data-dir", default="",
                   help="durable storage dir for the native engine (WAL + "
                        "snapshot); empty = in-memory")
    p.add_argument("--native-partitions", type=int, default=4,
                   help="partition count the native engine samples for "
                        "partition-parallel host scans")
    p.add_argument("--fsync", action="store_true",
                   help="fsync the WAL on every commit")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--client-port", type=int, default=2379)
    p.add_argument("--peer-port", type=int, default=2380)
    p.add_argument("--info-port", type=int, default=8081)
    p.add_argument("--prefix", default="/", help="key prefix served/compacted")
    p.add_argument("--skip-prefixes", default="", help="comma-separated prefixes compaction skips")
    p.add_argument("--watch-cache-size", type=int, default=200_000)
    p.add_argument("--disable-etcd-compatibility", action="store_true",
                   help="serve only the native brain protocol semantics "
                        "(Count over etcd is rejected; reference etcd-compat flag)")
    p.add_argument("--identity", default="", help="host:peerPort; autodetected when empty")
    p.add_argument("--single-node", action="store_true",
                   help="stub leader election (always leader)")
    p.add_argument("--enable-etcd-proxy", action="store_true",
                   help="followers forward writes to the leader")
    p.add_argument("--role", choices=("leader", "follower"), default="leader",
                   help="serving role (docs/replication.md): 'follower' "
                        "keeps a local mirror fed by a resumable "
                        "replication stream from --leader-address, serves "
                        "explicit-revision + bounded-staleness reads and "
                        "Watch locally, fences linearizable reads on the "
                        "leader's revision, and forwards writes/leases/"
                        "compaction")
    p.add_argument("--leader-address", default="",
                   help="leader client (gRPC) host:port (--role follower): "
                        "replication stream source + write/lease forward "
                        "target")
    p.add_argument("--leader-info", default="",
                   help="leader info/peer (HTTP) host:port (--role "
                        "follower): /status for the linearizable-read "
                        "revision fence + compact-watermark sync")
    p.add_argument("--max-staleness-rev", type=int, default=0,
                   help="follower bounded-staleness bound in revisions: "
                        "serializable reads REFUSE (etcdserver: replica "
                        "too stale) once the replication lag exceeds it; "
                        "0 = unbounded")
    p.add_argument("--max-staleness-ms", type=float, default=5000.0,
                   help="follower bounded-staleness bound in wall ms since "
                        "the watermark last covered the leader head; "
                        "refusal past it, 0 = unbounded")
    p.add_argument("--fence-timeout-ms", type=float, default=3000.0,
                   help="follower linearizable-read fence: how long the "
                        "applied watermark may chase the leader revision "
                        "before the read refuses (never answers stale)")
    p.add_argument("--enable-storage-metrics", action="store_true")
    p.add_argument("--tpu-fanout", action="store_true",
                   help="vectorized watch fan-out on the device mesh "
                        "(block-batched persistent-table matcher, "
                        "docs/watch.md)")
    p.add_argument("--mesh-wat", type=int, default=0,
                   help="devices on the watch fan-out mesh's `wat` axis: "
                        "the watcher table lives sharded across them and "
                        "each shard matches + compacts locally "
                        "(docs/watch.md). Composes with --mesh-part — the "
                        "two axes may share chips. Requires --tpu-fanout; "
                        "0 = single-device table")
    p.add_argument("--fanout-impl", choices=("block", "legacy"),
                   default="block",
                   help="--tpu-fanout implementation: 'block' = persistent "
                        "sharded watcher table, one dispatch per sequencer "
                        "drain block; 'legacy' = per-batch mask matcher "
                        "(kept for differential runs)")
    p.add_argument("--cert-file", default="")
    p.add_argument("--key-file", default="")
    p.add_argument("--ca-file", default="")
    p.add_argument("--secure-only", action="store_true",
                   help="with TLS configured, refuse plaintext clients "
                        "(reference endpoint secure modes, config.go:159)")
    p.add_argument("--sched-depth", type=int, default=4,
                   help="request scheduler: bounded in-flight device scan "
                        "dispatches (pipelined; bench pipelined_rows_per_sec "
                        "saturates by ~8). 0 = auto: sized from the tracer's "
                        "measured dispatch-RTT EWMA, clamped 2-16")
    p.add_argument("--trace-slow-ms", type=float, default=500.0,
                   help="request tracer: RPCs slower than this land in the "
                        "slow-request log (/debug/traces \"slow\") and a "
                        "warning log line; 0 disables the slow log")
    p.add_argument("--sched-shed-ms", type=float, default=5000.0,
                   help="request scheduler: shed queued range reads older "
                        "than this (etcd ResourceExhausted on the wire)")
    p.add_argument("--sched-queue-limit", type=int, default=1024,
                   help="request scheduler: per-lane queued-request bound; "
                        "enqueue past it sheds immediately")
    p.add_argument("--sched-batch", type=int, default=8,
                   help="request scheduler: max distinct ready Range/Count "
                        "requests drained into one dispatch slot — over the "
                        "TPU engine they become ONE query-batched kernel "
                        "launch (bench batched_rows_per_sec); 1 disables")
    p.add_argument("--sched-write-batch", type=int, default=8,
                   help="request scheduler: max queued write ops (create/"
                        "update/delete) drained into one group commit — a "
                        "contiguous revision block + ONE engine round trip "
                        "with per-op conflict demux (bench "
                        "write_txns_per_sec; docs/writes.md); 1 disables")
    p.add_argument("--grpc-workers", type=int, default=256,
                   help="gRPC worker threads; each open watch stream holds one")
    p.add_argument("--aio-port", type=int, default=0,
                   help="additional asyncio etcd3 listener (coroutine-held "
                        "watch streams — no thread-per-stream ceiling); 0 = off")
    p.add_argument("--front-port", type=int, default=0,
                   help="native C++ gRPC/HTTP frontend (kbfront) on this port: "
                        "single-port h2+http demux (reference cmux) with the "
                        "protocol work in C++; 0 = off")
    p.add_argument("--lease-reap-interval", type=float, default=1.0,
                   help="lease subsystem: leader-only reaper cadence; expired "
                        "leases' keys become revision-stamped deletes through "
                        "the sequencer (watch-visible, compaction-safe)")
    p.add_argument("--lease-checkpoint-interval", type=float, default=5.0,
                   help="lease subsystem: cadence for persisting remaining "
                        "TTLs + attachments through the storage engine "
                        "(grant/revoke checkpoint synchronously; this covers "
                        "keepalive-refreshed deadlines)")
    p.add_argument("--legacy-ttl-patterns", action=argparse.BooleanOptionalAction,
                   default=True,
                   help="key-pattern TTL fallback (/events/ = 1h, the "
                        "reference's lease.go behavior) for writes WITHOUT an "
                        "explicit lease; an attached lease always wins. "
                        "--no-legacy-ttl-patterns makes leases the only "
                        "expiry mechanism")
    p.add_argument("--faults", default="",
                   help="chaos mode (docs/faults.md): arm a deterministic "
                        "fault-injection plane with this preset (none, "
                        "smoke, storage, watch, merge, full). The plane is "
                        "INERT until GET /faults/arm on the info port "
                        "starts the window clock; 'none'/empty = no plane")
    p.add_argument("--fault-seed", type=int, default=0,
                   help="seed for the fault schedule (same preset+seed+"
                        "horizon => byte-identical schedule sha)")
    p.add_argument("--fault-horizon-s", type=float, default=30.0,
                   help="fault schedule horizon in real seconds from arm; "
                        "after it the plane goes quiet (recovery window)")
    p.add_argument("--cluster-name", default="")
    p.add_argument("--compact-interval", type=float, default=60.0)
    p.add_argument("--jax-platform", default=os.environ.get("KB_JAX_PLATFORM", ""),
                   help="force the jax backend (e.g. 'cpu'); applied in-process "
                        "before any kernel runs — the only override the axon "
                        "TPU-tunnel sitecustomize respects")
    p.add_argument("--version", action="store_true", help="print version and exit")
    return p


def apply_jax_platform(platform: str) -> None:
    if not platform:
        return
    os.environ["JAX_PLATFORMS"] = platform
    import jax

    jax.config.update("jax_platforms", platform)


def validate_args(args) -> None:
    """Flag validation (reference KubeBrainOption.Validate, option.go:207)."""
    ports = [args.client_port, args.peer_port, args.info_port]
    if len(set(ports)) != len(ports):
        raise SystemExit(f"client/peer/info ports must be distinct, got {ports}")
    for p in ports:
        if not 0 < p < 65536:
            raise SystemExit(f"invalid port {p}")
    if bool(args.cert_file) != bool(args.key_file):
        raise SystemExit("--cert-file and --key-file must be set together")
    if args.secure_only and not args.cert_file:
        raise SystemExit("--secure-only requires --cert-file/--key-file")
    for f in (args.cert_file, args.key_file, args.ca_file):
        if f and not os.path.exists(f):
            raise SystemExit(f"TLS file not found: {f}")
    if args.storage == "tpu" and args.inner_storage == "tpu":
        raise SystemExit("--inner-storage cannot be tpu")
    mesh_part = getattr(args, "mesh_part", 0)
    scan_parts = getattr(args, "scan_partitions", 0)
    if mesh_part < 0 or scan_parts < 0:
        raise SystemExit("--mesh-part and --scan-partitions must be >= 0")
    if (mesh_part or scan_parts) and args.storage != "tpu":
        raise SystemExit("--mesh-part/--scan-partitions require --storage=tpu")
    if getattr(args, "key_encoding", "") and args.storage != "tpu":
        raise SystemExit("--key-encoding requires --storage=tpu")
    if mesh_part and scan_parts and scan_parts % mesh_part:
        raise SystemExit(
            f"--scan-partitions {scan_parts} must be a multiple of "
            f"--mesh-part {mesh_part}")
    if getattr(args, "mesh_wat", 0) < 0:
        raise SystemExit("--mesh-wat must be >= 0")
    if getattr(args, "mesh_wat", 0) and not getattr(args, "tpu_fanout", False):
        raise SystemExit("--mesh-wat requires --tpu-fanout")
    if getattr(args, "sched_depth", 1) < 0 or getattr(args, "sched_queue_limit", 1) < 1:
        raise SystemExit("--sched-depth must be >= 0 (0 = auto) and "
                         "--sched-queue-limit must be >= 1")
    if getattr(args, "sched_batch", 1) < 1:
        raise SystemExit("--sched-batch must be >= 1 (1 disables batching)")
    if getattr(args, "sched_write_batch", 1) < 1:
        raise SystemExit(
            "--sched-write-batch must be >= 1 (1 disables group commit)")
    if getattr(args, "sched_shed_ms", 1.0) <= 0:
        raise SystemExit("--sched-shed-ms must be > 0")
    if getattr(args, "trace_slow_ms", 0.0) < 0:
        raise SystemExit("--trace-slow-ms must be >= 0")
    if getattr(args, "lease_reap_interval", 1.0) <= 0 or \
            getattr(args, "lease_checkpoint_interval", 1.0) <= 0:
        raise SystemExit("--lease-reap-interval and --lease-checkpoint-interval "
                         "must be > 0")
    if args.data_dir and not (
        args.storage == "native" or (args.storage == "tpu" and args.inner_storage == "native")
    ):
        raise SystemExit("--data-dir requires --storage=native (or tpu over native)")
    if getattr(args, "role", "leader") == "follower":
        if not getattr(args, "leader_address", ""):
            raise SystemExit("--role follower requires --leader-address")
        if not getattr(args, "leader_info", ""):
            raise SystemExit("--role follower requires --leader-info "
                             "(the leader's info/peer HTTP host:port)")
        if getattr(args, "aio_port", 0) or getattr(args, "front_port", 0):
            # those fronts build their services WITHOUT the replica gate:
            # they would serve ungated (silently stale) "linearizable"
            # reads and refuse lease RPCs instead of forwarding — refuse
            # loudly until they grow replica routing
            raise SystemExit("--role follower serves the sync gRPC front "
                             "only (--aio-port/--front-port have no "
                             "replica read gate yet)")
        if getattr(args, "fence_timeout_ms", 1.0) <= 0:
            raise SystemExit("--fence-timeout-ms must be > 0")
        if (getattr(args, "max_staleness_rev", 0) < 0
                or getattr(args, "max_staleness_ms", 0.0) < 0):
            raise SystemExit("--max-staleness-rev/--max-staleness-ms "
                             "must be >= 0 (0 = unbounded)")
    elif getattr(args, "leader_address", "") or getattr(args, "leader_info", ""):
        raise SystemExit("--leader-address/--leader-info require "
                         "--role follower")
    faults = getattr(args, "faults", "") or ""
    if faults:
        from .faults.schedule import PRESETS

        if faults not in PRESETS:
            raise SystemExit(
                f"--faults {faults!r} unknown; presets: {', '.join(PRESETS)}")
        if getattr(args, "fault_horizon_s", 1.0) <= 0:
            raise SystemExit("--fault-horizon-s must be > 0")


def build_endpoint(args):
    """Dependency wiring (reference KubeBrainOption.Run, option.go:230-259):
    storage → [metrics decorator] → backend → server → endpoint."""
    validate_args(args)
    # must happen before anything imports jax (embedding callers reach here
    # without going through main())
    apply_jax_platform(args.jax_platform)
    from .backend import Backend, BackendConfig
    from .endpoint import Endpoint, EndpointConfig
    from .metrics import new_metrics
    from .server import Server
    from .server.service import PeerService, SingleNodePeerService
    from .storage import new_storage
    from .util.net import get_host

    metrics = new_metrics(args.cluster_name)

    # arm the process tracer: stage histograms (kb_rpc_stage_seconds) flow
    # into this metrics sink, slow requests into the /debug/traces slow log
    from .trace import TRACER

    TRACER.configure(metrics=metrics,
                     slow_ms=getattr(args, "trace_slow_ms", 500.0))

    # chaos mode (docs/faults.md): build the deterministic fault plane.
    # INERT until /faults/arm — a --faults none (or never-armed) server is
    # byte-identical to a plain one by construction.
    fault_plane = None
    faults_preset = getattr(args, "faults", "") or ""
    if faults_preset and faults_preset != "none":
        from .faults import FaultPlane
        from .faults import generate as generate_faults

        fault_plane = FaultPlane(
            generate_faults(faults_preset, getattr(args, "fault_seed", 0),
                            getattr(args, "fault_horizon_s", 30.0)),
            metrics=metrics)

    native_kw = {"partitions": args.native_partitions}
    if getattr(args, "data_dir", ""):
        native_kw.update({"data_dir": args.data_dir, "fsync": args.fsync})
    if args.storage == "tpu":
        if args.inner_storage == "native":
            inner_kw = native_kw
        elif args.inner_storage == "remote":
            # the composed production topology: TPU data plane over the
            # shared kbstored tier (reference: scanner over TiKV partitions)
            inner_kw = {"address": args.storage_address, "pool": args.storage_pool,
                        "read_followers": args.storage_read_followers}
        else:
            inner_kw = {}
        if args.use_pallas:
            inner_kw["use_pallas"] = True
        if getattr(args, "key_encoding", ""):
            inner_kw["encode_keys"] = args.key_encoding == "encoded"
        if getattr(args, "merge_threshold", 0):
            inner_kw["merge_threshold"] = args.merge_threshold
        # multichip sharded serving (docs/multichip.md): an explicit mesh
        # flag builds the partition mesh HERE, so the flag errors surface at
        # boot, not on the first scan; no flags = today's every-device mesh
        mesh = None
        mesh_part = getattr(args, "mesh_part", 0)
        scan_parts = getattr(args, "scan_partitions", 0)
        if mesh_part or scan_parts:
            import jax

            from .parallel.mesh import make_mesh

            avail = len(jax.devices())
            if mesh_part > avail:
                raise SystemExit(
                    f"--mesh-part {mesh_part} exceeds the {avail} visible "
                    f"device(s); set XLA_FLAGS=--xla_force_host_platform_"
                    f"device_count for CPU simulation")
            mesh = make_mesh(n_devices=mesh_part or None)
            n_dev = int(mesh.devices.size)
            if scan_parts and scan_parts % n_dev:
                raise SystemExit(
                    f"--scan-partitions {scan_parts} must be a multiple of "
                    f"the mesh part-axis size {n_dev}")
        if fault_plane is not None:
            # wrap the INNER host engine so injected uncertainty poisons
            # (and quarantines) the device mirror like a real engine fault
            from .faults import FaultyStorage

            inner_kw["inner_wrap"] = (
                lambda s: FaultyStorage(s, fault_plane))
        store = new_storage("tpu", inner=args.inner_storage, mesh=mesh,
                            partitions=scan_parts, **inner_kw)
    elif args.storage == "native":
        store = new_storage("native", **native_kw)
    elif args.storage == "remote":
        store = new_storage(
            "remote", address=args.storage_address, pool=args.storage_pool,
            partitions=args.native_partitions,
            read_followers=args.storage_read_followers,
        )
    else:
        store = new_storage(args.storage)
    if fault_plane is not None and args.storage != "tpu":
        from .faults import FaultyStorage

        store = FaultyStorage(store, fault_plane)
    if args.enable_storage_metrics:
        from .storage.metrics_wrap import MetricsKvStorage

        store = MetricsKvStorage(store, metrics)

    fanout = None
    if args.tpu_fanout:
        # the fan-out mesh is independent of the scan mesh: the watcher
        # table is the large shardable side of the (E x W) product and
        # followers build one too (follower offload — fan-out capacity
        # scales with replicas, docs/watch.md)
        wat_mesh = None
        mesh_wat = getattr(args, "mesh_wat", 0)
        if mesh_wat:
            import jax

            from .parallel.mesh import make_mesh

            avail = len(jax.devices())
            if mesh_wat > avail:
                raise SystemExit(
                    f"--mesh-wat {mesh_wat} exceeds the {avail} visible "
                    f"device(s); set XLA_FLAGS=--xla_force_host_platform_"
                    f"device_count for CPU simulation")
            wat_mesh = make_mesh(n_devices=mesh_wat, axes=("wat",))
        if getattr(args, "fanout_impl", "block") == "legacy":
            from .ops.fanout import FanoutMatcher

            fanout = FanoutMatcher(mesh=wat_mesh)
        else:
            from .fanout import DeviceFanout

            fanout = DeviceFanout(mesh=wat_mesh)
        # kb_fanout_sharded: 1 when the table is really distributed
        fanout.set_metrics(metrics)

    backend = Backend(store, BackendConfig(
        prefix=args.prefix.encode(),
        skip_prefixes=[s.encode() for s in args.skip_prefixes.split(",") if s],
        watch_cache_capacity=args.watch_cache_size,
        enable_etcd_compatibility=not args.disable_etcd_compatibility,
        fanout_matcher=fanout,
    ))

    # watch-path lag instrumentation: commit->delivery histogram + per-
    # watcher backlog gauges on /metrics
    backend.watcher_hub.set_metrics(metrics)

    # uncertain-write repair observability: queue-depth gauge + per-outcome
    # repair counters (the chaos report reconciles against these)
    backend.retry.set_metrics(metrics)

    if fault_plane is not None:
        # bind the endpoint-level injections: the watch-reset daemon picks
        # victims from the hub; the TPU scanner gets the merge/encode
        # hooks; the gRPC front adds the conn-drop interceptor (endpoint
        # discovers the plane via backend._kb_faults)
        fault_plane.bind_hub(backend.watcher_hub)
        backend._kb_faults = fault_plane
        if hasattr(backend.scanner, "set_fault_plane"):
            backend.scanner.set_fault_plane(fault_plane)

    # per-shard HBM accounting (tpu engine): kb_mirror_bytes{device=}
    # scrape-time gauges off the live mirror (docs/multichip.md)
    if hasattr(backend.scanner, "register_metrics"):
        backend.scanner.register_metrics(metrics)

    # the device-aware request scheduler, created here (before any service
    # constructs a KVService) so every surface shares the flag-configured
    # instance with real metrics — later ensure_scheduler calls adopt it
    from .sched import SchedConfig, ensure_scheduler

    ensure_scheduler(backend, SchedConfig(
        depth=args.sched_depth,
        queue_limit=args.sched_queue_limit,
        shed_ms=args.sched_shed_ms,
        batch=args.sched_batch,
        write_batch=args.sched_write_batch,
    ), metrics=metrics)

    identity = args.identity or f"{get_host()}:{args.peer_port}"
    replica_role = None
    if getattr(args, "role", "leader") == "follower":
        # follower role (docs/replication.md): the role object IS the
        # peers surface (is_leader False, no-op revision sync) so every
        # existing service works unchanged, plus the per-RPC replica
        # routing the etcd terminals consult
        from .replica import FollowerConfig, FollowerRole

        leader_creds = None
        if args.ca_file:
            # a TLS-serving leader: verify it against the configured CA
            # on the forwarding + replication channels
            import grpc as _grpc

            with open(args.ca_file, "rb") as f:
                leader_creds = _grpc.ssl_channel_credentials(
                    root_certificates=f.read())
        replica_role = FollowerRole(
            backend,
            FollowerConfig(
                leader_address=args.leader_address,
                leader_info=args.leader_info,
                max_staleness_rev=getattr(args, "max_staleness_rev", 0),
                max_staleness_ms=getattr(args, "max_staleness_ms", 5000.0),
                fence_timeout_s=getattr(args, "fence_timeout_ms", 3000.0)
                / 1000.0,
                credentials=leader_creds,
            ),
            metrics=metrics, fault_plane=fault_plane, identity=identity)
        peers = replica_role
    elif args.single_node:
        peers = SingleNodePeerService(backend, identity)
    else:
        peers = PeerService(
            backend, identity, args.client_port, enable_proxy=args.enable_etcd_proxy
        )

    # lease subsystem: key-pattern TTLs demoted to a flag-gated fallback
    # (explicit PutRequest.lease always wins); registry + leader-only reaper
    # created here with the flag-derived cadences so every service surface
    # shares one table (later ensure_lease calls adopt it)
    from .backend import creator
    from .lease import ensure_lease

    creator.LEGACY_TTL_PATTERNS = bool(
        getattr(args, "legacy_ttl_patterns", True))
    ensure_lease(
        backend, peers=peers, metrics=metrics,
        reap_interval=args.lease_reap_interval,
        checkpoint_interval=args.lease_checkpoint_interval,
    )
    server = Server(
        backend, peers, metrics, identity,
        client_urls=[f"http://{identity.rsplit(':', 1)[0]}:{args.client_port}"],
        compact_interval=args.compact_interval,
        replica=replica_role,
    )
    extra_http = {}
    if fault_plane is not None:
        # chaos-runner control surface on the info port: arm aligns the
        # fault windows with replay start; state feeds the SLO report's
        # injected/observed reconciliation
        extra_http["/faults/arm"] = fault_plane.http_arm
        extra_http["/faults/state"] = fault_plane.http_state
    endpoint = Endpoint(server, metrics, EndpointConfig(
        host=args.host,
        client_port=args.client_port,
        peer_port=args.peer_port,
        info_port=args.info_port,
        cert_file=args.cert_file,
        key_file=args.key_file,
        ca_file=args.ca_file,
        insecure=not args.secure_only,
        grpc_workers=args.grpc_workers,
        extra_http=extra_http,
    ))
    if args.aio_port:
        from .endpoint.aio import AioEndpoint

        creds = None
        if args.cert_file and args.key_file:
            creds = endpoint._grpc_creds()
        aio = AioEndpoint(
            backend, peers, args.host, args.aio_port, identity,
            credentials=creds, insecure=not args.secure_only,
        )
        _orig_run, _orig_close = endpoint.run, endpoint.close

        def run_both():
            _orig_run()
            aio.run()

        def close_both(grace: float = 1.0):
            aio.close(grace)
            _orig_close(grace)

        endpoint.run = run_both
        endpoint.close = close_both
    if getattr(args, "front_port", 0):
        from .endpoint.front import FrontServer

        front = FrontServer(
            backend, peers, server, identity, metrics=metrics,
            brain=server.brain,
            inline_unary=args.storage != "remote",
        )
        _frun, _fclose = endpoint.run, endpoint.close

        def run_with_front():
            _frun()
            front.run(args.front_port, args.host,
                      cert_file=args.cert_file, key_file=args.key_file,
                      ca_file=args.ca_file, secure_only=args.secure_only)

        def close_with_front(grace: float = 1.0):
            front.close()
            _fclose(grace)

        endpoint.run = run_with_front
        endpoint.close = close_with_front
    if replica_role is not None:
        # start the replication stream once the listeners are up; stop it
        # (and the forwarding channel) before the backend goes away
        _rp_run, _rp_close = endpoint.run, endpoint.close

        def run_with_replica():
            _rp_run()
            replica_role.start()

        def close_with_replica(grace: float = 1.0):
            replica_role.close()
            _rp_close(grace)

        endpoint.run = run_with_replica
        endpoint.close = close_with_replica
    return endpoint, backend, store


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.version:
        print(f"kubebrain-tpu {__version__} (storage engines: memkv, tpu, native)")
        return 0

    # server-profile gc: the default thresholds collect every ~700
    # allocations — at informer fan-out scale (10k watch streams, 100k+
    # protobuf deliveries) collection pauses halved write throughput in the
    # config-5 sim. Protobufs/events are acyclic; raise the thresholds.
    # KB_GC_THRESHOLD=a[,b[,c]] overrides; 0 keeps Python defaults.
    gc_env = os.environ.get("KB_GC_THRESHOLD", "")
    if gc_env != "0":
        import gc

        try:
            parts = [int(x) for x in gc_env.split(",") if x.strip()]
        except ValueError:
            print(f"ignoring malformed KB_GC_THRESHOLD={gc_env!r}", file=sys.stderr)
            parts = []
        if not parts or any(p <= 0 for p in parts):
            # zero disables gc entirely; negatives crash set_threshold
            parts = [200_000, 1000, 1000]
        gc.set_threshold(*parts[:3])

    endpoint, backend, store = build_endpoint(args)
    if args.tier_auto_failover:
        if not endpoint.server.start_tier_watchdog():
            # an explicitly requested HA feature that cannot arm must not
            # be silently dropped (validate_args style)
            raise SystemExit(
                "--tier-auto-failover requires --storage=remote (or "
                "tpu-over-remote) with --storage-address primary,follower,...")
    stop = threading.Event()
    watchdog: list[threading.Timer] = []

    def _graceful_exit(signum, frame):  # noqa: ARG001
        # force-kill watchdog (reference forceExitWhileGracefulExitTimeout,
        # cmd/main.go:62): a wedged close must not block exit; budget covers
        # grpc drain + aio loop stop + engine checkpoint
        t = threading.Timer(10.0, lambda: os._exit(2))
        t.daemon = True
        t.start()
        watchdog.append(t)
        stop.set()

    signal.signal(signal.SIGINT, _graceful_exit)
    signal.signal(signal.SIGTERM, _graceful_exit)

    endpoint.run()
    print(
        f"kubebrain-tpu {__version__} serving: etcd3+brain gRPC :{args.client_port}, "
        f"peer http :{args.peer_port}, info http :{args.info_port} "
        f"(storage={args.storage})",
        file=sys.stderr,
    )
    stop.wait()
    endpoint.close()
    backend.close()
    store.close()
    for t in watchdog:
        t.cancel()
    return 0


if __name__ == "__main__":
    sys.exit(main())
