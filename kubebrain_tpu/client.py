"""Client library — the kubebrain-client module role (SURVEY §2.7).

Two surfaces, matching the server:

- ``EtcdCompatClient`` speaks the etcd3 subset (what kube-apiserver uses) and
  adds the custom-apiserver extensions the reference supports: partition
  borders via the magic revision (kv.go:33) and **partition-parallel
  listing** over the list-over-watch stream protocol (negative start
  revision, watch.go:150-152,204) — each partition streams concurrently,
  the client merges in key order (SURVEY §5c);
- ``BrainClient`` speaks the lean native protocol (Create/Update/Delete/
  Compact/Get/Range/RangeStream/Count/ListPartition/Watch).

No generated stubs: raw grpc channels + the protos in kubebrain_tpu.proto.
"""

from __future__ import annotations

import collections
import queue
import threading
import time
from dataclasses import dataclass
from typing import Callable, Iterable, Iterator

import grpc

from .proto import brain_pb2, kv_pb2, rpc_pb2
from .trace import make_traceparent

PARTITION_MAGIC_REVISION = 1888

# --------------------------------------------------------- retry classification
#
# The safe-vs-ambiguous discipline (docs/faults.md): a write RPC may only be
# retried when its failure provably means NOTHING was applied. The server
# splits its status codes for exactly this (docs/writes.md):
#
# - RESOURCE_EXHAUSTED      — admission shed BEFORE a revision was dealt;
# - "etcdserver:"-prefixed UNAVAILABLE — processed-and-refused (drift,
#   storage fault refusal, not-leader): the handler ran and definitively
#   declined;
# - DEADLINE_EXCEEDED / CANCELLED / UNKNOWN / bare UNAVAILABLE — the op may
#   have committed (result-wait timeout, engine uncertainty, connection
#   drop mid-call): NEVER blind-retry a non-idempotent write here — a
#   retried create/update that already landed reports a spurious conflict.
#
# Reads are idempotent: every failure is safe to retry.

#: deterministic refusals: provably nothing applied AND re-sending the
#: identical request cannot change the answer (bad lease, compacted
#: revision, unsupported shape, auth) — retrying is pure waste
_DETERMINISTIC_CODES = frozenset({
    grpc.StatusCode.NOT_FOUND,
    grpc.StatusCode.OUT_OF_RANGE,
    grpc.StatusCode.UNIMPLEMENTED,
    grpc.StatusCode.INVALID_ARGUMENT,
    grpc.StatusCode.FAILED_PRECONDITION,
    grpc.StatusCode.PERMISSION_DENIED,
    grpc.StatusCode.UNAUTHENTICATED,
})


def classify_rpc_error(err: grpc.RpcError, write: bool) -> str:
    """``"safe"`` (definitely not applied — a retry may succeed),
    ``"definite"`` (definitely not applied — retrying the identical
    request is pointless), or ``"ambiguous"`` (maybe applied — never
    blind-retry a write). Reads are never worse than ``"safe"``."""
    code = err.code() if hasattr(err, "code") else None
    if code in _DETERMINISTIC_CODES:
        return "definite"
    if not write:
        return "safe"
    if code == grpc.StatusCode.RESOURCE_EXHAUSTED:
        # admission shed BEFORE a revision was dealt: a backed-off retry
        # lands in capacity that may have freed up
        return "safe"
    details = err.details() if hasattr(err, "details") else ""
    if code == grpc.StatusCode.UNAVAILABLE and "etcdserver:" in (details or ""):
        # server-side transient refusal (drift / not-leader / storage-fault
        # refusal): the handler answered, nothing was applied, and the
        # condition clears (fresh revision, new leader, fault window ends)
        return "safe"
    return "ambiguous"


class _RetryingCall:
    """Bounded, jitter-backoff retry around a unary call, gated by the
    safe-vs-ambiguous classification — an ambiguous write failure is NEVER
    retried (it surfaces to the caller, who owns the read-back). Attempts
    beyond the first are counted in ``counter[method]`` so harnesses that
    reconcile client RPC counts against server /metrics stay exact."""

    __slots__ = ("_call", "_write", "_retries", "_backoff", "_method",
                 "_counter")

    def __init__(self, call, write: bool, retries: int, backoff_s: float,
                 method: str, counter):
        self._call = call
        self._write = write
        self._retries = retries
        self._backoff = backoff_s
        self._method = method
        self._counter = counter

    def __call__(self, request, timeout=None, metadata=None):
        import random

        attempt = 0
        while True:
            try:
                return self._call(request, timeout=timeout, metadata=metadata)
            except grpc.RpcError as e:
                attempt += 1
                if (attempt > self._retries
                        or classify_rpc_error(e, self._write) != "safe"):
                    raise
                if self._counter is not None:
                    self._counter[self._method] += 1
                time.sleep(self._backoff * attempt
                           * random.uniform(0.5, 1.5))

    def future(self, request, timeout=None, metadata=None):
        # the pipelined path manages its own windows; no transparent retry
        return self._call.future(request, timeout=timeout, metadata=metadata)


class _TracedCall:
    """Wraps a grpc multicallable so every invocation carries a W3C
    ``traceparent`` metadata entry — the server parents its span tree under
    it, so a client-observed slow call is findable in ``/debug/traces`` by
    trace id. Continues the ambient span's trace when the caller is itself
    inside one. ``future`` (unary multicallables only) is the pipelined
    path bulk helpers use to keep a window of RPCs in flight on one
    channel."""

    __slots__ = ("_call",)

    def __init__(self, callable_):
        self._call = callable_

    @staticmethod
    def _md(metadata):
        return tuple(metadata or ()) + (("traceparent", make_traceparent()),)

    def __call__(self, request, timeout=None, metadata=None):
        return self._call(request, timeout=timeout, metadata=self._md(metadata))

    def future(self, request, timeout=None, metadata=None):
        return self._call.future(
            request, timeout=timeout, metadata=self._md(metadata))


def _traced_call(callable_):
    return _TracedCall(callable_)


class _FailoverCall:
    """One logical unary method over several endpoints (docs/replication.md):
    round-robin selection per call, with SAFE-ONLY failover — an attempt
    whose failure classifies ``safe`` (provably nothing applied: replica
    staleness refusal, admission shed, not-leader) moves on to the next
    endpoint; ``ambiguous`` and ``definite`` failures surface immediately,
    exactly like a single-endpoint call. Extra attempts land in
    ``client.endpoint_failovers`` (the kb_client_endpoint_failovers count
    the workload harness surfaces), and every successful response's header
    revision is recorded per endpoint — the harness's
    response-revision <= applied-watermark reconcile reads it."""

    __slots__ = ("_client", "_calls", "_targets", "_write", "_method")

    def __init__(self, client, calls, targets, write: bool, method: str):
        self._client = client
        self._calls = calls
        self._targets = targets
        self._write = write
        self._method = method

    def __call__(self, request, timeout=None, metadata=None):
        n = len(self._calls)
        start = self._client._next_endpoint()
        last: grpc.RpcError | None = None
        for k in range(n):
            i = (start + k) % n
            try:
                resp = self._calls[i](request, timeout=timeout,
                                      metadata=metadata)
            except grpc.RpcError as e:
                last = e
                if k == n - 1 or classify_rpc_error(e, self._write) != "safe":
                    raise
                self._client._note_failover(self._method)
                continue
            self._client._note_endpoint_revision(self._targets[i], resp)
            return resp
        raise last  # unreachable; keeps the contract explicit

    def future(self, request, timeout=None, metadata=None):
        # pipelined bulk paths manage their own windows; no failover
        i = self._client._next_endpoint() % len(self._calls)
        return self._calls[i].future(request, timeout=timeout,
                                     metadata=metadata)


class _RotatingStreamCall:
    """Stream multicallable over several endpoints: each stream OPEN picks
    the next endpoint round-robin. Failover for streams is the consumer's
    re-open (WatchMux revive opens a fresh stream → next endpoint)."""

    __slots__ = ("_client", "_calls")

    def __init__(self, client, calls):
        self._client = client
        self._calls = calls

    def __call__(self, request_iterator):
        i = self._client._next_endpoint() % len(self._calls)
        return self._calls[i](request_iterator)


@dataclass
class ClientKV:
    key: bytes
    value: bytes
    mod_revision: int


class EtcdCompatClient:
    def __init__(self, target: str | list[str] | tuple[str, ...] | None = None,
                 credentials: grpc.ChannelCredentials | None = None,
                 retries: int = 0, retry_backoff_s: float = 0.05,
                 endpoints: list[str] | None = None):
        """``retries`` > 0 arms transparent retry of SAFE failures only
        (classify_rpc_error): reads retry on anything, writes only on
        provably-not-applied refusals — an ambiguous write outcome always
        surfaces. ``self.retries_sent`` counts the extra attempts per
        method (harnesses add them to their reconcile counts).

        Multi-endpoint mode (``endpoints=[...]`` or a list ``target``):
        one channel per endpoint, unary calls round-robin across them
        with SAFE-ONLY failover to the next endpoint (a replica staleness
        refusal or not-leader moves on; an ambiguous write failure never
        does) — ``self.endpoint_failovers`` counts the extra attempts,
        and ``self.max_header_revision[endpoint]`` tracks the highest
        response revision each endpoint served (the replica harness's
        revision-consistency reconcile). Streams (Watch/LeaseKeepAlive)
        pick an endpoint per stream open."""
        if endpoints is None and not isinstance(target, str):
            endpoints = list(target or ())
        if endpoints is not None:
            eps = [e for e in endpoints if e]
            if not eps:
                raise ValueError("endpoints must name at least one target")
        else:
            eps = [target]
        self._endpoints = eps
        self._multi = endpoints is not None
        mk = (lambda t: grpc.secure_channel(t, credentials)) if credentials \
            else grpc.insecure_channel
        self.channels = [mk(t) for t in eps]
        self.channel = self.channels[0]  # single-endpoint back-compat
        self._retry_budget = retries
        self._retry_backoff_s = retry_backoff_s
        self.retries_sent: collections.Counter = collections.Counter()
        #: safe-only endpoint failovers (kb_client_endpoint_failovers)
        self.endpoint_failovers = 0
        self.failovers_by_method: collections.Counter = collections.Counter()
        #: endpoint -> highest response header revision it served
        self.max_header_revision: dict[str, int] = {}
        self._ep_lock = threading.Lock()
        self._ep_rr = 0
        p = rpc_pb2
        self._range = self._unary("/etcdserverpb.KV/Range", p.RangeRequest, p.RangeResponse)
        #: per-endpoint Range callables for snapshot-pinned pagination
        #: (list()): later pages MUST stay on the endpoint that pinned
        #: page 1's revision — a different replica may not have applied
        #: that revision yet (or may have a higher compact floor)
        self._range_per_ep = [
            _RetryingCall(call, False, retries, retry_backoff_s,
                          "/etcdserverpb.KV/Range", self.retries_sent)
            if retries > 0 else call
            for call in (
                _traced_call(ch.unary_unary(
                    "/etcdserverpb.KV/Range",
                    request_serializer=p.RangeRequest.SerializeToString,
                    response_deserializer=p.RangeResponse.FromString,
                ))
                for ch in self.channels
            )
        ] if self._multi else None
        self._txn = self._unary("/etcdserverpb.KV/Txn", p.TxnRequest, p.TxnResponse,
                                write=True)
        self._compact = self._unary("/etcdserverpb.KV/Compact", p.CompactionRequest, p.CompactionResponse,
                                    write=True)
        self._watch = self._stream(
            "/etcdserverpb.Watch/Watch", p.WatchRequest, p.WatchResponse)
        self._lease_grant = self._unary(
            "/etcdserverpb.Lease/LeaseGrant", p.LeaseGrantRequest, p.LeaseGrantResponse,
            write=True)
        self._lease_revoke = self._unary(
            "/etcdserverpb.Lease/LeaseRevoke", p.LeaseRevokeRequest, p.LeaseRevokeResponse,
            write=True)
        self._lease_ttl = self._unary(
            "/etcdserverpb.Lease/LeaseTimeToLive",
            p.LeaseTimeToLiveRequest, p.LeaseTimeToLiveResponse)
        self._lease_leases = self._unary(
            "/etcdserverpb.Lease/LeaseLeases", p.LeaseLeasesRequest, p.LeaseLeasesResponse)
        self._lease_keepalive = self._stream(
            "/etcdserverpb.Lease/LeaseKeepAlive",
            p.LeaseKeepAliveRequest, p.LeaseKeepAliveResponse)

    # ------------------------------------------------- endpoint selection
    def _next_endpoint(self) -> int:
        with self._ep_lock:
            i = self._ep_rr
            self._ep_rr += 1
            return i

    def _note_failover(self, method: str) -> None:
        with self._ep_lock:
            self.endpoint_failovers += 1
            self.failovers_by_method[method] += 1

    def _note_endpoint_revision(self, target: str, resp) -> None:
        header = getattr(resp, "header", None)
        rev = int(getattr(header, "revision", 0) or 0)
        if not rev:
            return
        with self._ep_lock:
            if rev > self.max_header_revision.get(target, 0):
                self.max_header_revision[target] = rev

    def _unary(self, method, req, resp, write: bool = False):
        calls = [
            _traced_call(ch.unary_unary(
                method,
                request_serializer=req.SerializeToString,
                response_deserializer=resp.FromString,
            ))
            for ch in self.channels
        ]
        if self._multi:
            call = _FailoverCall(self, calls, self._endpoints, write, method)
        else:
            call = calls[0]
        if self._retry_budget > 0:
            call = _RetryingCall(call, write, self._retry_budget,
                                 self._retry_backoff_s, method,
                                 self.retries_sent)
        return call

    def _stream(self, method, req, resp):
        calls = [
            _traced_call(ch.stream_stream(
                method,
                request_serializer=req.SerializeToString,
                response_deserializer=resp.FromString,
            ))
            for ch in self.channels
        ]
        if self._multi:
            return _RotatingStreamCall(self, calls)
        return calls[0]

    # --------------------------------------------------------------- writes
    @staticmethod
    def _create_txn(key: bytes, value: bytes, lease: int = 0) -> rpc_pb2.TxnRequest:
        req = rpc_pb2.TxnRequest()
        c = req.compare.add()
        c.result, c.target, c.key, c.mod_revision = (
            rpc_pb2.Compare.EQUAL, rpc_pb2.Compare.MOD, key, 0,
        )
        req.success.add().request_put.CopyFrom(
            rpc_pb2.PutRequest(key=key, value=value, lease=lease))
        req.failure.add().request_range.CopyFrom(rpc_pb2.RangeRequest(key=key))
        return req

    @staticmethod
    def _parse_put_txn(r) -> tuple[bool, int]:
        if r.succeeded:
            return True, r.responses[0].response_put.header.revision
        kvs = r.responses[0].response_range.kvs
        return False, kvs[0].mod_revision if kvs else 0

    def create(self, key: bytes, value: bytes, lease: int = 0) -> tuple[bool, int]:
        """(succeeded, revision) — revision is the new mod revision on
        success, the existing one on conflict. ``lease`` attaches the key
        to a granted lease (see :meth:`lease`)."""
        return self._parse_put_txn(self._txn(self._create_txn(key, value, lease)))

    def create_bulk(self, items: Iterable[tuple[bytes, bytes]], lease: int = 0,
                    window: int = 128) -> list[tuple[bool, int]]:
        """Pipelined creates: up to ``window`` Txn futures in flight on one
        channel, results in input order. This is the preload path of the
        workload replay harness — a sequential create() loop is bounded by
        one RTT per key, the future window by the server's commit rate."""
        out: list[tuple[bool, int]] = []
        pending: collections.deque = collections.deque()
        for key, value in items:
            if len(pending) >= window:
                out.append(self._parse_put_txn(pending.popleft().result()))
            pending.append(self._txn.future(self._create_txn(key, value, lease)))
        while pending:
            out.append(self._parse_put_txn(pending.popleft().result()))
        return out

    def update(self, key: bytes, value: bytes, mod_revision: int,
               lease: int = 0) -> tuple[bool, int]:
        req = rpc_pb2.TxnRequest()
        c = req.compare.add()
        c.result, c.target, c.key, c.mod_revision = (
            rpc_pb2.Compare.EQUAL, rpc_pb2.Compare.MOD, key, mod_revision,
        )
        req.success.add().request_put.CopyFrom(
            rpc_pb2.PutRequest(key=key, value=value, lease=lease))
        req.failure.add().request_range.CopyFrom(rpc_pb2.RangeRequest(key=key))
        r = self._txn(req)
        if r.succeeded:
            return True, r.responses[0].response_put.header.revision
        kvs = r.responses[0].response_range.kvs
        return False, kvs[0].mod_revision if kvs else 0

    def delete(self, key: bytes, mod_revision: int = 0) -> bool:
        req = rpc_pb2.TxnRequest()
        c = req.compare.add()
        c.result, c.target, c.key, c.mod_revision = (
            rpc_pb2.Compare.EQUAL, rpc_pb2.Compare.MOD, key, mod_revision,
        )
        if mod_revision == 0:
            got = self.get(key)
            if got is None:
                return False
            c.mod_revision = got.mod_revision
        req.success.add().request_delete_range.CopyFrom(rpc_pb2.DeleteRangeRequest(key=key))
        req.failure.add().request_range.CopyFrom(rpc_pb2.RangeRequest(key=key))
        return self._txn(req).succeeded

    def compact(self, revision: int) -> None:
        self._compact(rpc_pb2.CompactionRequest(revision=revision))

    # ---------------------------------------------------------------- reads
    def get(self, key: bytes, revision: int = 0,
            serializable: bool = False) -> ClientKV | None:
        r = self._range(rpc_pb2.RangeRequest(key=key, revision=revision,
                                             serializable=serializable))
        if not r.kvs:
            return None
        kv = r.kvs[0]
        return ClientKV(kv.key, kv.value, kv.mod_revision)

    def list(
        self, start: bytes, end: bytes, revision: int = 0, limit: int = 0,
        page: int = 1000, stats: dict | None = None,
        serializable: bool = False,
    ) -> tuple[list[ClientKV], int]:
        """Paginated list; returns (kvs, list_revision). ``stats`` (if
        given) has its ``"rpcs"`` entry incremented per Range RPC *issued*
        (before the call, so shed/errored pages are still counted) — the
        workload harness reconciles client-side RPC counts against the
        server's /metrics, which counts failed RPCs too, and pagination
        makes ops != RPCs. ``serializable`` marks the read bounded-
        staleness-tolerant: a replica serves it locally at its applied
        watermark instead of fencing on the leader (docs/replication.md);
        later pages pin the first page's snapshot revision either way.

        Multi-endpoint clients pin the whole pagination to ONE endpoint:
        once page 1 pinned a snapshot revision, a different replica may
        not have applied it yet (bounded wait then a future-revision
        refusal) or may have compacted/bootstrapped above it — so only
        the FIRST page fails over (safe-classified errors rotate to the
        next endpoint, counted in ``endpoint_failovers``)."""
        out: list[ClientKV] = []
        key = start
        list_rev = revision
        if self._range_per_ep is not None:
            n = len(self._range_per_ep)
            ep = self._next_endpoint() % n
        first_attempts = 0
        while True:
            want = min(page, limit - len(out)) if limit else page
            if stats is not None:
                stats["rpcs"] = stats.get("rpcs", 0) + 1
            req = rpc_pb2.RangeRequest(
                key=key, range_end=end, revision=list_rev, limit=want,
                serializable=serializable,
            )
            if self._range_per_ep is None:
                r = self._range(req)
            else:
                try:
                    r = self._range_per_ep[ep](req)
                except grpc.RpcError as e:
                    first_attempts += 1
                    if (not out and list_rev == revision
                            and first_attempts < n
                            and classify_rpc_error(e, False) == "safe"):
                        # nothing pinned yet: rotate like _FailoverCall.
                        # endpoint_failovers only — the retried page is
                        # already counted in the caller's stats["rpcs"],
                        # so failovers_by_method (which reconciles as an
                        # EXTRA server-side RPC) must not count it twice
                        ep = (ep + 1) % n
                        with self._ep_lock:
                            self.endpoint_failovers += 1
                        continue
                    raise
                self._note_endpoint_revision(self._endpoints[ep], r)
            if list_rev == 0:
                list_rev = r.header.revision  # pin the snapshot for later pages
            out.extend(ClientKV(kv.key, kv.value, kv.mod_revision) for kv in r.kvs)
            if not r.more or (limit and len(out) >= limit):
                return out, list_rev
            key = r.kvs[-1].key + b"\x00"

    def list_unpaged(
        self, start: bytes, end: bytes, revision: int = 0,
        serializable: bool = False,
    ) -> tuple[list[ClientKV], int]:
        """One unpaged Range (limit=0) — the informer-relist/snapshot shape
        the scheduler classifies BACKGROUND. ``list()`` always pages and so
        always rides the NORMAL lane; replaying realistic relist storms
        needs the heavyweight shape on the wire."""
        r = self._range(rpc_pb2.RangeRequest(
            key=start, range_end=end, revision=revision,
            serializable=serializable))
        return ([ClientKV(kv.key, kv.value, kv.mod_revision) for kv in r.kvs],
                r.header.revision)

    def count(self, start: bytes, end: bytes,
              serializable: bool = False) -> int:
        r = self._range(rpc_pb2.RangeRequest(key=start, range_end=end,
                                             count_only=True,
                                             serializable=serializable))
        return r.count

    def current_revision(self) -> int:
        """The server's committed revision (one linearizable empty-count
        Range) — the replica harness's fence-probe anchor."""
        return self._range(rpc_pb2.RangeRequest(
            key=b"\x00kb-probe", range_end=b"\x00kb-probe0",
            count_only=True)).header.revision

    def partition_borders(self, start: bytes, end: bytes) -> list[bytes]:
        """Storage partition borders (magic revision; reference kv.go:33)."""
        r = self._range(rpc_pb2.RangeRequest(
            key=start, range_end=end, revision=PARTITION_MAGIC_REVISION
        ))
        return [kv.key for kv in r.kvs]

    def parallel_list(
        self, start: bytes, end: bytes, revision: int = 0
    ) -> Iterator[ClientKV]:
        """Partition-parallel listing: one list-over-watch stream per
        partition, all concurrent, yielded in key order (the scale trick the
        reference's custom apiserver uses for huge ranges, SURVEY §5c)."""
        borders = self.partition_borders(start, end)
        if len(borders) < 2:
            kvs, _ = self.list(start, end, revision)
            yield from kvs
            return
        rev = revision or self._range(
            rpc_pb2.RangeRequest(key=start, range_end=end, limit=1)
        ).header.revision
        parts = list(zip(borders[:-1], borders[1:]))
        results: list[list[ClientKV] | None] = [None] * len(parts)

        def fetch(i, lo, hi):
            results[i] = list(self._stream_partition(lo, hi, rev))

        threads = [
            threading.Thread(target=fetch, args=(i, lo, hi), daemon=True)
            for i, (lo, hi) in enumerate(parts)
        ]
        for t in threads:
            t.start()
        for i, t in enumerate(threads):
            t.join()
            yield from results[i]  # partitions are key-ordered

    def _stream_partition(self, lo: bytes, hi: bytes, revision: int):
        """One list-over-watch range stream (negative start revision)."""
        requests: queue.Queue = queue.Queue()
        req = rpc_pb2.WatchRequest()
        req.create_request.key = lo
        req.create_request.range_end = hi
        req.create_request.start_revision = -revision
        requests.put(req)
        responses = self._watch(iter(requests.get, None))
        try:
            for resp in responses:
                for ev in resp.events:
                    yield ClientKV(ev.kv.key, ev.kv.value, ev.kv.mod_revision)
                if resp.canceled:
                    return
        finally:
            requests.put(None)

    # ---------------------------------------------------------------- leases
    def lease_grant(self, ttl: int, lease_id: int = 0) -> tuple[int, int]:
        """Grant a lease; returns (id, granted_ttl_seconds)."""
        r = self._lease_grant(rpc_pb2.LeaseGrantRequest(TTL=ttl, ID=lease_id))
        return r.ID, r.TTL

    def lease_revoke(self, lease_id: int) -> None:
        """Revoke: every attached key is deleted (watch-visible tombstones)."""
        self._lease_revoke(rpc_pb2.LeaseRevokeRequest(ID=lease_id))

    def lease_time_to_live(self, lease_id: int, keys: bool = False
                           ) -> tuple[int, int, list[bytes]]:
        """(remaining_ttl, granted_ttl, attached_keys). remaining_ttl is -1
        once the lease is expired or unknown."""
        r = self._lease_ttl(rpc_pb2.LeaseTimeToLiveRequest(ID=lease_id, keys=keys))
        return r.TTL, r.grantedTTL, list(r.keys)

    def lease_leases(self) -> list[int]:
        return [l.ID for l in self._lease_leases(rpc_pb2.LeaseLeasesRequest()).leases]

    def lease(self, ttl: int, keepalive_interval: float | None = None,
              ready_timeout: float = 30.0) -> "LeaseHandle":
        """Grant a lease and keep it alive from a background thread.

        The thread pings on a jittered cadence (default TTL/3 ±20% — a
        fleet of clients granted in the same instant must not land their
        keepalives in the same instant forever). Like :meth:`watch`, the
        first keepalive ack is fenced by a stack-dumping watchdog: if the
        server doesn't ack within ``ready_timeout`` every thread's stack is
        dumped and the stream cancelled, instead of a silent wedge that
        surfaces minutes later as an expired lease."""
        lease_id, granted = self.lease_grant(ttl)
        interval = keepalive_interval if keepalive_interval is not None \
            else max(granted / 3.0, 0.5)
        return LeaseHandle(self, lease_id, granted, interval, ready_timeout)

    # ---------------------------------------------------------------- watch
    def watch(
        self, key: bytes, range_end: bytes = b"", start_revision: int = 0,
        prev_kv: bool = False, ready_timeout: float = 30.0,
    ):
        """Returns (events_iterator, cancel_fn). Events are (type, ClientKV,
        prev ClientKV|None) tuples; the iterator ends on cancel.

        Blocks until the server acks registration (``created=True``):
        without the ack, a write issued right after watch() returns races
        the server-side ``watch_range`` registration — with start_revision
        0 there is no replay, so the event is silently missed and the
        caller waits forever (the intermittent test_client crud_watch
        wedge). A watchdog dumps every thread's stack and cancels the
        stream if the ack doesn't arrive within ``ready_timeout``."""
        requests: queue.Queue = queue.Queue()
        req = rpc_pb2.WatchRequest()
        req.create_request.key = key
        req.create_request.range_end = range_end
        req.create_request.start_revision = start_revision
        req.create_request.prev_kv = prev_kv
        requests.put(req)
        responses = self._watch(iter(requests.get, None))
        rpc_error = grpc.RpcError  # closure-bound: survives module teardown

        ack_lock = threading.Lock()
        acked = False
        fired = False

        def _ack_watchdog():
            nonlocal fired
            import faulthandler
            import sys

            with ack_lock:
                if acked:
                    return  # ack won the race with the timer firing
                fired = True
            sys.__stderr__.write(
                f"[client.watch] no created ack within {ready_timeout}s; "
                "dumping all thread stacks and cancelling the stream\n")
            faulthandler.dump_traceback(file=sys.__stderr__)
            sys.__stderr__.flush()
            responses.cancel()

        pending: list = []  # event-bearing responses seen before the ack
        watchdog = threading.Timer(ready_timeout, _ack_watchdog)
        watchdog.daemon = True
        watchdog.start()
        try:
            for resp in responses:
                with ack_lock:
                    acked = True  # any server response proves liveness
                if resp.events or resp.canceled:
                    # events()/the caller must still see these
                    pending.append(resp)
                if resp.created or resp.canceled:
                    break
        except rpc_error as e:
            raise TimeoutError(
                "watch registration not acked by server "
                f"(stream error: {e})") from e
        finally:
            watchdog.cancel()
        with ack_lock:
            if fired:
                # the timer cancelled the stream just as the ack landed:
                # the watch is dead, surface it instead of silently ending
                raise TimeoutError(
                    "watch stream cancelled by the registration watchdog")

        import itertools

        def events():
            try:
                for resp in itertools.chain(pending, responses):
                    if resp.canceled:
                        return
                    for ev in resp.events:
                        kind = "DELETE" if ev.type == kv_pb2.Event.DELETE else "PUT"
                        prev = (
                            ClientKV(ev.prev_kv.key, ev.prev_kv.value, ev.prev_kv.mod_revision)
                            if ev.HasField("prev_kv")
                            else None
                        )
                        yield kind, ClientKV(ev.kv.key, ev.kv.value, ev.kv.mod_revision), prev
            except rpc_error:
                return

        def cancel():
            requests.put(None)

        return events(), cancel

    def close(self) -> None:
        for ch in self.channels:
            ch.close()


class LeaseHandle:
    """A granted lease plus its background keepalive thread (see
    EtcdCompatClient.lease). ``alive`` flips False once the server reports
    the lease gone (TTL<=0 on the keepalive stream) or the stream dies."""

    def __init__(self, client: EtcdCompatClient, lease_id: int, ttl: int,
                 interval: float, ready_timeout: float):
        self.id = lease_id
        self.ttl = ttl
        self._interval = interval
        self._stop = threading.Event()
        self._expired = threading.Event()
        self._requests: queue.Queue = queue.Queue()
        self._responses = client._lease_keepalive(iter(self._requests.get, None))
        self._client = client
        self._rpc_error = grpc.RpcError  # closure-bound, survives teardown

        # first ping under the watchdog: prove the stream is live before
        # handing back a handle the caller will trust for TTL seconds
        fired = [False]
        done = [False]
        lock = threading.Lock()

        def _ack_watchdog():
            import faulthandler
            import sys

            with lock:
                if done[0]:
                    return
                fired[0] = True
            sys.__stderr__.write(
                f"[client.lease] no keepalive ack within {ready_timeout}s; "
                "dumping all thread stacks and cancelling the stream\n")
            faulthandler.dump_traceback(file=sys.__stderr__)
            sys.__stderr__.flush()
            self._responses.cancel()

        watchdog = threading.Timer(ready_timeout, _ack_watchdog)
        watchdog.daemon = True
        watchdog.start()
        try:
            self._ping()
        except (self._rpc_error, StopIteration) as e:
            raise TimeoutError(
                f"lease keepalive stream not acked by server: {e}") from e
        finally:
            with lock:
                done[0] = True
            watchdog.cancel()
        if fired[0]:
            raise TimeoutError(
                "lease keepalive stream cancelled by the registration watchdog")

        self._thread = threading.Thread(
            target=self._keepalive_loop, name="kb-lease-keepalive", daemon=True)
        self._thread.start()

    def _ping(self) -> int:
        self._requests.put(rpc_pb2.LeaseKeepAliveRequest(ID=self.id))
        resp = next(self._responses)
        if resp.TTL <= 0:
            self._expired.set()
        return resp.TTL

    def _keepalive_loop(self) -> None:
        import random

        while not self._stop.wait(self._interval * random.uniform(0.8, 1.2)):
            try:
                if self._ping() <= 0:
                    return  # lease gone server-side; don't spin on a corpse
            except (self._rpc_error, StopIteration):
                if not self._stop.is_set():
                    self._expired.set()
                return

    @property
    def alive(self) -> bool:
        return not self._expired.is_set()

    def revoke(self) -> None:
        """Stop keepalives and revoke: attached keys are deleted now."""
        self.close()
        self._client.lease_revoke(self.id)

    def close(self) -> None:
        """Stop keepalives; the lease then expires naturally server-side."""
        self._stop.set()
        self._requests.put(None)
        if self._thread.is_alive():
            self._thread.join(timeout=5.0)


class MuxWatch:
    """One multiplexed watch (see :class:`WatchMux`): the server-assigned
    watch id plus reader-thread-maintained delivery counters. With resume
    armed, ``resumes`` counts server-side stream resets this watch
    survived (re-registered from last-delivered revision + 1);
    ``cancelled`` then only flips on TERMINAL cancels (compaction — the
    client must re-list)."""

    __slots__ = ("key", "range_end", "start_revision", "watch_id", "events",
                 "cancelled", "last_revision", "ready", "resumes",
                 "revisions", "baselined", "stream", "prev_kv", "sink")

    def __init__(self, key: bytes, range_end: bytes, start_revision: int = 0,
                 record: bool = False, prev_kv: bool = False, sink=None):
        self.key = key
        self.range_end = range_end
        self.start_revision = start_revision
        self.watch_id = -1
        self.events = 0
        self.cancelled = False
        self.last_revision = 0
        self.baselined = False  # watermark anchored at the created ack
        self.ready = threading.Event()
        self.resumes = 0
        self.revisions: list[int] | None = [] if record else None
        #: the stream currently carrying this watch — revive uses it to
        #: decide ownership, so one watch can never be re-registered on
        #: two live streams (set by _send_create)
        self.stream: object | None = None
        #: request prev_kv on (re-)registration (replication needs delete
        #: fidelity for the follower's own watchers)
        self.prev_kv = prev_kv
        #: optional delivery callback ``sink(events, header_revision)``,
        #: invoked on the reader thread IN ORDER — event batches with the
        #: wire events, progress marks with an empty tuple. The follower
        #: replication stream is the consumer (docs/replication.md).
        self.sink = sink

    def resume_revision(self) -> int:
        """Where a re-registration must start so no event is lost or
        duplicated: one past the delivery watermark (last delivered batch,
        or the registration revision the created ack baselined), or the
        original start when neither exists yet."""
        if self.last_revision or self.baselined:
            return self.last_revision + 1
        return self.start_revision


class _WatchMuxStream:
    """One Watch stream carrying many watches. The server's read loop
    handles create requests strictly in order, so created acks match the
    pending-add FIFO; event batches demux by ``watch_id``."""

    def __init__(self, client: "EtcdCompatClient", mux: "WatchMux | None" = None):
        self._requests: queue.Queue = queue.Queue()
        self._responses = client._watch(iter(self._requests.get, None))
        self._lock = threading.Lock()
        self._pending: collections.deque[MuxWatch] = collections.deque()
        self._by_id: dict[int, MuxWatch] = {}
        self._mux = mux
        self.dead = False
        self.closing = False
        self._reader = threading.Thread(
            target=self._read_loop, name="kb-watchmux", daemon=True)
        self._reader.start()

    def _send_create(self, w: MuxWatch, start_revision: int) -> None:
        """Append + send under one lock: concurrent adds must hit the wire
        in pending-FIFO order or created acks mismatch. Raises if the
        stream is already dead."""
        req = rpc_pb2.WatchRequest()
        req.create_request.key = w.key
        req.create_request.range_end = w.range_end
        req.create_request.start_revision = start_revision
        req.create_request.prev_kv = w.prev_kv
        with self._lock:
            if self.dead:
                raise TimeoutError("watch mux stream is dead")
            w.stream = self
            self._pending.append(w)
            self._requests.put(req)

    def request_progress(self) -> None:
        """Ask the server for ordered per-watch progress marks (bare
        headers carrying the fully-flushed floor, delivered through each
        watch's own queue so they cannot overtake owed events)."""
        req = rpc_pb2.WatchRequest()
        req.progress_request.SetInParent()
        with self._lock:
            if self.dead:
                return
            self._requests.put(req)

    def add(self, key: bytes, range_end: bytes, start_revision: int,
            timeout: float, record: bool = False, prev_kv: bool = False,
            sink=None) -> MuxWatch:
        w = MuxWatch(key, range_end, start_revision, record=record,
                     prev_kv=prev_kv, sink=sink)
        self._send_create(w, start_revision)
        if not w.ready.wait(timeout):
            raise TimeoutError(
                f"watch registration not acked within {timeout}s "
                f"(key={key!r})")
        return w

    def readd(self, w: MuxWatch) -> None:
        """Resume re-registration (no ready wait — called from reader/
        revive threads; the ack arrives on this stream's read loop)."""
        w.ready.clear()
        self._send_create(w, w.resume_revision())

    def _read_loop(self) -> None:
        rpc_error = grpc.RpcError  # closure-bound, survives teardown
        try:
            for resp in self._responses:
                if resp.created:
                    with self._lock:
                        w = self._pending.popleft() if self._pending else None
                    if w is not None:
                        w.watch_id = resp.watch_id
                        if (w.last_revision == 0 and w.start_revision == 0
                                and not w.baselined):
                            # live-only watch ("from now"): baseline the
                            # resume watermark at the registration
                            # revision the server acked, so a reset
                            # BEFORE the first delivery replays exactly
                            # the events committed since registration
                            w.last_revision = resp.header.revision
                            w.baselined = True
                        with self._lock:
                            self._by_id[resp.watch_id] = w
                        if resp.canceled:  # e.g. compacted start revision
                            w.cancelled = True
                        w.ready.set()
                if resp.events:
                    with self._lock:
                        w = self._by_id.get(resp.watch_id)
                    if w is not None:
                        # sink BEFORE advancing the resume watermark: a
                        # consumer crash mid-apply must re-receive this
                        # batch after the revive, never skip it
                        if w.sink is not None:
                            w.sink(list(resp.events), resp.header.revision)
                        w.events += len(resp.events)
                        w.last_revision = resp.header.revision
                        if w.revisions is not None:
                            w.revisions.extend(
                                ev.kv.mod_revision for ev in resp.events)
                elif not resp.created and not resp.canceled:
                    # bare header on a registered watch id = ordered
                    # progress mark: everything <= header.revision was
                    # already delivered on this stream, so the resume
                    # watermark may advance across the leader's revision
                    # gaps (watch_id -1 stream-level headers miss the map
                    # and are ignored)
                    with self._lock:
                        w = self._by_id.get(resp.watch_id)
                    if w is not None and resp.header.revision > w.last_revision:
                        if w.sink is not None:
                            w.sink((), resp.header.revision)
                        w.last_revision = resp.header.revision
                if resp.canceled and not resp.created:
                    with self._lock:
                        w = self._by_id.pop(resp.watch_id, None)
                    if w is None:
                        continue
                    mux = self._mux
                    if (mux is not None and mux.resume
                            and resp.compact_revision == 0):
                        # server-side stream reset (watcher dropped /
                        # fault-injected): re-register from the last
                        # delivered revision + 1 — the watch cache replays
                        # the gap, so no event is lost or duplicated
                        w.resumes += 1
                        try:
                            self.readd(w)
                        except TimeoutError:
                            w.cancelled = True
                            w.ready.set()
                    else:
                        # terminal: compacted history (client must
                        # re-list) or resume not armed
                        w.cancelled = True
        except (rpc_error, ValueError):
            pass  # stream torn down (close() or channel death)
        finally:
            with self._lock:
                self.dead = True
                stranded = list(self._pending) + list(self._by_id.values())
                self._pending.clear()
                self._by_id.clear()
                closing = self.closing
            mux = self._mux
            if mux is not None and mux.resume and not closing and stranded:
                # whole-stream death: revive on a fresh stream (off this
                # thread — the revive needs a new gRPC stream + re-adds)
                threading.Thread(
                    target=mux._revive, args=(self, stranded),
                    name="kb-watchmux-revive", daemon=True).start()
            else:
                for w in stranded:
                    w.cancelled = True
                    w.ready.set()

    def close(self) -> None:
        with self._lock:
            self.closing = True
        self._requests.put(None)


class WatchMux:
    """Many long-lived watches multiplexed over a few Watch streams.

    A :meth:`EtcdCompatClient.watch` session costs one client thread AND
    one server worker thread per watch — at informer scale (one watcher
    per controller) that is thousands of threads on each side. The mux
    rides the etcd protocol's native multiplexing instead: each stream
    carries any number of watches, so N watchers cost ``streams`` threads
    total. Deliveries are *counted* per watch (the workload harness's
    need), not queued — wire-lag attribution lives in the server's
    ``kb_watch_lag_seconds`` metric.

    ``resume=True`` arms chaos-grade robustness (docs/faults.md): a
    server-side stream reset (slow-consumer drop, fault injection) or a
    whole-stream death re-registers every surviving watch from its
    last-delivered revision + 1 — the server's watch cache replays the
    gap, so the delivered event sequence has no loss and no duplicates;
    only a compacted start revision is terminal (the client must
    re-list)."""

    def __init__(self, client: "EtcdCompatClient", streams: int = 4,
                 resume: bool = False, record_revisions: bool = False):
        if streams < 1:
            raise ValueError("streams must be >= 1")
        self._client = client
        self.resume = resume
        self._record = record_revisions
        self._streams = [_WatchMuxStream(client, mux=self)
                         for _ in range(streams)]
        self._revive_lock = threading.Lock()
        self._all: list[MuxWatch] = []
        self._all_lock = threading.Lock()
        self._closed = False
        self._rr = 0

    def add(self, key: bytes, range_end: bytes = b"", start_revision: int = 0,
            shard: int | None = None, timeout: float = 30.0,
            prev_kv: bool = False, sink=None) -> MuxWatch:
        if shard is None:
            shard, self._rr = self._rr, self._rr + 1
        s = self._streams[shard % len(self._streams)]
        w = s.add(key, range_end, start_revision, timeout,
                  record=self._record, prev_kv=prev_kv, sink=sink)
        with self._all_lock:
            self._all.append(w)
        return w

    def request_progress(self) -> None:
        """Ordered per-watch progress marks from every live stream (the
        replication stream's watermark-advance tick)."""
        for s in self._streams:
            if not s.dead:
                s.request_progress()

    def _revive(self, dead_stream: "_WatchMuxStream",
                stranded: list[MuxWatch]) -> None:
        """Replace a dead stream and re-register its watches from their
        resume revisions. Idempotent under partial failure: revives
        serialize on ``_revive_lock``, each watch is re-added only while
        it still BELONGS to the dead stream (``w.stream``), and a
        replacement that dies mid-revive hands its already-moved watches
        to its own revive — one watch can never be live on two streams.
        Bounded attempts with jittered backoff; watches the server never
        takes back get terminal cancels."""
        import random

        backoff = 0.1
        for _attempt in range(6):
            if self._closed:
                break
            todo = [w for w in stranded
                    if not w.cancelled and w.stream is dead_stream]
            if not todo:
                return  # every watch moved on (or terminally ended)
            # the lock covers ONLY the slot lookup/swap (never the backoff
            # sleeps below — kblint KB118/KB102); double-add safety comes
            # from the per-watch ownership gate (w.stream), not from
            # serializing whole revives
            target = None
            with self._revive_lock:
                try:
                    slot = self._streams.index(dead_stream)
                except ValueError:
                    slot = None  # replaced by an earlier attempt/revive
                if slot is not None:
                    try:
                        target = _WatchMuxStream(self._client, mux=self)
                        # install BEFORE re-adding: add() must never
                        # route to a stream this revive knows is gone
                        self._streams[slot] = target
                    except (grpc.RpcError, ValueError):
                        target = None
                else:
                    # a newer revive owns the slot: adopt a live stream
                    # from the rotation instead of minting an untracked
                    # (unclosable) one. A dead adoptee in the slot heals
                    # via its OWN revive.
                    target = next(
                        (s for s in self._streams if not s.dead), None)
            if target is not None:
                try:
                    for w in todo:
                        w.resumes += 1
                        target.readd(w)  # moves w.stream to target
                    return
                except (grpc.RpcError, TimeoutError, ValueError):
                    pass  # target died mid-re-add: watches already moved
                    # ride its own revive; the rest retry here
            time.sleep(backoff * random.uniform(0.5, 1.5))
            backoff = min(backoff * 2.0, 2.0)
        for w in stranded:
            if not w.cancelled and w.stream is dead_stream:
                w.cancelled = True
                w.ready.set()

    def watchers(self) -> list[MuxWatch]:
        with self._all_lock:
            return list(self._all)

    def total_events(self) -> int:
        return sum(w.events for w in self.watchers())

    def cancelled_count(self) -> int:
        return sum(1 for w in self.watchers() if w.cancelled)

    def resumed_total(self) -> int:
        return sum(w.resumes for w in self.watchers())

    def close(self) -> None:
        self._closed = True
        for s in self._streams:
            s.close()


class _KeepaliveMuxStream:
    """One LeaseKeepAlive stream multiplexing pings for many lease ids.
    The server answers requests in order, so ack matching is the send
    FIFO; each ack invokes the caller's callback with (latency_s, ttl)."""

    def __init__(self, client: "EtcdCompatClient"):
        self._requests: queue.Queue = queue.Queue()
        self._responses = client._lease_keepalive(iter(self._requests.get, None))
        self._lock = threading.Lock()
        self._pending: collections.deque = collections.deque()
        self._idle = threading.Condition(self._lock)
        self.sent = 0
        self.acked = 0
        self.expired_acks = 0
        self.dead = False
        self._reader = threading.Thread(
            target=self._read_loop, name="kb-leasemux", daemon=True)
        self._reader.start()

    def send(self, lease_id: int,
             on_ack: Callable[[float, int], None] | None = None) -> bool:
        with self._lock:
            if self.dead:
                return False
            # append + send under one lock (ack matching is the send FIFO)
            self._pending.append((time.monotonic(), on_ack))
            self.sent += 1
            self._requests.put(rpc_pb2.LeaseKeepAliveRequest(ID=lease_id))
        return True

    def _read_loop(self) -> None:
        rpc_error = grpc.RpcError
        try:
            for resp in self._responses:
                with self._lock:
                    t0, on_ack = (self._pending.popleft()
                                  if self._pending else (None, None))
                    self.acked += 1
                    if resp.TTL <= 0:
                        self.expired_acks += 1
                    self._idle.notify_all()
                if on_ack is not None and t0 is not None:
                    on_ack(time.monotonic() - t0, resp.TTL)
        except (rpc_error, ValueError):
            pass
        finally:
            with self._lock:
                self.dead = True
                self._idle.notify_all()

    def flush(self, timeout: float) -> bool:
        deadline = time.monotonic() + timeout
        with self._lock:
            while self.acked < self.sent and not self.dead:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._idle.wait(remaining)
            return self.acked >= self.sent

    def close(self) -> None:
        self._requests.put(None)


class LeaseMux:
    """Node-scale lease fan-out: pipelined grants plus keepalives for many
    lease ids multiplexed over a few LeaseKeepAlive streams (one
    :class:`LeaseHandle` per lease would cost a thread and a stream per
    node). Keepalives are fire-and-forget from the caller's perspective;
    acks are counted (and optionally called back) on the reader threads,
    and :meth:`flush` fences them all."""

    def __init__(self, client: "EtcdCompatClient", streams: int = 4):
        if streams < 1:
            raise ValueError("streams must be >= 1")
        self._client = client
        self._streams = [_KeepaliveMuxStream(client) for _ in range(streams)]

    def grant_bulk(self, n: int, ttl: int, window: int = 64) -> list[int]:
        """Grant ``n`` leases with pipelined LeaseGrant futures; returns
        the server-assigned ids in order."""
        ids: list[int] = []
        pending: collections.deque = collections.deque()
        for _ in range(n):
            if len(pending) >= window:
                ids.append(pending.popleft().result().ID)
            pending.append(self._client._lease_grant.future(
                rpc_pb2.LeaseGrantRequest(TTL=ttl)))
        while pending:
            ids.append(pending.popleft().result().ID)
        return ids

    def keepalive_async(self, lease_id: int, shard: int = 0,
                        on_ack: Callable[[float, int], None] | None = None) -> bool:
        return self._streams[shard % len(self._streams)].send(lease_id, on_ack)

    @property
    def sent(self) -> int:
        return sum(s.sent for s in self._streams)

    @property
    def acked(self) -> int:
        return sum(s.acked for s in self._streams)

    @property
    def expired_acks(self) -> int:
        return sum(s.expired_acks for s in self._streams)

    def flush(self, timeout: float = 30.0) -> bool:
        deadline = time.monotonic() + timeout
        return all(s.flush(max(0.001, deadline - time.monotonic()))
                   for s in self._streams)

    def close(self) -> None:
        for s in self._streams:
            s.close()


class BrainClient:
    """Native protocol client (leaner than the etcd shim: explicit
    revisions, no txn encoding)."""

    def __init__(self, target: str, credentials: grpc.ChannelCredentials | None = None):
        self.channel = (
            grpc.secure_channel(target, credentials)
            if credentials
            else grpc.insecure_channel(target)
        )
        p = brain_pb2

        def u(name, req, resp):
            return _traced_call(self.channel.unary_unary(
                f"/brainpb.Brain/{name}",
                request_serializer=req.SerializeToString,
                response_deserializer=resp.FromString,
            ))

        def us(name, req, resp):
            return _traced_call(self.channel.unary_stream(
                f"/brainpb.Brain/{name}",
                request_serializer=req.SerializeToString,
                response_deserializer=resp.FromString,
            ))

        self._create = u("Create", p.CreateRequest, p.CreateResponse)
        self._update = u("Update", p.UpdateRequest, p.UpdateResponse)
        self._delete = u("Delete", p.BrainDeleteRequest, p.BrainDeleteResponse)
        self._compact = u("Compact", p.BrainCompactRequest, p.BrainCompactResponse)
        self._get = u("Get", p.GetRequest, p.GetResponse)
        self._range = u("Range", p.BrainRangeRequest, p.BrainRangeResponse)
        self._range_stream = us("RangeStream", p.BrainRangeRequest, p.BrainRangeResponse)
        self._count = u("Count", p.CountRequest, p.CountResponse)
        self._list_partition = u("ListPartition", p.ListPartitionRequest, p.ListPartitionResponse)
        self._watch = us("Watch", p.BrainWatchRequest, p.BrainWatchResponse)

    def create(self, key: bytes, value: bytes):
        r = self._create(brain_pb2.CreateRequest(key=key, value=value))
        return r.succeeded, r.revision

    def update(self, key: bytes, value: bytes, expected_revision: int):
        r = self._update(brain_pb2.UpdateRequest(
            key=key, value=value, expected_revision=expected_revision
        ))
        return r.succeeded, r.revision

    def delete(self, key: bytes, expected_revision: int = 0):
        r = self._delete(brain_pb2.BrainDeleteRequest(
            key=key, expected_revision=expected_revision
        ))
        return r.succeeded, r.revision

    def compact(self, revision: int) -> int:
        return self._compact(brain_pb2.BrainCompactRequest(revision=revision)).compacted_revision

    def get(self, key: bytes, revision: int = 0) -> ClientKV | None:
        r = self._get(brain_pb2.GetRequest(key=key, revision=revision))
        if not r.HasField("kv"):
            return None
        return ClientKV(r.kv.key, r.kv.value, r.kv.revision)

    def range(self, start: bytes, end: bytes, revision: int = 0, limit: int = 0):
        r = self._range(brain_pb2.BrainRangeRequest(
            start=start, end=end, revision=revision, limit=limit
        ))
        return [ClientKV(kv.key, kv.value, kv.revision) for kv in r.kvs], r.more

    def range_stream(self, start: bytes, end: bytes, revision: int = 0):
        for resp in self._range_stream(brain_pb2.BrainRangeRequest(
            start=start, end=end, revision=revision
        )):
            for kv in resp.kvs:
                yield ClientKV(kv.key, kv.value, kv.revision)

    def count(self, start: bytes, end: bytes) -> int:
        return self._count(brain_pb2.CountRequest(start=start, end=end)).count

    def list_partition(self, start: bytes, end: bytes) -> list[bytes]:
        return list(self._list_partition(
            brain_pb2.ListPartitionRequest(start=start, end=end)
        ).borders)

    def watch(self, prefix: bytes, start_revision: int = 0):
        for resp in self._watch(brain_pb2.BrainWatchRequest(
            prefix=prefix, start_revision=start_revision
        )):
            if resp.expired:
                raise RuntimeError("watch expired; re-list required")
            for ev in resp.events:
                yield ev

    def close(self) -> None:
        self.channel.close()
