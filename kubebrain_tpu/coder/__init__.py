"""Internal key codec — the MVCC data model.

Reference: pkg/backend/coder/normal.go:26-71 and rev.go:32-47. The reference
encodes an *internal* storage key as

    magic(4B) + user_key + split_byte + big_endian_u64(revision)

so that (a) all versions of one user key are adjacent in engine key order with
revisions ascending, and (b) a dedicated *revision key* (revision == 0) sorts
immediately before the version chain and holds the latest revision + deletion
flag as its value — the CAS target for every write.

This rebuild keeps the same data model but makes two TPU-first changes:

1. The split byte is ``0x00`` instead of ``'$'``. With NUL-free user keys
   (Kubernetes registry paths always are), byte-lexicographic order of the
   *padded fixed-width* device representation equals the logical
   (user_key, revision) order, which is what lets the range-scan kernel compare
   zero-padded ``uint8[N, KEY_WIDTH]`` rows directly. Keys containing NULs are
   still encoded/decoded unambiguously (the trailing 9 bytes are fixed-width)
   but their *grouping order* relative to prefix-keys is not guaranteed, same
   caveat class as the reference's ``'$'``.
2. Batch (numpy) encode/pack helpers live in ``kubebrain_tpu.ops.keys`` so the
   device block store can vectorize without per-key Python.

Revision *values* (stored under the revision key) follow the reference:
8 bytes big-endian = live revision; 9 bytes (revision + 1 flag byte) = the key
is deleted at that revision (rev.go:32-47).
"""

from __future__ import annotations

import struct

# Distinct from the reference's magic (\x57\xfb\x80\x8b) — ours is ASCII "kbT0".
MAGIC = b"kbT0"
SPLIT = 0x00
REV_WIDTH = 8
SUFFIX_WIDTH = 1 + REV_WIDTH  # split byte + big-endian u64 revision
HEADER_WIDTH = len(MAGIC)

_REV_STRUCT = struct.Struct(">Q")


class CodecError(ValueError):
    """Raised when bytes do not parse as an internal key / revision value."""


def encode_object_key(user_key: bytes, revision: int) -> bytes:
    """Internal key holding the object value at ``revision``.

    Reference: coder/normal.go:26-56 (EncodeObjectKey).
    """
    return b"".join((MAGIC, user_key, b"\x00", _REV_STRUCT.pack(revision)))


def encode_revision_key(user_key: bytes) -> bytes:
    """Internal key (revision 0) whose value is the latest-revision record.

    Reference: coder/normal.go:53 (revision key = object key at revision 0).
    """
    return encode_object_key(user_key, 0)


def decode(internal_key: bytes) -> tuple[bytes, int]:
    """Split an internal key back into (user_key, revision).

    Reference: coder/normal.go:58-71 — validates magic and split byte.
    """
    if len(internal_key) < HEADER_WIDTH + SUFFIX_WIDTH + 1:
        raise CodecError(f"internal key too short: {len(internal_key)}B")
    if internal_key[:HEADER_WIDTH] != MAGIC:
        raise CodecError("bad magic prefix")
    if internal_key[-SUFFIX_WIDTH] != SPLIT:
        raise CodecError("bad split byte")
    user_key = internal_key[HEADER_WIDTH:-SUFFIX_WIDTH]
    (revision,) = _REV_STRUCT.unpack(internal_key[-REV_WIDTH:])
    return user_key, revision


def is_internal_key(raw: bytes) -> bool:
    return (
        len(raw) > HEADER_WIDTH + SUFFIX_WIDTH
        and raw[:HEADER_WIDTH] == MAGIC
        and raw[-SUFFIX_WIDTH] == SPLIT
    )


def encode_rev_value(revision: int, deleted: bool = False) -> bytes:
    """Value stored under the revision key. Reference: coder/rev.go:20-30."""
    raw = _REV_STRUCT.pack(revision)
    return raw + b"\x01" if deleted else raw


def decode_rev_value(value: bytes) -> tuple[int, bool]:
    """Parse a revision-key value into (revision, deleted).

    Reference: coder/rev.go:32-47 — 8B = live, 9B = deleted-at-revision.
    """
    if len(value) == REV_WIDTH:
        return _REV_STRUCT.unpack(value)[0], False
    if len(value) == REV_WIDTH + 1:
        return _REV_STRUCT.unpack(value[:REV_WIDTH])[0], True
    raise CodecError(f"bad revision value length {len(value)}")


def prefix_end(prefix: bytes) -> bytes:
    """Smallest key strictly greater than every key with ``prefix``.

    Reference: pkg/backend/util.go:50 (PrefixEnd). All-0xff prefixes have no
    upper bound; we return b"" sentinel meaning "to infinity" (callers treat an
    empty end as unbounded, matching etcd's \\0 semantics for ranges).
    """
    buf = bytearray(prefix)
    for i in reversed(range(len(buf))):
        if buf[i] != 0xFF:
            buf[i] += 1
            return bytes(buf[: i + 1])
    return b""


MAX_REVISION = 2**64 - 1  # bound sentinel; real revisions start at 1


def _bound_after_all_versions(user_key: bytes) -> bytes:
    """Internal key sorting after every version row of ``user_key`` and
    before any longer/greater user key's rows."""
    return encode_object_key(user_key, MAX_REVISION)


def internal_range(start_user_key: bytes, end_user_key: bytes) -> tuple[bytes, bytes]:
    """Map a user-key range [start, end) onto internal-key space.

    The start bound is the start key's revision key (revision 0, sorts before
    all its versions); the end bound is the end key's revision key so that all
    versions of keys < end are included. Reference: pkg/backend/range.go:151.

    NUL-bearing *bounds* (etcd continuation tokens are ``last_key + b"\\0"``)
    would interleave with the NUL split byte + small-revision rows of
    ``last_key``; since stored keys are NUL-free, such a bound is canonicalized
    by truncating at the first NUL: "everything > base" for a start bound /
    "everything <= base" for an end bound — both are the position just after
    base's version chain.
    """
    if b"\x00" in start_user_key:
        base = start_user_key.split(b"\x00", 1)[0]
        lo = _bound_after_all_versions(base)
    else:
        lo = encode_revision_key(start_user_key)
    if not end_user_key:
        hi = prefix_end(MAGIC)
    elif b"\x00" in end_user_key:
        base = end_user_key.split(b"\x00", 1)[0]
        hi = _bound_after_all_versions(base)
    else:
        hi = encode_revision_key(end_user_key)
    return lo, hi
