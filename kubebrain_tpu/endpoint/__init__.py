"""Endpoint layer (reference pkg/endpoint)."""

from .endpoint import Endpoint, EndpointConfig

__all__ = ["Endpoint", "EndpointConfig"]
