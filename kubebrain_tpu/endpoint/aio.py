"""Asyncio gRPC endpoint: coroutine-held watch streams.

The sync gRPC stack pins one worker thread per ACTIVE stream, capping
concurrent watches at the pool size. Here the etcd3 surface runs on
``grpc.aio``: unary RPCs execute the existing sync terminals in a small
executor, while Watch streams are native coroutines fed by a thread-safe
bridge queue — 10k open watch streams cost 10k queue objects, not 10k
threads (the goroutine-parity answer to the reference's watcher model,
watch.go:83-117).

Enabled with ``--aio``; serves the same wire surface as the sync endpoint.
"""

from __future__ import annotations

import asyncio
import collections
import queue as sync_queue
import threading

import grpc
import grpc.aio

from ..proto import rpc_pb2
from ..server.etcd import shim
from ..server.etcd.kv import KVService
from ..server.etcd.misc import ClusterService, LeaseService, MaintenanceService


class _LoopNotifier:
    """Coalesces cross-thread loop wakeups: ``call_soon_threadsafe`` writes
    the loop's self-pipe on EVERY call, so one hub batch fanning out to W
    subscriber queues used to cost W syscalls on the sequencer thread (the
    top stack in the 10k-watcher informer-sim profile). All queues of one
    loop share a notifier that schedules a single drain per burst."""

    def __init__(self, loop: asyncio.AbstractEventLoop):
        self._loop = loop
        self._lock = threading.Lock()
        self._pending: list[AioBridgeQueue] = []
        self._scheduled = False

    def notify(self, q: "AioBridgeQueue") -> None:
        with self._lock:
            self._pending.append(q)
            if self._scheduled:
                return
            self._scheduled = True
        self._loop.call_soon_threadsafe(self._drain)

    def _drain(self) -> None:
        with self._lock:
            pending, self._pending = self._pending, []
            self._scheduled = False
        for q in pending:
            q._event.set()


class AioBridgeQueue:
    """WatcherHub-compatible subscriber queue consumable from asyncio.

    The hub (sequencer thread) calls ``put_nowait`` / ``get_nowait`` and
    expects ``queue.Full`` on overflow; the watch coroutine awaits ``get``.
    A deque + lock keeps the sync side synchronous (so slow-consumer drop
    semantics hold); the loop is woken through the shared ``_LoopNotifier``
    (or a direct ``call_soon_threadsafe`` when none is given), and only on
    the empty -> non-empty transition — a queue with a backlog needs no
    further wakeups.
    """

    def __init__(self, maxsize: int, loop: asyncio.AbstractEventLoop,
                 notifier: _LoopNotifier | None = None):
        self._maxsize = maxsize
        self._loop = loop
        self._notifier = notifier
        self._lock = threading.Lock()
        self._items: collections.deque = collections.deque()
        self._event = asyncio.Event()

    # ---- sync side (sequencer / hub)
    def put_nowait(self, item) -> None:
        with self._lock:
            if len(self._items) >= self._maxsize:
                raise sync_queue.Full
            was_empty = not self._items
            self._items.append(item)
        if was_empty:
            if self._notifier is not None:
                self._notifier.notify(self)
            else:
                self._loop.call_soon_threadsafe(self._event.set)

    def get_nowait(self):
        with self._lock:
            if not self._items:
                raise sync_queue.Empty
            return self._items.popleft()

    def empty(self) -> bool:
        with self._lock:
            return not self._items

    def qsize(self) -> int:
        with self._lock:
            return len(self._items)

    # ---- async side (watch coroutine)
    async def get(self):
        while True:
            with self._lock:
                if self._items:
                    return self._items.popleft()
                self._event.clear()
            await self._event.wait()


class _AbortError(Exception):
    def __init__(self, code, details):
        self.code = code
        self.details = details


class _SyncContextAdapter:
    """Sync-terminal context whose abort raises through the executor."""

    def abort(self, code, details):
        raise _AbortError(code, details)

    def is_active(self) -> bool:
        return True


def _wrap_unary(fn):
    async def handler(request, context):
        loop = asyncio.get_running_loop()
        try:
            return await loop.run_in_executor(None, fn, request, _SyncContextAdapter())
        except _AbortError as e:
            await context.abort(e.code, e.details)

    return handler


class AioWatchService:
    """Native-async Watch terminal — full parity with the sync protocol
    (server/etcd/watch.py): shared response builders, negative-start-revision
    list-over-watch streams, progress-notify bookmarks, compacted cancels."""

    PROGRESS_INTERVAL = 60.0

    def __init__(self, backend, peers=None):
        self.backend = backend
        self.peers = peers
        self._notifiers: dict[int, _LoopNotifier] = {}

    def _notifier_for(self, loop) -> _LoopNotifier:
        n = self._notifiers.get(id(loop))
        if n is None:
            n = self._notifiers[id(loop)] = _LoopNotifier(loop)
        return n

    async def Watch(self, request_iterator, context):
        from ..server.etcd.watch import (
            compacted_response,
            dropped_response,
            events_response,
        )

        if self.peers is not None and not self.peers.is_leader():
            # follower watch-forwarding is a sync-proxy feature; refuse loudly
            # rather than serve from a non-leader pipeline
            await context.abort(
                grpc.StatusCode.UNAVAILABLE,
                "etcdserver: not leader (watch on the aio port requires the leader; "
                "use the sync client port for proxied watches)",
            )

        loop = asyncio.get_running_loop()
        out: asyncio.Queue = asyncio.Queue(maxsize=1024)
        watches: dict[int, tuple[int, asyncio.Task]] = {}
        stream_tasks: set[asyncio.Task] = set()
        next_id = [0]

        async def pump(watch_id, wid, q, want_prev, no_put, no_delete, progress_notify):
            last_sent = loop.time()
            # poll loop, not a retry loop: the TimeoutError tick is the
            # progress-notify cadence; exits on the queue's poison pill
            while True:  # kblint: disable=KB118 -- bounded by poison pill
                if progress_notify:
                    try:
                        batch = await asyncio.wait_for(q.get(), timeout=0.5)
                    except asyncio.TimeoutError:
                        if loop.time() - last_sent >= self.PROGRESS_INTERVAL:
                            last_sent = loop.time()
                            await out.put(rpc_pb2.WatchResponse(
                                header=shim.header(self.backend.current_revision()),
                                watch_id=watch_id,
                            ))
                        continue
                else:
                    # event-driven: at 10k idle streams, a 0.5s poll per pump
                    # is 20k timer events/s of pure loop overhead
                    batch = await q.get()
                if batch is None or getattr(q, "kb_dropped", False):
                    # the drop flag is checked BEFORE every delivery so
                    # buffered batches past the drop point never reach the
                    # wire — the delivered sequence stays a prefix (the
                    # hub drop protocol's no-invisible-gap contract)
                    await out.put(dropped_response(self.backend.current_revision(), watch_id))
                    return
                resp = events_response(batch, watch_id, want_prev, no_put, no_delete)
                if resp is not None:
                    last_sent = loop.time()
                    await out.put(resp)

        async def range_stream(creq, watch_id):
            """List-over-watch (negative start revision, watch.py protocol)."""
            from ..backend.errors import CompactedError, FutureRevisionError
            from ..proto import kv_pb2
            from ..server.service.revision import decode_list_revision

            revision = decode_list_revision(creq.start_revision)
            from ..sched import ensure_scheduler

            try:
                rev, stream = await loop.run_in_executor(
                    None, ensure_scheduler(self.backend).list_by_stream,
                    bytes(creq.key), bytes(creq.range_end), revision,
                )
            except (CompactedError, FutureRevisionError):
                await out.put(compacted_response(
                    self.backend.current_revision(),
                    self.backend.compact_revision(), watch_id,
                ))
                return
            except asyncio.CancelledError:
                raise
            except Exception as exc:
                # any other failure must still answer the client — otherwise
                # it waits forever on this watch_id
                await out.put(rpc_pb2.WatchResponse(
                    header=shim.header(self.backend.current_revision()),
                    watch_id=watch_id, canceled=True,
                    cancel_reason=f"range stream failed: {exc}",
                ))
                return
            await out.put(rpc_pb2.WatchResponse(
                header=shim.header(rev), watch_id=watch_id, created=True
            ))
            it = iter(stream)
            try:
                while True:
                    batch = await loop.run_in_executor(None, next, it, None)
                    if batch is None:
                        break
                    resp = rpc_pb2.WatchResponse(header=shim.header(rev), watch_id=watch_id)
                    for kv in batch:
                        resp.events.append(
                            kv_pb2.Event(type=kv_pb2.Event.PUT, kv=shim.to_kv(kv))
                        )
                    await out.put(resp)
                reason = ""
            except asyncio.CancelledError:
                raise
            except Exception as exc:  # mid-stream failure: tell the client
                reason = f"range stream failed: {exc}"
            await out.put(rpc_pb2.WatchResponse(
                header=shim.header(rev), watch_id=watch_id, canceled=True,
                cancel_reason=reason,
            ))

        async def reader():
            try:
                async for req in request_iterator:
                    which = req.WhichOneof("request_union")
                    if which == "create_request":
                        creq = req.create_request
                        next_id[0] += 1
                        watch_id = creq.watch_id if creq.watch_id > 0 else next_id[0]
                        from ..server.service.revision import is_list_over_watch

                        if is_list_over_watch(creq.start_revision):
                            task = asyncio.create_task(range_stream(creq, watch_id))
                            stream_tasks.add(task)
                            task.add_done_callback(stream_tasks.discard)
                            continue
                        end = bytes(creq.range_end)
                        if not end:
                            end = bytes(creq.key) + b"\x00"
                        elif end == b"\x00":
                            end = b""
                        from ..backend import WatchExpiredError

                        try:
                            wid, q = self.backend.watch_range(
                                bytes(creq.key), end, int(creq.start_revision),
                                queue_factory=lambda maxsize: AioBridgeQueue(
                                    maxsize, loop, self._notifier_for(loop)),
                            )
                        except WatchExpiredError:
                            await out.put(compacted_response(
                                self.backend.current_revision(),
                                self.backend.compact_revision(), watch_id,
                            ))
                            continue
                        await out.put(rpc_pb2.WatchResponse(
                            header=shim.header(self.backend.current_revision()),
                            watch_id=watch_id, created=True,
                        ))
                        no_put = rpc_pb2.WatchCreateRequest.NOPUT in creq.filters
                        no_delete = rpc_pb2.WatchCreateRequest.NODELETE in creq.filters
                        task = asyncio.create_task(pump(
                            watch_id, wid, q, bool(creq.prev_kv), no_put, no_delete,
                            bool(creq.progress_notify),
                        ))
                        watches[watch_id] = (wid, task)
                    elif which == "cancel_request":
                        watch_id = req.cancel_request.watch_id
                        entry = watches.pop(watch_id, None)
                        if entry:
                            wid, task = entry
                            task.cancel()
                            self.backend.unwatch(wid)
                        await out.put(rpc_pb2.WatchResponse(
                            header=shim.header(self.backend.current_revision()),
                            watch_id=watch_id, canceled=True,
                            cancel_reason="watch cancelled by client",
                        ))
                    elif which == "progress_request":
                        await out.put(rpc_pb2.WatchResponse(
                            header=shim.header(self.backend.current_revision()),
                            watch_id=-1,
                        ))
            except Exception:
                pass
            await out.put(None)

        reader_task = asyncio.create_task(reader())
        try:
            while True:
                item = await out.get()
                if item is None:
                    return
                yield item
        finally:
            reader_task.cancel()
            # list-over-watch tasks block on `out.put` once the consumer is
            # gone (bounded queue) — cancel them or they leak with their
            # backend list streams
            for task in list(stream_tasks):
                task.cancel()
            for wid, task in watches.values():
                task.cancel()
                self.backend.unwatch(wid)


def _aio_lease_keepalive(lease):
    """Coroutine keepalive stream over the shared LeaseService: the refresh
    goes through the scheduler's SYSTEM lane (a blocking submit), so it runs
    in the executor — the loop thread must never block on admission."""
    from ..server.etcd.misc import ERR_NOT_LEADER, LeaseNotLeaderError

    async def handler(request_iterator, context):
        loop = asyncio.get_running_loop()
        async for req in request_iterator:
            try:
                yield await loop.run_in_executor(None, lease.keepalive_one, req)
            except LeaseNotLeaderError:
                await context.abort(grpc.StatusCode.UNAVAILABLE, ERR_NOT_LEADER)

    return handler


def make_aio_handlers(backend, peers=None, identity="kubebrain-tpu"):
    kv = KVService(backend, peers)
    lease = LeaseService(backend, peers)
    cluster = ClusterService(backend, identity)
    maint = MaintenanceService(backend)
    watch = AioWatchService(backend, peers)
    p = rpc_pb2

    def unary(fn, req, resp):
        return grpc.unary_unary_rpc_method_handler(
            _wrap_unary(fn),
            request_deserializer=req.FromString,
            response_serializer=resp.SerializeToString,
        )

    return [
        grpc.method_handlers_generic_handler("etcdserverpb.KV", {
            "Range": unary(kv.Range, p.RangeRequest, p.RangeResponse),
            "Txn": unary(kv.Txn, p.TxnRequest, p.TxnResponse),
            "Compact": unary(kv.Compact, p.CompactionRequest, p.CompactionResponse),
            "Put": unary(kv.Put, p.PutRequest, p.PutResponse),
            "DeleteRange": unary(kv.DeleteRange, p.DeleteRangeRequest, p.DeleteRangeResponse),
        }),
        grpc.method_handlers_generic_handler("etcdserverpb.Watch", {
            "Watch": grpc.stream_stream_rpc_method_handler(
                watch.Watch,
                request_deserializer=p.WatchRequest.FromString,
                response_serializer=p.WatchResponse.SerializeToString,
            ),
        }),
        grpc.method_handlers_generic_handler("etcdserverpb.Lease", {
            "LeaseGrant": unary(lease.LeaseGrant, p.LeaseGrantRequest, p.LeaseGrantResponse),
            "LeaseRevoke": unary(lease.LeaseRevoke, p.LeaseRevokeRequest, p.LeaseRevokeResponse),
            "LeaseKeepAlive": grpc.stream_stream_rpc_method_handler(
                _aio_lease_keepalive(lease),
                request_deserializer=p.LeaseKeepAliveRequest.FromString,
                response_serializer=p.LeaseKeepAliveResponse.SerializeToString,
            ),
            "LeaseTimeToLive": unary(lease.LeaseTimeToLive, p.LeaseTimeToLiveRequest, p.LeaseTimeToLiveResponse),
            "LeaseLeases": unary(lease.LeaseLeases, p.LeaseLeasesRequest, p.LeaseLeasesResponse),
        }),
        grpc.method_handlers_generic_handler("etcdserverpb.Cluster", {
            "MemberList": unary(cluster.MemberList, p.MemberListRequest, p.MemberListResponse),
        }),
        grpc.method_handlers_generic_handler("etcdserverpb.Maintenance", {
            "Status": unary(maint.Status, p.StatusRequest, p.StatusResponse),
            "Defragment": unary(maint.Defragment, p.DefragmentRequest, p.DefragmentResponse),
        }),
    ]


class AioEndpoint:
    """Runs the aio gRPC server in a dedicated event-loop thread so the rest
    of the (threaded) process is unchanged. TLS mirrors the sync endpoint:
    with credentials configured, a secure port is bound (plus plaintext only
    when ``insecure``)."""

    def __init__(
        self, backend, peers, host: str, port: int, identity="kubebrain-tpu",
        credentials: grpc.ServerCredentials | None = None, insecure: bool = True,
    ):
        self.backend = backend
        self.peers = peers
        self.host = host
        self.port = port
        self.identity = identity
        self.credentials = credentials
        self.insecure = insecure
        self._loop: asyncio.AbstractEventLoop | None = None
        self._server = None
        self._thread: threading.Thread | None = None
        self._started = threading.Event()
        self._error: BaseException | None = None

    def run(self) -> None:
        self._thread = threading.Thread(target=self._serve, name="kb-aio", daemon=True)
        self._thread.start()
        self._started.wait(timeout=10)
        if self._error is not None:
            raise RuntimeError(f"aio endpoint failed to start: {self._error}")

    def _serve(self) -> None:
        self._loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self._loop)

        async def main():
            self._server = grpc.aio.server()
            for h in make_aio_handlers(self.backend, self.peers, self.identity):
                self._server.add_generic_rpc_handlers((h,))
            bound = False
            if self.credentials is not None:
                self._server.add_secure_port(f"{self.host}:{self.port}", self.credentials)
                bound = True
            if self.insecure or not bound:
                self._server.add_insecure_port(f"{self.host}:{self.port}")
            await self._server.start()
            self._started.set()
            await self._server.wait_for_termination()

        try:
            self._loop.run_until_complete(main())
        except Exception as e:
            import traceback

            traceback.print_exc()
            self._error = e
            self._started.set()

    def close(self, grace: float = 0.5) -> None:
        if self._loop is not None and self._server is not None:
            fut = asyncio.run_coroutine_threadsafe(self._server.stop(grace), self._loop)
            try:
                fut.result(timeout=grace + 1.0)
            except Exception:
                pass
            self._loop.call_soon_threadsafe(self._loop.stop)
        if self._thread is not None:
            self._thread.join(timeout=2)
