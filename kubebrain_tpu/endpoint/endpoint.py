"""Listeners: client gRPC port, peer HTTP port, info/metrics HTTP port.

Reference: pkg/endpoint/endpoint.go runs three root servers (client 2379 /
peer 2380 / info) with cmux demuxing HTTP1+gRPC on one TCP port
(server.go:65-100). Python grpcio owns its listening socket, so instead of
cmux this layer gives each protocol its own port — same surface, explicit
ports: the client port speaks gRPC (etcd3 + brain), the peer port serves the
HTTP control plane (/status revision sync, /health, /election), and the info
port serves /metrics + debug. TLS: gRPC via grpc.ssl_server_credentials,
HTTP via ssl context (reference security.go wraps with cmux.TLS()).
"""

from __future__ import annotations

import ssl
import threading
from concurrent import futures
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import grpc


class _MetricsInterceptor(grpc.ServerInterceptor):
    """Per-RPC method/latency/success metrics (reference: grpc-prometheus
    unary+stream interceptors, pkg/metrics/prometheus/grpc_server_options.go:29-36)."""

    def __init__(self, metrics):
        self._m = metrics

    def intercept_service(self, continuation, handler_call_details):
        handler = continuation(handler_call_details)
        if handler is None:
            return None
        method = handler_call_details.method
        m = self._m

        def wrap_unary(behavior):
            def inner(request, context):
                with m.timed("rpc.server", method=method):
                    return behavior(request, context)
            return inner

        def wrap_stream(behavior):
            def inner(request_or_iterator, context):
                with m.timed("rpc.server", method=method):
                    yield from behavior(request_or_iterator, context)
            return inner

        if handler.unary_unary:
            return grpc.unary_unary_rpc_method_handler(
                wrap_unary(handler.unary_unary),
                request_deserializer=handler.request_deserializer,
                response_serializer=handler.response_serializer,
            )
        if handler.unary_stream:
            return grpc.unary_stream_rpc_method_handler(
                wrap_stream(handler.unary_stream),
                request_deserializer=handler.request_deserializer,
                response_serializer=handler.response_serializer,
            )
        if handler.stream_stream:
            return grpc.stream_stream_rpc_method_handler(
                wrap_stream(handler.stream_stream),
                request_deserializer=handler.request_deserializer,
                response_serializer=handler.response_serializer,
            )
        return handler


class _FaultInterceptor(grpc.ServerInterceptor):
    """Chaos-mode connection drops (docs/faults.md): during an armed
    ``conn_drop`` window, unary client RPCs abort with a bare UNAVAILABLE
    BEFORE the handler runs — the wire shape of a dropped connection. No
    ``etcdserver:`` prefix on purpose: clients must classify it ambiguous
    (the handler never ran here, but a real connection drop gives the
    client no way to know that — the asymmetry is the fault). Ordered
    INSIDE the metrics interceptor so aborted RPCs still count in
    ``rpc_server_count`` and the harness reconcile stays exact."""

    def __init__(self, plane):
        self._plane = plane

    def intercept_service(self, continuation, handler_call_details):
        handler = continuation(handler_call_details)
        if handler is None or not handler.unary_unary:
            return handler  # streams are covered by watch_reset injection
        plane = self._plane
        behavior = handler.unary_unary

        def inner(request, context):
            if plane.conn_drop():
                context.abort(grpc.StatusCode.UNAVAILABLE,
                              "connection dropped (fault injection)")
            return behavior(request, context)

        return grpc.unary_unary_rpc_method_handler(
            inner,
            request_deserializer=handler.request_deserializer,
            response_serializer=handler.response_serializer,
        )


@dataclass
class EndpointConfig:
    host: str = "0.0.0.0"
    client_port: int = 2379
    peer_port: int = 2380
    info_port: int = 8081
    # TLS (applies to the client gRPC port + peer/info HTTPS when set)
    cert_file: str = ""
    key_file: str = ""
    ca_file: str = ""
    insecure: bool = True  # also serve plaintext when certs are configured
    # the sync gRPC stack holds one worker thread per ACTIVE stream (every
    # open Watch); kube-apiserver keeps dozens of watch streams open, so the
    # pool must be sized well above the expected stream count
    grpc_workers: int = 256
    extra_http: dict = field(default_factory=dict)


def http_call(fn, qs: str):
    """Zero-arg invoker for an HTTP route handler — the ONE place the
    query-string contract lives (both HTTP fronts dispatch through it):
    query-aware handlers (``fn.kb_query``, e.g. /debug/profile?seconds=N)
    receive the parsed query string as a flat last-value-wins dict."""
    if getattr(fn, "kb_query", False):
        from urllib.parse import parse_qs

        query = {k: v[-1] for k, v in parse_qs(qs).items()}
        return lambda: fn(query)
    return fn


class _HttpHandler(BaseHTTPRequestHandler):
    routes: dict = {}

    def do_GET(self):  # noqa: N802
        path, _, qs = self.path.partition("?")
        fn = self.routes.get(path)
        if fn is None:
            self.send_error(404)
            return
        try:
            content_type, body = http_call(fn, qs)()
        except Exception as e:  # surface handler errors as 500s
            self.send_error(500, str(e))
            return
        self.send_response(200)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt, *args):  # quiet
        pass


class Endpoint:
    def __init__(self, server, metrics, config: EndpointConfig):
        self.server = server
        self.metrics = metrics
        self.config = config
        self._grpc: grpc.Server | None = None
        self._https: list[ThreadingHTTPServer] = []
        self._threads: list[threading.Thread] = []

    # ------------------------------------------------------------------- run
    def run(self) -> None:
        cfg = self.config
        interceptors = [_MetricsInterceptor(self.metrics)]
        fault_plane = getattr(
            getattr(self.server, "backend", None), "_kb_faults", None)
        if fault_plane is not None:
            # after metrics, so fault-aborted RPCs still reconcile
            interceptors.append(_FaultInterceptor(fault_plane))
        self._grpc = grpc.server(
            futures.ThreadPoolExecutor(max_workers=cfg.grpc_workers),
            options=[
                ("grpc.max_receive_message_length", 16 * 1024 * 1024),
                ("grpc.max_send_message_length", 16 * 1024 * 1024),
            ],
            interceptors=interceptors,
        )
        for h in self.server.grpc_handlers:
            self._grpc.add_generic_rpc_handlers((h,))
        bound = False
        if cfg.cert_file and cfg.key_file:
            creds = self._grpc_creds()
            if not self._grpc.add_secure_port(f"{cfg.host}:{cfg.client_port}", creds):
                raise RuntimeError(
                    f"failed to bind client port {cfg.host}:{cfg.client_port} (TLS)")
            bound = True
        if cfg.insecure or not bound:
            # add_*_port returns 0 on failure instead of raising; unchecked,
            # the process keeps running and "serves" with no listener
            if not self._grpc.add_insecure_port(f"{cfg.host}:{cfg.client_port}"):
                raise RuntimeError(
                    f"failed to bind client port {cfg.host}:{cfg.client_port}")
        self._grpc.start()

        routes = dict(self.server.http_handlers())
        routes["/metrics"] = self.metrics.http_handler()
        routes.update(cfg.extra_http)
        for port in {cfg.peer_port, cfg.info_port}:
            self._serve_http(port, routes)
        self.server.start_background()

    def _grpc_creds(self):
        cfg = self.config
        with open(cfg.key_file, "rb") as f:
            key = f.read()
        with open(cfg.cert_file, "rb") as f:
            cert = f.read()
        root = None
        if cfg.ca_file:
            with open(cfg.ca_file, "rb") as f:
                root = f.read()
        return grpc.ssl_server_credentials(
            [(key, cert)], root_certificates=root,
            require_client_auth=bool(root),
        )

    def _serve_http(self, port: int, routes: dict) -> None:
        handler = type("Handler", (_HttpHandler,), {"routes": routes})
        httpd = ThreadingHTTPServer((self.config.host, port), handler)
        if self.config.cert_file and self.config.key_file and not self.config.insecure:
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(self.config.cert_file, self.config.key_file)
            httpd.socket = ctx.wrap_socket(httpd.socket, server_side=True)
        t = threading.Thread(target=httpd.serve_forever, daemon=True, name=f"kb-http-{port}")
        t.start()
        self._https.append(httpd)
        self._threads.append(t)

    def wait(self) -> None:
        if self._grpc is not None:
            self._grpc.wait_for_termination()

    def close(self, grace: float = 1.0) -> None:
        if self._grpc is not None:
            self._grpc.stop(grace)
        for httpd in self._https:
            httpd.shutdown()
            httpd.server_close()
        self.server.close()
