"""Native-frontend backhaul: the Python side of kbfront.

kbfront (native/front/kbfront.cc) terminates gRPC (HTTP/2) and plain HTTP
on one TCP port — the single-port protocol demux the reference builds with
cmux (pkg/endpoint/server.go:65-100) — and forwards de-framed requests over
a pipelined unix socket. This module is the other end of that socket: an
asyncio server that dispatches frames to the SAME service terminals the
grpc stacks use (server/etcd/kv.py, server/brain/server.py, endpoint/aio.py),
so MVCC semantics stay in exactly one place.

Why it is fast: the per-RPC interpreter work drops to frame header parse +
protobuf decode (upb) + the backend op. All HTTP/2, HPACK and gRPC message
framing runs in C++. Hot unary terminals run INLINE on the event loop —
backend writes are inline-drain sequenced and take ~tens of microseconds,
so a thread hop would cost more than the op.

Frame protocol (little-endian), mirrored in kbfront.cc:
  u32 payload_len | u32 conn_id | u32 stream_id | u8 kind | payload
front -> python kinds: 1 START(path) 2 MSG 3 HALF_CLOSE 4 RST 6 HTTP(req)
python -> front kinds: 2 MSG 5 END(u32 status|u16 len|msg) 4 RST
"""

from __future__ import annotations

import asyncio
import logging
import os
import struct
import subprocess
import sys
import threading

import grpc

from ..proto import brain_pb2, rpc_pb2
from ..server.etcd.kv import KVService
from ..server.etcd.misc import ClusterService, LeaseService, MaintenanceService
from .aio import AioBridgeQueue, AioWatchService, _AbortError, _SyncContextAdapter

logger = logging.getLogger("kubebrain")

K_START, K_MSG, K_HALF_CLOSE, K_RST, K_END, K_HTTP = 1, 2, 3, 4, 5, 6

_HDR = struct.Struct("<IIIB")


def _status_num(code) -> int:
    return code.value[0] if hasattr(code, "value") else int(code)


_SYNC_CTX = _SyncContextAdapter()
# the backhaul forwards pre-serialized responses verbatim, so handlers may
# take the raw wire fast path (kv.py _list / _RawResponse)
_SYNC_CTX.kb_raw_ok = True
_END_OK = struct.pack("<IH", 0, 0)  # END payload: status 0, empty message


class _Stream:
    __slots__ = ("queue", "task", "half_closed")

    def __init__(self):
        self.queue: asyncio.Queue = asyncio.Queue(maxsize=1024)
        self.task: asyncio.Task | None = None
        self.half_closed = False


class FrontServer:
    """Backhaul listener + kbfront subprocess supervisor."""

    def __init__(self, backend, peers=None, server=None, identity="kubebrain-tpu",
                 metrics=None, brain=None, inline_unary: bool = True):
        # inline_unary: run unary terminals on the event loop (right for
        # in-process engines, ~tens of us/op). With a NETWORK engine
        # (--storage=remote) every op is a TCP round trip that would stall
        # all frontend traffic — those run in the executor instead.
        self._inline_unary = inline_unary
        self.backend = backend
        self.peers = peers
        self.server = server  # Server composite for /status etc (may be None)
        self.identity = identity
        self.metrics = metrics
        self.kv = KVService(backend, peers)
        self.lease = LeaseService(backend, peers)
        self.cluster = ClusterService(backend, identity)
        self.maint = MaintenanceService(backend)
        self.watch = AioWatchService(backend, peers)
        self.brain = brain
        self._loop: asyncio.AbstractEventLoop | None = None
        self._thread: threading.Thread | None = None
        self._proc: subprocess.Popen | None = None
        self._writer: asyncio.StreamWriter | None = None
        # corked backhaul writes: every response frame lands here and ONE
        # flusher task does one write()+drain() per burst — per-message
        # write/drain was the loop thread's top cost in the 10k-watcher sim
        self._cork: list[bytes] = []
        self._cork_bytes = 0
        self._cork_event: asyncio.Event | None = None
        # producer gate: cleared while the cork backlog is over the high-water
        # mark so stream producers pause (keeps the hub's slow-consumer drop
        # reachable); unary replies are bounded by kbfront's in-flight request
        # window and bypass the gate
        self._gate: asyncio.Event | None = None
        self._flusher: asyncio.Task | None = None
        self._streams: dict[tuple[int, int], _Stream] = {}
        # unary fast path: (cid, sid) -> [(req_cls, fn), raw_request_bytes]
        self._unary_pending: dict[tuple[int, int], list] = {}
        self._ready = threading.Event()
        self._closing = False

        p = rpc_pb2
        b = brain_pb2
        # path -> (request_cls, handler, kind); kind: "unary" | "sstream"
        self.unary = {}
        self.sstream = {}

        def u(path, req_cls, fn):
            self.unary[path] = (req_cls, fn)

        u("/etcdserverpb.KV/Range", p.RangeRequest, self.kv.Range)
        u("/etcdserverpb.KV/Txn", p.TxnRequest, self.kv.Txn)
        u("/etcdserverpb.KV/Compact", p.CompactionRequest, self.kv.Compact)
        u("/etcdserverpb.KV/Put", p.PutRequest, self.kv.Put)
        u("/etcdserverpb.KV/DeleteRange", p.DeleteRangeRequest, self.kv.DeleteRange)
        u("/etcdserverpb.Lease/LeaseGrant", p.LeaseGrantRequest, self.lease.LeaseGrant)
        u("/etcdserverpb.Lease/LeaseRevoke", p.LeaseRevokeRequest, self.lease.LeaseRevoke)
        u("/etcdserverpb.Lease/LeaseTimeToLive", p.LeaseTimeToLiveRequest, self.lease.LeaseTimeToLive)
        u("/etcdserverpb.Lease/LeaseLeases", p.LeaseLeasesRequest, self.lease.LeaseLeases)
        u("/etcdserverpb.Cluster/MemberList", p.MemberListRequest, self.cluster.MemberList)
        u("/etcdserverpb.Maintenance/Status", p.StatusRequest, self.maint.Status)
        u("/etcdserverpb.Maintenance/Defragment", p.DefragmentRequest, self.maint.Defragment)
        if brain is not None:
            u("/brainpb.Brain/Create", b.CreateRequest, brain.Create)
            u("/brainpb.Brain/Update", b.UpdateRequest, brain.Update)
            u("/brainpb.Brain/Delete", b.BrainDeleteRequest, brain.Delete)
            u("/brainpb.Brain/Compact", b.BrainCompactRequest, brain.Compact)
            u("/brainpb.Brain/Get", b.GetRequest, brain.Get)
            u("/brainpb.Brain/Range", b.BrainRangeRequest, brain.Range)
            u("/brainpb.Brain/Count", b.CountRequest, brain.Count)
            u("/brainpb.Brain/ListPartition", b.ListPartitionRequest, brain.ListPartition)
            self.sstream["/brainpb.Brain/RangeStream"] = (
                b.BrainRangeRequest, brain.RangeStream)
            self.sstream["/brainpb.Brain/Watch"] = (
                b.BrainWatchRequest, brain.Watch)

    # ------------------------------------------------------------- lifecycle
    def run(self, tcp_port: int, host: str = "127.0.0.1",
            socket_path: str | None = None, cert_file: str = "",
            key_file: str = "", ca_file: str = "",
            secure_only: bool = False) -> None:
        """Start the backhaul loop thread + kbfront subprocess.

        With cert/key, kbfront terminates TLS in its reactor (reference
        secure modes, endpoint/config.go:159): both-modes by default,
        plaintext refused when ``secure_only``."""
        self.socket_path = socket_path or f"/tmp/kbfront-{os.getpid()}-{tcp_port}.sock"
        self.tcp_port = tcp_port
        self.host = host
        self._tls_args: list[str] = []
        if cert_file and key_file:
            self._tls_args = ["--cert", cert_file, "--key", key_file]
            if ca_file:
                self._tls_args += ["--ca", ca_file]
            if secure_only:
                self._tls_args.append("--secure-only")
        self._start_error: Exception | None = None
        self._thread = threading.Thread(
            target=self._thread_main, name="kb-front", daemon=True
        )
        self._thread.start()
        if not self._ready.wait(timeout=20):
            raise RuntimeError("kbfront backhaul failed to start")
        if self._start_error is not None:
            raise self._start_error

    def _thread_main(self) -> None:
        asyncio.run(self._main())

    async def _main(self) -> None:
        self._loop = asyncio.get_running_loop()
        if os.path.exists(self.socket_path):
            os.unlink(self.socket_path)
        server = await asyncio.start_unix_server(self._on_backhaul, self.socket_path)
        binary = os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
            "native", "front", "kbfront",
        )
        self._proc = subprocess.Popen(  # kblint: disable=KB101 -- one-shot startup fork/exec before any stream is served; the loop is not shared yet
            [binary, str(self.tcp_port), self.socket_path, self.host,
             *getattr(self, "_tls_args", [])],
            stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL if os.environ.get("KB_FRONT_QUIET") else None,
        )
        # startup must fail loudly: wait for kbfront's READY line (printed
        # after bind+listen+backhaul connect) before reporting up
        loop = asyncio.get_running_loop()
        try:
            line = await asyncio.wait_for(
                loop.run_in_executor(None, self._proc.stdout.readline), timeout=10
            )
        except asyncio.TimeoutError:
            line = b""
        if b"READY" not in line:
            rc = self._proc.poll()
            self._start_error = RuntimeError(
                f"kbfront failed to start (rc={rc}) — port {self.tcp_port} in "
                "use, or libnghttp2 missing?"
            )
            self._proc.terminate()
            self._ready.set()
            return
        self._ready.set()
        async with server:
            while not self._closing:
                await asyncio.sleep(0.5)
                if self._proc.poll() is not None and not self._closing:
                    logger.critical(
                        "kbfront exited rc=%s; native frontend down",
                        self._proc.returncode,
                    )
                    return

    def close(self) -> None:
        self._closing = True
        if self._proc is not None:
            self._proc.terminate()
            try:
                self._proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self._proc.kill()
        if self._thread is not None:
            self._thread.join(timeout=5)
        try:
            os.unlink(self.socket_path)
        except OSError:
            pass

    # --------------------------------------------------------------- framing
    _CORK_HIGH_WATER = 4 << 20

    def _send(self, cid: int, sid: int, kind: int, payload: bytes = b"") -> None:
        w = self._writer
        if w is None or w.is_closing():
            return
        frame = _HDR.pack(len(payload), cid, sid, kind) + payload
        self._cork.append(frame)
        self._cork_bytes += len(frame)
        if self._cork_bytes > self._CORK_HIGH_WATER and self._gate is not None:
            self._gate.clear()
        if self._cork_event is not None:
            self._cork_event.set()

    async def _send_gated(self, cid: int, sid: int, kind: int,
                          payload: bytes = b"") -> None:
        """_send for stream producers: waits out a backlogged backhaul first
        (the pump stalls, its hub queue fills, the hub drops it if slow)."""
        if self._gate is not None and not self._gate.is_set():
            await self._gate.wait()
        self._send(cid, sid, kind, payload)

    async def _flush_loop(self, writer: asyncio.StreamWriter) -> None:
        ev = self._cork_event
        try:
            while True:
                await ev.wait()
                ev.clear()
                if self._cork:
                    bufs, self._cork = self._cork, []
                    self._cork_bytes = 0
                    writer.write(b"".join(bufs))
                    await writer.drain()  # sole backpressure point
                    if self._gate is not None and self._cork_bytes <= self._CORK_HIGH_WATER:
                        self._gate.set()
        except asyncio.CancelledError:
            raise
        except Exception:
            # ANY transport failure: the backhaul is done for — don't leave
            # producers parked on a gate nobody will ever open
            logger.exception("backhaul flusher died; closing writer")
            writer.close()
        finally:
            if self._gate is not None:
                self._gate.set()

    def _send_end(self, cid: int, sid: int, status: int = 0, msg: str = "") -> None:
        raw = msg.encode()[:65535]
        self._send(cid, sid, K_END, struct.pack("<IH", status, len(raw)) + raw)

    async def _on_backhaul(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter) -> None:
        self._writer = writer
        self._cork_event = asyncio.Event()
        self._gate = asyncio.Event()
        self._gate.set()
        self._flusher = asyncio.get_running_loop().create_task(
            self._flush_loop(writer))
        logger.info("kbfront connected on %s", self.socket_path)
        buf = b""
        try:
            while True:
                chunk = await reader.read(1 << 16)
                if not chunk:
                    break
                buf += chunk
                off = 0
                n = len(buf)
                while n - off >= 13:
                    plen, cid, sid, kind = _HDR.unpack_from(buf, off)
                    if n - off - 13 < plen:
                        break
                    payload = buf[off + 13:off + 13 + plen]
                    off += 13 + plen
                    self._handle(cid, sid, kind, payload)
                buf = buf[off:]
        except (ConnectionResetError, BrokenPipeError):
            pass
        finally:
            if self._flusher is not None:
                self._flusher.cancel()
                self._flusher = None
            self._cork.clear()
            self._cork_bytes = 0
            if self._gate is not None:
                self._gate.set()  # unblock producers so their tasks can exit
            for key, st in list(self._streams.items()):
                if st.task is not None:
                    st.task.cancel()
            self._streams.clear()
            self._unary_pending.clear()

    # -------------------------------------------------------------- dispatch
    def _handle(self, cid: int, sid: int, kind: int, payload: bytes) -> None:
        """Frame dispatch. Unary RPCs take a fast path with NO task and no
        queue: the request is buffered on START/MSG and the terminal runs
        inline at HALF_CLOSE — per-op cost is a dict hit + protobuf decode +
        the backend op. Task machinery (~the cost of the op itself) is
        reserved for genuinely streaming methods."""
        key = (cid, sid)
        if kind == K_START:
            path = payload.decode()
            u = self.unary.get(path)
            if u is not None:
                self._unary_pending[key] = [u, b""]
                return
            st = _Stream()
            self._streams[key] = st
            st.task = asyncio.ensure_future(self._run_stream(cid, sid, path, st))
        elif kind == K_MSG:
            pending = self._unary_pending.get(key)
            if pending is not None:
                pending[1] = payload
                return
            st = self._streams.get(key)
            if st is not None:
                try:
                    st.queue.put_nowait(payload)
                except asyncio.QueueFull:
                    self._send(cid, sid, K_RST)
                    self._drop(key)
        elif kind == K_HALF_CLOSE:
            pending = self._unary_pending.pop(key, None)
            if pending is not None:
                if self._inline_unary:
                    self._unary_finish(cid, sid, pending)
                else:
                    loop = asyncio.get_running_loop()
                    fut = loop.run_in_executor(
                        None, self._unary_compute, pending)
                    fut.add_done_callback(
                        lambda f, c=cid, s=sid: self._unary_done(c, s, f))
                return
            st = self._streams.get(key)
            if st is not None:
                st.half_closed = True
                st.queue.put_nowait(None)
        elif kind == K_RST:
            self._unary_pending.pop(key, None)
            self._drop(key)
        elif kind == K_HTTP:
            asyncio.ensure_future(self._run_http(cid, sid, payload.decode()))

    def _drop(self, key) -> None:
        st = self._streams.pop(key, None)
        if st is not None and st.task is not None:
            st.task.cancel()

    # ----------------------------------------------------------- unary paths
    @staticmethod
    def _unary_compute(pending):
        """The handler call itself (inline or in the executor)."""
        (req_cls, fn), raw = pending
        return fn(req_cls.FromString(raw), _SYNC_CTX)

    def _unary_reply(self, cid: int, sid: int, result) -> None:
        """ONE copy of the response/error protocol: result() yields the
        response message or raises."""
        try:
            resp = result()
            out = bytes(resp) if isinstance(resp, bytes) else resp.SerializeToString()
            w = self._writer
            if w is not None and not w.is_closing():
                # MSG + END corked as one frame pair; counted against the
                # high-water gate (unary sends bypass the gate but their
                # bytes must still backpressure the stream producers)
                frame = (
                    _HDR.pack(len(out), cid, sid, K_MSG) + out
                    + _HDR.pack(6, cid, sid, K_END) + _END_OK
                )
                self._cork.append(frame)
                self._cork_bytes += len(frame)
                if self._cork_bytes > self._CORK_HIGH_WATER and self._gate is not None:
                    self._gate.clear()
                if self._cork_event is not None:
                    self._cork_event.set()
        except _AbortError as e:
            self._send_end(cid, sid, _status_num(e.code), e.details)
        except Exception as exc:
            logger.exception("front unary failed")
            self._send_end(
                cid, sid, _status_num(grpc.StatusCode.INTERNAL), str(exc))

    def _unary_finish(self, cid: int, sid: int, pending) -> None:
        self._unary_reply(cid, sid, lambda: self._unary_compute(pending))

    def _unary_done(self, cid: int, sid: int, fut) -> None:
        self._unary_reply(cid, sid, fut.result)

    # --------------------------------------------------------------- streams
    async def _run_stream(self, cid: int, sid: int, path: str, st: _Stream) -> None:
        # unary paths never reach here — _handle's fast path serves them
        # inline without a task
        key = (cid, sid)
        try:
            if path == "/etcdserverpb.Watch/Watch":
                await self._run_watch(cid, sid, st)
            elif path == "/etcdserverpb.Lease/LeaseKeepAlive":
                from ..server.etcd.misc import ERR_NOT_LEADER, LeaseNotLeaderError

                loop = asyncio.get_running_loop()
                while True:
                    raw = await st.queue.get()
                    if raw is None:
                        break
                    req = rpc_pb2.LeaseKeepAliveRequest.FromString(raw)
                    # real refresh via the shared registry; the scheduler
                    # SYSTEM-lane submit blocks, so keep it off the loop
                    try:
                        resp = await loop.run_in_executor(
                            None, self.lease.keepalive_one, req)
                    except LeaseNotLeaderError:
                        self._send_end(
                            cid, sid,
                            _status_num(grpc.StatusCode.UNAVAILABLE),
                            ERR_NOT_LEADER)
                        return
                    self._send(cid, sid, K_MSG, resp.SerializeToString())
                self._send_end(cid, sid, 0)
            elif path in self.sstream:
                req_cls, fn = self.sstream[path]
                first = await st.queue.get()
                request = req_cls.FromString(first or b"")
                loop = asyncio.get_running_loop()
                ctx = _SyncContextAdapter()
                gen = fn(request, ctx)
                it = iter(gen)
                try:
                    while True:
                        resp = await loop.run_in_executor(None, next, it, None)
                        if resp is None:
                            break
                        await self._send_gated(cid, sid, K_MSG, resp.SerializeToString())
                except _AbortError as e:
                    self._send_end(cid, sid, _status_num(e.code), e.details)
                    return
                self._send_end(cid, sid, 0)
            elif path == "/grpc.health.v1.Health/Check":
                from ..proto import health_pb2
                resp = health_pb2.HealthCheckResponse(status=1)  # SERVING
                self._send(cid, sid, K_MSG, resp.SerializeToString())
                self._send_end(cid, sid, 0)
            else:
                self._send_end(
                    cid, sid, _status_num(grpc.StatusCode.UNIMPLEMENTED),
                    f"unknown method {path}")
        except asyncio.CancelledError:
            raise
        except Exception as exc:  # terminal bug: surface as INTERNAL
            logger.exception("front stream %s failed", path)
            self._send_end(cid, sid, _status_num(grpc.StatusCode.INTERNAL), str(exc))
        finally:
            self._streams.pop(key, None)

    def _header(self):
        from ..server.etcd import shim
        return shim.header(self.backend.current_revision())

    async def _run_watch(self, cid: int, sid: int, st: _Stream) -> None:
        """Drive the shared AioWatchService against a backhaul-fed iterator."""
        async def req_iter():
            while True:
                raw = await st.queue.get()
                if raw is None:
                    return
                yield rpc_pb2.WatchRequest.FromString(raw)

        ctx = _FrontStreamContext()
        try:
            async for resp in self.watch.Watch(req_iter(), ctx):
                await self._send_gated(cid, sid, K_MSG, resp.SerializeToString())
        except _AbortError as e:
            self._send_end(cid, sid, _status_num(e.code), e.details)
            return
        self._send_end(cid, sid, 0)

    async def _run_http(self, cid: int, sid: int, req: str) -> None:
        """Plain-HTTP on the gRPC port (cmux parity): /health /status
        /election /metrics /debug/*."""
        parts = req.split(" ", 1)
        path = parts[1] if len(parts) == 2 else "/"
        path, _, qs = path.partition("?")
        handlers = self.server.http_handlers() if self.server is not None else {}
        try:
            if path in handlers:
                from .endpoint import http_call

                loop = asyncio.get_running_loop()
                _ctype, body = await loop.run_in_executor(
                    None, http_call(handlers[path], qs))
                self._send(cid, sid, K_END, struct.pack("<IH", 200, 0) + body)
            elif path == "/metrics" and self.metrics is not None:
                loop = asyncio.get_running_loop()
                _ctype, body = await loop.run_in_executor(
                    None, self.metrics.http_handler())
                self._send(cid, sid, K_END, struct.pack("<IH", 200, 0) + body)
            else:
                self._send(cid, sid, K_END,
                           struct.pack("<IH", 404, 0) + b"not found\n")
        except Exception as exc:
            logger.exception("front http %s failed", path)
            self._send(cid, sid, K_END,
                       struct.pack("<IH", 500, 0) + str(exc).encode())


class _FrontStreamContext:
    """Context shim for the aio watch coroutine."""

    def abort(self, code, details):
        raise _AbortError(code, details)

    async def write(self, *_a, **_k):  # pragma: no cover - not used
        raise NotImplementedError

    def is_active(self) -> bool:
        return True
