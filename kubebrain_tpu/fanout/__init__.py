"""kubebrain_tpu.fanout — production-scale watch fan-out (docs/watch.md).

The layer between the sequencer and the wire: a persistent device-resident
watcher-spec table (:class:`WatcherTable`), the single dispatch funnel
(:func:`fanout_dispatch`, kblint KB127), and the hub-facing matcher
(:class:`DeviceFanout`) with its byte-identical host oracle
(:func:`match_oracle`).

One device dispatch matches a whole sequencer drain block (the contiguous
revision block group commit hands ``Backend._notify_many``) against the
entire watcher population, sharded over the ``wat`` mesh axis, and returns
delivery work sized O(matched pairs) — never the [E, W] mask.
"""

from .dispatch import fanout_dispatch
from .matcher import DeviceFanout, match_oracle
from .table import WatcherTable

__all__ = ["DeviceFanout", "WatcherTable", "fanout_dispatch", "match_oracle"]
