"""The single fan-out kernel dispatch funnel (docs/watch.md).

:func:`fanout_dispatch` is the ONE place the block-batched path launches the
range-match kernel (``ops.fanout.fanout_mask_range_wmajor``) — kblint KB127
confines
``fanout_mask*`` references to this module and the legacy per-batch funnel
(``ops/fanout.py``), the way KB109 confines the scan kernels to their
assembly points. Everything above (matcher, hub, backend) works in terms of
compacted (watcher, event) index pairs and never sees the [E, W] mask.

Layout contract (mirrors the PR 7 ``_part_indices_of_mask`` discipline):

- Watcher columns arrive sharded over the mesh's first axis (``wat`` from
  the CLI); event columns are replicated — every shard matches every event
  against its own watcher slice, so the [E, W] mask only ever exists
  shard-local and is consumed in-register.
- Per shard the mask is compacted to watcher-major flat indices
  ``w_local * E + e`` scatter-free: one popcount cumsum over the flat mask,
  then a batched binary search that asks, for each of the ``size`` output
  slots, where the running count first reaches it (``_compact``). Measured
  on CPU this beats ``jnp.nonzero(size=)`` (sort-based) ~9x and a
  drop-mode scatter ~5x, and the cost is flat in match density. Output:
  real matches first in ascending order, then ``fill = Wl * E``. The host
  reads the first ``sum(shard counts)`` entries of each shard's slice — a
  transfer O(matched pairs) + O(W) counts, never O(E·W).
- ``size`` and ``mesh`` are static (two jit cache keys per (epad, W,
  size) triple); ``n_ev`` is a traced scalar so drain-depth churn within an
  E bucket never recompiles, and E-padding rows are masked out on device
  (a zero-key padding event would otherwise match every unbounded
  min_rev=0 watcher).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..ops.fanout import fanout_mask_range_wmajor


def _wat_shard_map(f, mesh, n_wat_args: int, n_rep_args: int, n_out: int):
    """shard_map ``f`` along the mesh's first axis when it is multi-device:
    the LAST ``n_wat_args`` args shard on axis 0, the first ``n_rep_args``
    replicate, and every output shards on axis 0 (counts over W, indices
    over the per-shard slices). Single-device / no mesh: run unsharded —
    the compaction layout degenerates to one shard covering the table."""
    if mesh is None or mesh.devices.size <= 1:
        return f
    from jax.sharding import PartitionSpec as PS

    axis = mesh.axis_names[0]
    specs = dict(
        in_specs=(PS(),) * n_rep_args + (PS(axis),) * n_wat_args,
        out_specs=(PS(axis),) * n_out,
    )
    shard_map = getattr(jax, "shard_map", None)
    if shard_map is None:  # pre-0.8 jax
        from jax.experimental.shard_map import shard_map

        specs["check_rep"] = False
    else:
        specs["check_vma"] = False
    return shard_map(f, mesh=mesh, **specs)


@functools.partial(jax.jit, static_argnames=("size", "mesh"))
def fanout_dispatch(
    event_keys: jnp.ndarray,   # uint32[E, C] packed event keys (E-padded)
    ev_rev_hi: jnp.ndarray,    # uint32[E]
    ev_rev_lo: jnp.ndarray,    # uint32[E]
    n_ev: jnp.ndarray,         # int32 scalar: real events (rest is padding)
    w_start: jnp.ndarray,      # uint32[W, C] sharded over wat
    w_end: jnp.ndarray,        # uint32[W, C]
    w_unbounded: jnp.ndarray,  # bool[W]
    min_rev_hi: jnp.ndarray,   # uint32[W]
    min_rev_lo: jnp.ndarray,   # uint32[W]
    size: int,
    mesh=None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Match one drain block against the whole watcher table in one launch.

    Returns ``(counts int32[W], idx int32[n_shards * size])``: per-slot
    match counts plus each shard's compacted watcher-major flat indices
    (``w_local * E + e``, ascending; first sum-of-shard-counts entries
    real, rest ``fill = Wl * E``). ``sum(shard counts) > size`` means that
    shard's indices were truncated — the caller re-dispatches with a
    bigger static ``size``.
    """
    def local(ek, ehi, elo, nev, ws, we, wu, whi, wlo):
        # watcher-major from the source: the compaction consumes the mask
        # flat in w_local * E + e order, and producing [Wl, E] directly
        # fuses with the compare (an explicit .T re-materializes [E, W])
        mask = fanout_mask_range_wmajor(ek, ehi, elo, ws, we, wu, whi, wlo)
        e = mask.shape[1]
        mask = mask & (jnp.arange(e, dtype=jnp.int32) < nev)[None, :]
        counts = jnp.sum(mask, axis=1, dtype=jnp.int32)               # [Wl]
        # watcher-major flat indices: w_local * E + e
        return counts, _compact(mask.reshape(-1), size)

    f = _wat_shard_map(local, mesh, n_wat_args=5, n_rep_args=4, n_out=2)
    return f(event_keys, ev_rev_hi, ev_rev_lo, jnp.asarray(n_ev, jnp.int32),
             w_start, w_end, w_unbounded, min_rev_hi, min_rev_lo)


def _compact(flat: jnp.ndarray, size: int) -> jnp.ndarray:
    """Compact a flat bool mask to its ``True`` indices: ascending, first
    ``popcount(flat)`` entries real, ``fill = len(flat)``, truncated at
    ``size`` (the caller detects truncation from the exact counts and
    re-dispatches bigger).

    Scatter-free: the j-th match's flat index is the first position whose
    running popcount reaches j+1, so one cumsum plus a batched binary
    search over the ``size`` output slots replaces any scatter of the n
    candidate positions. On XLA CPU a 5M-element drop-mode scatter costs
    ~0.3s where cumsum + searchsorted costs ~0.07s, and unlike
    ``jnp.nonzero(size=)`` (sort-based, ~9x slower) the cost is flat in
    the match density — dense broad-watcher populations that grow ``size``
    toward n pay the same single pass. Queries past the total count find
    no position and return n: the fill value, by construction.
    Shard-local under shard_map."""
    csum = jnp.cumsum(flat.astype(jnp.int32))
    q = jnp.arange(1, size + 1, dtype=jnp.int32)
    return jnp.searchsorted(csum, q).astype(jnp.int32)
