"""Hub-facing device matcher for block-batched watch fan-out (docs/watch.md).

:class:`DeviceFanout` is what the CLI hands the WatcherHub when
``--tpu-fanout`` is armed. It exposes two protocols:

- ``deliver(batch, specs, version)`` — the block path: one device dispatch
  for the WHOLE drain block against the persistent sharded
  :class:`~kubebrain_tpu.fanout.table.WatcherTable`, then one vectorized
  demux of the compacted (watcher, event) pairs into per-subscriber event
  lists. The hub prefers this when present (``prefers_blocks``).
- ``__call__(events, specs, version)`` — the legacy mask protocol
  (bool[E, W] in spec order), kept so the hub's per-batch fallback and the
  differential tests run the same machinery.

Dispatch sizing: the per-shard index capacity is a persistent pow2 bucket.
When the counts transfer shows a shard overflowed it, the matcher doubles
the bucket and re-dispatches — so the steady state is ONE launch per drain
and the compile cache holds a handful of sizes, never one per depth.

:func:`match_oracle` is the brute-force host oracle the tests hold every
path byte-identical to (raw-bytes etcd range semantics — no packing, no
canonicalization: the packed compare must agree with it by construction).
"""

from __future__ import annotations

import numpy as np

from ..ops import keys as keyops
from ..trace import TRACER
from .table import WatcherTable, pow2_at_least

#: smallest per-shard compacted-index transfer (pow2; grows on overflow)
MIN_IDX_SIZE = 128

#: smallest E bucket, matching the legacy matcher (drain depths 1..8 share
#: one compiled shape)
MIN_EVENT_BUCKET = 8


def match_oracle(events, specs) -> np.ndarray:
    """bool[E, W] delivery mask, brute force on raw bytes in spec order.

    Plain etcd watch semantics — ``start <= key`` and (unbounded or
    ``key < end``) and ``rev >= min_rev`` — with Python bytes comparison,
    so NUL-bearing bounds (single-key watch end = key + b"\\0") are
    exercised unrewritten. Every device/index path must match this
    byte-for-byte.
    """
    out = np.zeros((len(events), len(specs)), dtype=bool)
    for j, (_wid, start, end, min_rev) in enumerate(specs):
        for i, ev in enumerate(events):
            out[i, j] = (
                ev.key >= start
                and (not end or ev.key < end)
                and ev.revision >= min_rev
            )
    return out


class DeviceFanout:
    """Persistent-table device matcher with block delivery and overflow-
    regrown compacted transfers. Thread-compat: the hub calls from its
    single drainer thread; table sync is internally locked."""

    #: hub protocol marker: hand this matcher whole drain blocks
    prefers_blocks = True

    def __init__(self, width: int | None = None, mesh=None,
                 metrics=None):
        # width None = auto: the table buckets the packed width to the
        # population's longest key (half the chunk compares of the 128-byte
        # protocol max on typical registry keys); an int pins it
        self._table = WatcherTable(width=width, mesh=mesh)
        # the table owns the "is this mesh real" decision; a single-device
        # mesh must not poison the jit cache key with a dead mesh object
        self._mesh = mesh if self._table.sharded else None
        self._idx_size = MIN_IDX_SIZE
        self._metrics = None
        self.stats = {"dispatches": 0, "redispatches": 0, "pairs": 0,
                      "blocks": 0}
        if metrics is not None:
            self.set_metrics(metrics)

    def set_metrics(self, metrics) -> None:
        """Arm the ``kb.fanout.sharded`` gauge (1 = watcher table sharded
        over a multi-device wat mesh, 0 = single-device fallback) — the
        observable for the old silent ragged-count fallback."""
        self._metrics = metrics
        if metrics is not None:
            sharded = 1.0 if self._table.sharded else 0.0
            metrics.emit_gauge("kb.fanout.sharded", sharded)
            metrics.register_gauge_fn(
                "kb.fanout.sharded",
                lambda: 1.0 if self._table.sharded else 0.0)

    @property
    def table(self) -> WatcherTable:
        return self._table

    # ------------------------------------------------------------- matching
    def _pack_events(self, batch):
        e = len(batch)
        epad = pow2_at_least(e, MIN_EVENT_BUCKET)
        keys = [ev.key for ev in batch] + [b""] * (epad - e)
        revs = [ev.revision for ev in batch] + [0] * (epad - e)
        # event keys must fit the table's packed width (and must be packed
        # AT that width — the kernel compares chunk-for-chunk)
        self._table.ensure_width(max(len(k) for k in keys) + 2)
        ek, _ = keyops.pack_keys(keys, self._table.width)
        ehi, elo = keyops.split_revs(np.array(revs, dtype=np.uint64))
        return ek, ehi, elo, epad

    def _match(self, batch, specs, version=None):
        """One block → (slots int64[M], eidx int64[M], wids int64[cap]):
        compacted matched pairs in ascending (slot, event) order plus the
        slot→wid map snapshot. Transfer is O(M) + O(cap) counts."""
        from .dispatch import fanout_dispatch

        self._table.sync(specs, version)
        empty = (np.zeros(0, np.int64), np.zeros(0, np.int64),
                 np.zeros(0, np.int64))
        if not batch or not specs:
            return empty
        ek, ehi, elo, epad = self._pack_events(batch)
        ws, we, wu, whi, wlo, wids, _ver = self._table.device_view()
        cap = wids.shape[0]
        n_sh = max(self._table.stats()["devices"], 1)
        w_local = cap // n_sh
        while True:
            self.stats["dispatches"] += 1
            with TRACER.stage("fanout_dispatch"):
                counts, idx = fanout_dispatch(
                    ek, ehi, elo, np.int32(len(batch)),
                    ws, we, wu, whi, wlo,
                    size=self._idx_size, mesh=self._mesh)
            with TRACER.stage("fanout_copy"):
                counts = np.asarray(counts)
                shard_tot = counts.reshape(n_sh, w_local).sum(axis=1)
                overflow = int(shard_tot.max(initial=0))
                if overflow > self._idx_size:
                    # a shard truncated its index slice: double the bucket
                    # and re-launch (rare — the bucket is persistent, so
                    # the steady state is one launch per drain)
                    self._idx_size = pow2_at_least(overflow,
                                                   self._idx_size * 2)
                    self.stats["redispatches"] += 1
                    continue
                idx = np.asarray(idx)
                break
        slots, eidx = [], []
        for s in range(n_sh):
            nv = int(shard_tot[s])
            if not nv:
                continue
            loc = idx[s * self._idx_size: s * self._idx_size + nv].astype(
                np.int64)
            slots.append(s * w_local + loc // epad)
            eidx.append(loc % epad)
        if not slots:
            return (empty[0], empty[1], wids)
        slots = np.concatenate(slots)
        eidx = np.concatenate(eidx)
        self.stats["pairs"] += len(slots)
        return slots, eidx, wids

    # ------------------------------------------------------------ protocols
    def deliver(self, batch, specs, version=None) -> dict[int, list]:
        """Block protocol: {wid: [events, batch order]} for one drain block
        — sync, one dispatch, one vectorized demux (matched pairs arrive
        slot-major so the per-subscriber split is diff + split, no sort)."""
        self.stats["blocks"] += 1
        slots, eidx, wids = self._match(batch, specs, version)
        if not len(slots):
            return {}
        cuts = np.flatnonzero(np.diff(slots)) + 1
        groups = np.split(eidx, cuts)
        heads = slots[np.concatenate(([0], cuts))]
        out: dict[int, list] = {}
        for slot, evs in zip(heads, groups):
            wid = int(wids[slot])
            if wid < 0:
                continue  # sentinel rows never match; belt and braces
            out[wid] = [batch[int(i)] for i in evs]
        return out

    def __call__(self, events, watcher_specs, version=None) -> np.ndarray:
        """Legacy mask protocol: bool[E, W] in ``watcher_specs`` order."""
        slots, eidx, wids = self._match(events, watcher_specs, version)
        mask = np.zeros((len(events), len(watcher_specs)), dtype=bool)
        if len(slots):
            col = {wid: j for j, (wid, *_r) in enumerate(watcher_specs)}
            cols = np.array([col[int(wids[s])] for s in slots],
                            dtype=np.int64)
            mask[eidx, cols] = True
        return mask
