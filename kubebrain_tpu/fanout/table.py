"""Persistent device-resident watcher-spec table (docs/watch.md).

The hub's watcher population, held as five device-resident columns in the
packed-key domain the dispatch kernel compares event keys against:

    start[W, C]   end[W, C]   unbounded[W]   min_rev_hi[W]   min_rev_lo[W]

Lifecycle:

- ``sync(specs, version)`` reconciles the table with a hub snapshot by
  DIFF, not rebuild: only rows whose watcher changed are re-packed and
  marked dirty, so steady-state watcher churn costs O(changed rows), not
  O(W) packing. The O(1) fast path (version unchanged) skips the diff
  entirely. A hub restart reuses versions from 0 — the diff is keyed on
  watcher ids + filters, so a version REGRESSION (or an id collision with
  different filters) rewrites exactly the rows that differ and can never
  match against a dead population (the stale-packed-table bug the legacy
  matcher needed an explicit regression check for).
- ``device_view()`` publishes the columns: a full transfer on first use /
  capacity growth, a dirty-slot scatter otherwise.
- Capacity is a bucket (pow2 to 1024, 1024-steps beyond) rounded up to a
  multiple of the mesh device count, so the ``wat`` sharding ALWAYS
  applies — there is no ragged-count unsharded fallback by construction.
- The packed width is sized to the POPULATION, not to the 128-byte
  protocol maximum: registry keys run ~50 bytes, so packing at a pow2
  bucket over the longest live bound (plus the canonicalization margin)
  halves the kernel's chunk-compare work for typical populations. Width
  only grows (pow2 steps, so at most a handful of recompiles ever), and a
  growth is a full republish like a capacity growth. Passing an explicit
  ``width`` pins it (pack_keys then rejects longer keys loudly).

Free slots hold a never-match sentinel: a bounded EMPTY range
(end = all-zero chunks, unbounded = False) fails the ``key < end`` test
for every possible key, so padding and freed slots are inert regardless
of the start column or revision filter.
"""

from __future__ import annotations

import threading

import numpy as np

from ..ops import keys as keyops

#: smallest table capacity — watcher counts below this pay one compile
MIN_CAPACITY = 64

#: smallest auto-sized packed width in bytes (8 uint32 chunks)
MIN_WIDTH = 32


def pow2_at_least(n: int, lo: int = 1) -> int:
    b = lo
    while b < n:
        b *= 2
    return b


class WatcherTable:
    def __init__(self, width: int | None = None, mesh=None):
        self._auto_width = width is None
        self._width = width if width is not None else MIN_WIDTH
        self._chunks = self._width // 4
        # a mesh only shards when it is actually multi-device; axis name is
        # taken from the mesh (``wat`` from the CLI, anything in embedders)
        self._mesh = mesh if (mesh is not None and mesh.devices.size > 1) else None
        self._lock = threading.Lock()
        self._specs: dict[int, tuple[bytes, bytes, int]] = {}
        self._slot_of: dict[int, int] = {}
        self._free: list[int] = []
        self._version: int | None = None  # hub watcher-set version last synced
        # widened O(1) fast-path key: (version, count, first wid, last wid).
        # A hub restart reuses versions from 0, so a bare version-equality
        # check could alias a DEAD population of the same version; widening
        # with the population's cheap shape makes the skip safe, and any
        # mismatch (including version REGRESSION) falls through to the
        # content diff, which is exact.
        self._sync_key: tuple | None = None
        self._epoch = 0          # bumps on (re)allocation → full republish
        self._dev: tuple | None = None
        self._dev_epoch = -1
        self._dirty: set[int] = set()
        self._cap = 0
        # under the lock like every other _alloc site: _alloc touches the
        # column/free-list fields the sync path guards, and construction
        # being single-threaded is a fact about callers, not the fields
        with self._lock:
            self._alloc(self._capacity_for(1))

    # ---------------------------------------------------------------- layout
    def _n_dev(self) -> int:
        return int(self._mesh.devices.size) if self._mesh is not None else 1

    def _capacity_for(self, n: int) -> int:
        """Pow2 buckets up to 1024, then 1024-step buckets: at 10k watchers
        a pure pow2 bucket pads to 16384 — 64% of the kernel's rows would
        be dead sentinels. Capacity only grows, so the compile-cache shape
        count stays bounded either way."""
        n = max(n, 1)
        if n <= 1024:
            cap = pow2_at_least(n, MIN_CAPACITY)
        else:
            cap = ((n + 1023) // 1024) * 1024
        nd = self._n_dev()
        return ((cap + nd - 1) // nd) * nd

    def _grow_width_locked(self, n_bytes: int) -> None:
        """Grow the packed width so an ``n_bytes`` key (or bound) fits.
        Auto-width mode only — an explicit width stays pinned and overlong
        keys fail loudly in pack_keys. Growth re-packs every live row at
        the new chunk count and bumps the epoch (full republish)."""
        if not self._auto_width:
            return
        width = pow2_at_least(max(n_bytes, MIN_WIDTH))
        if width <= self._width:
            return
        self._width = width
        self._chunks = width // 4
        cap = self._cap
        self._cap = 0          # fresh zeroed columns at the new chunk count
        self._free = []
        self._alloc(cap)
        used = set(self._slot_of.values())
        self._free = [s for s in range(cap - 1, -1, -1) if s not in used]
        for wid, slot in self._slot_of.items():
            self._write_row_locked(slot, wid, self._specs[wid])

    def ensure_width(self, n_bytes: int) -> None:
        """Public width guard for the EVENT side: the matcher calls this
        with the block's longest key before packing at ``self.width``."""
        with self._lock:
            self._grow_width_locked(n_bytes)

    def _alloc(self, cap: int) -> None:
        """(Re)allocate the host shadow columns at ``cap`` slots, preserving
        live rows; every new slot is a never-match sentinel."""
        starts = np.zeros((cap, self._chunks), dtype=np.uint32)
        ends = np.zeros((cap, self._chunks), dtype=np.uint32)  # empty range
        unb = np.zeros(cap, dtype=bool)
        hi = np.zeros(cap, dtype=np.uint32)
        lo = np.zeros(cap, dtype=np.uint32)
        wids = np.full(cap, -1, dtype=np.int64)
        if self._cap:
            starts[: self._cap] = self._starts
            ends[: self._cap] = self._ends
            unb[: self._cap] = self._unb
            hi[: self._cap] = self._hi
            lo[: self._cap] = self._lo
            wids[: self._cap] = self._wids
        self._free.extend(range(cap - 1, self._cap - 1, -1))
        self._starts, self._ends, self._unb = starts, ends, unb
        self._hi, self._lo, self._wids = hi, lo, wids
        self._cap = cap
        self._epoch += 1
        self._dirty.clear()  # full republish supersedes any pending scatter

    def _rows_for(self, start: bytes, end: bytes, min_rev: int):
        """Packed chunk rows for one watcher spec. NUL-bearing bounds
        (single-key watches use end = key + b"\\0") are canonicalized the
        same way the legacy matcher and the scan path do."""
        srow = keyops.pack_one(keyops.canonicalize_bound(start), self._width)
        erow = keyops.pack_one(keyops.canonicalize_bound(end), self._width)
        hi, lo = keyops.split_revs(np.array([min_rev], dtype=np.uint64))
        return srow, erow, (not end), hi[0], lo[0]

    def _write_row_locked(self, slot: int, wid: int,
                          spec: tuple[bytes, bytes, int] | None) -> None:
        if spec is None:  # sentinel: bounded empty range can never match
            self._starts[slot] = 0
            self._ends[slot] = 0
            self._unb[slot] = False
            self._hi[slot] = 0
            self._lo[slot] = 0
            self._wids[slot] = -1
        else:
            s, e, u, hi, lo = self._rows_for(*spec)
            self._starts[slot] = s
            self._ends[slot] = e
            self._unb[slot] = u
            self._hi[slot] = hi
            self._lo[slot] = lo
            self._wids[slot] = wid
        self._dirty.add(slot)

    # ----------------------------------------------------------------- sync
    def sync(self, specs: list[tuple[int, bytes, bytes, int]],
             version: int | None = None) -> None:
        """Reconcile with a hub snapshot ``[(wid, start, end, min_rev)]``.

        O(1) when ``version`` matches the last sync; otherwise an O(W) dict
        diff that re-packs only changed rows. Correct under version
        regression / wid collision by construction (rows are compared by
        content, not trusted by version)."""
        key = (version, len(specs),
               specs[0][0] if specs else None,
               specs[-1][0] if specs else None)
        with self._lock:
            if version is not None and key == self._sync_key:
                return
            if specs:
                # +2: canonicalize_bound may extend a NUL-bearing bound by
                # one byte past its base
                self._grow_width_locked(
                    max(max(len(s), len(e)) for _, s, e, _ in specs) + 2)
            want = {wid: (s, e, r) for wid, s, e, r in specs}
            for wid in [w for w in self._slot_of if w not in want]:
                slot = self._slot_of.pop(wid)
                del self._specs[wid]
                self._write_row_locked(slot, wid, None)
                self._free.append(slot)
            if len(want) > self._cap:
                # live rows survive the realloc; the epoch bump republishes
                # them without re-packing
                self._alloc(self._capacity_for(len(want)))
            for wid, spec in want.items():
                have = self._specs.get(wid)
                if have == spec:
                    continue
                slot = self._slot_of.get(wid)
                if slot is None:
                    slot = self._free.pop()
                    self._slot_of[wid] = slot
                self._specs[wid] = spec
                self._write_row_locked(slot, wid, spec)
            self._version = version
            self._sync_key = key

    # ----------------------------------------------------------- publication
    def _put(self, arr):
        import jax

        if self._mesh is None:
            return jax.device_put(arr)
        from jax.sharding import NamedSharding, PartitionSpec

        axis = self._mesh.axis_names[0]
        spec = PartitionSpec(axis, *(None,) * (arr.ndim - 1))
        return jax.device_put(arr, NamedSharding(self._mesh, spec))

    def device_view(self):
        """Publish dirty rows (or the whole table on first use / growth) and
        return ``(starts, ends, unb, hi, lo, wids, version)`` — device
        columns plus the slot→wid host map the demux decodes with. The wids
        array is a snapshot copy: a concurrent sync can't mutate it under a
        caller mid-demux."""
        with self._lock:
            if self._dev is None or self._dev_epoch != self._epoch:
                self._dev = tuple(
                    self._put(a) for a in
                    (self._starts, self._ends, self._unb, self._hi, self._lo))
                self._dev_epoch = self._epoch
                self._dirty.clear()
            elif self._dirty:
                # dirty-slot scatter, index count bucketed to a pow2 (pad
                # repeats a real slot — same-value double write, idempotent)
                # so churn depth doesn't grow the compile cache
                idx = np.fromiter(self._dirty, dtype=np.int64,
                                  count=len(self._dirty))
                pad = pow2_at_least(len(idx), 8) - len(idx)
                if pad:
                    idx = np.concatenate([idx, np.full(pad, idx[0])])
                cols = []
                for dev, host in zip(self._dev, (self._starts, self._ends,
                                                 self._unb, self._hi, self._lo)):
                    updated = dev.at[idx].set(host[idx])
                    # re-pin the sharding device-to-device (device_put on a
                    # jax array never round-trips the host): the scatter's
                    # output sharding is whatever GSPMD picked
                    cols.append(self._put(updated)
                                if self._mesh is not None else updated)
                self._dev = tuple(cols)
                self._dirty.clear()
            return (*self._dev, self._wids.copy(), self._version)

    # ------------------------------------------------------------- inspection
    @property
    def version(self) -> int | None:
        with self._lock:
            return self._version

    @property
    def capacity(self) -> int:
        with self._lock:
            return self._cap

    @property
    def width(self) -> int:
        with self._lock:
            return self._width

    @property
    def sharded(self) -> bool:
        return self._mesh is not None

    def spec_count(self) -> int:
        with self._lock:
            return len(self._specs)

    def stats(self) -> dict:
        with self._lock:
            return {
                "capacity": self._cap,
                "width": self._width,
                "watchers": len(self._specs),
                "devices": self._n_dev(),
                "sharded": self._mesh is not None,
                "epoch": self._epoch,
                "dirty": len(self._dirty),
                "version": self._version,
            }
