"""Deterministic fault injection + chaos-mode replay (docs/faults.md).

Three pieces, mirroring the reference's robustness posture (the whole
``retry/`` backend component exists to survive storage faults):

- :mod:`.schedule` — pure, seeded fault schedules (the replay identity:
  same preset+seed+horizon ⇒ byte-identical trace sha);
- :mod:`.plane` — the armed runtime plane answering injection decisions
  at every boundary (storage ops, endpoint RPCs, watch streams, the TPU
  mirror's merge machinery), inert until armed;
- :mod:`.inject` — the ``FaultyStorage`` engine decorator injecting the
  storage error taxonomy (latency / definite error / *uncertain*
  outcome) under any engine.

The chaos runner (``make bench-cluster FAULTS=<preset>``) replays a
workload against a fault-armed server and proves the keystone invariant:
every client-acknowledged write is present in a final authoritative scan
and every definite error is absent — ambiguous outcomes may be either
(the linearizability discipline of tests/test_linearizability.py).
"""

from .inject import FaultyStorage, wrap_engine
from .plane import FaultInjectedError, FaultPlane
from .schedule import (
    ALL_KINDS,
    CONN_DROP,
    ENCODE_OVERFLOW,
    FENCE_TIMEOUT,
    LEADER_UNREACH,
    MERGE_FAIL,
    MERGE_SUPPRESS,
    PRESETS,
    REPL_RESET,
    REPLICA_KINDS,
    STORAGE_ERROR,
    STORAGE_LATENCY,
    STORAGE_UNCERTAIN,
    WATCH_RESET,
    FaultSchedule,
    FaultWindow,
    generate,
)

__all__ = [
    "FaultyStorage", "wrap_engine", "FaultPlane", "FaultInjectedError",
    "FaultSchedule", "FaultWindow", "generate", "PRESETS", "ALL_KINDS",
    "STORAGE_LATENCY", "STORAGE_ERROR", "STORAGE_UNCERTAIN",
    "WATCH_RESET", "CONN_DROP", "MERGE_FAIL", "MERGE_SUPPRESS",
    "ENCODE_OVERFLOW", "REPL_RESET", "LEADER_UNREACH", "FENCE_TIMEOUT",
    "REPLICA_KINDS",
]
