"""Engine decorator injecting the storage error taxonomy at the op
boundary — the fault-plane twin of ``storage/metrics_wrap.py``.

``FaultyStorage`` wraps any engine and, per boundary call, asks the
:class:`~kubebrain_tpu.faults.plane.FaultPlane` for a decision:

- ``latency``  — sleep, then delegate (slow disk / network hiccup);
- ``error``    — raise :class:`FaultInjectedError` WITHOUT delegating: a
  definite failure, provably nothing applied (the keystone consistency
  check's "definite errors must be absent" side);
- ``uncertain_applied`` — delegate (the op really commits), then raise
  ``UncertainResultError``: the commit landed but the caller cannot know;
- ``uncertain_dropped`` — raise ``UncertainResultError`` without
  delegating: the commit did NOT land, and the caller cannot know that
  either.

The two uncertain arms are indistinguishable above this layer by
construction — exactly the shape ``backend/retry.py``'s async FIFO
read-back repair and the TSO revision-gap accounting exist for. In the
TPU topology this decorator wraps the *inner host engine* (below
``TpuKvStorage``) so injected uncertainty exercises the mirror's
quarantine/rebuild state machine, not just the client surface.

Group commits (``write_batch``) get PER-OP injection: faulted members are
carved out of the engine round trip (definite/dropped members are never
applied; applied-uncertain members ride a real engine commit) and their
outcomes spliced back in op order, so one poisoned rider fails alone and
the group's survivors commit normally.
"""

from __future__ import annotations

import time

from .. import storage as _storage
from ..storage import BatchWrite, KvStorage, UncertainResultError
from .plane import FaultInjectedError, FaultPlane


class FaultyStorage(KvStorage):
    def __init__(self, inner: KvStorage, plane: FaultPlane) -> None:
        self._inner = inner
        self._plane = plane
        # capability mirroring (the metrics_wrap pattern): hasattr() on this
        # wrapper must answer exactly like the wrapped engine
        if hasattr(inner, "mvcc_write"):
            self.mvcc_write = self._mvcc_write_faulty
        if hasattr(inner, "mvcc_delete"):
            self.mvcc_delete = self._mvcc_delete_faulty
        if hasattr(inner, "write_batch"):
            self.write_batch = self._write_batch_faulty
        if hasattr(inner, "prune_versions"):
            self.prune_versions = inner.prune_versions
        if hasattr(inner, "export_mvcc"):
            self.export_mvcc = inner.export_mvcc

    # ------------------------------------------------------------- decisions
    def _write_gate(self):
        """Pre-apply write decision. Returns True when the op must ALSO be
        applied before raising (uncertain_applied); raises for the
        definite/dropped arms; sleeps for latency."""
        d = self._plane.decide_storage(write=True)
        if d is None:
            return False
        kind, param = d
        if kind == "latency":
            time.sleep(param)
            return False
        if kind == "error":
            raise FaultInjectedError("injected storage error (definite)")
        if kind == "uncertain_dropped":
            raise UncertainResultError("injected uncertain outcome")
        return True  # uncertain_applied: caller applies, then raises

    def _read_gate(self) -> None:
        d = self._plane.decide_storage(write=False)
        if d is None:
            return
        kind, param = d
        if kind == "latency":
            time.sleep(param)
            return
        raise FaultInjectedError("injected storage read error")

    # ------------------------------------------------------------ fast paths
    def _mvcc_write_faulty(self, *args, **kwargs):
        raise_after = self._write_gate()
        out = self._inner.mvcc_write(*args, **kwargs)
        if raise_after:
            raise UncertainResultError("injected uncertain outcome (applied)")
        return out

    def _mvcc_delete_faulty(self, *args, **kwargs):
        raise_after = self._write_gate()
        out = self._inner.mvcc_delete(*args, **kwargs)
        if raise_after:
            raise UncertainResultError("injected uncertain outcome (applied)")
        return out

    def _write_batch_faulty(self, ops: list) -> list:
        """Per-op injection with the survivors committed in ONE inner round
        trip; outcomes aligned with ``ops`` (the engine write_batch
        contract — ``("uncertain", exc)`` members ride the retry FIFO)."""
        out: list = [None] * len(ops)
        send: list[tuple[int, tuple]] = []
        uncertain_applied: list[int] = []
        for i, op in enumerate(ops):
            d = self._plane.decide_storage(write=True)
            if d is None:
                send.append((i, op))
                continue
            kind, param = d
            if kind == "latency":
                time.sleep(param)
                send.append((i, op))
            elif kind == "error":
                out[i] = ("error",
                          FaultInjectedError("injected storage error"))
            elif kind == "uncertain_dropped":
                out[i] = ("uncertain",
                          UncertainResultError("injected uncertain outcome"))
            else:  # uncertain_applied: commit it, report uncertainty
                send.append((i, op))
                uncertain_applied.append(i)
        if send:
            results = self._inner.write_batch([op for _i, op in send])
            for (i, _op), res in zip(send, results):
                out[i] = res
        for i in uncertain_applied:
            out[i] = ("uncertain",
                      UncertainResultError("injected uncertain (applied)"))
        return out

    # ---------------------------------------------------------- engine iface
    def get_timestamp_oracle(self) -> int:
        return self._inner.get_timestamp_oracle()

    def get_partitions(self, start, end):
        return self._inner.get_partitions(start, end)

    def get(self, key, snapshot_ts=None):
        self._read_gate()
        return self._inner.get(key, snapshot_ts)

    def iter(self, start, end, snapshot_ts=None, limit=0):
        self._read_gate()
        return self._inner.iter(start, end, snapshot_ts, limit)

    def begin_batch_write(self) -> BatchWrite:
        return _FaultyBatch(self._inner.begin_batch_write(), self)

    def delete(self, key):
        raise_after = self._write_gate()
        self._inner.delete(key)
        if raise_after:
            raise UncertainResultError("injected uncertain outcome (applied)")

    def del_current(self, key, expected_value):
        raise_after = self._write_gate()
        self._inner.del_current(key, expected_value)
        if raise_after:
            raise UncertainResultError("injected uncertain outcome (applied)")

    def support_ttl(self) -> bool:
        return self._inner.support_ttl()

    def exclusive_client(self) -> KvStorage:
        return FaultyStorage(self._inner.exclusive_client(), self._plane)

    def make_scanner(self, **kwargs):
        return self._inner.make_scanner(**kwargs)

    def close(self) -> None:
        self._inner.close()


class _FaultyBatch(BatchWrite):
    """Records ops on the inner batch; the injection decision happens at
    commit (the atomic boundary — a batch either applies whole or not)."""

    def __init__(self, inner: BatchWrite, owner: FaultyStorage) -> None:
        self._inner = inner
        self._owner = owner

    def put_if_not_exist(self, key, value, ttl_seconds=0):
        self._inner.put_if_not_exist(key, value, ttl_seconds)

    def cas(self, key, new_value, old_value, ttl_seconds=0):
        self._inner.cas(key, new_value, old_value, ttl_seconds)

    def put(self, key, value, ttl_seconds=0):
        self._inner.put(key, value, ttl_seconds)

    def delete(self, key):
        self._inner.delete(key)

    def del_current(self, key, expected_value):
        self._inner.del_current(key, expected_value)

    def commit(self):
        raise_after = self._owner._write_gate()
        self._inner.commit()
        if raise_after:
            raise UncertainResultError("injected uncertain outcome (applied)")


def wrap_engine(store: KvStorage, plane: FaultPlane) -> KvStorage:
    return FaultyStorage(store, plane)


# the registry entry exists mainly so tests can compose engines by name
_storage.register_engine(
    "faulty",
    lambda inner="memkv", plane=None, **kw: FaultyStorage(
        _storage.new_storage(inner, **kw), plane),
)
