"""The runtime fault plane: one armed :class:`FaultPlane` per process maps
a deterministic :class:`~kubebrain_tpu.faults.schedule.FaultSchedule` onto
the monotonic clock and answers injection decisions from every boundary
(docs/faults.md).

The plane is INERT until armed: decisions short-circuit to None/False so a
``--faults none`` server (or one whose runner never calls ``/faults/arm``)
takes exactly the un-instrumented code paths — the inertness contract the
chaos acceptance gate asserts byte-identically. Arming starts the window
clock and the watch-reset daemon; it happens over the info HTTP port so
the chaos runner can align windows with replay start (after preload).

Decision randomness is a seeded ``random.Random(seed)`` draw per boundary
call under one lock — runtime decision *counts* depend on op arrival (and
are reconciled injected-vs-observed in the SLO report); the schedule
itself is the deterministic replay identity (its sha).
"""

from __future__ import annotations

import json
import logging
import random
import threading
import time
from collections import Counter
from typing import Any

from ..storage.errors import StorageError, UncertainResultError
from . import schedule as _sched

logger = logging.getLogger("kubebrain")


class FaultInjectedError(StorageError):
    """Definite injected storage failure: nothing was applied."""


class FaultPlane:
    #: cadence of the watch-reset daemon's window polling
    WATCH_TICK_S = 0.25

    def __init__(self, sched: _sched.FaultSchedule,
                 metrics: Any = None) -> None:
        self.schedule = sched
        self._metrics = metrics
        self._lock = threading.Lock()
        self._rng = random.Random(sched.seed)
        self._t0: float | None = None  # None = not armed (inert)
        self._stop = threading.Event()
        self._hub = None  # WatcherHub, bound by the server wiring
        self._watch_thread: threading.Thread | None = None
        self.injected: Counter = Counter()

    # ------------------------------------------------------------- lifecycle
    def bind_hub(self, hub: Any) -> None:
        """Give the plane the watcher hub so armed ``watch_reset`` windows
        can drop live watch streams server-side."""
        self._hub = hub

    @property
    def armed(self) -> bool:
        with self._lock:
            return self._t0 is not None

    def arm(self) -> None:
        with self._lock:
            if self._t0 is not None:
                return
            self._t0 = time.monotonic()
        if self._hub is not None and any(
                w.kind == _sched.WATCH_RESET for w in self.schedule.windows):
            self._watch_thread = threading.Thread(
                target=self._watch_reset_loop, name="kb-fault-watchreset",
                daemon=True)
            self._watch_thread.start()
        logger.warning("fault plane ARMED: preset=%s seed=%d horizon=%dms "
                       "sha=%s", self.schedule.preset, self.schedule.seed,
                       self.schedule.horizon_ms, self.schedule.sha256())

    def close(self) -> None:
        self._stop.set()

    # -------------------------------------------------------------- plumbing
    def _elapsed_ms(self) -> int | None:
        # snapshot under the lock: arm() publishes _t0 under it, and this
        # runs on every injection-point probe across request threads and
        # the watch-reset daemon (kblint KB120)
        with self._lock:
            t0 = self._t0
        if t0 is None:
            return None
        return int((time.monotonic() - t0) * 1000)

    def _count(self, kind: str) -> None:
        with self._lock:
            self.injected[kind] += 1
        if self._metrics is not None:
            self._metrics.emit_counter("kb.faults.injected", 1, kind=kind)

    def _roll(self, rate: float) -> bool:
        with self._lock:
            return self._rng.random() < rate

    # -------------------------------------------------------------- storage
    def decide_storage(self, write: bool) -> tuple[str, float] | None:
        """One decision per storage boundary call. Returns None (no fault)
        or ``(kind, param)`` with kind one of ``latency`` / ``error`` /
        ``uncertain_applied`` / ``uncertain_dropped``. Reads only ever see
        latency/error — a read cannot be "maybe applied"."""
        t = self._elapsed_ms()
        if t is None:
            return None
        kinds = _sched.WRITE_KINDS if write else _sched.READ_KINDS
        for kind in kinds:
            for w in self.schedule.active(t, kind):
                if not self._roll(w.rate):
                    continue
                if kind == _sched.STORAGE_LATENCY:
                    self._count(kind)
                    return ("latency", w.param or 0.02)
                if kind == _sched.STORAGE_ERROR:
                    self._count(kind)
                    return ("error", 0.0)
                # uncertain: the injector itself flips whether the op
                # really committed — the layer above must treat both
                # identically (that asymmetry of knowledge IS the fault)
                applied = self._roll(0.5)
                self._count(kind)
                self._count(_sched.STORAGE_UNCERTAIN
                            + ("_applied" if applied else "_dropped"))
                return ("uncertain_applied" if applied
                        else "uncertain_dropped", 0.0)
        return None

    # ------------------------------------------------------------- endpoint
    def conn_drop(self) -> bool:
        """Abort this RPC as if the client's connection dropped (the
        endpoint interceptor consults this per unary call)."""
        t = self._elapsed_ms()
        if t is None:
            return False
        for w in self.schedule.active(t, _sched.CONN_DROP):
            if self._roll(w.rate):
                self._count(_sched.CONN_DROP)
                return True
        return False

    def _watch_reset_loop(self) -> None:
        while not self._stop.wait(self.WATCH_TICK_S):
            t = self._elapsed_ms()
            if t is None or t > self.schedule.horizon_ms:
                return
            for w in self.schedule.active(t, _sched.WATCH_RESET):
                if not self._roll(w.rate):
                    continue
                n = self._reset_watchers(int(w.param) or 1)
                for _ in range(n):
                    self._count(_sched.WATCH_RESET)

    def _reset_watchers(self, n: int) -> int:
        """Drop up to ``n`` seeded-randomly-chosen live watchers: their
        pumps see the hub poison pill and send the client the same
        retriable cancel a slow-consumer drop sends — the shape the client
        WatchMux must resume from (revision+1, no lost or dup events)."""
        hub = self._hub
        if hub is None:
            return 0
        wids = hub.watcher_ids()
        if not wids:
            return 0
        with self._lock:
            picks = self._rng.sample(wids, min(n, len(wids)))
        for wid in picks:
            hub.delete_watcher(wid)
        return len(picks)

    # ----------------------------------------------------------- tpu engine
    def merge_fault(self) -> bool:
        t = self._elapsed_ms()
        if t is None:
            return False
        for w in self.schedule.active(t, _sched.MERGE_FAIL):
            if self._roll(w.rate):
                self._count(_sched.MERGE_FAIL)
                return True
        return False

    def merge_fail_active(self) -> bool:
        """Pure window check (no roll, no count): the engine kicks merges
        eagerly while a merge-fail window is open so the failing-merge
        machinery is actually exercised — a fault window nothing runs in
        proves nothing."""
        t = self._elapsed_ms()
        if t is None:
            return False
        return any(True for _ in self.schedule.active(t, _sched.MERGE_FAIL))

    def merges_suppressed(self) -> bool:
        """Pure window check (no counting — the engine checks this per
        write). The engine reports actually-suppressed merge kicks via
        :meth:`note_suppressed_merge` so the injected counter reflects
        suppressed *merges*, not write ops."""
        t = self._elapsed_ms()
        if t is None:
            return False
        return any(True for _ in self.schedule.active(
            t, _sched.MERGE_SUPPRESS))

    def note_suppressed_merge(self) -> None:
        self._count(_sched.MERGE_SUPPRESS)

    def compact_fault(self) -> bool:
        """Fail the compaction's mirror half (stored-domain survivor
        merge), pre-mutation — the GC deletes stay durable; the engine's
        bounded retries then re-roll here, and exhausting them must
        escalate to quarantine + background rebuild (docs/compaction.md)."""
        t = self._elapsed_ms()
        if t is None:
            return False
        for w in self.schedule.active(t, _sched.COMPACT_FAIL):
            if self._roll(w.rate):
                self._count(_sched.COMPACT_FAIL)
                return True
        return False

    # -------------------------------------------------------- replica role
    def repl_reset(self) -> bool:
        """Tear the follower's replication stream down (checked once per
        stream ticker tick): the next pass must resume from the applied
        watermark + 1 with no event lost or duplicated."""
        t = self._elapsed_ms()
        if t is None:
            return False
        for w in self.schedule.active(t, _sched.REPL_RESET):
            if self._roll(w.rate):
                self._count(_sched.REPL_RESET)
                return True
        return False

    def leader_unreachable(self) -> bool:
        """Window gate consulted before every leader-touching action on a
        follower (fence fetch, write/lease forward, stream reconnect).
        Counted per gated action — both counter views (plane state and
        /metrics) increment together, so the chaos reconcile stays exact."""
        t = self._elapsed_ms()
        if t is None:
            return False
        for w in self.schedule.active(t, _sched.LEADER_UNREACH):
            if self._roll(w.rate):
                self._count(_sched.LEADER_UNREACH)
                return True
        return False

    def fence_timeout(self) -> bool:
        """Force a linearizable-read fence to report the follower stale
        (checked once per fence): the read must REFUSE, proving bounded
        staleness degrades to refusals, never stale answers."""
        t = self._elapsed_ms()
        if t is None:
            return False
        for w in self.schedule.active(t, _sched.FENCE_TIMEOUT):
            if self._roll(w.rate):
                self._count(_sched.FENCE_TIMEOUT)
                return True
        return False

    def encode_overflow(self) -> bool:
        t = self._elapsed_ms()
        if t is None:
            return False
        for w in self.schedule.active(t, _sched.ENCODE_OVERFLOW):
            if self._roll(w.rate):
                self._count(_sched.ENCODE_OVERFLOW)
                return True
        return False

    # ----------------------------------------------------------- HTTP admin
    def http_arm(self) -> tuple[str, bytes]:
        """GET /faults/arm — starts the window clock (chaos runner calls
        this when replay begins so windows align with replay time)."""
        self.arm()
        return ("application/json", json.dumps(
            {"armed": True, "sha256": self.schedule.sha256()}).encode())

    def http_state(self) -> tuple[str, bytes]:
        """GET /faults/state — schedule identity + injected counters, the
        server half of the report's injected/observed reconciliation."""
        with self._lock:
            injected = dict(self.injected)
        return ("application/json", json.dumps({
            "armed": self.armed,
            "schedule": self.schedule.to_dict(),
            "elapsed_ms": self._elapsed_ms(),
            "injected": injected,
        }).encode())

    def snapshot(self) -> dict:
        with self._lock:
            return dict(self.injected)


__all__ = ["FaultPlane", "FaultInjectedError", "UncertainResultError"]
