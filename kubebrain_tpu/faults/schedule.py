"""Deterministic fault schedules: the chaos-mode analogue of the workload
generator (docs/faults.md).

``generate(preset, seed, horizon_s)`` is a pure function: one seeded
``random.Random`` lays a set of :class:`FaultWindow` records over a real-
time horizon and the canonical byte trace's sha256 is the fault plane's
replay identity — same (preset, seed, horizon) ⇒ byte-identical schedule,
re-checked by the chaos runner on every run exactly like the workload
trace sha. kblint KB110 covers this package: no unseeded randomness, no
wall-clock reads — arming (mapping window offsets onto the monotonic
clock) happens at runtime in :mod:`.plane`, never here.

Window times are REAL milliseconds since the plane was armed (the chaos
runner arms the plane when replay starts, so windows align with replay
wall time regardless of preload cost). ``rate`` is the per-boundary-call
injection probability for storage faults, the per-tick firing probability
for watch resets, and the per-RPC abort probability for connection drops.
"""

from __future__ import annotations

import hashlib
import random
from dataclasses import dataclass
from typing import Iterator

# ------------------------------------------------------------ fault taxonomy
#: storage-op boundary (create/update/delete/write_batch/get/iter/scan)
STORAGE_LATENCY = "storage_latency"    # param = added latency seconds
STORAGE_ERROR = "storage_error"        # definite failure, nothing applied
STORAGE_UNCERTAIN = "storage_uncertain"  # outcome unknowable: may have landed
#: endpoint boundary
WATCH_RESET = "watch_reset"            # server-side watch stream reset
CONN_DROP = "conn_drop"                # RPC aborted as if the conn dropped
#: TPU-engine boundary
MERGE_FAIL = "merge_fail"              # background delta merge raises
MERGE_SUPPRESS = "merge_suppress"      # merges suppressed: delta overlay grows
ENCODE_OVERFLOW = "encode_overflow"    # forced EncodeOverflow -> re-dictionary
COMPACT_FAIL = "compact_fail"          # compaction's mirror merge raises
#: replica (follower-role) boundary — docs/replication.md
REPL_RESET = "repl_reset"              # replication stream torn down client-side
LEADER_UNREACH = "leader_unreachable"  # fence/forward/stream gated off
FENCE_TIMEOUT = "fence_timeout"        # linearizable-read fences forced stale

ALL_KINDS = (
    STORAGE_LATENCY, STORAGE_ERROR, STORAGE_UNCERTAIN,
    WATCH_RESET, CONN_DROP,
    MERGE_FAIL, MERGE_SUPPRESS, ENCODE_OVERFLOW, COMPACT_FAIL,
    REPL_RESET, LEADER_UNREACH, FENCE_TIMEOUT,
)

#: kinds that only act on a --role follower process (the chaos runner arms
#: followers with the `replica` preset; on a leader they never fire)
REPLICA_KINDS = (REPL_RESET, LEADER_UNREACH, FENCE_TIMEOUT)

#: kinds that fire at the storage write boundary
WRITE_KINDS = (STORAGE_LATENCY, STORAGE_ERROR, STORAGE_UNCERTAIN)
#: kinds that fire at the storage read boundary (reads are never uncertain)
READ_KINDS = (STORAGE_LATENCY, STORAGE_ERROR)

PRESETS = ("none", "smoke", "storage", "watch", "merge", "full", "replica")


@dataclass(frozen=True)
class FaultWindow:
    """One active-fault interval: ``kind`` fires with probability ``rate``
    per eligible boundary call while armed-elapsed time is in
    [t0_ms, t1_ms). ``param`` is kind-specific (latency seconds, watchers
    per reset tick)."""

    kind: str
    t0_ms: int
    t1_ms: int
    rate: float
    param: float = 0.0

    def to_line(self) -> bytes:
        return b"%s %09d %09d %.6f %.6f" % (
            self.kind.encode(), self.t0_ms, self.t1_ms, self.rate, self.param)

    def active(self, t_ms: int) -> bool:
        return self.t0_ms <= t_ms < self.t1_ms


@dataclass(frozen=True)
class FaultSchedule:
    preset: str
    seed: int
    horizon_ms: int
    windows: tuple[FaultWindow, ...]

    def trace_bytes(self) -> bytes:
        head = b"kubebrain-faults/v1 %s seed=%d horizon=%d\n" % (
            self.preset.encode(), self.seed, self.horizon_ms)
        return head + b"\n".join(w.to_line() for w in self.windows) + b"\n"

    def sha256(self) -> str:
        return hashlib.sha256(self.trace_bytes()).hexdigest()

    def kinds(self) -> tuple[str, ...]:
        seen: list[str] = []
        for w in self.windows:
            if w.kind not in seen:
                seen.append(w.kind)
        return tuple(seen)

    def active(self, t_ms: int, kind: str) -> "Iterator[FaultWindow]":
        for w in self.windows:
            if w.kind == kind and w.active(t_ms):
                yield w

    def to_dict(self) -> dict:
        return {
            "preset": self.preset,
            "seed": self.seed,
            "horizon_ms": self.horizon_ms,
            "sha256": self.sha256(),
            "windows": len(self.windows),
            "kinds": list(self.kinds()),
        }


def _spread(rng: random.Random, horizon_ms: int, kind: str, n: int,
            frac: float, rate: float, param: float = 0.0,
            lo: float = 0.0, hi: float = 1.0) -> list[FaultWindow]:
    """``n`` windows of ``kind``, each ~``frac`` of the horizon long,
    placed by the seeded rng inside ``[lo, hi]`` of the horizon. Windows
    are clamped inside the horizon so a post-horizon grace period is
    always fault-free (recovery + the final authoritative scan must run
    against a healthy plane)."""
    out: list[FaultWindow] = []
    lo_ms, hi_ms = int(horizon_ms * lo), int(horizon_ms * hi)
    width = max(1, int((hi_ms - lo_ms) * frac))
    for _ in range(n):
        t0 = lo_ms + rng.randrange(max(1, hi_ms - lo_ms - width))
        out.append(FaultWindow(kind, t0, min(hi_ms, t0 + width),
                               rate, param))
    return out


def generate(preset: str, seed: int, horizon_s: float) -> FaultSchedule:
    """Pure schedule generation — same arguments ⇒ byte-identical windows
    (the chaos determinism gate asserts the sha twice per run)."""
    if preset not in PRESETS:
        raise ValueError(f"unknown fault preset {preset!r}; have {PRESETS}")
    if horizon_s <= 0:
        raise ValueError("horizon_s must be > 0")
    horizon_ms = int(horizon_s * 1000)
    rng = random.Random(seed)
    windows: list[FaultWindow] = []
    if preset in ("storage", "smoke", "full"):
        heavy = preset == "full"
        windows += _spread(rng, horizon_ms, STORAGE_LATENCY,
                           2 if heavy else 1, 0.25, 0.5 if heavy else 0.3,
                           param=0.05 if heavy else 0.02)
        windows += _spread(rng, horizon_ms, STORAGE_ERROR,
                           2 if heavy else 1, 0.2, 0.25 if heavy else 0.15)
        windows += _spread(rng, horizon_ms, STORAGE_UNCERTAIN,
                           2 if heavy else 1, 0.25, 0.25 if heavy else 0.15)
    if preset in ("watch", "smoke", "full"):
        heavy = preset == "full"
        # rate = per-0.25s-tick firing probability; param = resets per fire
        windows += _spread(rng, horizon_ms, WATCH_RESET,
                           2 if heavy else 1, 0.3, 0.8,
                           param=4 if heavy else 2)
        windows += _spread(rng, horizon_ms, CONN_DROP,
                           2 if heavy else 1, 0.15, 0.3 if heavy else 0.15)
    if preset in ("merge", "smoke", "full"):
        heavy = preset == "full"
        # the merge-machinery windows are laid DISJOINT (fail in the first
        # half, suppress in the second): an overlapping suppress window
        # would starve the fail window of merges to fail on small runs
        windows += _spread(rng, horizon_ms, MERGE_FAIL,
                           1, 0.6, 1.0, lo=0.0, hi=0.5)
        windows += _spread(rng, horizon_ms, MERGE_SUPPRESS,
                           1, 0.8, 1.0, lo=0.55, hi=1.0)
        # clear of the horizon's edges: the first real seconds of a cold
        # replay are kernel-compile stall (no engine writes to overflow)
        windows += _spread(rng, horizon_ms, ENCODE_OVERFLOW,
                           1, 0.3, 0.5 if heavy else 0.25, lo=0.2, hi=0.9)
        # compaction is CLIENT-cadenced (the workload's COMPACT ops), so
        # the window is laid wide at rate 1.0: any compaction landing in
        # ~80% of the horizon exercises the mirror-half's retry/backoff →
        # quarantine+rebuild escalation path (docs/compaction.md)
        windows += _spread(rng, horizon_ms, COMPACT_FAIL,
                           1, 0.8, 1.0, lo=0.05, hi=0.95)
    if preset == "replica":
        # follower-role chaos (docs/replication.md). Windows are laid
        # DISJOINT by design: a replication reset while the leader is
        # "unreachable" would just be the same outage twice, and the
        # fence-timeout window must meet a HEALTHY stream so it proves the
        # refusal path, not the outage. Early replication resets exercise
        # resume-from-watermark; the mid-run unreachable window grows lag
        # until bounded-staleness refusals provably fire; the late window
        # forces fences stale while serving is otherwise healthy.
        # wide enough that several 0.2s stream-ticker ticks land inside
        # each window even on a smoke-sized horizon
        windows += _spread(rng, horizon_ms, REPL_RESET,
                           2, 0.3, 0.6, lo=0.02, hi=0.42)
        windows += _spread(rng, horizon_ms, LEADER_UNREACH,
                           1, 0.5, 1.0, lo=0.45, hi=0.70)
        windows += _spread(rng, horizon_ms, FENCE_TIMEOUT,
                           1, 0.6, 1.0, lo=0.75, hi=1.0)
    # canonical order: by (t0, kind) so generation insertion order can't
    # leak into the trace identity
    windows.sort(key=lambda w: (w.t0_ms, w.kind, w.t1_ms))
    return FaultSchedule(preset=preset, seed=seed, horizon_ms=horizon_ms,
                         windows=tuple(windows))
