"""The lease subsystem: real etcd lease semantics over the MVCC core.

Grant/Revoke/KeepAlive/TimeToLive/Leases are served from a monotonic-clock
TTL state machine (registry.py over clock.py), key↔lease attachment is
driven by ``PutRequest.lease`` in the backend write path, and expiry is a
leader-only reaper (reaper.py) that turns each expired lease's keys into
revision-stamped deletes through the sequencer — MVCC-visible,
compaction-safe, and emitting normal WatchEvents.

``ensure_lease`` mirrors ``sched.ensure_scheduler``: one registry + reaper
per backend, first caller wins (cli.build_endpoint calls it early with the
flag-derived intervals, peers, and real metrics).

See docs/leases.md for the state machine, reaper design, and metrics.
"""

from __future__ import annotations

import threading
from typing import Any

from .reaper import DEFAULT_CHECKPOINT_INTERVAL, DEFAULT_REAP_INTERVAL, LeaseReaper
from .registry import Lease, LeaseExistsError, LeaseNotFoundError, LeaseRegistry

__all__ = [
    "Lease",
    "LeaseExistsError",
    "LeaseNotFoundError",
    "LeaseRegistry",
    "LeaseReaper",
    "ensure_lease",
    "DEFAULT_REAP_INTERVAL",
    "DEFAULT_CHECKPOINT_INTERVAL",
]

_ENSURE_LOCK = threading.Lock()


def ensure_lease(backend: Any, peers: Any = None, metrics: Any = None,
                 reap_interval: float = DEFAULT_REAP_INTERVAL,
                 checkpoint_interval: float = DEFAULT_CHECKPOINT_INTERVAL,
                 ) -> LeaseRegistry:
    """The process-wide lease registry for ``backend``: every service
    surface (sync etcd, aio, native front) must share one table or
    attachments and expiry drift apart. Creates + starts the reaper on
    first call; ``Backend.close`` closes it (final checkpoint included)."""
    reg = getattr(backend, "_kb_lease", None)
    if reg is not None:
        return reg
    with _ENSURE_LOCK:
        reg = getattr(backend, "_kb_lease", None)
        if reg is None:
            reg = LeaseRegistry(backend.store, metrics=metrics)
            reaper = LeaseReaper(
                backend, reg, peers=peers,
                reap_interval=reap_interval,
                checkpoint_interval=checkpoint_interval,
            )
            # reaper first: the lock-free fast path returns as soon as
            # _kb_lease is visible, and LeaseService reads _kb_lease_reaper
            # right after — publishing in the other order races it
            backend._kb_lease_reaper = reaper
            backend._kb_lease = reg
            reaper.start()
    return reg
