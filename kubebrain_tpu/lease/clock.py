"""The lease clock — the ONLY serving-path module allowed to do TTL /
deadline arithmetic (kblint KB108).

Lease TTLs are *durations*, not wall-clock instants: an NTP step (or a VM
suspend/resume wall-clock jump) must neither mass-expire every lease nor
grant them hours of free life. etcd's lessor learned this the hard way
(leases keyed on ``time.Now()`` revoked en masse on clock steps); the fix
there and here is the same — all live deadlines are points on the
**monotonic** clock, and wall time never enters the arithmetic.

Persistence converts deadlines to *remaining seconds* (a duration survives
a reboot; a monotonic instant does not) and back through
:func:`deadline_for` on rehydration.
"""

from __future__ import annotations

import time


def now() -> float:
    """Monotonic seconds. Comparable only against values from this module,
    never against wall clock."""
    return time.monotonic()


def deadline_for(ttl_seconds: float) -> float:
    """The monotonic instant ``ttl_seconds`` from now."""
    return now() + ttl_seconds


def remaining(deadline: float) -> float:
    """Seconds until ``deadline``; negative once it has passed."""
    return deadline - now()


def expired(deadline: float) -> bool:
    return remaining(deadline) <= 0.0
