"""Leader-only expiry reaper: expired leases become revision-stamped
deletes through the sequencer.

The naive alternative — engine-level TTLs on leased keys — creates a second,
unversioned deletion path: keys vanish without a revision, watchers never
hear about it, and compaction cannot reason about the hole. Following the
multiversion-delete discipline (PAPERS: MVCC B-trees), every expiry here is
an ordinary ``Backend.delete``: it deals a revision, writes a tombstone,
flows through the single sequencer, lands in the watch cache and fan-out
hub, and inherits the ``kb_watch_lag_seconds`` instrumentation for free.

Leadership: only the leader reaps (followers would race it and double-
delete); on a follower→leader transition the registry rehydrates from the
persisted checkpoint so the new leader adopts the old leader's table
instead of its own stale copy. The same thread drives the checkpoint
cadence (``--lease-checkpoint-interval``) that persists keepalive-refreshed
remaining TTLs.
"""

from __future__ import annotations

import logging
import threading
from typing import Any

from ..backend.errors import FutureRevisionError
from ..storage.errors import KeyNotFoundError
from . import clock
from .registry import LeaseNotFoundError, LeaseRegistry

logger = logging.getLogger(__name__)

DEFAULT_REAP_INTERVAL = 1.0
DEFAULT_CHECKPOINT_INTERVAL = 5.0


class LeaseReaper:
    def __init__(self, backend: Any, registry: LeaseRegistry,
                 peers: Any = None,
                 reap_interval: float = DEFAULT_REAP_INTERVAL,
                 checkpoint_interval: float = DEFAULT_CHECKPOINT_INTERVAL,
                 ) -> None:
        self.backend = backend
        self.registry = registry
        self.peers = peers
        self.reap_interval = reap_interval
        self.checkpoint_interval = checkpoint_interval
        self._stop = threading.Event()
        self._lifecycle_lock = threading.Lock()
        self._thread: threading.Thread | None = None
        self._was_leader: bool | None = None  # None until the first tick

    # ------------------------------------------------------------- lifecycle
    def start(self) -> None:
        from ..util.env import crash_guard

        with self._lifecycle_lock:
            if self._thread is not None:
                return
            self._thread = threading.Thread(
                target=crash_guard(self._loop), name="kb-lease-reaper",
                daemon=True
            )
            self._thread.start()

    def close(self) -> None:
        self._stop.set()
        with self._lifecycle_lock:
            thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=2.0)
        # persist remaining TTLs one last time so a restart resumes the
        # countdown instead of granting expired leases a fresh life
        self.registry.close()

    def _loop(self) -> None:
        # first pass runs immediately: leases that expired while the
        # process was down are reaped at boot, not after one interval
        next_ckpt = clock.deadline_for(self.checkpoint_interval)
        while True:
            if self._leader():
                self.reap()
            # attach/detach changes persist every tick (a crash must not
            # leak never-expiring keys for more than one reap interval);
            # keepalive-refreshed deadlines ride the cheaper cadence below
            self.registry.checkpoint(structural_only=True)
            if clock.expired(next_ckpt):
                self.registry.checkpoint()
                next_ckpt = clock.deadline_for(self.checkpoint_interval)
            if self._stop.wait(self.reap_interval):
                return

    def _leader(self) -> bool:
        leader = self.peers is None or self.peers.is_leader()
        if leader and self._was_leader is False:
            # promotion mid-life: adopt the persisted table (the old
            # leader's checkpoint) over this node's stale in-memory copy.
            # Boot-time leadership is NOT a transition — the registry
            # already rehydrated at construction, and re-reading here would
            # roll back keepalives that arrived since.
            self.registry.rehydrate()
        self._was_leader = leader
        return leader

    # ----------------------------------------------------------------- reaps
    def reap(self) -> int:
        """Delete every expired lease's keys through the MVCC write path,
        then drop the lease. Returns the number of leases reaped. A lease
        whose keys could not all be deleted is kept for the next tick —
        dropping it early would leak undeletable keys forever."""
        reaped = 0
        for lease_id, keys in self.registry.expired_leases():
            if self._stop.is_set():
                break
            if self._delete_range(keys, lease_id):
                self.registry.drop(lease_id, reason="expired")
                reaped += 1
        return reaped

    def revoke(self, lease_id: int) -> int:
        """Explicit LeaseRevoke: same delete discipline as expiry, ordered
        keys-first so a crash mid-revoke leaves a still-expiring lease
        rather than orphaned keys. Returns the number of keys deleted."""
        lease = self.registry.peek(lease_id)
        if lease is None:
            raise LeaseNotFoundError(lease_id)
        keys = tuple(sorted(lease.keys))
        if not self._delete_range(keys, lease_id):
            raise RuntimeError(f"lease {lease_id}: attached keys not fully deleted")
        self.registry.drop(lease_id, reason="revoked")
        return len(keys)

    def _delete_range(self, keys: tuple[bytes, ...], lease_id: int) -> bool:
        """Batch the lease's keys into revision-stamped deletes submitted
        through the sequencer (each Backend.delete deals a revision, posts
        its WatchEvent, and commits in order). Each delete re-checks the
        key's CURRENT owner first: the snapshot in ``keys`` is stale by the
        time the loop runs, and a key the user detached (put with lease=0)
        or moved to a fresh lease since must not be deleted — that would be
        data loss of a write etcd preserves. Missing keys are fine (the
        user deleted them first); a drift-back race retries once with a
        fresh revision."""
        ok = True
        for key in keys:
            if self.registry.owner_of(key) != lease_id:
                continue  # detached or re-leased since the snapshot
            for _attempt in range(2):
                try:
                    self.backend.delete(key)
                    break
                except KeyNotFoundError:
                    break
                except FutureRevisionError:
                    continue  # concurrent writer drew a higher revision
                except Exception:
                    logger.exception("lease reap: delete %r failed", key)
                    ok = False
                    break
            else:
                ok = False
        return ok
