"""Lease registry: the TTL state machine.

etcd semantics (server/lease/lessor.go), adapted to this store's MVCC
discipline:

- ``grant`` mints a lease (caller-chosen or random positive int64 id) with
  a TTL measured on the monotonic clock (clock.py — kblint KB108);
- ``attach``/``reattach`` bind keys to a lease from the backend write path
  (``PutRequest.lease``); a put without a lease detaches;
- ``keepalive`` refreshes the deadline to ``now + granted_ttl``; an expired
  or unknown lease returns 0 and is never resurrected (etcd
  ErrLeaseNotFound maps to TTL=0 on the keepalive stream);
- ``time_to_live`` reports remaining seconds, or -1 once the lease is
  expired or gone (etcd LeaseTimeToLive contract);
- expiry itself is NOT enforced here: the reaper (reaper.py) turns expired
  leases into revision-stamped deletes through the sequencer, so watchers
  and compaction see normal MVCC events rather than keys silently
  vanishing.

Persistence: the whole table (ids, granted TTLs, *remaining* TTL as of the
checkpoint, attached keys) is length-framed into one metadata row
(``LEASE_STATE_KEY``, outside the MVCC keyspace like the compact/election
records) — written synchronously on structural changes (grant/drop) and on
a cadence for keepalive-refreshed deadlines. Rehydration converts remaining
seconds back into monotonic deadlines; a lease that was already expired at
checkpoint time comes back expired, so the boot reap deletes its keys
instead of resurrecting them.
"""

from __future__ import annotations

import logging
import os
import struct
import threading
from dataclasses import dataclass, field

from ..backend.common import LEASE_STATE_KEY
from ..storage.errors import KeyNotFoundError
from . import clock

logger = logging.getLogger(__name__)

_MAGIC = b"KBLEASE1"
_INT64_MAX = (1 << 63) - 1


class LeaseNotFoundError(Exception):
    """etcd ErrLeaseNotFound: the lease does not exist (or has expired)."""

    def __init__(self, lease_id: int) -> None:
        super().__init__(f"lease {lease_id} not found")
        self.lease_id = lease_id


class LeaseExistsError(Exception):
    """etcd ErrLeaseExist: grant with an explicit id that is already live."""

    def __init__(self, lease_id: int) -> None:
        super().__init__(f"lease {lease_id} already exists")
        self.lease_id = lease_id


@dataclass
class Lease:
    id: int
    granted_ttl: float          # seconds, as granted (keepalive resets to this)
    deadline: float             # monotonic expiry instant (clock.py domain)
    keys: set[bytes] = field(default_factory=set)

    def remaining(self) -> float:
        return clock.remaining(self.deadline)


class LeaseRegistry:
    def __init__(self, store=None, metrics=None):
        self._store = store
        self._metrics = metrics
        self._lock = threading.Lock()       # protects _leases/_key_owner/_dirty
        self._ckpt_lock = threading.Lock()  # serializes encode+commit pairs
        self._leases: dict[int, Lease] = {}
        self._key_owner: dict[bytes, int] = {}
        self._dirty = False         # any unpersisted change (incl. keepalives)
        self._dirty_struct = False  # unpersisted attach/detach changes
        if store is not None:
            self.rehydrate()
        if metrics is not None:
            metrics.register_gauge_fn("kb.lease.active", self.count)
            metrics.register_gauge_fn("kb.lease.attached.keys", self.attached_count)

    # ------------------------------------------------------------- lifecycle
    def grant(self, ttl: float, lease_id: int = 0) -> Lease:
        """Mint a lease. ``lease_id`` 0 = server-chosen (random positive
        int64, the etcd contract); an explicit id that is already live
        raises LeaseExistsError. Synchronously checkpointed — a granted
        lease must survive an immediate restart."""
        ttl = max(float(ttl), 0.0)
        with self._lock:
            if lease_id:
                if lease_id in self._leases:
                    raise LeaseExistsError(lease_id)
            else:
                while True:
                    lease_id = int.from_bytes(os.urandom(8), "big") & _INT64_MAX
                    if lease_id and lease_id not in self._leases:
                        break
            lease = Lease(lease_id, ttl, clock.deadline_for(ttl))
            self._leases[lease_id] = lease
            self._dirty = True
        if self._metrics is not None:
            self._metrics.emit_counter("kb.lease.granted.total", 1)
        self.checkpoint()
        return Lease(lease.id, lease.granted_ttl, lease.deadline, set(lease.keys))

    def drop(self, lease_id: int, reason: str = "revoked") -> None:
        """Remove the lease record (the caller has already dealt with its
        keys — reaper.revoke/reap own that ordering)."""
        with self._lock:
            lease = self._leases.pop(lease_id, None)
            if lease is None:
                return
            for key in lease.keys:
                if self._key_owner.get(key) == lease_id:
                    del self._key_owner[key]
            self._dirty = True
        if self._metrics is not None:
            self._metrics.emit_counter(f"kb.lease.{reason}.total", 1)
        self.checkpoint()

    def keepalive(self, lease_id: int) -> int:
        """Refresh the deadline to now + granted TTL. Returns the new TTL in
        whole seconds, or 0 when the lease is unknown/expired (the etcd
        keepalive-stream encoding of ErrLeaseNotFound); an expired lease is
        left for the reaper, never revived."""
        with self._lock:
            lease = self._leases.get(lease_id)
            if lease is None or clock.expired(lease.deadline):
                return 0
            lease.deadline = clock.deadline_for(lease.granted_ttl)
            self._dirty = True
            ttl = max(1, int(lease.granted_ttl))
        # successful refreshes are counted so an external traffic source
        # (the workload replay harness) can reconcile its keepalive acks
        # against the server's own view
        if self._metrics is not None:
            self._metrics.emit_counter("kb.lease.keepalive.total", 1)
        return ttl

    # ------------------------------------------------------------ attachment
    def require(self, lease_id: int) -> None:
        """Gate for the write path: putting under an unknown or expired
        lease is etcd ErrLeaseNotFound."""
        with self._lock:
            lease = self._leases.get(lease_id)
            if lease is None or clock.expired(lease.deadline):
                raise LeaseNotFoundError(lease_id)

    def attach(self, lease_id: int, key: bytes) -> None:
        """Bind ``key`` to the lease (after its write committed). A key
        belongs to at most one lease; re-attaching moves it. An expired but
        not-yet-reaped lease still accepts the attachment — the reaper
        deletes the key moments later, which is strictly safer than leaking
        an unexpirable key."""
        with self._lock:
            lease = self._leases.get(lease_id)
            if lease is None:
                raise LeaseNotFoundError(lease_id)
            old = self._key_owner.get(key)
            if old is not None and old != lease_id:
                prev = self._leases.get(old)
                if prev is not None:
                    prev.keys.discard(key)
            lease.keys.add(key)
            self._key_owner[key] = lease_id
            self._dirty = self._dirty_struct = True

    def reattach(self, key: bytes, lease_id: int) -> None:
        """Write-path update hook: lease 0 detaches (an etcd put without a
        lease clears the attachment), nonzero moves the key."""
        if lease_id:
            self.attach(lease_id, key)
        else:
            self.detach_key(key)

    def detach_key(self, key: bytes) -> None:
        """The key was deleted (or re-put without a lease): forget it."""
        with self._lock:
            owner = self._key_owner.pop(key, None)
            if owner is None:
                return
            lease = self._leases.get(owner)
            if lease is not None:
                lease.keys.discard(key)
            self._dirty = self._dirty_struct = True

    # ----------------------------------------------------------------- reads
    def peek(self, lease_id: int) -> Lease | None:
        with self._lock:
            lease = self._leases.get(lease_id)
            if lease is None:
                return None
            return Lease(lease.id, lease.granted_ttl, lease.deadline, set(lease.keys))

    def time_to_live(self, lease_id: int) -> tuple[int, int, tuple[bytes, ...]]:
        """(remaining_ttl, granted_ttl, keys). remaining_ttl is -1 once the
        lease is gone OR past its deadline (even if the reaper has not run
        yet) — the etcd LeaseTimeToLive contract."""
        with self._lock:
            lease = self._leases.get(lease_id)
            if lease is None or clock.expired(lease.deadline):
                return -1, 0, ()
            rem = max(1, int(clock.remaining(lease.deadline)))
            return rem, int(lease.granted_ttl), tuple(sorted(lease.keys))

    def owner_of(self, key: bytes) -> int:
        """The lease currently owning ``key`` (0 = unattached) — the
        reaper's pre-delete re-check against its earlier snapshot."""
        with self._lock:
            return self._key_owner.get(key, 0)

    def ids(self) -> list[int]:
        with self._lock:
            return sorted(self._leases)

    def count(self) -> int:
        with self._lock:
            return len(self._leases)

    def attached_count(self) -> int:
        with self._lock:
            return len(self._key_owner)

    def expired_leases(self) -> list[tuple[int, tuple[bytes, ...]]]:
        """Snapshot of (id, keys) for every lease past its deadline — the
        reaper's work list, taken under the lock so the subsequent deletes
        run without it (KB102: no RPC/engine work under a lock)."""
        with self._lock:
            return [
                (lease.id, tuple(sorted(lease.keys)))
                for lease in self._leases.values()
                if clock.expired(lease.deadline)
            ]

    # ----------------------------------------------------------- persistence
    def checkpoint(self, force: bool = False, structural_only: bool = False
                   ) -> bool:
        """Persist the table through the storage engine. Best-effort: a
        failed write leaves the state dirty for the next cadence tick.
        ``structural_only`` writes only when an attach/detach is pending —
        the reaper calls it every reap tick so attachment loss is bounded
        by ``--lease-reap-interval``, while keepalive-refreshed deadlines
        ride the cheaper ``--lease-checkpoint-interval`` cadence.

        The encode and the engine write happen under one ``_ckpt_lock``
        hold: two concurrent checkpointers must not commit their blobs in
        the opposite order they encoded them, or the older table would
        overwrite the newer one with ``_dirty`` already cleared."""
        if self._store is None:
            return False
        with self._ckpt_lock:
            with self._lock:
                if structural_only and not self._dirty_struct and not force:
                    return False
                if not self._dirty and not force:
                    return False
                blob = self._encode_locked()
                self._dirty = self._dirty_struct = False
            try:
                batch = self._store.begin_batch_write()
                batch.put(LEASE_STATE_KEY, blob)
                batch.commit()
                return True
            except Exception:
                logger.exception("lease checkpoint failed; state stays dirty")
                with self._lock:
                    self._dirty = self._dirty_struct = True
                return False

    def rehydrate(self) -> int:
        """Replace in-memory state with the persisted checkpoint (boot, or
        a follower adopting the table on promotion). Remaining TTLs become
        fresh monotonic deadlines; already-expired leases come back expired
        so the next reap deletes their keys instead of resurrecting them.
        Returns the number of leases loaded."""
        if self._store is None:
            return 0
        try:
            raw = self._store.get(LEASE_STATE_KEY)
        except KeyNotFoundError:
            return 0
        try:
            leases = _decode(raw)
        except (ValueError, struct.error):
            logger.exception("corrupt lease checkpoint; starting empty")
            return 0
        with self._lock:
            self._leases = {l.id: l for l in leases}
            self._key_owner = {
                key: l.id for l in leases for key in l.keys
            }
            self._dirty = False
        return len(leases)

    def _encode_locked(self) -> bytes:
        frames = [_MAGIC, struct.pack(">I", len(self._leases))]
        for lease in self._leases.values():
            # both TTLs in milliseconds: the registry API accepts fractional
            # TTLs (sub-second leases in tests), and integer-second encoding
            # would round a 0.3s grant down to an instantly-expired 0
            rem_ms = int(clock.remaining(lease.deadline) * 1000.0)
            granted_ms = int(lease.granted_ttl * 1000.0)
            frames.append(struct.pack(
                ">QQqI", lease.id, granted_ms, rem_ms, len(lease.keys),
            ))
            for key in sorted(lease.keys):
                frames.append(struct.pack(">I", len(key)))
                frames.append(key)
        return b"".join(frames)

    def close(self) -> None:
        self.checkpoint(force=True)
        if self._metrics is not None:
            self._metrics.unregister_gauge_fn("kb.lease.active")
            self._metrics.unregister_gauge_fn("kb.lease.attached.keys")


def _decode(raw: bytes) -> list[Lease]:
    if raw[: len(_MAGIC)] != _MAGIC:
        raise ValueError("bad lease checkpoint magic")
    off = len(_MAGIC)
    (count,) = struct.unpack_from(">I", raw, off)
    off += 4
    out: list[Lease] = []
    for _ in range(count):
        lease_id, granted_ms, rem_ms, nkeys = struct.unpack_from(">QQqI", raw, off)
        off += struct.calcsize(">QQqI")
        keys: set[bytes] = set()
        for _ in range(nkeys):
            (klen,) = struct.unpack_from(">I", raw, off)
            off += 4
            keys.add(raw[off:off + klen])
            off += klen
        out.append(Lease(
            lease_id, granted_ms / 1000.0,
            clock.deadline_for(rem_ms / 1000.0), keys,
        ))
    return out
