"""Porcupine-style linearizability checker for MVCC op histories.

The reference lists Jepsen-style verification as an open TODO
(/root/reference/README.md:30-34); this module closes it with an offline
checker in the style of Porcupine / Wing-Gong: record every client
operation as a (call_ts, return_ts, result) interval, then search for a
legal linearization — a total order consistent with real time in which
every operation's observed result matches a sequential MVCC register.

Structure exploited:

- All point ops (create / update / delete / get) name a single user key, so
  the history is P-compositional: check each key independently against a
  single-register model, which turns one exponential search into many tiny
  ones (Horn & Kroening, "Faster linearizability checking via
  P-compositionality").
- Successful writes carry the globally-allocated revision, which must be
  unique and must respect real time ACROSS keys (A returned before B was
  called => rev(A) < rev(B)); that cross-key slice is checked directly in
  O(n log n) rather than by search.

Unknown outcomes (client crashed / UncertainResultError mid-failover) are
modeled the Jepsen way: the op either never took effect or took effect at
some point after its call — both branches are searched. Its revision is
unknown, so the model tracks an UNKNOWN revision that a later read or CAS
may observe (permissive: UNKNOWN matches any expected revision).

Usage:
    h = History()
    h.record(client, "create", key, call, ret, value=v, ok=True, rev=r)
    res = h.check()           # {"ok": bool, "violation": str | None, ...}
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

UNKNOWN_REV = -1  # revision of a write whose outcome was never observed


@dataclass
class Op:
    client: int
    kind: str  # create | update | delete | get
    key: bytes
    call: float
    ret: float  # math.inf when the client never saw a response
    value: bytes | None = None  # written value (writes) / returned value (get)
    prev_rev: int = 0  # expected revision for update / conditional delete
    ok: bool | None = None  # None = outcome unknown
    rev: int = 0  # revision returned on success / mod_revision of a get
    err: str | None = None  # "conflict" | "notfound" when ok is False
    conflict_rev: int = 0  # revision carried by a conflict error (0 = not captured)


# A per-key register state: (exists, value, revision). revision is the mod
# revision of the latest write, or UNKNOWN_REV right after an unknown write,
# or the tombstone's revision after a delete (exists=False).
_INIT = (False, b"", 0)


def _apply(op: Op, state):
    """Sequential MVCC-register model. Returns the list of states the key can
    be in after `op` executes atomically from `state` — [] when the observed
    result is impossible from `state`."""
    exists, value, rev = state
    known = rev != UNKNOWN_REV

    if op.kind == "get":
        if op.ok:
            if not exists or value != op.value:
                return []
            if known and rev != op.rev:
                return []
            # a read of an unknown-rev write reveals its revision
            return [(True, value, op.rev)]
        else:  # not found
            return [] if exists else [state]

    if op.ok is None:
        # outcome unknown: "took effect" branch (skip branch handled by caller)
        if op.kind == "create":
            return [] if exists else [(True, op.value, UNKNOWN_REV)]
        if op.kind == "update":
            if not exists or (known and rev != op.prev_rev):
                return []
            return [(True, op.value, UNKNOWN_REV)]
        if op.kind == "delete":
            if not exists or (op.prev_rev and known and rev != op.prev_rev):
                return []
            return [(False, b"", UNKNOWN_REV)]
        return []

    if op.kind == "create":
        if op.ok:
            if exists or (known and op.rev <= rev):
                return []
            return [(True, op.value, op.rev)]
        # conflict must be justified by a live key (create's only failure)
        if not exists:
            return []
        if op.conflict_rev and known and op.conflict_rev != rev:
            return []
        return [state]

    if op.kind == "update":
        if op.ok:
            if not exists or (known and rev != op.prev_rev) or (known and op.rev <= rev):
                return []
            return [(True, op.value, op.rev)]
        if op.err == "conflict":
            # justified iff the key is missing or at a different revision;
            # an UNKNOWN rev may or may not equal prev_rev — permissive
            if exists and known and rev == op.prev_rev:
                return []
            if op.conflict_rev and exists and known and op.conflict_rev != rev:
                return []
            return [state]
        return []

    if op.kind == "delete":
        if op.ok:
            if not exists or (op.prev_rev and known and rev != op.prev_rev):
                return []
            if known and op.rev <= rev:
                return []
            return [(False, b"", op.rev)]
        if op.err == "notfound":
            return [] if exists else [state]
        if op.err == "conflict":
            if not exists:
                return []
            if known and op.prev_rev and rev == op.prev_rev:
                return []
            return [state]
        return []

    raise ValueError(f"unknown op kind {op.kind!r}")


class BudgetExhausted(Exception):
    """The Wing-Gong search was truncated before reaching a verdict.

    A truncated search proves nothing — in particular it must NOT count as a
    pass (the histories hard enough to exhaust the budget are exactly the
    ones most likely to hide an anomaly). check() surfaces this as a failed
    result unless the caller explicitly opts into permissive mode."""


def _search_segment(ops: list[Op], seeds, node_budget: int, nodes: int,
                    collect_finals: bool, total_ops: int):
    """Wing-Gong search over one segment with memoization on
    (remaining-set, state), seeded with every state the previous segment
    could have ended in.

    An op may be linearized first among the remaining ops iff no other
    remaining op returned before it was called. Unknown-outcome ops may also
    be dropped entirely (they never took effect).

    When collect_finals is set, enumerates ALL reachable end states (needed
    to seed the next segment); otherwise exits on the first complete
    linearization. Returns (ok, finals, nodes). Raises BudgetExhausted when
    the shared node budget runs out before a verdict."""
    n = len(ops)
    calls = [o.call for o in ops]
    rets = [o.ret for o in ops]
    # Symmetry reduction: two ops with the same observable signature are
    # interchangeable — their _apply effect is identical, so among the ones
    # currently available it suffices to expand ONLY the smallest-ret one
    # (both branches). Soundness: availability (call < min_ret(remaining))
    # is monotone as ops are removed, so any schedule that takes an
    # identical sibling now can be rewritten to take the minimal-ret op now
    # and the sibling at the later slot, and keeping the larger-ret sibling
    # only raises min_ret for everyone else. This collapses the 2^k subsets
    # of k identical unknown-outcome writes (e.g. a failover window full of
    # uncertain creates carrying the same per-client value) to k+1 prefixes.
    sigs = [
        (o.kind, o.value, o.prev_rev, o.ok, o.rev, o.err, o.conflict_rev)
        for o in ops
    ]
    full = (1 << n) - 1
    seen: set = set()
    finals: set = set()
    stack = [(full, s) for s in seeds]
    while stack:
        mask, state = stack.pop()
        if mask == 0:
            if not collect_finals:
                return True, finals, nodes
            finals.add(state)
            continue
        key = (mask, state)
        if key in seen:
            continue
        seen.add(key)
        nodes += 1
        if nodes > node_budget:
            n_unknown = sum(1 for o in ops if o.ok is None)
            raise BudgetExhausted(
                f"key {ops[0].key!r}: search budget ({node_budget} nodes) "
                f"exhausted over {total_ops} ops — no verdict "
                f"(segment: {n} ops, {n_unknown} unknown-outcome, "
                f"{len(seeds)} seed states)"
            )
        min_ret = math.inf
        m = mask
        while m:
            i = (m & -m).bit_length() - 1
            m &= m - 1
            if rets[i] < min_ret:
                min_ret = rets[i]
        chosen: dict = {}  # signature -> available index with minimal ret
        m = mask
        while m:
            i = (m & -m).bit_length() - 1
            m &= m - 1
            if calls[i] >= min_ret:
                continue
            j = chosen.get(sigs[i])
            if j is None or rets[i] < rets[j]:
                chosen[sigs[i]] = i
        for i in chosen.values():
            op = ops[i]
            for nxt in _apply(op, state):
                stack.append((mask & ~(1 << i), nxt))
            if op.ok is None:
                # the unacknowledged op may simply never have happened
                stack.append((mask & ~(1 << i), state))
    return bool(finals), finals, nodes


def _check_key(ops: list[Op], node_budget: int = 2_000_000):
    """Per-key search, decomposed at quiescent cuts.

    A cut is a point in real time that no op interval spans: every earlier
    op returned strictly before every later op was called. Real-time order
    then forces ALL pre-cut ops before ALL post-cut ops in any
    linearization, so the history factors into segments that compose
    through their reachable end states — turning one exponential search
    over hundreds of ops into many small ones. Open-window ops
    (ret = inf, i.e. unknown outcomes) span every later cut and keep their
    segment intact, preserving Jepsen semantics.

    Returns (ok, why, nodes_searched). Raises BudgetExhausted when the
    node budget runs out before a verdict."""
    ops = sorted(ops, key=lambda o: (o.call, o.ret))
    n = len(ops)
    if n == 0:
        return True, None, 0
    segments: list[list[Op]] = []
    seg_start = 0
    max_ret = -math.inf
    for i, o in enumerate(ops):
        if i > seg_start and o.call > max_ret:
            segments.append(ops[seg_start:i])
            seg_start = i
        if o.ret > max_ret:
            max_ret = o.ret
    segments.append(ops[seg_start:])

    seeds: set = {_INIT}
    nodes = 0
    for si, seg in enumerate(segments):
        last = si == len(segments) - 1
        ok, finals, nodes = _search_segment(
            seg, seeds, node_budget, nodes,
            collect_finals=not last, total_ops=n)
        if not ok:
            first = seg[0]
            return False, (
                f"key {first.key!r}: no legal linearization of {n} ops "
                f"(segment of {len(seg)} starting {first.kind} "
                f"@ {first.call:.6f})"
            ), nodes
        seeds = finals
    return True, None, nodes


class History:
    """Collects ops (thread-safe append via list.append) and checks them."""

    def __init__(self):
        self.ops: list[Op] = []

    def record(self, client, kind, key, call, ret, **kw):
        self.ops.append(Op(client=client, kind=kind, key=key, call=call, ret=ret, **kw))

    # -------------------------------------------------------------- checks
    def _check_global_revisions(self):
        """Revisions are a global TSO: unique, and real-time ordered across
        keys (if A returned before B was called, rev(A) < rev(B))."""
        import bisect

        writes = [
            o for o in self.ops
            if o.kind != "get" and o.ok and o.rev > 0
        ]
        by_rev: dict[int, Op] = {}
        for o in writes:
            if o.rev in by_rev:
                return (
                    f"revision {o.rev} allocated twice "
                    f"({by_rev[o.rev].kind} {by_rev[o.rev].key!r} and {o.kind} {o.key!r})"
                )
            by_rev[o.rev] = o
        ends = sorted((o.ret, o.rev) for o in writes if o.ret != math.inf)
        end_times = [e[0] for e in ends]
        max_rev_prefix = []
        mx = 0
        for _, r in ends:
            mx = max(mx, r)
            max_rev_prefix.append(mx)
        for o in sorted(writes, key=lambda w: w.call):
            idx = bisect.bisect_left(end_times, o.call) - 1
            if idx >= 0 and max_rev_prefix[idx] >= o.rev:
                return (
                    f"real-time violation: {o.kind} {o.key!r} got rev {o.rev} "
                    f"but a write with rev >= {max_rev_prefix[idx]} had already returned "
                    f"before it was called"
                )
        return None

    def check(self, node_budget: int = 2_000_000, strict: bool = True) -> dict:
        """Check the whole history. Strict by default: a key whose search
        exhausts the node budget FAILS the check (no verdict is not a pass).
        Pass strict=False only for exploratory runs; the result then carries
        truncated_keys so the caller can still see what was unproven.

        The result always records nodes_searched (total) and max_key_nodes so
        soaks can size their histories to fit the budget with headroom."""
        v = self._check_global_revisions()
        if v is not None:
            return {"ok": False, "violation": v, "ops": len(self.ops)}
        per_key: dict[bytes, list[Op]] = {}
        for o in self.ops:
            per_key.setdefault(o.key, []).append(o)
        total_nodes = 0
        max_key_nodes = 0
        truncated: list[bytes] = []
        for key, ops in per_key.items():
            try:
                ok, why, nodes = _check_key(ops, node_budget=node_budget)
            except BudgetExhausted as e:
                if strict:
                    return {
                        "ok": False,
                        "violation": str(e),
                        "truncated": True,
                        "ops": len(self.ops),
                        "nodes_searched": total_nodes + node_budget,
                    }
                truncated.append(key)
                total_nodes += node_budget
                max_key_nodes = max(max_key_nodes, node_budget)
                continue
            total_nodes += nodes
            max_key_nodes = max(max_key_nodes, nodes)
            if not ok:
                return {
                    "ok": False,
                    "violation": why,
                    "ops": len(self.ops),
                    "nodes_searched": total_nodes,
                }
        return {
            "ok": True,
            "violation": None,
            "ops": len(self.ops),
            "keys": len(per_key),
            "nodes_searched": total_nodes,
            "max_key_nodes": max_key_nodes,
            "truncated_keys": truncated,
        }
