"""Vendor-neutral metrics facade.

Reference: pkg/metrics/metrics.go:36-52 — EmitCounter/EmitGauge/EmitHistogram
plus gRPC server interceptors and HTTP handlers, with a Prometheus
implementation and a no-op/minimal one for tests (pkg/metrics/mock).
"""

from __future__ import annotations

import abc
import time


class Metrics(abc.ABC):
    @abc.abstractmethod
    def emit_counter(self, name: str, value: float = 1, **tags: str) -> None: ...

    @abc.abstractmethod
    def emit_gauge(self, name: str, value: float, **tags: str) -> None: ...

    @abc.abstractmethod
    def emit_histogram(self, name: str, value: float, **tags: str) -> None: ...

    def http_handler(self):
        """(content_type, body_bytes) callable for the /metrics endpoint."""
        return lambda: ("text/plain", b"")

    def register_gauge_fn(self, name: str, fn, **tags: str) -> None:
        """Register a gauge sampled at scrape time (``fn() -> float``).
        Backpressure state (queue depths, in-flight counts) is sampled, not
        emitted per event — per-op emit_gauge on a hot path both costs and
        under-reports between scrapes. Default: no-op."""

    def unregister_gauge_fn(self, name: str, **tags: str) -> None:
        """Drop every scrape-time gauge registered under (name, tags).
        Short-lived subjects (watchers) must unregister eagerly — relying
        on scrape-time GC alone leaks entries on unscraped servers.
        Default: no-op."""

    def timed(self, name: str, **tags: str):
        """Context manager emitting a latency histogram + count."""
        return _Timer(self, name, tags)


class _Timer:
    def __init__(self, metrics: Metrics, name: str, tags: dict):
        self._m = metrics
        self._name = name
        self._tags = tags

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        ok = "false" if exc_type else "true"
        self._m.emit_histogram(
            self._name + ".latency.seconds", time.perf_counter() - self._t0,
            success=ok, **self._tags,
        )
        self._m.emit_counter(self._name + ".count", 1, success=ok, **self._tags)
        return False


class NoopMetrics(Metrics):
    """Test/minimal sink (reference mock/minimal.go:22-32)."""

    def emit_counter(self, name, value=1, **tags):
        pass

    def emit_gauge(self, name, value, **tags):
        pass

    def emit_histogram(self, name, value, **tags):
        pass


def new_metrics(cluster: str = "", backend: str = "prometheus") -> Metrics:
    if backend == "noop":
        return NoopMetrics()
    from .prom import PrometheusMetrics

    return PrometheusMetrics(cluster)
