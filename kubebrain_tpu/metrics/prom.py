"""Prometheus implementation.

Reference: pkg/metrics/prometheus/prometheus.go — one lazily-registered vec
per metric name (dots→underscores), global cluster label, /metrics handler.
"""

from __future__ import annotations

import threading

from prometheus_client import (
    CollectorRegistry,
    Counter,
    Gauge,
    Histogram,
    generate_latest,
)
from prometheus_client import CONTENT_TYPE_LATEST

from . import Metrics

_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


class PrometheusMetrics(Metrics):
    def __init__(self, cluster: str = ""):
        self.registry = CollectorRegistry()
        self._cluster = cluster
        self._lock = threading.Lock()
        self._vecs: dict[tuple[str, str], object] = {}

    def _vec(self, kind: str, name: str, tags: dict):
        pname = name.replace(".", "_").replace("-", "_")
        labels = tuple(sorted(tags)) + (("cluster",) if self._cluster else ())
        key = (kind, pname)
        with self._lock:
            vec = self._vecs.get(key)
            if vec is None:
                cls = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}[kind]
                kw = {"buckets": _BUCKETS} if kind == "histogram" else {}
                vec = cls(pname, pname, labelnames=labels, registry=self.registry, **kw)
                self._vecs[key] = vec
        if self._cluster:
            tags = {**tags, "cluster": self._cluster}
        return vec.labels(**{k: str(v) for k, v in tags.items()}) if tags else vec

    def emit_counter(self, name, value=1, **tags):
        self._vec("counter", name, tags).inc(value)

    def emit_gauge(self, name, value, **tags):
        self._vec("gauge", name, tags).set(value)

    def emit_histogram(self, name, value, **tags):
        self._vec("histogram", name, tags).observe(value)

    def http_handler(self):
        def handler():
            return (CONTENT_TYPE_LATEST, generate_latest(self.registry))

        return handler
