"""Prometheus implementation.

Reference: pkg/metrics/prometheus/prometheus.go — one lazily-registered vec
per metric name (dots→underscores), global cluster label, /metrics handler.
"""

from __future__ import annotations

import threading

from prometheus_client import (
    CollectorRegistry,
    Counter,
    Gauge,
    Histogram,
    generate_latest,
)
from prometheus_client import CONTENT_TYPE_LATEST

from . import Metrics

_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


class _CallbackGauges:
    """Scrape-time gauges: callables sampled inside ``collect()`` so
    backpressure state (scheduler queue depths, in-flight dispatches) is
    always current on /metrics without per-event emission on hot paths."""

    def __init__(self):
        self._lock = threading.Lock()
        # pname -> (labelnames, [(labelvalues, fn), ...])
        self._gauges: dict[str, tuple[tuple[str, ...], list]] = {}

    def register(self, pname: str, labelnames: tuple[str, ...],
                 labelvalues: tuple[str, ...], fn) -> None:
        with self._lock:
            entry = self._gauges.setdefault(pname, (labelnames, []))
            entry[1].append((labelvalues, fn))

    def unregister(self, pname: str, labelvalues: tuple[str, ...]) -> None:
        with self._lock:
            entry = self._gauges.get(pname)
            if entry is not None:
                entry[1][:] = [it for it in entry[1] if it[0] != labelvalues]

    def collect(self):
        from prometheus_client.core import GaugeMetricFamily

        with self._lock:
            snapshot = [
                (pname, names, list(items))
                for pname, (names, items) in self._gauges.items()
            ]
        for pname, names, items in snapshot:
            g = GaugeMetricFamily(pname, pname, labels=list(names))
            dead: list = []
            for values, fn in items:
                try:
                    g.add_metric(list(values), float(fn()))
                except LookupError:
                    # the provider says its subject is gone (e.g. a watcher
                    # backlog gauge after the watcher dropped): unregister,
                    # or churn leaks one dead entry per registration forever
                    dead.append((values, fn))
                except Exception:
                    continue  # a dead provider must not break the scrape
            if dead:
                with self._lock:
                    entry = self._gauges.get(pname)
                    if entry is not None:
                        for item in dead:
                            try:
                                entry[1].remove(item)
                            except ValueError:
                                pass
            yield g


class PrometheusMetrics(Metrics):
    def __init__(self, cluster: str = ""):
        self.registry = CollectorRegistry()
        self._cluster = cluster
        self._lock = threading.Lock()
        self._vecs: dict[tuple[str, str], object] = {}
        self._callbacks: _CallbackGauges | None = None

    def _vec(self, kind: str, name: str, tags: dict):
        pname = name.replace(".", "_").replace("-", "_")
        labels = tuple(sorted(tags)) + (("cluster",) if self._cluster else ())
        key = (kind, pname)
        with self._lock:
            vec = self._vecs.get(key)
            if vec is None:
                cls = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}[kind]
                kw = {"buckets": _BUCKETS} if kind == "histogram" else {}
                vec = cls(pname, pname, labelnames=labels, registry=self.registry, **kw)
                self._vecs[key] = vec
        if self._cluster:
            tags = {**tags, "cluster": self._cluster}
        return vec.labels(**{k: str(v) for k, v in tags.items()}) if tags else vec

    def emit_counter(self, name, value=1, **tags):
        self._vec("counter", name, tags).inc(value)

    def emit_gauge(self, name, value, **tags):
        self._vec("gauge", name, tags).set(value)

    def emit_histogram(self, name, value, **tags):
        self._vec("histogram", name, tags).observe(value)

    def register_gauge_fn(self, name, fn, **tags):
        pname, _names, values = self._gauge_key(name, tags)
        with self._lock:
            if self._callbacks is None:
                self._callbacks = _CallbackGauges()
                self.registry.register(self._callbacks)
        self._callbacks.register(pname, _names, values, fn)

    def unregister_gauge_fn(self, name, **tags):
        if self._callbacks is None:
            return
        pname, _names, values = self._gauge_key(name, tags)
        self._callbacks.unregister(pname, values)

    def _gauge_key(self, name, tags):
        pname = name.replace(".", "_").replace("-", "_")
        if self._cluster:
            tags = {**tags, "cluster": self._cluster}
        names = tuple(sorted(tags))
        values = tuple(str(tags[k]) for k in names)
        return pname, names, values

    def http_handler(self):
        def handler():
            return (CONTENT_TYPE_LATEST, generate_latest(self.registry))

        return handler
