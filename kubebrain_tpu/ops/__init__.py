"""TPU kernels for the MVCC hot loops.

The reference's hot loops are byte-key comparison, revision filtering, MVCC
visibility selection, GC victim marking, and watch fan-out filtering
(scanner worker.run scanner.go:389-516; watcherhub.go:78-100). Here they are
vectorized JAX/Pallas ops over fixed-width packed key blocks:

- ``keys``   — pack variable-length NUL-free keys into big-endian ``uint32``
  lane chunks so lexicographic byte order == vectorized u32 tuple order.
- ``scan``   — blockwise range/visibility/count kernels (the north-star
  "prefix-match + revision-filter" kernel).
- ``fanout`` — (events × watchers) prefix-match mask for watch broadcast.
- ``compact``— GC victim mask (superseded versions, tombstones, TTL).
"""
