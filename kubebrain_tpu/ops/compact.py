"""Compaction / GC victim-mask kernel.

Reference: the compact branches of the scan worker (scanner.go:445-491) — in
one pass over a sorted block, mark rows that compaction at ``compact_rev``
makes unreachable:

- superseded: a newer version of the same key exists at <= compact_rev;
- dead tombstone: the row is a tombstone and is the last version
  <= compact_rev (nothing can ever read it again);
- TTL-expired: every version of a TTL key (``/events/``) is <= the TTL
  cutoff revision (derived from the compact-history log when the engine has
  no native TTL, scanner.go:566-591).

The mask comes back to the host, which applies the deletes to the
authoritative store and shrinks the device mirror by compaction-gather —
the "pmap'd k-way merge + tombstone sweep" of the north star is this mask +
a gather, fanned out per partition over the mesh.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .scan import rev_leq, same_as_next


@functools.partial(jax.jit, static_argnames=("with_ttl",))
def victim_mask(
    keys: jnp.ndarray,     # uint32[N, C] sorted packed user keys
    rev_hi: jnp.ndarray,   # uint32[N]
    rev_lo: jnp.ndarray,   # uint32[N]
    tomb: jnp.ndarray,     # bool[N]
    ttl_key: jnp.ndarray,  # bool[N] row belongs to a TTL (/events/) key
    n_valid: jnp.ndarray,  # int32 scalar
    compact_hi: jnp.ndarray,
    compact_lo: jnp.ndarray,
    ttl_cutoff_hi: jnp.ndarray,  # TTL cutoff revision
    ttl_cutoff_lo: jnp.ndarray,
    with_ttl: bool = True,  # STATIC: compile out the carry when TTL is off
) -> jnp.ndarray:
    """bool[N]: version rows deletable when compacting to compact_rev."""
    n = keys.shape[0]
    valid = jnp.arange(n) < n_valid
    le_compact = valid & rev_leq(rev_hi, rev_lo, compact_hi, compact_lo)
    same_next = same_as_next(keys)
    le_next = jnp.roll(le_compact, -1)
    superseded = le_compact & same_next & le_next
    is_last_le = le_compact & ~(same_next & le_next)
    dead_tombstone = is_last_le & tomb
    if not with_ttl:
        return superseded | dead_tombstone

    # TTL expiry: a group is expired ⇔ its LAST row (any revision) is <= the
    # cutoff. Broadcast the group-last verdict backwards with a log-step
    # segmented carry: version chains are short post-compaction, so
    # MAX_CHAIN covers real chains; longer ones expire over successive
    # compactions.
    last_of_group = valid & ~same_next
    last_le_cutoff = last_of_group & rev_leq(rev_hi, rev_lo, ttl_cutoff_hi, ttl_cutoff_lo)
    MAX_CHAIN = 64
    expired = last_le_cutoff
    run = same_next  # run[i]: rows i..i+step are one group
    step = 1
    while step < MAX_CHAIN:
        expired = expired | (run & jnp.roll(expired, -step))
        run = run & jnp.roll(run, -step)
        step *= 2
    expired = expired & ttl_key & valid

    return superseded | dead_tombstone | expired


def compact_block(keys, rev_hi, rev_lo, tomb, mask):
    """Shrink a block by dropping masked rows (device-side gather); returns
    (keys, rev_hi, rev_lo, tomb, new_count). Order is preserved so the block
    stays sorted."""
    keep = ~mask
    n = keys.shape[0]
    (idx,) = jnp.nonzero(keep, size=n, fill_value=n - 1)
    new_count = jnp.sum(keep, dtype=jnp.int32)
    return keys[idx], rev_hi[idx], rev_lo[idx], tomb[idx], new_count
