"""Pallas TPU kernel for the compaction/GC victim mask.

Same victim rule as ops.compact.victim_mask (reference: the compact branches
of the scan worker, scanner.go:445-491 + TTL derivation scanner.go:566-591),
tiled for the VPU exactly like the scan kernel (ops/scan_pallas.py): rows on
the 128-wide lane axis, chunk-major sign-flipped keys, 31-bit revision
split, reverse-tile grid with a carry.

Three verdicts per row, all needing the NEXT row of the same key:

- superseded: row and its next-newer version are both <= compact_rev;
- dead tombstone: row is the newest version <= compact_rev and a tombstone;
- TTL-expired: the whole group's newest version is <= the TTL cutoff —
  a backward broadcast from each group's last row, done with an in-tile
  log-step segmented OR (in-tile run links only; the tile's last column is
  seeded from the carried verdict of the next tile's first row, so group
  chains of ANY length propagate across tiles — one tile per grid step,
  grid steps run in order).

The carry holds the next tile's first key, its <=compact_rev flag, and its
group-expired verdict. The range restriction ([start, end) borders from the
backend's compact fences) is folded into the same kernel pass.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .scan_pallas import (
    LANE_TILE,
    _flip_sign_jnp,
    _lex_less,
    _split31_jnp,
)


def _kernel(scal_ref, start_ref, end_ref,
            keys_ref, rh_ref, rl_ref, tomb_ref, ttl_ref,
            mask_ref,
            carry_key, carry_flags,
            *, with_ttl: bool):
    i = pl.program_id(0)
    nt = pl.num_programs(0)
    t = nt - 1 - i  # reversed tile order

    n_valid = scal_ref[0]
    unbounded = scal_ref[1]
    chi = scal_ref[2]  # compact revision, 31-bit split
    clo = scal_ref[3]
    thi = scal_ref[4]  # TTL cutoff revision, 31-bit split
    tlo = scal_ref[5]

    keys = keys_ref[:, :]          # [C, T] int32 sign-flipped chunks
    rh = rh_ref[:, :]              # [1, T] int32 31-bit rev hi
    rl = rl_ref[:, :]
    tomb = tomb_ref[:, :] != 0     # [1, T]
    c, tile = keys.shape

    lane = jax.lax.broadcasted_iota(jnp.int32, (1, tile), 1)
    idx = t * tile + lane
    valid = idx < n_valid
    is_last_col = lane == (tile - 1)
    have_i = ((t + 1) * tile < n_valid).astype(jnp.int32)

    le_compact = valid & ((rh < chi) | ((rh == chi) & (rl <= clo)))

    # range restriction (compact borders), same lex compare as the scan
    start = start_ref[:, :]
    end = end_ref[:, :]
    less_start = _lex_less(keys, start, keys != start, keys < start)
    less_end = _lex_less(keys, end, keys != end, keys < end)
    in_range = (~less_start) & ((unbounded != 0) | less_end)

    # same-key-as-next across the tile boundary via the carried first key
    nxt_keys = jnp.roll(keys, -1, axis=1)
    nxt_keys = jnp.where(is_last_col, carry_key[:, :], nxt_keys)
    same_next = jnp.all(keys == nxt_keys, axis=0, keepdims=True)
    same_next = same_next & (jnp.where(is_last_col, have_i, 1) != 0)

    le_next_i = jnp.roll(le_compact.astype(jnp.int32), -1, axis=1)
    le_next = jnp.where(is_last_col, carry_flags[0] * have_i, le_next_i) != 0

    superseded = le_compact & same_next & le_next
    is_last_le = le_compact & ~(same_next & le_next)
    victims = superseded | (is_last_le & tomb)

    if with_ttl:
        ttlk = ttl_ref[:, :] != 0
        # seed: each group's true last row carries the group verdict
        seed = (valid & ~same_next) & ((rh < thi) | ((rh == thi) & (rl <= tlo)))
        # the tile's last column inherits the carried verdict when its group
        # continues into the next tile (same_next at last col implies have)
        seed_i = seed.astype(jnp.int32)
        boundary = same_next & is_last_col
        seed_i = jnp.where(boundary, carry_flags[1], seed_i)
        expired = seed_i != 0
        # in-tile links only: the last column's link is the boundary seed
        run = same_next & ~is_last_col
        step = 1
        while step < tile:
            # wrapping rolls are safe: run windows containing the cut last
            # column are False, so wrapped values never land
            expired = expired | (run & jnp.roll(expired, -step))
            run = run & jnp.roll(run, -step)
            step *= 2
        victims = victims | (expired & ttlk & valid)
        carry_flags[1] = expired.astype(jnp.int32)[0, 0]

    mask_ref[:, :] = (victims & in_range).astype(jnp.int8)

    # publish this tile's first column for the next grid step (tile t-1)
    carry_key[:, :] = keys[:, 0:1]
    carry_flags[0] = le_compact.astype(jnp.int32)[0, 0]


@functools.partial(jax.jit, static_argnames=("with_ttl", "interpret"))
def victim_mask_pallas(keys_t, rh31, rl31, tomb8, ttl8, n_valid, start, end,
                       unbounded, chi31, clo31, thi31, tlo31,
                       with_ttl=True, interpret=False):
    """Victim mask via the Pallas kernel over one partition.

    keys_t int32[C, N] chunk-major sign-flipped (N % LANE_TILE == 0);
    rh31/rl31 int32[N]; tomb8/ttl8 int8[N]; start/end int32[C] sign-flipped
    bounds; scalars n_valid/unbounded/compact/ttl-cutoff. Returns bool[N].
    """
    c, n = keys_t.shape
    assert n % LANE_TILE == 0, "pad rows to LANE_TILE"
    nt = n // LANE_TILE
    scal = jnp.stack([
        jnp.asarray(n_valid, jnp.int32),
        jnp.asarray(unbounded, jnp.int32),
        jnp.asarray(chi31, jnp.int32),
        jnp.asarray(clo31, jnp.int32),
        jnp.asarray(thi31, jnp.int32),
        jnp.asarray(tlo31, jnp.int32),
    ])
    rev_map = lambda i: (0, nt - 1 - i)
    mask = pl.pallas_call(
        functools.partial(_kernel, with_ttl=with_ttl),
        grid=(nt,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),          # scalars
            pl.BlockSpec((c, 1), lambda i: (0, 0)),          # start bound
            pl.BlockSpec((c, 1), lambda i: (0, 0)),          # end bound
            pl.BlockSpec((c, LANE_TILE), rev_map),           # keys
            pl.BlockSpec((1, LANE_TILE), rev_map),           # rev hi
            pl.BlockSpec((1, LANE_TILE), rev_map),           # rev lo
            pl.BlockSpec((1, LANE_TILE), rev_map),           # tombstones
            pl.BlockSpec((1, LANE_TILE), rev_map),           # ttl-key flags
        ],
        out_specs=pl.BlockSpec((1, LANE_TILE), rev_map),
        out_shape=jax.ShapeDtypeStruct((1, n), jnp.int8),
        scratch_shapes=[
            pltpu.VMEM((c, 1), jnp.int32),                   # carried first key
            pltpu.SMEM((2,), jnp.int32),                     # le_compact, expired
        ],
        interpret=interpret,
    )(
        scal,
        start.reshape(c, 1), end.reshape(c, 1),
        keys_t, rh31.reshape(1, n), rl31.reshape(1, n),
        tomb8.reshape(1, n), ttl8.reshape(1, n),
    )
    return mask.reshape(n) != 0


@functools.partial(jax.jit, static_argnames=("with_ttl", "interpret"))
def victim_mask_batch_cached(keys_t, rh31, rl31, tomb8, ttl8, nv, start, end,
                             unbounded, compact_hi, compact_lo,
                             ttl_hi, ttl_lo, with_ttl=True, interpret=False):
    """Batched (vmapped over partitions) victim masks over the
    `prepare_mirror`-cached layout, mirroring engine._victim_batch's contract:
    32-bit uint revision splits in, bool[P, Npad] out (caller slices padding).

    start/end are uint32[C] packed bounds; compact/ttl revisions are 32-bit
    (hi, lo) uint32 splits, re-split to 31-bit in-graph."""
    chi31, clo31 = _split31_jnp(
        jnp.asarray(compact_hi, jnp.uint32), jnp.asarray(compact_lo, jnp.uint32)
    )
    thi31, tlo31 = _split31_jnp(
        jnp.asarray(ttl_hi, jnp.uint32), jnp.asarray(ttl_lo, jnp.uint32)
    )
    s = _flip_sign_jnp(jnp.asarray(start, jnp.uint32))
    e = _flip_sign_jnp(jnp.asarray(end, jnp.uint32))
    unb = jnp.asarray(unbounded, jnp.int32)
    f = lambda kt, a, b, t8, x8, n: victim_mask_pallas(
        kt, a, b, t8, x8, n, s, e, unb, chi31, clo31, thi31, tlo31,
        with_ttl=with_ttl, interpret=interpret,
    )
    return jax.vmap(f)(keys_t, rh31, rl31, tomb8, ttl8, nv)
