"""Vectorized watch fan-out: (events × watchers) prefix-match mask.

Reference: the per-watcher prefix+revision filter applied to every event
batch (watcherhub.go:78-100, watch.go:140-160) — O(E·W) Python/Go string
compares per batch at 10k watchers × 1k events/s (BASELINE config 3). Here
the whole mask is one broadcasted masked-compare:

    match[e, w] = all((event_key_chunks[e] & prefix_mask[w]) == prefix_chunk[w])
                  & event_rev[e] >= watcher_min_rev[w]

Prefixes of arbitrary byte length become (chunk, mask) pairs at registration
time (ops.keys.chunk_prefix_masks); the kernel is pure compare+reduce on the
VPU and shards over the watcher axis on the device mesh (all watchers see
every event; the watcher table is the large, shardable side).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from . import keys as keyops
from ..trace import TRACER
from .scan import lex_less, rev_leq


@jax.jit
def fanout_mask(
    event_keys: jnp.ndarray,   # uint32[E, C] packed event keys
    ev_rev_hi: jnp.ndarray,    # uint32[E]
    ev_rev_lo: jnp.ndarray,    # uint32[E]
    prefix_chunks: jnp.ndarray,  # uint32[W, C] pre-masked prefix chunks
    prefix_masks: jnp.ndarray,   # uint32[W, C]
    min_rev_hi: jnp.ndarray,   # uint32[W]
    min_rev_lo: jnp.ndarray,   # uint32[W]
) -> jnp.ndarray:
    """bool[E, W] delivery mask."""
    masked = event_keys[:, None, :] & prefix_masks[None, :, :]  # [E, W, C]
    prefix_ok = jnp.all(masked == prefix_chunks[None, :, :], axis=-1)  # [E, W]
    # event.rev >= watcher.min_rev  ⇔  min_rev <= event.rev
    rev_ok = rev_leq(min_rev_hi[None, :], min_rev_lo[None, :], ev_rev_hi[:, None], ev_rev_lo[:, None])
    return prefix_ok & rev_ok


@jax.jit
def fanout_mask_range(
    event_keys: jnp.ndarray,   # uint32[E, C]
    ev_rev_hi: jnp.ndarray,    # uint32[E]
    ev_rev_lo: jnp.ndarray,    # uint32[E]
    w_start: jnp.ndarray,      # uint32[W, C]
    w_end: jnp.ndarray,        # uint32[W, C]
    w_unbounded: jnp.ndarray,  # bool[W]
    min_rev_hi: jnp.ndarray,   # uint32[W]
    min_rev_lo: jnp.ndarray,   # uint32[W]
) -> jnp.ndarray:
    """bool[E, W] delivery mask for key-*range* watchers [start, end)
    (etcd watch semantics — the hub's filter shape)."""
    ge = ~lex_less(event_keys[:, None, :], w_start[None, :, :])   # [E, W]
    lt = lex_less(event_keys[:, None, :], w_end[None, :, :])
    rev_ok = rev_leq(min_rev_hi[None, :], min_rev_lo[None, :], ev_rev_hi[:, None], ev_rev_lo[:, None])
    return ge & (w_unbounded[None, :] | lt) & rev_ok


@jax.jit
def fanout_mask_range_wmajor(
    event_keys: jnp.ndarray,   # uint32[E, C]
    ev_rev_hi: jnp.ndarray,    # uint32[E]
    ev_rev_lo: jnp.ndarray,    # uint32[E]
    w_start: jnp.ndarray,      # uint32[W, C]
    w_end: jnp.ndarray,        # uint32[W, C]
    w_unbounded: jnp.ndarray,  # bool[W]
    min_rev_hi: jnp.ndarray,   # uint32[W]
    min_rev_lo: jnp.ndarray,   # uint32[W]
) -> jnp.ndarray:
    """bool[W, E] — :func:`fanout_mask_range` transposed at the source.

    The block-batched dispatch compacts the mask watcher-major; computing
    it watcher-major in the first place lets XLA fuse the compaction into
    the compare, where an explicit ``.T`` on the E-major mask costs a full
    [E, W] re-materialization (measured ~half the dispatch at 2k x 10k on
    CPU)."""
    ge = ~lex_less(event_keys[None, :, :], w_start[:, None, :])   # [W, E]
    lt = lex_less(event_keys[None, :, :], w_end[:, None, :])
    rev_ok = rev_leq(min_rev_hi[:, None], min_rev_lo[:, None],
                     ev_rev_hi[None, :], ev_rev_lo[None, :])
    return ge & (w_unbounded[:, None] | lt) & rev_ok


class FanoutMatcher:
    """Host adapter: WatcherHub-compatible matcher backed by the range kernel.

    Callable as (events, [(wid, start, end, min_rev)]) -> bool[E][W] (the
    hub's ``fanout_matcher`` hook). Re-packs the watcher table only when the
    watcher set changes; event batches are packed per call. With a mesh, the
    watcher table lives sharded across devices (the watcher axis is the
    large, shardable side at 10k watchers — SURVEY P4) and GSPMD computes
    the (E × W) mask shard-locally.
    """

    def __init__(self, width: int = keyops.KEY_WIDTH, mesh=None):
        self._width = width
        self._mesh = mesh
        self._cache_key: tuple | None = None
        self._cached = None
        self._metrics = None

    def set_metrics(self, metrics) -> None:
        """Arm the ``kb.fanout.sharded`` gauge: 1 when the watcher table is
        actually distributed over a multi-device mesh, 0 otherwise. The old
        ragged-count code path fell back to an unsharded table SILENTLY —
        now the bucket is padded to a device-count multiple so sharding
        always applies, and the gauge makes the state observable."""
        self._metrics = metrics
        if metrics is not None:
            metrics.emit_gauge("kb.fanout.sharded", self._sharded())
            metrics.register_gauge_fn("kb.fanout.sharded", self._sharded)

    def _sharded(self) -> float:
        return 1.0 if (self._mesh is not None
                       and self._mesh.devices.size > 1) else 0.0

    def _put_watcher(self, arr):
        a = jnp.asarray(arr)
        if self._mesh is None:
            return a
        from jax.sharding import NamedSharding, PartitionSpec

        axis = self._mesh.axis_names[0]
        spec = PartitionSpec(axis, *(None,) * (a.ndim - 1))
        return jax.device_put(a, NamedSharding(self._mesh, spec))

    @staticmethod
    def _bucket(n: int, lo: int) -> int:
        b = lo
        while b < n:
            b *= 2
        return b

    def _watcher_table(self, specs: list[tuple[int, bytes, bytes, int]],
                       version=None):
        """Packed watcher table, W-padded to a power-of-2 bucket so watcher
        churn doesn't change the kernel shape (each distinct shape is an XLA
        compile), then rounded up to a multiple of the mesh device count so
        the ``wat`` sharding ALWAYS divides evenly (no ragged fallback).
        ``version`` (the hub's watcher-set counter) makes the cache check
        O(1); without it the fallback key is the O(W) spec tuple.

        The version key is widened with the population's cheap shape
        (count + first/last wid): a restarted hub reuses versions from 0,
        so a bare version match could alias the packed table of a DEAD
        population — version regression (or any shape change) now misses
        the cache and rebuilds."""
        if version is not None:
            cache_key = (version, len(specs),
                         specs[0][0] if specs else None,
                         specs[-1][0] if specs else None)
        else:
            cache_key = tuple(specs)
        if cache_key != self._cache_key:
            w = len(specs)
            wpad = self._bucket(max(w, 1), 64)
            if self._mesh is not None:
                n_dev = int(self._mesh.devices.size)
                wpad = ((wpad + n_dev - 1) // n_dev) * n_dev
            # canonicalize NUL-bearing bounds (single-key watches use
            # end = key + b"\0", which zero-pads equal to the key)
            starts, _ = keyops.pack_keys(
                [keyops.canonicalize_bound(s) for _, s, _, _ in specs], self._width
            )
            ends, _ = keyops.pack_keys(
                [keyops.canonicalize_bound(e) for _, _, e, _ in specs], self._width
            )
            unbounded = np.array([not e for _, _, e, _ in specs])
            hi, lo = keyops.split_revs(np.array([r for _, _, _, r in specs], dtype=np.uint64))
            if wpad > w:
                # padding watchers can never match: start = max key, bounded
                # end = 0 (empty range)
                pad = wpad - w
                starts = np.concatenate(
                    [starts, np.full((pad, starts.shape[1]), 0xFFFFFFFF, starts.dtype)]
                )
                ends = np.concatenate(
                    [ends, np.zeros((pad, ends.shape[1]), ends.dtype)]
                )
                unbounded = np.concatenate([unbounded, np.zeros(pad, bool)])
                hi = np.concatenate([hi, np.zeros(pad, hi.dtype)])
                lo = np.concatenate([lo, np.zeros(pad, lo.dtype)])
            self._cached = (
                self._put_watcher(starts), self._put_watcher(ends),
                self._put_watcher(unbounded),
                self._put_watcher(hi), self._put_watcher(lo),
            )
            self._cache_key = cache_key
        return self._cached

    def __call__(self, events, watcher_specs, version=None):
        ws, we, wu, whi, wlo = self._watcher_table(watcher_specs, version)
        e = len(events)
        # E-pad to a bucket: event batches arrive in every size from 1 to the
        # ring's drain depth; without bucketing each size is its own compile
        epad = self._bucket(max(e, 1), 8)
        keys = [ev.key for ev in events]
        revs = [ev.revision for ev in events]
        if epad > e:
            keys += [b""] * (epad - e)
            revs += [0] * (epad - e)
        ek, _ = keyops.pack_keys(keys, self._width)
        ehi, elo = keyops.split_revs(np.array(revs, dtype=np.uint64))
        # watch fan-out device time: dispatch (async kernel enqueue) vs the
        # blocking mask pull — the watch path's slice of kb_rpc_stage_seconds
        with TRACER.stage("fanout_dispatch"):
            mask = fanout_mask_range(
                jnp.asarray(ek), jnp.asarray(ehi), jnp.asarray(elo),
                ws, we, wu, whi, wlo,
            )
        with TRACER.stage("fanout_copy"):
            return np.asarray(mask)[:e, :len(watcher_specs)]
