"""Vectorized watch fan-out: (events × watchers) prefix-match mask.

Reference: the per-watcher prefix+revision filter applied to every event
batch (watcherhub.go:78-100, watch.go:140-160) — O(E·W) Python/Go string
compares per batch at 10k watchers × 1k events/s (BASELINE config 3). Here
the whole mask is one broadcasted masked-compare:

    match[e, w] = all((event_key_chunks[e] & prefix_mask[w]) == prefix_chunk[w])
                  & event_rev[e] >= watcher_min_rev[w]

Prefixes of arbitrary byte length become (chunk, mask) pairs at registration
time (ops.keys.chunk_prefix_masks); the kernel is pure compare+reduce on the
VPU and shards over the watcher axis on the device mesh (all watchers see
every event; the watcher table is the large, shardable side).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from . import keys as keyops
from .scan import rev_leq


@jax.jit
def fanout_mask(
    event_keys: jnp.ndarray,   # uint32[E, C] packed event keys
    ev_rev_hi: jnp.ndarray,    # uint32[E]
    ev_rev_lo: jnp.ndarray,    # uint32[E]
    prefix_chunks: jnp.ndarray,  # uint32[W, C] pre-masked prefix chunks
    prefix_masks: jnp.ndarray,   # uint32[W, C]
    min_rev_hi: jnp.ndarray,   # uint32[W]
    min_rev_lo: jnp.ndarray,   # uint32[W]
) -> jnp.ndarray:
    """bool[E, W] delivery mask."""
    masked = event_keys[:, None, :] & prefix_masks[None, :, :]  # [E, W, C]
    prefix_ok = jnp.all(masked == prefix_chunks[None, :, :], axis=-1)  # [E, W]
    # event.rev >= watcher.min_rev  ⇔  min_rev <= event.rev
    rev_ok = rev_leq(min_rev_hi[None, :], min_rev_lo[None, :], ev_rev_hi[:, None], ev_rev_lo[:, None])
    return prefix_ok & rev_ok


class FanoutMatcher:
    """Host adapter: WatcherHub-compatible matcher backed by the kernel.

    Callable as (events, [(wid, prefix, min_rev)]) -> bool[E][W] (the hub's
    ``fanout_matcher`` hook). Re-packs the watcher table only when the watcher
    set changes; event batches are packed per call.
    """

    def __init__(self, width: int = keyops.KEY_WIDTH):
        self._width = width
        self._cache_key: tuple | None = None
        self._cached = None

    def _watcher_table(self, specs: list[tuple[int, bytes, int]]):
        cache_key = tuple((wid, prefix, rev) for wid, prefix, rev in specs)
        if cache_key != self._cache_key:
            chunks, masks = keyops.chunk_prefix_masks([p for _, p, _ in specs], self._width)
            hi, lo = keyops.split_revs(np.array([r for _, _, r in specs], dtype=np.uint64))
            self._cached = (
                jnp.asarray(chunks), jnp.asarray(masks), jnp.asarray(hi), jnp.asarray(lo),
            )
            self._cache_key = cache_key
        return self._cached

    def __call__(self, events, watcher_specs):
        chunks, masks, whi, wlo = self._watcher_table(watcher_specs)
        ek, _ = keyops.pack_keys([e.key for e in events], self._width)
        ehi, elo = keyops.split_revs(np.array([e.revision for e in events], dtype=np.uint64))
        mask = fanout_mask(
            jnp.asarray(ek), jnp.asarray(ehi), jnp.asarray(elo), chunks, masks, whi, wlo
        )
        return np.asarray(mask)
