"""Fixed-width packed key representation for device kernels.

Variable-length byte keys defeat vectorization; Kubernetes registry keys are
bounded and NUL-free, so we pack each user key into a zero-padded row of
``KEY_WIDTH`` bytes stored as ``KEY_WIDTH//4`` big-endian ``uint32`` chunks:

- zero padding + NUL-free keys ⇒ padded byte order == true lexicographic
  order (the coder's split byte is also NUL — same design decision,
  kubebrain_tpu/coder/__init__.py);
- big-endian u32 packing ⇒ byte order == unsigned-int tuple order, quartering
  the comparisons per key versus byte-wise compare;
- prefix matches of arbitrary length become masked u32 compares
  (see ``chunk_prefix_masks``).

Revisions are split into (hi, lo) ``uint32`` pairs — TPUs have no native
int64, and revision compares are cheap next to key compares.

Reference analogue: the internal-key decode + byte compare in the scan worker
(scanner.go:435, coder/normal.go:58-71) — here performed once at pack time
instead of per row per scan.
"""

from __future__ import annotations

import numpy as np

KEY_WIDTH = 128  # bytes; must be % 4 == 0; k8s registry keys fit comfortably
CHUNKS = KEY_WIDTH // 4


def pack_keys(keys: list[bytes], width: int = KEY_WIDTH) -> tuple[np.ndarray, np.ndarray]:
    """Pack N variable-length keys → (uint32[N, width//4] big-endian chunks,
    int32[N] lengths). Keys longer than ``width`` are rejected."""
    n = len(keys)
    out = np.zeros((n, width), dtype=np.uint8)
    lens = np.zeros((n,), dtype=np.int32)
    for i, k in enumerate(keys):
        if len(k) > width:
            raise ValueError(f"key length {len(k)} exceeds KEY_WIDTH {width}")
        out[i, : len(k)] = np.frombuffer(k, dtype=np.uint8)
        lens[i] = len(k)
    return bytes_to_chunks(out), lens


def bytes_to_chunks(rows: np.ndarray) -> np.ndarray:
    """uint8[N, W] → big-endian uint32[N, W//4]."""
    n, w = rows.shape
    assert w % 4 == 0
    be = rows.reshape(n, w // 4, 4).astype(np.uint32)
    return (be[..., 0] << 24) | (be[..., 1] << 16) | (be[..., 2] << 8) | be[..., 3]


def chunks_to_u8(chunks: np.ndarray) -> np.ndarray:
    """big-endian uint32[N, C] → uint8[N, C*4] (inverse of bytes_to_chunks)."""
    n, c = chunks.shape
    out = np.zeros((n, c * 4), dtype=np.uint8)
    out[:, 0::4] = (chunks >> 24) & 0xFF
    out[:, 1::4] = (chunks >> 16) & 0xFF
    out[:, 2::4] = (chunks >> 8) & 0xFF
    out[:, 3::4] = chunks & 0xFF
    return out


def chunks_to_bytes(chunks: np.ndarray, lens: np.ndarray) -> list[bytes]:
    """Inverse of pack_keys for host-side materialization."""
    out = chunks_to_u8(chunks)
    return [out[i, : lens[i]].tobytes() for i in range(len(out))]


def u8_void(rows: np.ndarray) -> np.ndarray:
    """uint8[N, W] → void[N] scalar view: rows compare as raw bytes
    (memcmp order), so one ``np.searchsorted``/``np.unique`` resolves many
    key probes at once. Zero-padded NUL-free keys keep the padded compare
    equal to true byte order — the invariant the whole packed layout
    (and the encoded layout, storage/tpu/encode.py) rests on."""
    rows = np.ascontiguousarray(rows)
    n, w = rows.shape
    assert w > 0, "void view of zero-width rows"
    return rows.view(f"V{w}").reshape(n)


def gather_arena(arena: np.ndarray, offsets: np.ndarray, perm: np.ndarray):
    """Reorder variable-length records of a byte arena by ``perm``.

    Returns (new_arena uint8[∑len], new_offsets uint64[len(perm)+1]) —
    fully vectorized (per-row source ranges expanded with repeat+arange).
    """
    offsets = offsets.astype(np.int64)
    lens = (offsets[1:] - offsets[:-1])[perm]
    new_offsets = np.zeros(len(perm) + 1, dtype=np.int64)
    np.cumsum(lens, out=new_offsets[1:])
    total = int(new_offsets[-1])
    if total == 0:
        return np.zeros(0, dtype=np.uint8), new_offsets.astype(np.uint64)
    starts = offsets[:-1][perm]
    idx = np.arange(total, dtype=np.int64)
    idx += np.repeat(starts - new_offsets[:-1], lens)
    return arena[idx], new_offsets.astype(np.uint64)


def pack_one(key: bytes, width: int = KEY_WIDTH) -> np.ndarray:
    """Single key → uint32[width//4] (for range bounds)."""
    return pack_keys([key], width)[0][0]


def canonicalize_bound(key: bytes) -> bytes:
    """Rewrite a NUL-bearing range bound for the zero-padded compare.

    Stored keys are NUL-free, so a bound like etcd's continuation token
    ``base + b"\\0"`` means "strictly after base" — but zero-padded it
    compares EQUAL to base. ``base + b"\\0\\1"`` sits strictly between base
    and every longer NUL-free key, preserving the intended position.
    """
    if b"\x00" not in key:
        return key
    base = key.split(b"\x00", 1)[0]
    return base + b"\x00\x01"


def split_revs(revs: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """uint64[N] → (hi uint32[N], lo uint32[N])."""
    revs = np.asarray(revs, dtype=np.uint64)
    return (revs >> np.uint64(32)).astype(np.uint32), (revs & np.uint64(0xFFFFFFFF)).astype(np.uint32)


def join_revs(hi: np.ndarray, lo: np.ndarray) -> np.ndarray:
    return (np.asarray(hi, dtype=np.uint64) << np.uint64(32)) | np.asarray(lo, dtype=np.uint64)


def chunk_prefix_masks(prefixes: list[bytes], width: int = KEY_WIDTH) -> tuple[np.ndarray, np.ndarray]:
    """Prefixes → (chunks uint32[P, C], masks uint32[P, C]) such that key k
    starts with prefix p  ⇔  all((k_chunks & masks[p]) == chunks[p]).

    A prefix of length L covers L//4 full chunks (mask 0xFFFFFFFF) plus,
    big-endian, the HIGH (L%4)*8 bits of the next chunk; chunks beyond the
    prefix get mask 0 (always match).
    """
    chunks, _lens = pack_keys(prefixes, width)
    c = width // 4
    masks = np.zeros((len(prefixes), c), dtype=np.uint32)
    for i, p in enumerate(prefixes):
        full, rem = divmod(len(p), 4)
        masks[i, :full] = 0xFFFFFFFF
        if rem:
            masks[i, full] = np.uint32(0xFFFFFFFF) << np.uint32(8 * (4 - rem))
    return chunks & masks, masks
