"""Blockwise MVCC range-scan kernels — the north-star hot loop.

Reference hot loop: pkg/backend/scanner/scanner.go worker.run :389-516 — per
row: decode internal key, prefix/range compare, revision filter, "last
version <= read_rev per user key" selection, tombstone suppression. Here the
whole pass is a handful of vectorized ops over a sorted packed block:

    rows sorted by (key asc, revision asc)
    cand[i]    = valid[i] & in_range[i] & rev[i] <= read_rev
    visible[i] = cand[i] & !(same_key[i,i+1] & cand[i+1]) & !tombstone[i]

The "next row differs" test replaces the scan worker's prev-key carry
(scanner.go:408-414,451-470). Blocks are always split at user-key boundaries
(the same trick as adjustPartitionBorders, scanner.go:202-225), so no
cross-block carry is needed and every block/shard is independent — which is
exactly what makes the scan embarrassingly parallel over the device mesh.

All functions are shape-polymorphic pure jax and run under jit/shard_map on
TPU or CPU. The Pallas variant (scan_pallas.py) tiles the same math through
VMEM explicitly for the large-block case.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def lex_less(keys: jnp.ndarray, bound: jnp.ndarray) -> jnp.ndarray:
    """keys[N, C] < bound[C] lexicographically over big-endian u32 chunks.

    First-differing-chunk decides: O(N*C) compares, no data-dependent control
    flow — XLA maps it straight onto the VPU.
    """
    eq = keys == bound
    lt = keys < bound
    neq = ~eq
    has_diff = jnp.any(neq, axis=-1)
    first = jnp.argmax(neq, axis=-1)
    lt_first = jnp.take_along_axis(lt, first[..., None], axis=-1)[..., 0]
    return has_diff & lt_first


def lex_geq(keys: jnp.ndarray, bound: jnp.ndarray) -> jnp.ndarray:
    return ~lex_less(keys, bound)


def rev_leq(rev_hi: jnp.ndarray, rev_lo: jnp.ndarray, read_hi, read_lo) -> jnp.ndarray:
    """(hi, lo) uint32 pair compare: rev <= read_rev."""
    return (rev_hi < read_hi) | ((rev_hi == read_hi) & (rev_lo <= read_lo))


def same_as_next(keys: jnp.ndarray) -> jnp.ndarray:
    """bool[N]: row i has the same user key as row i+1 (False for the last
    row — blocks never split a user key's version chain)."""
    nxt = jnp.roll(keys, -1, axis=0)
    same = jnp.all(keys == nxt, axis=-1)
    n = keys.shape[0]
    return same & (jnp.arange(n) != n - 1)


def visibility_mask(
    keys: jnp.ndarray,      # uint32[N, C] packed user keys, sorted
    rev_hi: jnp.ndarray,    # uint32[N]
    rev_lo: jnp.ndarray,    # uint32[N]
    tomb: jnp.ndarray,      # bool[N]
    n_valid: jnp.ndarray,   # int32 scalar: rows beyond are padding
    start: jnp.ndarray,     # uint32[C] packed start bound (inclusive)
    end: jnp.ndarray,       # uint32[C] packed end bound (exclusive)
    unbounded_end: jnp.ndarray,  # bool scalar: ignore `end`
    read_hi: jnp.ndarray,   # uint32 scalar
    read_lo: jnp.ndarray,   # uint32 scalar
) -> jnp.ndarray:
    """bool[N]: rows visible at read_rev within [start, end)."""
    n = keys.shape[0]
    valid = jnp.arange(n) < n_valid
    in_range = lex_geq(keys, start) & (unbounded_end | lex_less(keys, end))
    cand = valid & in_range & rev_leq(rev_hi, rev_lo, read_hi, read_lo)
    cand_next = jnp.roll(cand, -1)
    superseded = same_as_next(keys) & cand_next
    return cand & ~superseded & ~tomb


def visibility_mask_queries(
    keys, rev_hi, rev_lo, tomb, n_valid, starts, ends, unbounded_ends,
    read_his, read_los,
) -> jnp.ndarray:
    """Query axis over :func:`visibility_mask`: Q distinct Range/Count
    queries (``starts``/``ends`` uint32[Q, C] packed bounds,
    ``unbounded_ends`` bool[Q], ``read_his``/``read_los`` uint32[Q])
    answered against ONE block in one traced program. Returns bool[Q, N] —
    the jnp fallback of the query-batched Pallas kernel
    (scan_pallas.scan_mask_pallas_q)."""
    f = lambda s, e, u, hi, lo: visibility_mask(
        keys, rev_hi, rev_lo, tomb, n_valid, s, e, u, hi, lo
    )
    return jax.vmap(f)(starts, ends, unbounded_ends, read_his, read_los)


@jax.jit
def count_visible(keys, rev_hi, rev_lo, tomb, n_valid, start, end, unbounded_end, read_hi, read_lo):
    mask = visibility_mask(
        keys, rev_hi, rev_lo, tomb, n_valid, start, end, unbounded_end, read_hi, read_lo
    )
    return jnp.sum(mask, dtype=jnp.int32)


@jax.jit
def visible_mask_jit(keys, rev_hi, rev_lo, tomb, n_valid, start, end, unbounded_end, read_hi, read_lo):
    return visibility_mask(
        keys, rev_hi, rev_lo, tomb, n_valid, start, end, unbounded_end, read_hi, read_lo
    )


def visible_indices(mask: jnp.ndarray, size: int) -> jnp.ndarray:
    """First ``size`` set positions of mask (fill = len(mask)); jit-safe with
    static ``size`` — the device-side equivalent of the receiver append loop
    (receiver.go:21-31)."""
    (idx,) = jnp.nonzero(mask, size=size, fill_value=mask.shape[0])
    return idx


def make_point_lookup(n_chunks: int):
    """Point-get kernel: latest version of ONE key at read_rev.

    Returns (found bool, rev_hi, rev_lo, row int32, tombstone bool). The
    binary-search-free formulation: exact-match mask & rev filter & take last.
    """

    @jax.jit
    def lookup(keys, rev_hi, rev_lo, tomb, n_valid, key, read_hi, read_lo):
        n = keys.shape[0]
        valid = jnp.arange(n) < n_valid
        match = valid & jnp.all(keys == key, axis=-1) & rev_leq(rev_hi, rev_lo, read_hi, read_lo)
        # last matching row = highest revision <= read_rev
        idx = n - 1 - jnp.argmax(match[::-1])
        found = jnp.any(match)
        idx = jnp.where(found, idx, 0)
        return found, rev_hi[idx], rev_lo[idx], idx.astype(jnp.int32), tomb[idx]

    return lookup
