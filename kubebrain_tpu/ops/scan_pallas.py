"""Pallas TPU kernel for the MVCC visibility scan.

Same math as ops.scan.visibility_mask, tiled explicitly for the TPU VPU:

- **chunk-major layout** ``int32[C, N]``: rows ride the 128-wide lane axis,
  key chunks ride sublanes, so per-row reductions (lex compare, equality)
  are cheap sublane reductions instead of cross-lane ones;
- **sign-flipped chunks**: packed big-endian u32 chunks XOR 0x8000_0000 make
  signed int32 order equal unsigned byte order — Mosaic-native compares;
- **31-bit revision split** (hi = rev >> 31, lo = rev & 0x7fff_ffff): both
  halves non-negative int32, so revision compares stay signed-safe;
- **reverse-tile grid + carry**: "is this row superseded?" looks at the NEXT
  row, so tiles run last→first and a VMEM/SMEM scratch carries the next
  tile's first key/candidate across grid steps (TPU grid iterations are
  sequential, so the carry is well-defined — the Pallas analogue of the scan
  worker's prev-key carry, scanner.go:408-414);
- the lex compare avoids argmax/gather: first-differing-chunk selection via
  an exclusive cumsum over the not-equal mask.

Falls back to interpret mode off-TPU (tests run it on CPU against the jnp
kernel as oracle).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANE_TILE = 1024  # rows per grid step


def flip_sign(chunks: np.ndarray) -> np.ndarray:
    """uint32 chunks -> order-preserving int32 (big-endian unsigned order)."""
    return (chunks.astype(np.uint32) ^ np.uint32(0x80000000)).view(np.int32)


def split_revs31(revs: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """uint64 -> (hi, lo) non-negative int32 halves (31-bit low split)."""
    revs = np.asarray(revs, dtype=np.uint64)
    hi = (revs >> np.uint64(31)).astype(np.int64)
    if (hi >= 2**31).any():
        raise ValueError("revision exceeds 2^62")
    return hi.astype(np.int32), (revs & np.uint64(0x7FFFFFFF)).astype(np.int32)


def _lex_less(keys, bound, neq, lt):
    """columns of keys < bound, via exclusive-cumsum first-diff selection.

    keys/neq/lt: [C, T]; bound: [C, 1]. Returns [1, T] bool.
    """
    del keys, bound
    before = jnp.cumsum(neq.astype(jnp.int32), axis=0) - neq.astype(jnp.int32)
    first_diff = neq & (before == 0)
    return jnp.any(first_diff & lt, axis=0, keepdims=True)


def _kernel(scal_ref, start_ref, end_ref,
            keys_ref, rh_ref, rl_ref, tomb_ref,
            mask_ref,
            carry_key, carry_flag):
    i = pl.program_id(0)
    nt = pl.num_programs(0)
    t = nt - 1 - i  # reversed tile order

    n_valid = scal_ref[0]
    unbounded = scal_ref[1]
    qhi = scal_ref[2]
    qlo = scal_ref[3]

    keys = keys_ref[:, :]          # [C, T] int32 (sign-flipped chunks)
    rh = rh_ref[:, :]              # [1, T]
    rl = rl_ref[:, :]
    tomb = tomb_ref[:, :] != 0     # [1, T]
    c, tile = keys.shape

    start = start_ref[:, :]        # [C, 1]
    end = end_ref[:, :]

    neq_s = keys != start
    lt_s = keys < start
    less_start = _lex_less(keys, start, neq_s, lt_s)
    neq_e = keys != end
    lt_e = keys < end
    less_end = _lex_less(keys, end, neq_e, lt_e)
    in_range = (~less_start) & ((unbounded != 0) | less_end)

    rev_le = (rh < qhi) | ((rh == qhi) & (rl <= qlo))

    lane = jax.lax.broadcasted_iota(jnp.int32, (1, tile), 1)
    idx = t * tile + lane
    valid = idx < n_valid

    cand = valid & in_range & rev_le & True

    # same-key-as-next within the tile; the last column compares against the
    # carried first key of the NEXT tile (processed in the previous step)
    nxt_keys = jnp.roll(keys, -1, axis=1)
    carried = carry_key[:, :]  # [C, 1]
    is_last_col = lane == (tile - 1)
    nxt_keys = jnp.where(is_last_col, carried, nxt_keys)
    same_next = jnp.all(keys == nxt_keys, axis=0, keepdims=True)
    have_next = (t + 1) * tile < n_valid
    same_next = same_next & (~is_last_col | have_next)

    cand_next = jnp.roll(cand, -1, axis=1)
    carried_cand = carry_flag[0] != 0
    cand_next = jnp.where(is_last_col, carried_cand & have_next, cand_next)

    visible = cand & ~(same_next & cand_next) & ~tomb
    mask_ref[:, :] = visible.astype(jnp.int8)

    # publish this tile's first column for the next grid step (tile t-1)
    carry_key[:, :] = keys[:, 0:1]
    carry_flag[0] = cand[0, 0].astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("interpret",))
def scan_mask_pallas(keys_t, rh31, rl31, tomb, n_valid, start, end, unbounded,
                     qhi31, qlo31, interpret=False):
    """Visibility mask via the Pallas kernel.

    keys_t: int32[C, N] chunk-major sign-flipped; rh31/rl31: int32[N];
    tomb: int8[N]; start/end: int32[C] sign-flipped bounds;
    scalars: n_valid, unbounded, qhi31, qlo31.
    Returns bool[N].
    """
    c, n = keys_t.shape
    assert n % LANE_TILE == 0, "pad rows to LANE_TILE"
    nt = n // LANE_TILE
    scal = jnp.stack([
        jnp.asarray(n_valid, jnp.int32),
        jnp.asarray(unbounded, jnp.int32),
        jnp.asarray(qhi31, jnp.int32),
        jnp.asarray(qlo31, jnp.int32),
    ])
    rev_map = lambda i: (0, nt - 1 - i)
    mask = pl.pallas_call(
        _kernel,
        grid=(nt,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),          # scalars
            pl.BlockSpec((c, 1), lambda i: (0, 0)),          # start bound
            pl.BlockSpec((c, 1), lambda i: (0, 0)),          # end bound
            pl.BlockSpec((c, LANE_TILE), rev_map),           # keys
            pl.BlockSpec((1, LANE_TILE), rev_map),           # rev hi
            pl.BlockSpec((1, LANE_TILE), rev_map),           # rev lo
            pl.BlockSpec((1, LANE_TILE), rev_map),           # tombstones
        ],
        out_specs=pl.BlockSpec((1, LANE_TILE), rev_map),
        out_shape=jax.ShapeDtypeStruct((1, n), jnp.int8),
        scratch_shapes=[
            pltpu.VMEM((c, 1), jnp.int32),                   # carried first key
            pltpu.SMEM((1,), jnp.int32),                     # carried first cand
        ],
        interpret=interpret,
    )(
        scal,
        start.reshape(c, 1), end.reshape(c, 1),
        keys_t, rh31.reshape(1, n), rl31.reshape(1, n), tomb.reshape(1, n),
    )
    return mask.reshape(n) != 0


def prepare_blocks(chunks: np.ndarray, revs: np.ndarray, tomb: np.ndarray,
                   tile: int = LANE_TILE):
    """Row-major uint32 blocks -> pallas layout (padded, chunk-major)."""
    n, c = chunks.shape
    pad = (-n) % tile
    if pad:
        chunks = np.pad(chunks, ((0, pad), (0, 0)))
        revs = np.pad(revs, (0, pad))
        tomb = np.pad(tomb, (0, pad))
    keys_t = np.ascontiguousarray(flip_sign(chunks).T)
    rh31, rl31 = split_revs31(revs)
    return keys_t, rh31, rl31, tomb.astype(np.int8), n


def pack_bound_flipped(bound_chunks: np.ndarray) -> np.ndarray:
    return flip_sign(bound_chunks.reshape(1, -1)).reshape(-1)
