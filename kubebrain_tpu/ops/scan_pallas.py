"""Pallas TPU kernel for the MVCC visibility scan.

Same math as ops.scan.visibility_mask, tiled explicitly for the TPU VPU:

- **chunk-major layout** ``int32[C, N]``: rows ride the 128-wide lane axis,
  key chunks ride sublanes, so per-row reductions (lex compare, equality)
  are cheap sublane reductions instead of cross-lane ones;
- **sign-flipped chunks**: packed big-endian u32 chunks XOR 0x8000_0000 make
  signed int32 order equal unsigned byte order — Mosaic-native compares;
- **31-bit revision split** (hi = rev >> 31, lo = rev & 0x7fff_ffff): both
  halves non-negative int32, so revision compares stay signed-safe;
- **reverse-tile grid + carry**: "is this row superseded?" looks at the NEXT
  row, so tiles run last→first and a VMEM/SMEM scratch carries the next
  tile's first key/candidate across grid steps (TPU grid iterations are
  sequential, so the carry is well-defined — the Pallas analogue of the scan
  worker's prev-key carry, scanner.go:408-414);
- the lex compare avoids argmax/gather/cumsum (none lower through Mosaic):
  first-differing-chunk selection via an unrolled prefix-AND over the
  static chunk axis.

Falls back to interpret mode off-TPU (tests run it on CPU against the jnp
kernel as oracle).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

import os as _os

# Rows per grid step. Grid iteration overhead dominates at small tiles (a
# 20M-row scan is ~20k steps at 1024) and VMEM per step is only ~66B * TILE.
# Real-chip sweep (tools/tile_sweep.py, v5e, 20M rows, 2026-07-29):
#   512: 90.3ms  1024: 87.8ms  2048: 84.4ms  4096: 82.6ms  8192: 83.1ms
#   16384: 84.5ms (p50; best-case runs hit 43ms — per-dispatch tunnel RTT
# dominates the residual). 4096 is the measured optimum and the default.
LANE_TILE = int(_os.environ.get("KB_PALLAS_TILE", "4096"))
if LANE_TILE <= 0 or LANE_TILE % 128:
    raise ValueError(
        f"KB_PALLAS_TILE={LANE_TILE} must be a positive multiple of 128 lanes")


def flip_sign(chunks: np.ndarray) -> np.ndarray:
    """uint32 chunks -> order-preserving int32 (big-endian unsigned order)."""
    return (chunks.astype(np.uint32) ^ np.uint32(0x80000000)).view(np.int32)


def split_revs31(revs: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """uint64 -> (hi, lo) non-negative int32 halves (31-bit low split)."""
    revs = np.asarray(revs, dtype=np.uint64)
    hi = (revs >> np.uint64(31)).astype(np.int64)
    if (hi >= 2**31).any():
        raise ValueError("revision exceeds 2^62")
    return hi.astype(np.int32), (revs & np.uint64(0x7FFFFFFF)).astype(np.int32)


def _lex_less(keys, bound, neq, lt):
    """columns of keys < bound: first-differing-chunk decides.

    keys/neq/lt: [C, T]; bound: [C, 1]. Returns [1, T] bool.

    Unrolled prefix-AND over the (static, small) chunk axis — Mosaic has no
    cumsum lowering, and C is 16 for 64-byte keys, so a trace-time loop of
    plain VPU mask ops is both lowerable and cheap.
    """
    del keys, bound
    c = neq.shape[0]
    out = lt[0:1, :]
    prefix_eq = ~neq[0:1, :]
    for ci in range(1, c):
        out = out | (prefix_eq & lt[ci : ci + 1, :])
        prefix_eq = prefix_eq & ~neq[ci : ci + 1, :]
    return out


def _tile_visibility(t, n_valid, unbounded, qhi, qlo, start, end,
                     keys_ref, rh_ref, rl_ref, tomb_ref,
                     carry_key, carry_flag):
    """One reverse-order tile of the visibility scan: the shared body of the
    single-query and query-batched kernels (so adding the query grid axis
    cannot drift from the proven single-query math). Returns the int8
    visibility block and updates the carry scratch for tile ``t - 1``."""
    keys = keys_ref[:, :]          # [C, T] int32 (sign-flipped chunks)
    rh = rh_ref[:, :]              # [1, T]
    rl = rl_ref[:, :]
    tomb = tomb_ref[:, :] != 0     # [1, T]
    c, tile = keys.shape

    neq_s = keys != start
    lt_s = keys < start
    less_start = _lex_less(keys, start, neq_s, lt_s)
    neq_e = keys != end
    lt_e = keys < end
    less_end = _lex_less(keys, end, neq_e, lt_e)
    in_range = (~less_start) & ((unbounded != 0) | less_end)

    rev_le = (rh < qhi) | ((rh == qhi) & (rl <= qlo))

    lane = jax.lax.broadcasted_iota(jnp.int32, (1, tile), 1)
    idx = t * tile + lane
    valid = idx < n_valid

    cand = valid & in_range & rev_le

    # same-key-as-next within the tile; the last column compares against the
    # carried first key of the NEXT tile (processed in the previous step)
    nxt_keys = jnp.roll(keys, -1, axis=1)
    carried = carry_key[:, :]  # [C, 1]
    is_last_col = lane == (tile - 1)
    nxt_keys = jnp.where(is_last_col, carried, nxt_keys)
    same_next = jnp.all(keys == nxt_keys, axis=0, keepdims=True)
    # scalar bools broadcast into vector selects lower as i8->i1 truncations
    # Mosaic rejects; keep the carried flags in int32 until the final compare
    have_i = ((t + 1) * tile < n_valid).astype(jnp.int32)
    same_next = same_next & (jnp.where(is_last_col, have_i, 1) != 0)

    cand_next_i = jnp.roll(cand.astype(jnp.int32), -1, axis=1)
    cand_next = jnp.where(is_last_col, carry_flag[0] * have_i, cand_next_i) != 0

    visible = cand & ~(same_next & cand_next) & ~tomb

    # publish this tile's first column for the next grid step (tile t-1)
    carry_key[:, :] = keys[:, 0:1]
    carry_flag[0] = cand.astype(jnp.int32)[0, 0]
    return visible.astype(jnp.int8)


def _kernel(scal_ref, start_ref, end_ref,
            keys_ref, rh_ref, rl_ref, tomb_ref,
            mask_ref,
            carry_key, carry_flag):
    i = pl.program_id(0)
    nt = pl.num_programs(0)
    t = nt - 1 - i  # reversed tile order

    mask_ref[:, :] = _tile_visibility(
        t, scal_ref[0], scal_ref[1], scal_ref[2], scal_ref[3],
        start_ref[:, :], end_ref[:, :],
        keys_ref, rh_ref, rl_ref, tomb_ref,
        carry_key, carry_flag,
    )


def _kernel_q(scal_ref, qscal_ref, start_ref, end_ref,
              keys_ref, rh_ref, rl_ref, tomb_ref,
              mask_ref,
              carry_key, carry_flag):
    """Query-batched variant: grid = (queries, reverse tiles). TPU grid
    steps run sequentially with the LAST axis minor, so for each query q
    the tile sweep i = 0..nt-1 is contiguous and the carry discipline of
    the single-query kernel holds unchanged. No cross-query carry reset is
    needed: tile nt-1 (the first step of every query) masks the carried
    flag/key out via ``have_i`` exactly as the single-query kernel does on
    its own first step."""
    q = pl.program_id(0)
    i = pl.program_id(1)
    nt = pl.num_programs(1)
    t = nt - 1 - i  # reversed tile order within the query

    mask_ref[0] = _tile_visibility(
        t, scal_ref[0], qscal_ref[q, 0], qscal_ref[q, 1], qscal_ref[q, 2],
        start_ref[0], end_ref[0],
        keys_ref, rh_ref, rl_ref, tomb_ref,
        carry_key, carry_flag,
    )


@functools.partial(jax.jit, static_argnames=("interpret",))
def scan_mask_pallas(keys_t, rh31, rl31, tomb, n_valid, start, end, unbounded,
                     qhi31, qlo31, interpret=False):
    """Visibility mask via the Pallas kernel.

    keys_t: int32[C, N] chunk-major sign-flipped; rh31/rl31: int32[N];
    tomb: int8[N]; start/end: int32[C] sign-flipped bounds;
    scalars: n_valid, unbounded, qhi31, qlo31.
    Returns bool[N].
    """
    c, n = keys_t.shape
    assert n % LANE_TILE == 0, "pad rows to LANE_TILE"
    nt = n // LANE_TILE
    scal = jnp.stack([
        jnp.asarray(n_valid, jnp.int32),
        jnp.asarray(unbounded, jnp.int32),
        jnp.asarray(qhi31, jnp.int32),
        jnp.asarray(qlo31, jnp.int32),
    ])
    rev_map = lambda i: (0, nt - 1 - i)
    mask = pl.pallas_call(
        _kernel,
        grid=(nt,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),          # scalars
            pl.BlockSpec((c, 1), lambda i: (0, 0)),          # start bound
            pl.BlockSpec((c, 1), lambda i: (0, 0)),          # end bound
            pl.BlockSpec((c, LANE_TILE), rev_map),           # keys
            pl.BlockSpec((1, LANE_TILE), rev_map),           # rev hi
            pl.BlockSpec((1, LANE_TILE), rev_map),           # rev lo
            pl.BlockSpec((1, LANE_TILE), rev_map),           # tombstones
        ],
        out_specs=pl.BlockSpec((1, LANE_TILE), rev_map),
        out_shape=jax.ShapeDtypeStruct((1, n), jnp.int8),
        scratch_shapes=[
            pltpu.VMEM((c, 1), jnp.int32),                   # carried first key
            pltpu.SMEM((1,), jnp.int32),                     # carried first cand
        ],
        interpret=interpret,
    )(
        scal,
        start.reshape(c, 1), end.reshape(c, 1),
        keys_t, rh31.reshape(1, n), rl31.reshape(1, n), tomb.reshape(1, n),
    )
    return mask.reshape(n) != 0


@functools.partial(jax.jit, static_argnames=("interpret",))
def scan_mask_pallas_q(keys_t, rh31, rl31, tomb, n_valid, starts, ends,
                       unbounded, qhi31, qlo31, interpret=False):
    """Query-batched visibility masks: ONE kernel launch answers Q distinct
    Range/Count queries over the same block (grid = queries × reverse
    tiles) — the dispatch-bound regime's lever (BENCH_r05: pipelined
    dispatch of the same kernel is 3.8× its single-dispatch p50, so a
    kernel launch amortized over Q queries beats Q launches).

    keys_t: int32[C, N] chunk-major sign-flipped; rh31/rl31: int32[N];
    tomb: int8[N]; starts/ends: int32[Q, C] sign-flipped bounds;
    unbounded/qhi31/qlo31: int32[Q] per-query scalars; n_valid scalar.
    Returns bool[Q, N]. Q=1 is bit-identical to :func:`scan_mask_pallas`:
    both kernels run the same ``_tile_visibility`` body, the batched grid
    only adds a sequential query axis.
    """
    c, n = keys_t.shape
    assert n % LANE_TILE == 0, "pad rows to LANE_TILE"
    nq = starts.shape[0]
    nt = n // LANE_TILE
    scal = jnp.asarray(n_valid, jnp.int32).reshape(1)
    qscal = jnp.stack([
        jnp.asarray(unbounded, jnp.int32).reshape(nq),
        jnp.asarray(qhi31, jnp.int32).reshape(nq),
        jnp.asarray(qlo31, jnp.int32).reshape(nq),
    ], axis=1)  # [Q, 3] per-query scalars, dynamically indexed from SMEM
    rev_map = lambda q, i: (0, nt - 1 - i)
    mask = pl.pallas_call(
        _kernel_q,
        grid=(nq, nt),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),            # n_valid
            pl.BlockSpec(memory_space=pltpu.SMEM),            # per-query scalars
            pl.BlockSpec((1, c, 1), lambda q, i: (q, 0, 0)),   # start bounds
            pl.BlockSpec((1, c, 1), lambda q, i: (q, 0, 0)),   # end bounds
            pl.BlockSpec((c, LANE_TILE), rev_map),             # keys
            pl.BlockSpec((1, LANE_TILE), rev_map),             # rev hi
            pl.BlockSpec((1, LANE_TILE), rev_map),             # rev lo
            pl.BlockSpec((1, LANE_TILE), rev_map),             # tombstones
        ],
        out_specs=pl.BlockSpec((1, 1, LANE_TILE),
                               lambda q, i: (q, 0, nt - 1 - i)),
        out_shape=jax.ShapeDtypeStruct((nq, 1, n), jnp.int8),
        scratch_shapes=[
            pltpu.VMEM((c, 1), jnp.int32),                     # carried first key
            pltpu.SMEM((1,), jnp.int32),                       # carried first cand
        ],
        interpret=interpret,
    )(
        scal, qscal,
        starts.reshape(nq, c, 1), ends.reshape(nq, c, 1),
        keys_t, rh31.reshape(1, n), rl31.reshape(1, n), tomb.reshape(1, n),
    )
    return mask.reshape(nq, n) != 0


def _flip_sign_jnp(x: jnp.ndarray) -> jnp.ndarray:
    """In-graph equivalent of :func:`flip_sign` (uint32 -> int32 bitcast)."""
    return jax.lax.bitcast_convert_type(x ^ jnp.uint32(0x80000000), jnp.int32)


def _split31_jnp(hi32: jnp.ndarray, lo32: jnp.ndarray):
    """(hi, lo) 32-bit uint32 split -> (hi, lo) 31-bit int32 split in-graph.

    Safe for revisions < 2^62 (hi < 2^30, so hi<<1|lo>>31 < 2^31)."""
    rh31 = jax.lax.bitcast_convert_type(
        (hi32 << jnp.uint32(1)) | (lo32 >> jnp.uint32(31)), jnp.int32
    )
    rl31 = jax.lax.bitcast_convert_type(lo32 & jnp.uint32(0x7FFFFFFF), jnp.int32)
    return rh31, rl31


@functools.partial(jax.jit, static_argnames=("interpret",))
def visibility_mask_batch(keys, rh, rl, tomb, n_valid, start, end, unbounded,
                          read_hi, read_lo, interpret=False):
    """Pallas visibility masks straight off the row-major mirror layout,
    converting in-graph on every call — the UNCACHED variant, kept as the
    kernel-level differential-test entry point. Production (`TpuScanner`
    under --use-pallas) uses `prepare_mirror` + `visibility_mask_batch_cached`
    so the layout conversion happens once per mirror publish, not per query.

    Same contract as ``vmap(ops.scan.visibility_mask)``:
    keys uint32[P, N, C] big-endian chunks, rh/rl uint32[P, N] (32-bit rev
    split), tomb bool[P, N], n_valid int32[P], start/end uint32[C] packed
    bounds, unbounded bool, read_hi/read_lo uint32. Returns bool[P, N].

    Layout conversion (transpose to chunk-major, sign flip, 31-bit rev
    resplit, LANE_TILE padding) happens in-graph: XLA fuses it into the
    surrounding program and the kernel sees its native tiling.
    """
    p, n, c = keys.shape
    if n == 0:
        return jnp.zeros((p, 0), dtype=bool)
    pad = (-n) % LANE_TILE
    if pad:
        keys = jnp.pad(keys, ((0, 0), (0, pad), (0, 0)))
        rh = jnp.pad(rh, ((0, 0), (0, pad)))
        rl = jnp.pad(rl, ((0, 0), (0, pad)))
        tomb = jnp.pad(tomb, ((0, 0), (0, pad)))
    keys_t = _flip_sign_jnp(jnp.swapaxes(keys, 1, 2))  # [P, C, Npad]
    rh31, rl31 = _split31_jnp(jnp.asarray(rh, jnp.uint32), jnp.asarray(rl, jnp.uint32))
    qhi31, qlo31 = _split31_jnp(
        jnp.asarray(read_hi, jnp.uint32), jnp.asarray(read_lo, jnp.uint32)
    )
    s = _flip_sign_jnp(jnp.asarray(start, jnp.uint32))
    e = _flip_sign_jnp(jnp.asarray(end, jnp.uint32))
    unb = jnp.asarray(unbounded, jnp.int32)
    f = lambda kt, h, l, t, nv: scan_mask_pallas(
        kt, h, l, t, nv, s, e, unb, qhi31, qlo31, interpret=interpret
    )
    mask = jax.vmap(f)(keys_t, rh31, rl31, tomb.astype(jnp.int8), n_valid)
    return mask[:, :n]


def prepare_mirror(keys_host: np.ndarray, revs_host: np.ndarray,
                   tomb_host: np.ndarray, tile: int = LANE_TILE):
    """Row-major mirror arrays → Pallas layout, computed ONCE per mirror
    publish (numpy, host-side): chunk-major sign-flipped keys, 31-bit rev
    split, int8 tombstones, rows padded to ``tile``.

    keys_host uint32[P, N, C], revs_host uint64[P, N], tomb_host bool[P, N].
    Returns (keys_t int32[P, C, Npad], rh31 int32[P, Npad],
    rl31 int32[P, Npad], tomb8 int8[P, Npad], n).

    The per-query path (`visibility_mask_batch_cached`) then only converts
    the bounds and read revision — O(C) per scan instead of O(P·N·C).
    """
    p, n, c = keys_host.shape
    pad = (-n) % tile
    if pad:
        keys_host = np.pad(keys_host, ((0, 0), (0, pad), (0, 0)))
        revs_host = np.pad(revs_host, ((0, 0), (0, pad)))
        tomb_host = np.pad(tomb_host, ((0, 0), (0, pad)))
    keys_t = np.ascontiguousarray(np.transpose(flip_sign(keys_host), (0, 2, 1)))
    rh31, rl31 = split_revs31(np.asarray(revs_host, dtype=np.uint64).reshape(-1))
    npad = n + pad
    return (keys_t, rh31.reshape(p, npad), rl31.reshape(p, npad),
            tomb_host.astype(np.int8), n)


@functools.partial(jax.jit, static_argnames=("n", "interpret"))
def visibility_mask_batch_cached(keys_t, rh31, rl31, tomb8, nv, start, end,
                                 unbounded, read_hi, read_lo, n, interpret=False):
    """Per-query Pallas path over a `prepare_mirror`-cached layout. Only the
    bounds (uint32[C] packed) and read revision (uint32 split) are converted
    in-graph. Returns bool[P, n]."""
    qhi31, qlo31 = _split31_jnp(
        jnp.asarray(read_hi, jnp.uint32), jnp.asarray(read_lo, jnp.uint32)
    )
    s = _flip_sign_jnp(jnp.asarray(start, jnp.uint32))
    e = _flip_sign_jnp(jnp.asarray(end, jnp.uint32))
    unb = jnp.asarray(unbounded, jnp.int32)
    f = lambda kt, h, l, t, v: scan_mask_pallas(
        kt, h, l, t, v, s, e, unb, qhi31, qlo31, interpret=interpret
    )
    mask = jax.vmap(f)(keys_t, rh31, rl31, tomb8, nv)
    return mask[:, :n]


@functools.partial(jax.jit, static_argnames=("n", "interpret"))
def visibility_mask_batch_cached_q(keys_t, rh31, rl31, tomb8, nv, starts, ends,
                                   unbounded, read_hi, read_lo, n,
                                   interpret=False):
    """Query-batched Pallas path over a `prepare_mirror`-cached layout:
    Q distinct queries × P partitions resolved in ONE dispatch. Only the
    per-query bounds (uint32[Q, C] packed) and read revisions (uint32[Q]
    split) are converted in-graph. Returns bool[Q, P, n]."""
    qhi31, qlo31 = _split31_jnp(
        jnp.asarray(read_hi, jnp.uint32), jnp.asarray(read_lo, jnp.uint32)
    )
    s = _flip_sign_jnp(jnp.asarray(starts, jnp.uint32))
    e = _flip_sign_jnp(jnp.asarray(ends, jnp.uint32))
    unb = jnp.asarray(unbounded, jnp.int32)
    f = lambda kt, h, l, t, v: scan_mask_pallas_q(
        kt, h, l, t, v, s, e, unb, qhi31, qlo31, interpret=interpret
    )
    mask = jax.vmap(f, out_axes=1)(keys_t, rh31, rl31, tomb8, nv)  # [Q, P, Npad]
    return mask[:, :, :n]


def prepare_blocks(chunks: np.ndarray, revs: np.ndarray, tomb: np.ndarray,
                   tile: int = LANE_TILE):
    """Row-major uint32 blocks -> pallas layout (padded, chunk-major)."""
    n, c = chunks.shape
    pad = (-n) % tile
    if pad:
        chunks = np.pad(chunks, ((0, pad), (0, 0)))
        revs = np.pad(revs, (0, pad))
        tomb = np.pad(tomb, (0, pad))
    keys_t = np.ascontiguousarray(flip_sign(chunks).T)
    rh31, rl31 = split_revs31(revs)
    return keys_t, rh31, rl31, tomb.astype(np.int8), n


def pack_bound_flipped(bound_chunks: np.ndarray) -> np.ndarray:
    return flip_sign(bound_chunks.reshape(1, -1)).reshape(-1)
