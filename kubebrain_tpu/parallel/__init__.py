"""Device-mesh parallelism for the MVCC data plane.

The reference scales scans/compaction by running one Go worker per storage
partition (scanner.go:264-288) and fans watch events out over subscriber
channels (watcherhub.go:78). The TPU equivalents (SURVEY §2.9):

- P1/P2: partitions = a mesh axis; each device owns the sorted block(s) of
  its key-range shard; scan/compact kernels run under shard_map with no
  cross-device traffic except the final count psum / result gather — blocks
  are split at user-key boundaries so shards are fully independent.
- P4: watch fan-out shards the *watcher table* over the mesh; events are
  replicated (small) and the (E × W) mask is computed shard-local, then
  gathered.
- Cross-host control plane (revision sync, election) stays on gRPC/DCN —
  see kubebrain_tpu/server/service.
"""

from .mesh import make_mesh, partition_spec

__all__ = ["make_mesh", "partition_spec"]
