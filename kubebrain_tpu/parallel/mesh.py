"""Mesh construction + sharding helpers.

One logical axis ``part`` shards the key space (storage partitions); an
optional second axis ``rep`` replicates for read scaling / shards the watcher
table — mirroring the reference's reader-replica parallelism (SURVEY P6).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec


def make_mesh(
    n_devices: int | None = None, axes: tuple[str, ...] = ("part",), shape: tuple[int, ...] | None = None
) -> Mesh:
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    if shape is None:
        shape = (len(devices),) + (1,) * (len(axes) - 1)
    dev_array = np.array(devices).reshape(shape)
    return Mesh(dev_array, axes)


def partition_spec(mesh: Mesh, *axis_names: str | None) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec(*axis_names))


def shard_rows(mesh: Mesh, arr, axis: str = "part") -> jax.Array:
    """Put an array on the mesh sharded along its leading axis."""
    spec = PartitionSpec(axis, *(None,) * (arr.ndim - 1))
    return jax.device_put(arr, NamedSharding(mesh, spec))


def replicate(mesh: Mesh, arr) -> jax.Array:
    return jax.device_put(arr, NamedSharding(mesh, PartitionSpec()))
