"""Multi-host deployment: ICI/DCN split and process-group initialization.

The scale model (SURVEY §2.10): inside one slice, the data plane moves over
**ICI** — scan masks, counts (psum), fan-out masks all run under shard_map
on the global mesh, with XLA inserting the collectives. Across hosts, the
**control plane** rides DCN exactly like the reference's gRPC/HTTP plumbing:
leader election through the storage layer, follower revision sync over
HTTP /status, write/watch forwarding over gRPC. Storage partitions map onto
the mesh's ``part`` axis so data placement follows key-space sharding on
every host.

``init_multihost`` wraps jax.distributed initialization; on a pod slice each
host then sees the global device set and builds the same Mesh from
``jax.devices()`` — the kernels in kubebrain_tpu.ops need no changes (they
are written against a mesh, not a device count). Single-host development and
the CI virtual CPU mesh go through the same code path with n_processes=1.
"""

from __future__ import annotations

import jax

from .mesh import make_mesh


def init_multihost(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Join the jax distributed process group (no-op for single-process).

    On TPU pods the three arguments are inferred from the environment;
    elsewhere pass them explicitly (coordinator host:port, world size, rank).
    """
    if num_processes is not None and num_processes <= 1:
        return
    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    jax.distributed.initialize(**kwargs)


def global_data_plane_mesh(wat_axis: int = 1):
    """The full-slice mesh: ``part`` shards the key space across every chip
    on every host (collectives ride ICI within the slice), ``wat`` shards
    the watcher table / replicates blocks for read scaling."""
    n = len(jax.devices())
    assert n % wat_axis == 0, "wat axis must divide the device count"
    return make_mesh(axes=("part", "wat"), shape=(n // wat_axis, wat_axis))
