"""The full data-plane step, sharded over a 2D device mesh.

One compiled step = everything the TPU does for the MVCC store per tick:

- partition-sharded range scan (visibility masks + global count via psum
  over ``part``) — SURVEY P1;
- partition-sharded compaction victim marking — SURVEY P2;
- watcher-sharded watch fan-out mask (events replicated, watcher table
  sharded over ``wat``) — SURVEY P4.

Mesh axes: ``part`` shards the key space (storage partitions), ``wat``
shards the watcher table / replicates block data — the reader-replica axis
(SURVEY P6). Collectives: psum over ``part`` for the scan count; the fan-out
mask stays sharded (each wat-shard serves its own watcher subset).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..ops.compact import victim_mask
from ..ops.fanout import fanout_mask_range
from ..ops.scan import visibility_mask


def make_data_plane_step(mesh):
    """Returns a jitted step(fn) over ``mesh`` (axes ``part``, ``wat``)."""

    block = P("part", None, None)
    row = P("part", None)
    rep = P()

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(
            block, row, row, row, row, P("part"),          # blocks
            rep, rep, rep, rep, rep,                       # scan query
            rep, rep, rep, rep,                            # compact query
            P("wat", None), P("wat", None), P("wat"), P("wat"), P("wat"),  # watcher table
            rep, rep, rep,                                 # event batch
        ),
        out_specs=(row, rep, row, P(None, "wat")),
    )
    def step(
        keys, rh, rl, tomb, ttl, nv,
        start, end, unb, qhi, qlo,
        chi, clo, thi, tlo,
        ws, we, wu, whi, wlo,
        ek, ehi, elo,
    ):
        vis = jax.vmap(
            lambda k, a, b, t, n: visibility_mask(k, a, b, t, n, start, end, unb, qhi, qlo)
        )(keys, rh, rl, tomb, nv)
        local = jnp.sum(vis, dtype=jnp.int32)
        total = jax.lax.psum(local, "part")
        victims = jax.vmap(
            lambda k, a, b, t, x, n: victim_mask(k, a, b, t, x, n, chi, clo, thi, tlo)
        )(keys, rh, rl, tomb, ttl, nv)
        fmask = fanout_mask_range(ek, ehi, elo, ws, we, wu, whi, wlo)
        return vis, total, victims, fmask

    return jax.jit(step)


def make_example_args(mesh, n_parts=None, rows=64, chunks=16, watchers=8, events=8, seed=0):
    """Tiny, correctly-sharded example inputs for the step (dry-run/compile
    checks). Returns a tuple matching make_data_plane_step's signature."""
    import numpy as np

    from ..ops import keys as keyops

    part = mesh.shape["part"]
    wat = mesh.shape["wat"]
    n_parts = n_parts or part
    assert n_parts % part == 0 and watchers % wat == 0
    rng = np.random.RandomState(seed)

    width = chunks * 4
    all_keys, all_revs, all_tomb, all_ttl, nv = [], [], [], [], []
    rev = 0
    for p in range(n_parts):
        ks, rs = [], []
        for i in range(rows // 2):
            k = b"/registry/pods/p%02d-%04d" % (p, i)
            for _ in range(2):
                rev += 1
                ks.append(k)
                rs.append(rev)
        packed, _ = keyops.pack_keys(ks, width)
        pad = rows - len(ks)
        all_keys.append(np.pad(packed, ((0, pad), (0, 0))))
        all_revs.append(np.pad(np.array(rs, dtype=np.uint64), (0, pad)))
        all_tomb.append(rng.rand(rows) < 0.1)
        all_ttl.append(np.zeros(rows, dtype=bool))
        nv.append(len(ks))

    keys = np.stack(all_keys)
    revs = np.stack(all_revs)
    rh, rl = keyops.split_revs(revs.reshape(-1))
    rh, rl = rh.reshape(n_parts, rows), rl.reshape(n_parts, rows)
    tomb = np.stack(all_tomb)
    ttl = np.stack(all_ttl)
    nvv = np.array(nv, dtype=np.int32)

    def q(rev):
        hi, lo = keyops.split_revs(np.array([rev], dtype=np.uint64))
        return np.uint32(hi[0]), np.uint32(lo[0])

    start = keyops.pack_one(b"/registry/", width)
    end = keyops.pack_one(b"/registry0", width)
    qhi, qlo = q(rev)
    chi, clo = q(max(rev // 2, 1))
    thi, tlo = q(0)

    prefixes = [b"/registry/pods/p%02d" % (i % n_parts) for i in range(watchers)]
    from .. import coder

    ws, _ = keyops.pack_keys(prefixes, width)
    we, _ = keyops.pack_keys([coder.prefix_end(p) for p in prefixes], width)
    wu = np.zeros(watchers, dtype=bool)
    whi, wlo = keyops.split_revs(np.zeros(watchers, dtype=np.uint64))

    ev_keys = [b"/registry/pods/p%02d-%04d" % (i % n_parts, i) for i in range(events)]
    ek, _ = keyops.pack_keys(ev_keys, width)
    ehi, elo = keyops.split_revs(np.arange(1, events + 1, dtype=np.uint64))

    return (
        keys, rh, rl, tomb, ttl, nvv,
        start, end, np.False_, qhi, qlo,
        chi, clo, thi, tlo,
        ws, we, wu, whi, wlo,
        ek, ehi, elo,
    )
