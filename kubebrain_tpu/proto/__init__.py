"""Generated protobuf modules (protoc --python_out; service handlers are
hand-written in kubebrain_tpu.server since grpc_tools is not available).

protoc emits flat sibling imports (``import kv_pb2``), so this package dir
is put on sys.path before loading them.
"""

import os
import sys

_here = os.path.dirname(__file__)
if _here not in sys.path:
    sys.path.insert(0, _here)

import brain_pb2  # noqa: E402
import health_pb2  # noqa: E402
import kv_pb2  # noqa: E402
import rpc_pb2  # noqa: E402

__all__ = ["kv_pb2", "rpc_pb2", "brain_pb2", "health_pb2"]
