"""Read scale-out: stateless follower serving (docs/replication.md).

The first *horizontal* scaling axis (replicas x chips, vs the `part` mesh
axis's chips-per-replica). A follower process keeps its own storage stack
(including the TPU mirror when --storage=tpu) fed by a resumable
replication stream from the leader — the etcd Watch protocol over the
whole keyspace, ridden through the client's WatchMux resume machinery —
and serves reads locally under an explicit consistency contract:

- explicit-revision reads <= the applied watermark: served locally,
  byte-identical to the leader by construction (same MVCC rows, same
  scanner stack);
- bounded-staleness reads (``serializable=true``): served locally at the
  applied watermark while the replica's lag stays inside
  ``--max-staleness-rev`` / ``--max-staleness-ms``; past the bound the
  follower REFUSES (``etcdserver: replica too stale``) instead of
  answering stale — clients fail over;
- linearizable reads (rev-0, serializable=false): a TSO revision fence —
  fetch the leader's committed revision, wait until the local watermark
  reaches it, then serve locally;
- writes, lease RPCs, and Compact: forwarded to the leader with status
  passthrough (an ambiguous forward failure stays ambiguous).

Reference: the kubebrain service layer's follower role (PAPER.md §1:
follower→leader revision sync + etcd-proxy write forwarding), extended
with the explicit-revision snapshot serving that the MVCC multiversion
line of work (PAPERS.md) shows needs no coordination at all.
"""

from .apply import ReplicaApplier
from .role import (
    FenceTimeoutError,
    FollowerConfig,
    FollowerRole,
    FutureRevisionWaitError,
    LeaderUnreachableError,
    ReplicaRefusedError,
    StaleReplicaError,
)
from .stream import ReplicationStream

__all__ = [
    "FollowerConfig",
    "FollowerRole",
    "ReplicaApplier",
    "ReplicationStream",
    "ReplicaRefusedError",
    "StaleReplicaError",
    "FenceTimeoutError",
    "FutureRevisionWaitError",
    "LeaderUnreachableError",
]
