"""ReplicaApplier: replicated wire events → local MVCC rows + watch fan-out.

Apply path (docs/replication.md): one replicated event batch becomes ONE
tracked engine batch — revision record + object row per event, the
LAST_REV watermark row once — committed through the storage stack's
normal write surface. On the TPU engine that surface is the tracked batch
whose commit records the whole block's version rows into the scanner's
``_DeltaIndex`` in ONE call, in revision order: replicated blocks seal
into the delta exactly like local group commits do, and the entire
mirror/merge/compaction machinery (PRs 9-12) runs unchanged underneath.

Ordering contract: the replication stream delivers events strictly
revision-ascending (etcd watch semantics + WatchMux resume's no-loss/
no-dup guarantee), so the applier can (a) write rows unconditionally
(idempotent on the rare stream-replacement overlap), (b) hand the block
to ``Backend.ingest_replicated`` — watch cache + hub + the TSO committed
floor — and (c) advance the applied watermark to the batch header
revision. Progress notifications (no events) advance the watermark across
the leader's revision gaps (failed ops consume revisions but stream
nothing); the leader only emits them for fully-flushed floors, so the
advance can never skip an owed event.
"""

from __future__ import annotations

import threading
from typing import Any, Sequence

from .. import coder
from ..backend import creator
from ..backend.common import LAST_REV_KEY, TOMBSTONE, Verb, WatchEvent
from ..proto import kv_pb2

#: bootstrap rows per engine batch (bounds peak batch size while keeping
#: the delta-seal granularity coarse enough to merge efficiently)
BOOTSTRAP_CHUNK = 512


class ReplicaApplier:
    def __init__(self, backend, role=None):
        self.backend = backend
        self.store = backend.store
        self._role = role
        self._lock = threading.Lock()  # serializes applies across streams
        self.applied_events = 0
        self.applied_batches = 0

    # ------------------------------------------------------------ bootstrap
    def apply_bootstrap(self, kvs: "Sequence[Any]",
                        revision: int) -> None:
        """Seed a stateless follower from one leader list pinned at
        ``revision``: every (key, value, mod_revision) becomes its MVCC row
        pair, the compact floor moves to ``revision`` (history below the
        bootstrap is unservable — refused as compacted, the honest etcd
        answer), and the watermark opens at ``revision``."""
        with self._lock:
            for i in range(0, len(kvs), BOOTSTRAP_CHUNK):
                batch = self.store.begin_batch_write()
                for kv in kvs[i:i + BOOTSTRAP_CHUNK]:
                    self._put_rows(batch, kv.key, kv.mod_revision, kv.value,
                                   deleted=False)
                batch.commit()
            # the watermark row lands ONLY after every row chunk is
            # durable: on a persistent engine, a crash mid-bootstrap must
            # recover to revision 0 and re-bootstrap (idempotent), never
            # to a watermark claiming rows that were still in later chunks
            batch = self.store.begin_batch_write()
            batch.put(LAST_REV_KEY, coder.encode_rev_value(revision))
            batch.commit()
            self.backend.ingest_replicated([], revision)
            self.backend.set_compact_floor(revision)
        if self._role is not None:
            self._role.note_applied(revision, revision)

    # --------------------------------------------------------- wire events
    def apply_wire_events(self, events: "Sequence[Any]",
                          header_revision: int) -> None:
        """One replicated batch (possibly empty = progress notification)."""
        with self._lock:
            watermark = self.backend.tso.committed()
            fresh = [ev for ev in events if ev.kv.mod_revision > watermark]
            if fresh:
                batch = self.store.begin_batch_write()
                local: list[WatchEvent] = []
                for ev in fresh:
                    local.append(self._apply_one(batch, ev))
                batch.put(LAST_REV_KEY,
                          coder.encode_rev_value(local[-1].revision))
                batch.commit()
                self.applied_events += len(local)
                self.applied_batches += 1
                # cache + hub + committed floor, downstream of the leader's
                # sequencer (never the local ring/TSO deal path)
                self.backend.ingest_replicated(
                    local, max(header_revision, local[-1].revision))
            elif header_revision > watermark:
                # progress mark: the leader vouches everything <= header is
                # flushed to this stream — cross the revision gap
                self.backend.ingest_replicated([], header_revision)
        if self._role is not None:
            self._role.note_applied(
                self.backend.tso.committed(), header_revision)

    def _apply_one(self, batch: Any, ev: Any) -> WatchEvent:
        key = bytes(ev.kv.key)
        rev = int(ev.kv.mod_revision)
        if ev.type == kv_pb2.Event.DELETE:
            self._put_rows(batch, key, rev, TOMBSTONE, deleted=True)
            event = WatchEvent(revision=rev, verb=Verb.DELETE, key=key)
        else:
            value = bytes(ev.kv.value)
            self._put_rows(batch, key, rev, value, deleted=False)
            create_rev = int(ev.kv.create_revision)
            verb = Verb.CREATE if create_rev == rev else Verb.PUT
            event = WatchEvent(revision=rev, verb=verb, key=key, value=value,
                               prev_revision=0 if verb == Verb.CREATE
                               else create_rev)
        if ev.HasField("prev_kv"):
            event.prev_revision = int(ev.prev_kv.mod_revision)
            event.prev_value = bytes(ev.prev_kv.value)
        return event

    def _put_rows(self, batch: Any, key: bytes, rev: int, value: bytes,
                  deleted: bool) -> None:
        # same TTL policy as the leader's write path: replicated lease
        # expiry arrives as ordinary delete EVENTS (the reaper's revision-
        # stamped tombstones), while legacy key-pattern TTLs (/events/)
        # are engine-level on the leader with no delete event — applying
        # the same pattern keeps both sides expiring in step
        ttl = 0 if deleted else (creator.ttl_for_key(key) or 0)
        batch.put(coder.encode_revision_key(key),
                  coder.encode_rev_value(rev, deleted=deleted), ttl)
        batch.put(coder.encode_object_key(key, rev), value, ttl)
