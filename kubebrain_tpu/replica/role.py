"""FollowerRole: per-RPC routing policy + the revision fence.

One object answers every "may this follower serve this request, and how"
question (docs/replication.md):

- :meth:`gate_read` admits/blocks/refuses a Range/Count before it enters
  the scheduler lanes (local serving then rides the SAME lanes/batching
  as on the leader — the gate only decides consistency, never executes);
- :meth:`forward_txn` / :meth:`forward_unary` / :meth:`forward_keepalive`
  proxy the leader-only surfaces over a raw gRPC channel with status
  passthrough — an ambiguous forward outcome (DEADLINE/CANCELLED/bare
  UNAVAILABLE from the leader) reaches the client unchanged, so the
  safe-vs-ambiguous retry discipline (docs/faults.md) survives the hop;
- the role also implements the PeerService contract (``is_leader`` False,
  no-op ``sync_read_revision``) so every existing service keeps working
  unmodified: the brain front refuses writes, the watch service serves
  locally, the lease reaper never arms.

The fence (linearizable reads): fetch the leader's committed revision
(``/status`` over HTTP, singleflighted so a read burst costs one round
trip), wait until the local applied watermark reaches it (the TSO's
``wait_committed`` — the applier commits the watermark there), then serve
locally. A fence that cannot complete inside ``fence_timeout_s`` REFUSES
(``etcdserver: replica fence timeout``) — never a silently stale answer.
"""

from __future__ import annotations

import threading
import time
from collections import Counter
from dataclasses import dataclass
from typing import Any

import grpc

from ..proto import rpc_pb2
from ..server.service.revision import HttpRevisionSyncer

#: how long an explicit-revision read slightly ahead of the watermark may
#: wait for replication to catch up before refusing as a future revision
FUTURE_WAIT_CAP_S = 1.0


class ReplicaRefusedError(Exception):
    """A follower refusing to serve (never a wrong answer). ``reason`` is
    the kb_replica_refused_total label; transports map subclasses to the
    etcd statuses clients classify as safe-to-retry."""

    reason = "refused"


class StaleReplicaError(ReplicaRefusedError):
    """Bounded-staleness bound exceeded: refusal instead of a stale answer."""

    reason = "stale"


class FenceTimeoutError(ReplicaRefusedError):
    """The applied watermark did not reach the fence revision in time."""

    reason = "fence_timeout"


class LeaderUnreachableError(ReplicaRefusedError):
    """The leader could not be asked for the fence revision / a forward."""

    reason = "leader_unreachable"


class FutureRevisionWaitError(ReplicaRefusedError):
    """Explicit read revision still ahead of the watermark after waiting."""

    reason = "future_revision"


@dataclass(frozen=True)
class FollowerConfig:
    leader_address: str                 # leader client (gRPC) host:port
    leader_info: str                    # leader info/peer (HTTP) host:port
    max_staleness_rev: int = 0          # 0 = unbounded
    max_staleness_ms: float = 5000.0    # 0 = unbounded
    fence_timeout_s: float = 3.0
    progress_interval_s: float = 0.2    # replication progress-request cadence
    compact_sync_interval_s: float = 5.0
    #: gRPC channel credentials for the leader connection (forwarding +
    #: the replication stream) — a TLS-serving leader needs them; cli
    #: builds them from --ca-file (the /status fence fetch auto-probes
    #: http/https on its own)
    credentials: object = None


class FollowerRole:
    """The follower's routing/consistency brain. Also implements the
    PeerService surface so it can be passed wherever ``peers`` goes."""

    def __init__(self, backend: Any, config: FollowerConfig,
                 metrics: Any = None, fault_plane: Any = None,
                 identity: str = "follower") -> None:
        self.backend = backend
        self.config = config
        self.identity = identity
        self._metrics = metrics
        self._plane = fault_plane
        self._lock = threading.Lock()
        #: highest leader committed revision this follower has observed
        #: (events, progress notifications, fence fetches)
        self._leader_rev = 0
        #: monotonic instant the watermark last provably covered the then-
        #: known leader head — the zero point of the time-staleness bound
        self._fresh_t: float | None = None
        self.served: Counter = Counter()
        self.forwarded: Counter = Counter()
        self.refused: Counter = Counter()
        # leader-revision fetch: the raw /status transport comes from the
        # reference's revision syncer, but fences must NOT ride its plain
        # singleflight — joining an already-in-flight fetch could hand a
        # fence a revision sampled BEFORE the read began (a real-time
        # linearizability hole). _fresh_leader_revision below runs a
        # TICKETED singleflight instead: a fence only accepts a fetch
        # that STARTED after it arrived (etcd's ReadIndex batching
        # discipline). The WATERMARK stays owned by the replication
        # applier — an HTTP poll proves nothing about applied events.
        self._syncer = HttpRevisionSyncer(
            lambda: config.leader_info, self._note_leader_rev)
        self._fl_cv = threading.Condition()
        self._fl_done = 0        # completed fetch generations
        self._fl_inflight = False
        self._fl_result: tuple[int | None, str | None] = (None, None)
        self._channel: grpc.Channel | None = None
        self._stubs: dict[str, object] = {}
        self._stream = None  # ReplicationStream, attached by start()
        if metrics is not None:
            metrics.register_gauge_fn(
                "kb.replica.applied.revision",
                lambda: float(self.applied_revision()))
            metrics.register_gauge_fn(
                "kb.replica.lag.revisions",
                lambda: float(self.lag_revisions()))
            metrics.register_gauge_fn(
                "kb.replica.lag.seconds", lambda: self.lag_seconds())

    # ------------------------------------------------------------ watermark
    def applied_revision(self) -> int:
        """The applied watermark: every leader event with revision <= this
        has been applied to the local store (the applier commits it into
        the local TSO, so rev-0 local reads resolve here too)."""
        return self.backend.tso.committed()

    def lag_revisions(self) -> int:
        with self._lock:
            leader = self._leader_rev
        return max(0, leader - self.applied_revision())

    def lag_seconds(self) -> float:
        """Seconds since the watermark last provably covered the leader
        head. Infinity before the first sync (never served stale-blind)."""
        with self._lock:
            fresh = self._fresh_t
        if fresh is None:
            return float("inf")
        return time.monotonic() - fresh

    def _note_leader_rev(self, revision: int) -> None:
        with self._lock:
            if revision > self._leader_rev:
                self._leader_rev = revision

    def note_applied(self, watermark: int, leader_head: int) -> None:
        """Applier callback after a replicated block (or progress mark) is
        applied: ``watermark`` is the new applied revision, ``leader_head``
        the leader revision the stream vouched for at that instant."""
        now = time.monotonic()
        with self._lock:
            if leader_head > self._leader_rev:
                self._leader_rev = leader_head
            if watermark >= self._leader_rev:
                self._fresh_t = now

    # ----------------------------------------------------------- the fence
    def leader_revision(self, timeout: float | None = None) -> int:
        """The leader's committed revision, sampled by a fetch that
        STARTED after this call (ticketed singleflight): any fetch
        already in flight began before us, so its answer could predate a
        write this read must observe — concurrent fences share the NEXT
        fetch instead. Raises LeaderUnreachableError."""
        if self._plane is not None and self._plane.leader_unreachable():
            raise LeaderUnreachableError(
                "leader unreachable (fault injection)")
        wait_s = timeout if timeout is not None \
            else self.config.fence_timeout_s
        deadline = time.monotonic() + wait_s
        with self._fl_cv:
            # an in-flight fetch began before us: its answer is tainted
            # for a fence; the next generation is the first sound one.
            # A whole read burst shares that one next fetch (generation
            # singleflight) — at most two round trips ever queue.
            # Production is claimed INSIDE the wait loop (first waiter to
            # observe the slot free takes it), never pre-committed: a
            # pre-committed claimant that times out would leave a
            # generation nobody produces and wedge every later fence.
            need = self._fl_done + (2 if self._fl_inflight else 1)
            while True:
                if self._fl_done >= need:
                    rev, err = self._fl_result
                    if err is not None:
                        raise LeaderUnreachableError(err)
                    return int(rev or 0)
                if not self._fl_inflight and self._fl_done == need - 1:
                    self._fl_inflight = True
                    break  # we produce generation `need`
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not self._fl_cv.wait(remaining):
                    raise LeaderUnreachableError(
                        "leader /status fetch timed out")
        rev_val: int | None = None
        err_str: str | None = None
        try:
            rev_val = self._syncer._fetch()
        except Exception as e:
            err_str = str(e)
        with self._fl_cv:
            self._fl_inflight = False
            self._fl_done = need
            self._fl_result = (rev_val, err_str)
            self._fl_cv.notify_all()
        if err_str is not None:
            raise LeaderUnreachableError(err_str)
        self._note_leader_rev(int(rev_val or 0))
        return int(rev_val or 0)

    def fence(self) -> int:
        """Linearizable-read fence: leader committed revision R, then wait
        until the applied watermark reaches R. Returns R. The wait rides
        the TSO's committed condition — the applier's ``tso.commit`` is
        the wake-up."""
        t0 = time.monotonic()
        # ONE deadline for the whole fence (leader fetch + watermark
        # wait): --fence-timeout-ms bounds the read's total block time,
        # not each of its phases separately
        deadline = t0 + self.config.fence_timeout_s
        try:
            if self._plane is not None and self._plane.fence_timeout():
                # injected stale-follower: the fence must REFUSE, proving
                # the degradation is a refusal, not a stale answer
                raise FenceTimeoutError("fence timeout (fault injection)")
            target = self.leader_revision(
                timeout=max(0.001, deadline - time.monotonic()))
            if not self.backend.tso.wait_committed(
                    target, timeout=max(0.001, deadline - time.monotonic())):
                raise FenceTimeoutError(
                    f"applied {self.applied_revision()} never reached fence "
                    f"{target} within {self.config.fence_timeout_s}s")
            return target
        finally:
            if self._metrics is not None:
                self._metrics.emit_histogram(
                    "kb.fence.wait.seconds", time.monotonic() - t0)

    # ------------------------------------------------------------- serving
    def gate_read(self, revision: int, serializable: bool) -> None:
        """Admit a Range/Count for local serving (docs/replication.md):

        - explicit revision <= watermark: serve (below the local compact
          floor the backend's own CompactedError refusal applies);
        - explicit revision ahead of the watermark: bounded wait for
          replication, then refuse as a future revision;
        - rev-0 serializable: staleness gate — refuse past the bound;
        - rev-0 linearizable: the revision fence.

        Raises a ReplicaRefusedError subclass; on return the caller serves
        locally through the normal scheduler lanes.
        """
        if revision:
            if revision <= self.applied_revision():
                return
            wait = min(FUTURE_WAIT_CAP_S, self.config.fence_timeout_s)
            if self.backend.tso.wait_committed(revision, timeout=wait):
                return
            self._refuse(FutureRevisionWaitError(
                f"revision {revision} ahead of applied watermark "
                f"{self.applied_revision()}"))
        if serializable:
            self.check_staleness()
            return
        try:
            self.fence()
        except ReplicaRefusedError as e:
            self._refuse(e)

    def check_staleness(self) -> None:
        """The bounded-staleness gate for serializable reads: lag past
        either bound is a REFUSAL (clients fail over), never a stale
        answer."""
        cfg = self.config
        if cfg.max_staleness_ms:
            lag_ms = self.lag_seconds() * 1000.0
            if lag_ms > cfg.max_staleness_ms:
                self._refuse(StaleReplicaError(
                    f"replica lag {lag_ms:.0f}ms > max-staleness-ms "
                    f"{cfg.max_staleness_ms:.0f}"))
        if cfg.max_staleness_rev:
            lag = self.lag_revisions()
            if lag > cfg.max_staleness_rev:
                self._refuse(StaleReplicaError(
                    f"replica lag {lag} revisions > max-staleness-rev "
                    f"{cfg.max_staleness_rev}"))

    def _refuse(self, err: ReplicaRefusedError) -> None:
        self.refused[err.reason] += 1
        if self._metrics is not None:
            self._metrics.emit_counter(
                "kb.replica.refused", 1, reason=err.reason)
        raise err

    def note_served(self, rpc: str) -> None:
        self.served[rpc] += 1
        if self._metrics is not None:
            self._metrics.emit_counter("kb.replica.served", 1, rpc=rpc)

    def _note_forwarded(self, rpc: str) -> None:
        self.forwarded[rpc] += 1
        if self._metrics is not None:
            self._metrics.emit_counter("kb.replica.forwarded", 1, rpc=rpc)

    # ---------------------------------------------------------- forwarding
    _METHODS = {
        "txn": ("/etcdserverpb.KV/Txn",
                rpc_pb2.TxnRequest, rpc_pb2.TxnResponse),
        "compact": ("/etcdserverpb.KV/Compact",
                    rpc_pb2.CompactionRequest, rpc_pb2.CompactionResponse),
        "lease_grant": ("/etcdserverpb.Lease/LeaseGrant",
                        rpc_pb2.LeaseGrantRequest, rpc_pb2.LeaseGrantResponse),
        "lease_revoke": ("/etcdserverpb.Lease/LeaseRevoke",
                         rpc_pb2.LeaseRevokeRequest,
                         rpc_pb2.LeaseRevokeResponse),
        "lease_ttl": ("/etcdserverpb.Lease/LeaseTimeToLive",
                      rpc_pb2.LeaseTimeToLiveRequest,
                      rpc_pb2.LeaseTimeToLiveResponse),
        "lease_leases": ("/etcdserverpb.Lease/LeaseLeases",
                         rpc_pb2.LeaseLeasesRequest,
                         rpc_pb2.LeaseLeasesResponse),
    }
    FORWARD_TIMEOUT_S = 10.0

    def _leader_channel_locked(self) -> grpc.Channel:
        if self._channel is None:
            creds = self.config.credentials
            self._channel = (
                grpc.secure_channel(self.config.leader_address, creds)
                if creds is not None
                else grpc.insecure_channel(self.config.leader_address))
        return self._channel

    def _stub(self, name: str) -> Any:
        with self._lock:
            self._leader_channel_locked()
            stub = self._stubs.get(name)
            if stub is None:
                method, req, resp = self._METHODS[name]
                stub = self._channel.unary_unary(
                    method, request_serializer=req.SerializeToString,
                    response_deserializer=resp.FromString)
                self._stubs[name] = stub
            return stub

    def _gate_forward(self) -> None:
        """Injected leader-unreachable window: refuse BEFORE sending, so
        the refusal is provably not-applied (clients may safely retry /
        fail over — the consistency ledger counts it definite)."""
        if self._plane is not None and self._plane.leader_unreachable():
            self.refused[LeaderUnreachableError.reason] += 1
            if self._metrics is not None:
                self._metrics.emit_counter(
                    "kb.replica.refused", 1,
                    reason=LeaderUnreachableError.reason)
            raise LeaderUnreachableError(
                "leader unreachable (fault injection)")

    def forward_unary(self, name: str, request: Any, context: Any) -> Any:
        """Forward one unary RPC to the leader. gRPC failures re-abort with
        the LEADER'S status code + details verbatim: the client's
        safe-vs-ambiguous classification must see exactly what a direct
        call would have seen (a swallowed DEADLINE re-labelled "not
        leader" would launder an ambiguous write into a safe retry)."""
        try:
            self._gate_forward()
        except LeaderUnreachableError as e:
            context.abort(grpc.StatusCode.UNAVAILABLE,
                          f"etcdserver: leader unreachable: {e}")
        self._note_forwarded(name)
        try:
            return self._stub(name)(request, timeout=self.FORWARD_TIMEOUT_S)
        except grpc.RpcError as e:
            code = e.code() if hasattr(e, "code") else None
            details = e.details() if hasattr(e, "details") else ""
            context.abort(code or grpc.StatusCode.UNAVAILABLE,
                          details or "forward to leader failed")

    def forward_keepalive(self, request_iterator, context):
        """Pipe a LeaseKeepAlive stream through the leader (the reference's
        etcd-proxy watch piping, applied to the keepalive stream)."""
        try:
            self._gate_forward()
        except LeaderUnreachableError as e:
            context.abort(grpc.StatusCode.UNAVAILABLE,
                          f"etcdserver: leader unreachable: {e}")
        with self._lock:
            self._leader_channel_locked()
            stream = self._stubs.get("_keepalive_stream")
            if stream is None:
                stream = self._channel.stream_stream(
                    "/etcdserverpb.Lease/LeaseKeepAlive",
                    request_serializer=(
                        rpc_pb2.LeaseKeepAliveRequest.SerializeToString),
                    response_deserializer=(
                        rpc_pb2.LeaseKeepAliveResponse.FromString))
                self._stubs["_keepalive_stream"] = stream
        def counted(it):
            for req in it:
                self._note_forwarded("lease_keepalive")
                yield req
        try:
            yield from stream(counted(request_iterator))
        except grpc.RpcError as e:
            code = e.code() if hasattr(e, "code") else None
            if code == grpc.StatusCode.CANCELLED:
                return  # client went away; not an error
            details = e.details() if hasattr(e, "details") else ""
            context.abort(code or grpc.StatusCode.UNAVAILABLE,
                          details or "keepalive forward to leader failed")

    # --------------------------------------------------- PeerService shape
    def is_leader(self) -> bool:
        return False

    def campaign(self) -> None:
        pass  # followers never campaign: the role is explicit, not elected

    def sync_read_revision(self) -> None:
        # the replication stream owns the watermark; a per-read HTTP sync
        # (the legacy follower mode) would defeat local serving entirely
        pass

    def forward_txn(self, request):  # noqa: ARG002 — brain-front contract
        return None

    def forward_watch(self, request_iterator):  # noqa: ARG002
        return None  # watches are served from the LOCAL pipeline

    def leader_peer_address(self) -> str:
        return self.config.leader_info

    # ------------------------------------------------------------ lifecycle
    def start(self) -> None:
        from .stream import ReplicationStream

        if self._stream is None:
            self._stream = ReplicationStream(self, self.backend,
                                             plane=self._plane)
            self._stream.start()

    def status(self) -> dict:
        lag_s = self.lag_seconds()
        return {
            "role": "follower",
            "leader_address": self.config.leader_address,
            "applied_revision": self.applied_revision(),
            "leader_revision": self._leader_rev,
            "lag_revisions": self.lag_revisions(),
            "lag_seconds": None if lag_s == float("inf") else round(lag_s, 3),
            "served": dict(self.served),
            "forwarded": dict(self.forwarded),
            "refused": dict(self.refused),
            "stream": (self._stream.status() if self._stream is not None
                       else {"state": "not_started"}),
        }

    def close(self) -> None:
        if self._stream is not None:
            self._stream.close()
        with self._lock:
            if self._channel is not None:
                self._channel.close()
                self._channel = None
                self._stubs.clear()
        if self._metrics is not None:
            # the lag/watermark gauges registered in __init__ close over
            # this role: leaving them registered keeps a closed follower
            # reachable from the metrics registry and scrapes stale lag
            self._metrics.unregister_gauge_fn("kb.replica.applied.revision")
            self._metrics.unregister_gauge_fn("kb.replica.lag.revisions")
            self._metrics.unregister_gauge_fn("kb.replica.lag.seconds")
