"""ReplicationStream: the follower's resumable feed from the leader.

Transport is the etcd Watch protocol itself — one whole-keyspace watch
(``prev_kv`` so follower-local watchers keep full delete fidelity) ridden
through the client's :class:`~kubebrain_tpu.client.WatchMux` with resume
armed: a server-side stream reset (slow-consumer drop, fault injection,
leader restart inside the cache window) re-registers from the applied
watermark + 1 and the leader's watch cache replays the gap — no event
lost, none duplicated (the PR 11 exactly-once machinery, reused wholesale).

Watermark advancement across revision gaps: failed leader ops consume
revisions but stream nothing, so event revisions alone under-count the
applied floor. The stream sends a watch *progress request* every
``progress_interval_s``; the leader answers (per watch, through the
watcher's own queue, so ordering with in-flight events holds — see
``WatcherHub.post_progress``) with its fully-flushed floor, and the
applier advances the watermark to it.

Degradation ladder (docs/replication.md):

1. stream reset → WatchMux resume from watermark + 1 (invisible);
2. whole-stream death / injected ``repl_reset`` → reconnect + re-register
   from watermark + 1 (replayed from the leader's watch cache);
3. resume expired (watermark fell out of the cache) / terminal compacted
   cancel → RESYNC: one leader list pinned at head R, applied as a diff
   against local state (puts for changed keys, tombstones at R for
   vanished keys), compact floor moved to R — coarse, like a kube relist,
   but never wrong;
4. leader unreachable → the stream idles, lag grows, and the serving gate
   degrades to explicit-revision-only: bounded-staleness reads REFUSE
   past the bound, fences time out — refusals, not stale answers.
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Any

from ..backend.common import TOMBSTONE, Verb, WatchEvent
from ..client import EtcdCompatClient, WatchMux
from .apply import ReplicaApplier

_RECONNECT_BACKOFF_MAX_S = 2.0


class ReplicationStream:
    def __init__(self, role, backend, plane=None, client_factory=None):
        self.role = role
        self.backend = backend
        self._plane = plane
        self._client_factory = client_factory or (
            lambda: EtcdCompatClient(role.config.leader_address,
                                     credentials=role.config.credentials))
        self.applier = ReplicaApplier(backend, role=role)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.state = "init"
        self.resets = 0          # stream teardowns this side initiated
        self.bootstraps = 0      # full bootstrap/resync passes
        self.mux_resumes = 0     # server-side resets survived via resume
        self._force_reset = False  # test hook: one deliberate reset

    def reset(self) -> None:
        """Tear the stream down at the next tick (tests/chaos tooling);
        the following pass resumes from the applied watermark + 1."""
        self._force_reset = True

    # ------------------------------------------------------------ lifecycle
    def start(self) -> None:
        if self._thread is None:
            from ..util.env import crash_guard

            self._thread = threading.Thread(
                target=crash_guard(self._run), name="kb-replica-stream",
                daemon=True)
            self._thread.start()

    def close(self) -> None:
        self._stop.set()

    def status(self) -> dict:
        return {
            "state": self.state,
            "resets": self.resets,
            "bootstraps": self.bootstraps,
            "mux_resumes": self.mux_resumes,
            "applied_events": self.applier.applied_events,
            "applied_batches": self.applier.applied_batches,
        }

    # ------------------------------------------------------------ main loop
    def _run(self) -> None:
        backoff = 0.2
        while not self._stop.is_set():
            if self._plane is not None and self._plane.leader_unreachable():
                self.state = "leader_unreachable"
                self._stop.wait(0.2)
                continue
            client = mux = None
            clean = False
            try:
                client = self._client_factory()
                if self.backend.tso.committed() == 0:
                    self.state = "bootstrapping"
                    self._bootstrap(client)
                mux = WatchMux(client, streams=1, resume=True)
                watch = mux.add(
                    b"", b"\x00",
                    start_revision=self.backend.tso.committed() + 1,
                    prev_kv=True, sink=self.applier.apply_wire_events,
                    timeout=30.0)
                if watch.cancelled:
                    # resume window expired server-side (compacted cancel):
                    # rung 3 of the ladder — full resync
                    self.state = "resync"
                    self._resync(client)
                    clean = True
                    continue
                self.state = "streaming"
                backoff = 0.2
                clean = self._tick_loop(mux, watch)
            except Exception as e:  # reconnect with backoff (rung 2)
                self.state = f"reconnecting ({type(e).__name__})"
            finally:
                base = self.mux_resumes
                if mux is not None:
                    self.mux_resumes = base + mux.resumed_total()
                    mux.close()
                if client is not None:
                    client.close()
            if not clean:
                self._stop.wait(backoff)
                backoff = min(backoff * 2.0, _RECONNECT_BACKOFF_MAX_S)

    def _tick_loop(self, mux: WatchMux, watch: Any) -> bool:
        """Progress-request ticker + fault gates + compact sync. Returns
        True when the teardown was deliberate (no reconnect backoff)."""
        cfg = self.role.config
        next_compact_sync = time.monotonic() + cfg.compact_sync_interval_s
        while not self._stop.wait(cfg.progress_interval_s):
            if watch.cancelled:
                # terminal cancel (compacted resume point): reconnect, and
                # the registration path takes the resync rung
                return False
            if self._force_reset:
                self._force_reset = False
                self.resets += 1
                self.state = "reset (requested)"
                return True
            if self._plane is not None:
                if self._plane.repl_reset():
                    # injected replication-stream reset: tear the stream
                    # down client-side; the next pass resumes from the
                    # watermark + 1 and must lose nothing
                    self.resets += 1
                    self.state = "reset (fault injection)"
                    return True
                if self._plane.leader_unreachable():
                    self.resets += 1
                    self.state = "leader_unreachable"
                    return True
            mux.request_progress()
            now = time.monotonic()
            if now >= next_compact_sync:
                next_compact_sync = now + cfg.compact_sync_interval_s
                self._sync_compact()
        return True  # close() requested

    # ---------------------------------------------------- bootstrap/resync
    def _bootstrap(self, client: EtcdCompatClient) -> None:
        """Stateless cold start: one leader list pinned at head R, applied
        as creates at their mod revisions; compact floor = R (history
        below the bootstrap is honestly unservable); watch then starts at
        R + 1."""
        kvs, rev = client.list(b"", b"\x00", page=1000)
        self.applier.apply_bootstrap(kvs, rev)
        self.bootstraps += 1

    def _resync(self, client: EtcdCompatClient) -> None:
        """Rung 3: the watermark fell out of the leader's watch cache. One
        leader list at head R diffed against local state — puts for new/
        changed keys, synthesized tombstones at R for keys the leader no
        longer has (the coarse kube-relist shape: follower watchers see
        one DELETE per vanished key at R, never a silent disappearance) —
        then the compact floor moves to R over the unservable gap."""
        kvs, rev = client.list(b"", b"\x00", page=1000)
        wm = self.backend.tso.committed()
        local_kvs, _ = self.backend.scanner.range_(b"", b"", wm, 0)
        local = {kv.key: kv.revision for kv in local_kvs}
        batch = self.store_batch()
        watch_events: list[WatchEvent] = []
        for kv in kvs:
            if local.pop(kv.key, None) == kv.mod_revision:
                continue  # unchanged across the partition
            # the applier's row writer, so the row format AND the leader's
            # key-pattern TTL policy can never diverge from the streaming
            # apply path (an /events/ row resynced without its TTL would
            # ghost on the follower forever)
            self.applier._put_rows(batch, kv.key, kv.mod_revision, kv.value,
                                   deleted=False)
            watch_events.append(WatchEvent(
                revision=kv.mod_revision, verb=Verb.PUT, key=kv.key,
                value=kv.value))
        for key in local:  # vanished while we were partitioned
            self.applier._put_rows(batch, key, rev, TOMBSTONE, deleted=True)
            watch_events.append(WatchEvent(
                revision=rev, verb=Verb.DELETE, key=key))
        batch.commit()
        watch_events.sort(key=lambda e: e.revision)
        self.backend.ingest_replicated(
            [e for e in watch_events if e.revision > wm], rev)
        self.backend.set_compact_floor(rev)
        self.role.note_applied(rev, rev)
        self.bootstraps += 1

    def store_batch(self):
        return self.backend.store.begin_batch_write()

    # -------------------------------------------------------- compact sync
    def _leader_status(self) -> dict | None:
        """The leader's /status payload via the role's shared transport
        (HttpRevisionSyncer.fetch_status: http/https auto-probing + schema
        cache — one implementation for the fence and this sync);
        best-effort, None on failure."""
        try:
            return self.role._syncer.fetch_status()
        except Exception:
            return None

    def _sync_compact(self) -> None:
        """Adopt the leader's compact watermark: fetch /status, then run a
        LOCAL compaction to the same revision — followers GC their own
        version chains (replicated updates accumulate history exactly like
        the leader's), fenced by the same CompactedError refusal."""
        payload = self._leader_status()
        if payload is None:
            return  # best-effort: staleness accounting copes
        rev = int(payload.get("revision", 0))
        if rev:
            self.role._note_leader_rev(rev)
        compacted = int(payload.get("compact_revision", 0) or 0)
        try:
            if compacted > self.backend.compact_revision():
                self.backend.compact(compacted)
        except Exception as e:
            print(f"[replica] compact sync to {compacted} failed: {e!r}",
                  file=sys.stderr)
