"""Device-aware request scheduler (the service→storage admission layer).

The serving path used to issue one blocking device scan per Range RPC;
the same kernel sustains ~3.8x the single-dispatch rate when dispatches
are pipelined (bench.py pipelined_rows_per_sec). This package closes that
gap at the serving layer: concurrent Range/Count requests are queued into
APF-style priority lanes, coalesced when identical, and dispatched with a
bounded in-flight depth so the device pipeline stays full while the host
overlays deltas for earlier requests. Overload is handled by bounded
queues + deadline shedding (etcd ``ResourceExhausted`` on the wire).

See docs/scheduler.md for the queue model, lanes, and shedding policy.
"""

from .lanes import Lane, classify, classify_write
from .scheduler import (
    RequestScheduler,
    SchedConfig,
    SchedClosedError,
    SchedOverloadError,
    SchedResultTimeoutError,
    client_of,
    ensure_scheduler,
)

__all__ = [
    "Lane",
    "classify",
    "classify_write",
    "client_of",
    "RequestScheduler",
    "SchedConfig",
    "SchedClosedError",
    "SchedOverloadError",
    "SchedResultTimeoutError",
    "ensure_scheduler",
]
