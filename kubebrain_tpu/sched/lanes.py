"""Priority lanes + request classification.

Reference analogue: kube-apiserver API Priority and Fairness (APF) — a
small fixed set of priority levels with fair queuing per flow inside each
level. Three lanes are enough for the traffic kube-apiserver actually
sends a metadata store:

- ``SYSTEM``: reads that gate control-plane liveness — leader-election
  leases, masterleases, and the compactor's coordination key. Starving
  these flaps leadership cluster-wide, so they always dispatch first.
- ``NORMAL``: everything else — paged LISTs, Counts, point-range gets.
- ``BACKGROUND``: unpaged full-range LISTs (informer relist storms,
  Snapshot dumps). These move the most bytes per request and are the
  first to shed under pressure.
"""

from __future__ import annotations

import enum


class Lane(enum.IntEnum):
    """Dispatch priority; lower value pops first."""

    SYSTEM = 0
    NORMAL = 1
    BACKGROUND = 2


#: key prefixes whose reads gate control-plane liveness (leader election
#: leases + the apiserver compactor's coordination key)
SYSTEM_PREFIXES: tuple[bytes, ...] = (
    b"/registry/leases/",
    b"/registry/masterleases/",
    b"/registry/services/endpoints/kube-system/",  # pre-Lease leader election
    b"compact_rev_key",
)


def classify_write(key: bytes) -> Lane:
    """Lane for a write (create/update/delete) of ``key``. Writes that gate
    control-plane liveness — leader-election lease renewals, masterlease
    heartbeats, the compactor's coordination txn — ride SYSTEM so a pod-
    churn storm cannot queue ahead of them; everything else is NORMAL.
    Writes are never BACKGROUND: a write the apiserver issued is state the
    cluster already committed to."""
    for p in SYSTEM_PREFIXES:
        if key.startswith(p):
            return Lane.SYSTEM
    return Lane.NORMAL


def classify(start: bytes, end: bytes = b"", limit: int = 0,
             count_only: bool = False) -> Lane:
    """Lane for a range read over [start, end). etcd single-key reads never
    reach the scheduler (they use the point-read path), so by the time a
    request is classified ``end == b""`` means *unbounded above* — backend
    range semantics — not "single key". An unbounded unpaged list (e.g. the
    Snapshot dump's ``list_by_stream(b"", b"")``) is the heaviest background
    shape there is."""
    for p in SYSTEM_PREFIXES:
        if start.startswith(p):
            return Lane.SYSTEM
    if count_only:
        return Lane.NORMAL
    if limit == 0:
        # unpaged LIST (bounded range or whole keyspace): relist/snapshot
        return Lane.BACKGROUND
    return Lane.NORMAL
