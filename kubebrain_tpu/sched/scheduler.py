"""The device-aware request scheduler.

Queue model (docs/scheduler.md):

- every range read (list / count / list_wire / list_by_stream) becomes a
  ``_Request`` in one of three priority lanes (lanes.py), with per-client
  FIFO sub-queues served round-robin inside a lane — one chatty client
  cannot monopolize its lane;
- ONE dispatcher thread pops strictly by lane priority and hands requests
  to a worker pool whose in-flight count is bounded by ``depth``. Workers
  block on their own result, so up to ``depth`` device dispatches are in
  flight at once — the async-dispatch pipelining the bench proves out
  (bench.py pipelined_rows_per_sec), with host-side overlay/materialize
  work overlapping device compute for neighbors;
- identical queued requests coalesce: followers attach to the queued
  leader and share its one execution. This is revision-safe for rev-0
  reads because the leader resolves its read revision at *execution*
  start, which is later than every follower's enqueue — so each follower
  sees everything it wrote before asking (read-your-writes holds);
  explicit-revision requests additionally join an already-executing
  leader, whose result is deterministic;
- DISTINCT queued scan requests batch: when a dispatch slot frees, the
  dispatcher drains up to ``batch - 1`` additional compatible ready scan
  requests (same backend batch executor; iterators and wire-encoded
  lists excluded) and the worker launches them as ONE batched backend
  call — over the TPU engine that is one query-batched kernel dispatch
  for the whole set (``TpuScanner.scan_batch``) — then demuxes each
  member's result (or per-query error) to its own waiter. Rev-0 members
  are safe for the same reason coalescing is: the batch resolves read
  revisions at execution start, after every member's enqueue. Batching
  composes with lanes (members drain in strict lane-priority order, so a
  SYSTEM read rides the next slot rather than queuing behind it),
  with coalescing (a drained member's followers share its demuxed
  result), and with pipelined depth (each slot now carries a batch);
- WRITES ride the same lanes (create/update/delete entry points;
  docs/writes.md): a write never coalesces (it is an effect, not a pure
  read), but when a dispatch slot frees behind a write leader the
  dispatcher drains up to ``write_batch - 1`` additional queued write ops
  (same head-only per-client pops, so same-client order is sequential
  order) into ONE ``backend.write_batch`` commit group — a contiguous
  revision block, one engine round trip with per-op CAS/exists demux,
  one event-ring pass. Conflicts inside a group fail only their own op,
  byte-identical to back-to-back sequential commits by construction;
- overload: each lane queue is bounded (``queue_limit``; enqueue sheds
  immediately when full) and every request carries an age deadline
  (``shed_ms``; stale requests shed at pop). Shed requests surface as
  ``SchedOverloadError`` which the etcd surface maps to the
  ``ResourceExhausted`` wire status kube-apiserver already retries on —
  for writes this is new but safe admission control: a shed write was
  never dealt a revision, and the apiserver's etcd3 client retries the
  txn exactly like an overloaded etcd.

The scheduler is engine-agnostic: it schedules *backend* entry points, so
the same admission path runs over the TPU mirror scanner and the generic
iterator scanner (the CPU fallback exercised by tier-1).
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable

from ..trace import TRACER
from ..util import fieldcheck
from .lanes import Lane, classify, classify_write

#: wire message kube-apiserver's etcd3 client recognizes and retries on
ERR_TOO_MANY_REQUESTS = "etcdserver: too many requests"

#: auto-depth (--sched-depth 0) bounds: the measured dispatch-RTT / compute
#: ratio is clamped here so a noisy EWMA can neither serialize the pipeline
#: nor oversubscribe the device queue
AUTO_DEPTH_MIN = 2
AUTO_DEPTH_MAX = 16
#: depth used in auto mode until the tracer has device-stage measurements
AUTO_DEPTH_DEFAULT = 4


class SchedOverloadError(Exception):
    """Request shed by admission control (queue full or deadline passed)."""

    def __init__(self, lane: Lane, reason: str) -> None:
        super().__init__(f"{ERR_TOO_MANY_REQUESTS} (lane={lane.name.lower()}, {reason})")
        self.lane = lane
        self.reason = reason


class SchedResultTimeoutError(SchedOverloadError):
    """The submitter gave up waiting for a result AFTER the request may
    have been dispatched: the outcome is ambiguous (the op may yet commit).
    Distinct from admission-control sheds (queue full / deadline passed,
    which happen strictly before a revision is dealt) so write surfaces can
    map it to an ambiguous status (DEADLINE_EXCEEDED) instead of etcd's
    safe-to-retry RESOURCE_EXHAUSTED."""


class SchedClosedError(Exception):
    """Scheduler shut down while the request was queued."""


def client_of(context: Any) -> str:
    """Fair-queuing flow id for a gRPC(-ish) context: the transport peer
    when the context has one (python-grpc), else anonymous (native-front
    backhaul contexts have no peer()). Shared by every service surface so
    flow ids cannot drift between protocols."""
    peer = getattr(context, "peer", None)
    try:
        return peer() if callable(peer) else ""
    except Exception:
        return ""


@dataclass
class SchedConfig:
    depth: int = 4           # bounded in-flight device dispatches; 0 = auto
    #                          (sized from the tracer's dispatch-RTT EWMA,
    #                          clamped AUTO_DEPTH_MIN..MAX)
    queue_limit: int = 1024  # per-lane queued-request bound
    shed_ms: float = 5000.0  # max queue age before a request is shed
    workers: int = 0         # worker threads; 0 = same as depth
    batch: int = 8           # max distinct ready scan requests per dispatch
    #                          slot (query-batched device scan); 1 disables
    write_batch: int = 8     # max queued write ops drained into one commit
    #                          group (backend.write_batch: one contiguous
    #                          revision block + one engine round trip);
    #                          1 disables grouping


class _Request:
    __slots__ = ("fn", "lane", "client", "key", "deterministic", "enqueued",
                 "done", "result", "error", "followers", "span", "joined",
                 "finished_at", "bargs", "bexec", "batch_members",
                 "joined_batch")

    def __init__(self, fn: Callable[[], Any], lane: Lane, client: str,
                 key: Any, deterministic: bool = False,
                 bargs: Any = None, bexec: Any = None) -> None:
        self.fn = fn
        self.lane = lane
        self.client = client
        self.key = key
        self.deterministic = deterministic
        self.enqueued = time.monotonic()
        self.done = threading.Event()
        self.result = None
        self.error: BaseException | None = None
        self.followers: list["_Request"] = []
        # the submitting thread's trace span: workers adopt it so scheduler
        # and backend stages land on the RPC's span tree
        self.span = TRACER.current()
        self.joined = False       # attached to a coalesced leader
        self.finished_at = 0.0    # monotonic completion time (result_deliver)
        # query-batching descriptor + executor: requests sharing ``bexec``
        # may ride one dispatch slot as ``bexec([bargs...]) -> [result...]``
        self.bargs = bargs
        self.bexec = bexec
        self.batch_members: list["_Request"] = []  # set on a batch leader
        self.joined_batch = False  # rode another leader's batched dispatch

    # ---- completion (leader result fans out to coalesced followers)
    def finish(self, result: Any = None,
               error: BaseException | None = None) -> None:
        self.result = result
        self.error = error
        self.finished_at = time.monotonic()
        self.done.set()
        for f in self.followers:
            f.result = result
            f.error = error
            f.finished_at = self.finished_at
            f.done.set()

    def wait(self, timeout: float) -> object:
        if not self.done.wait(timeout):
            raise SchedResultTimeoutError(self.lane, "result wait timed out")
        if self.error is not None:
            raise self.error
        return self.result


class _LaneQueue:
    """Per-client FIFOs + round-robin service order, O(1) ops.

    Invariant: a client appears in ``order`` exactly once while (and only
    while) it has a non-empty deque in ``clients`` — push creates both
    together, pop removes both together when the deque drains, or re-queues
    the client at the back of the service order otherwise. Anything looser
    accumulates stale ``order`` entries across drain/refill cycles, which
    both leaks and skews the round-robin toward long-lived clients."""

    __slots__ = ("clients", "order", "size")

    def __init__(self):
        self.clients: dict[str, deque] = {}
        self.order: deque[str] = deque()
        self.size = 0

    def push(self, req: _Request) -> None:
        q = self.clients.get(req.client)
        if q is None:
            q = self.clients[req.client] = deque()
            self.order.append(req.client)
        q.append(req)
        self.size += 1

    def pop(self) -> _Request | None:
        while self.order:
            client = self.order.popleft()
            q = self.clients.get(client)
            if not q:  # defensive; unreachable while the invariant holds
                self.clients.pop(client, None)
                continue
            req = q.popleft()
            self.size -= 1
            if q:
                self.order.append(client)  # back of the service order
            else:
                del self.clients[client]
            return req
        return None

    def pop_matching(self, pred: Callable[[_Request], bool]) -> _Request | None:
        """Pop the first request satisfying ``pred``, scanning clients in
        service order but inspecting only each client's queue HEAD — a
        client's own FIFO order is never reordered, and non-matching
        clients keep their place in the round-robin."""
        for i, client in enumerate(self.order):
            q = self.clients.get(client)
            if not q or not pred(q[0]):
                continue
            req = q.popleft()
            self.size -= 1
            del self.order[i]
            if q:
                self.order.append(client)  # back of the service order
            else:
                del self.clients[client]
            return req
        return None


@fieldcheck.track
class RequestScheduler:
    """Admission + coalescing + bounded-depth pipelined dispatch.

    ``backend`` may be None for generic use (``submit``/``submit_async``
    only, e.g. the bench microharness).
    """

    def __init__(self, backend: Any = None,
                 config: SchedConfig | None = None,
                 metrics: Any = None) -> None:
        self.backend = backend
        self.config = config or SchedConfig()
        self.metrics = metrics
        self._cv = threading.Condition()
        self._queues = {lane: _LaneQueue() for lane in Lane}
        self._pending: dict[object, _Request] = {}   # queued, by coalesce key
        self._inflight: dict[object, _Request] = {}  # executing, by key
        self._inflight_count = 0
        # dispatch-slot gate (was a BoundedSemaphore): a counter + condition
        # so the bound can follow current_depth() when depth is auto (0)
        self._slots_cv = threading.Condition()
        self._slots_used = 0
        self._closed = False
        self._started = False
        self._dispatcher: threading.Thread | None = None
        self._workers: list[threading.Thread] = []
        self._runq: deque[_Request] = deque()
        self._run_cv = threading.Condition()
        self.shed_counts = {lane: 0 for lane in Lane}
        self.coalesced = 0
        self.dispatched = 0
        self.batched = 0  # requests that rode another leader's batch slot
        self.write_batched = 0  # write ops that rode another leader's group
        # the backend's batch executors, resolved ONCE so member
        # compatibility is an identity check (bound methods are fresh
        # objects per access). Scan batches and write groups never mix:
        # each request carries exactly one executor identity.
        self._backend_bexec = (
            getattr(backend, "list_batch", None) if backend is not None else None
        )
        self._backend_wexec = (
            getattr(backend, "write_batch", None) if backend is not None else None
        )
        if metrics is not None:
            for lane in Lane:
                metrics.register_gauge_fn(
                    "kb.sched.queue.depth",
                    (lambda l=lane: self._queues[l].size), lane=lane.name.lower(),
                )
            metrics.register_gauge_fn(
                "kb.sched.inflight", lambda: self._inflight_count)
            metrics.register_gauge_fn("kb.sched.depth", self.current_depth)
            metrics.register_gauge_fn(
                "kb.sched.dispatch.rtt.seconds",
                lambda: TRACER.dispatch_rtt() or 0.0)

    # ---------------------------------------------------------------- depth
    def current_depth(self) -> int:
        """The in-flight dispatch bound. Fixed (--sched-depth N) or, in auto
        mode (N=0), derived from the tracer's measured device timings: to
        keep the device busy the pipeline must cover the full dispatch round
        trip, so depth ≈ ceil(dispatch_rtt / device_compute) — over a remote
        accelerator link (axon tunnel) the RTT dwarfs compute and depth
        grows toward AUTO_DEPTH_MAX; with locally attached chips it settles
        near AUTO_DEPTH_MIN."""
        if self.config.depth > 0:
            return self.config.depth
        # device-marked EWMAs only: host-path scans share the stage names
        # (uniform traces) but must not shrink the divisor — see
        # Tracer.record_stage(device=)
        rtt = TRACER.dispatch_rtt()
        compute = TRACER.device_ewma("device_compute")
        if not rtt or not compute or compute <= 0:
            return AUTO_DEPTH_DEFAULT
        return max(AUTO_DEPTH_MIN, min(AUTO_DEPTH_MAX, math.ceil(rtt / compute)))

    def _acquire_slot(self) -> bool:
        """Block until an in-flight slot frees (False when closing). The
        bound is re-read each wakeup so auto depth applies immediately."""
        with self._slots_cv:
            while True:
                if self._closed:
                    return False
                if self._slots_used < self.current_depth():
                    self._slots_used += 1
                    return True
                self._slots_cv.wait(timeout=0.2)

    def _release_slot(self) -> None:
        with self._slots_cv:
            self._slots_used -= 1
            self._slots_cv.notify()

    # ------------------------------------------------------------- lifecycle
    def _ensure_started(self) -> None:
        if self._started:
            return
        with self._cv:
            if self._started or self._closed:
                return
            from ..util.env import crash_guard

            self._dispatcher = threading.Thread(
                target=crash_guard(self._dispatch_loop), name="kb-sched",
                daemon=True,
            )
            # auto depth (0) can grow to AUTO_DEPTH_MAX at runtime; the
            # worker pool must already be wide enough to use those slots
            n = self.config.workers or max(1, self.config.depth or AUTO_DEPTH_MAX)
            self._workers = [
                threading.Thread(target=self._work_loop,
                                 name=f"kb-sched-w{i}", daemon=True)
                for i in range(n)
            ]
            self._started = True
            self._dispatcher.start()
            for w in self._workers:
                w.start()

    def close(self) -> None:
        with self._cv:
            if self._closed:
                return
            # the close latch is read under all three condition variables
            # (dispatcher under _cv, slot waiters under _slots_cv, workers
            # under _run_cv): set it while holding each so every reader
            # shares a guard with this write, and notify inside the same
            # holds — waiters wake immediately instead of riding out
            # their 0.2 s poll timeout (kblint KB120). Acquisition order
            # _cv -> _slots_cv -> _run_cv is new; KB115's static graph
            # stays acyclic (no path takes them in reverse).
            with self._slots_cv:
                with self._run_cv:
                    self._closed = True
                    self._run_cv.notify_all()
                self._slots_cv.notify_all()
            dangling: list[_Request] = []
            for lq in self._queues.values():
                while True:
                    r = lq.pop()
                    if r is None:
                        break
                    dangling.append(r)
            self._pending.clear()
            self._cv.notify_all()
        for r in dangling:
            r.finish(error=SchedClosedError("scheduler closed"))
        if self._dispatcher is not None:
            self._dispatcher.join(timeout=2.0)
        for w in self._workers:
            w.join(timeout=2.0)
        # final sweep: anything the dispatcher managed to hand off after
        # the workers exited must still be completed, not strand a caller
        with self._run_cv:
            leftovers = list(self._runq)
            self._runq.clear()
        for r in leftovers:
            for m in r.batch_members:  # batch riders must not strand either
                m.finish(error=SchedClosedError("scheduler closed"))
            r.finish(error=SchedClosedError("scheduler closed"))
        if self.metrics is not None:
            # drop the gauge callbacks registered in __init__: they close
            # over this instance, so a dangling registration keeps a dead
            # scheduler (and its backend) alive in the metrics registry
            for lane in Lane:
                self.metrics.unregister_gauge_fn(
                    "kb.sched.queue.depth", lane=lane.name.lower())
            self.metrics.unregister_gauge_fn("kb.sched.inflight")
            self.metrics.unregister_gauge_fn("kb.sched.depth")
            self.metrics.unregister_gauge_fn("kb.sched.dispatch.rtt.seconds")

    # -------------------------------------------------------------- enqueue
    def submit_async(self, fn: Callable[[], Any],
                     lane: Lane = Lane.NORMAL, client: str = "",
                     key: Any = None, deterministic: bool = False,
                     bargs: Any = None, bexec: Any = None) -> _Request:
        """Enqueue ``fn`` and return the waitable request (``.wait(t)``).
        Raises SchedOverloadError immediately when the lane queue is full.
        ``deterministic`` marks a request whose result is a pure function
        of its key (explicit read revision): it may additionally join an
        already-executing leader. ``bargs`` (with an optional ``bexec``
        override, default: the backend's ``list_batch``) marks the request
        query-batchable: a freed dispatch slot may drain it alongside other
        requests sharing the same executor and run
        ``bexec([bargs, ...]) -> [result-or-Exception, ...]`` as one
        dispatch, demuxing element i to waiter i."""
        self._ensure_started()
        if bargs is not None and bexec is None:
            bexec = self._backend_bexec
        req = _Request(fn, lane, client, key, deterministic,
                       bargs=bargs, bexec=bexec)
        with self._cv:
            if self._closed:
                raise SchedClosedError("scheduler closed")
            if key is not None:
                leader = self._pending.get(key)
                if leader is not None:
                    req.joined = True
                    leader.followers.append(req)
                    self.coalesced += 1
                    self._emit_counter("kb.sched.coalesced.total", lane)
                    return req
                if req.deterministic:
                    running = self._inflight.get(key)
                    if running is not None:
                        req.joined = True
                        running.followers.append(req)
                        self.coalesced += 1
                        self._emit_counter("kb.sched.coalesced.total", lane)
                        return req
            lq = self._queues[lane]
            if lq.size >= self.config.queue_limit:
                self.shed_counts[lane] += 1
                self._emit_counter("kb.sched.shed.total", lane, reason="queue_full")
                raise SchedOverloadError(lane, "queue full")
            lq.push(req)
            if key is not None:
                self._pending[key] = req
            self._cv.notify()
        return req

    def submit(self, fn: Callable[[], Any], lane: Lane = Lane.NORMAL,
               client: str = "", key: Any = None,
               deterministic: bool = False, bargs: Any = None,
               bexec: Any = None) -> Any:
        """Blocking submit: schedule ``fn`` and return its result."""
        req = self.submit_async(fn, lane, client, key, deterministic,
                                bargs=bargs, bexec=bexec)
        timeout = self.config.shed_ms / 1000.0 * 4 + 60.0
        try:
            res = req.wait(timeout)
        finally:
            now = time.monotonic()
            if req.joined:
                # follower: its whole scheduler residency is one stage — the
                # execution stages live on the leader's span
                TRACER.record_stage("coalesce_join", req.enqueued, now,
                                    span=req.span)
            elif req.finished_at:
                # worker completion -> waiter wakeup, so stage durations sum
                # to the observed end-to-end latency (no unattributed tail)
                TRACER.record_stage("result_deliver", req.finished_at, now,
                                    span=req.span)
        if self.metrics is not None:
            self.metrics.emit_histogram(
                "kb.sched.wait.seconds", time.monotonic() - req.enqueued,
                lane=lane.name.lower(),
            )
        return res

    # ----------------------------------------------- backend range entries
    # (the only scan path the service layer may use; kblint KB106)
    def list_(self, start: bytes, end: bytes, revision: int = 0,
              limit: int = 0, client: str = "") -> Any:
        lane = classify(start, end, limit)
        key = ("list", start, end, revision, limit)
        return self.submit(
            lambda: self.backend.list_(start, end, revision, limit),
            lane, client, key, deterministic=revision != 0,
            bargs=("list", start, end, revision, limit),
        )

    def count(self, start: bytes, end: bytes, revision: int = 0,
              client: str = "") -> Any:
        lane = classify(start, end, count_only=True)
        key = ("count", start, end, revision)
        return self.submit(
            lambda: self.backend.count(start, end, revision), lane, client,
            key, deterministic=revision != 0,
            bargs=("count", start, end, revision),
        )

    def list_wire(self, start: bytes, end: bytes, revision: int = 0,
                  limit: int = 0, client: str = "") -> Any:
        if getattr(self.backend.scanner, "list_wire", None) is None:
            return None  # engine has no wire encoder; skip the queue round
        lane = classify(start, end, limit)
        key = ("wire", start, end, revision, limit)
        return self.submit(
            lambda: self.backend.list_wire(start, end, revision, limit),
            lane, client, key, deterministic=revision != 0,
        )

    def list_by_stream(self, start: bytes, end: bytes, revision: int = 0,
                       client: str = "") -> Any:
        """Admission + initial dispatch for a streamed list. The returned
        iterator is consumed on the caller's thread (a stream can outlive
        any sane queue deadline); coalescing is disabled — iterators are
        single-consumer."""
        lane = classify(start, end, limit=0)
        return self.submit(
            lambda: self.backend.list_by_stream(start, end, revision),
            lane, client, key=None,
        )

    # ----------------------------------------------- backend write entries
    # (the only write path the service layer may use; kblint KB106. Writes
    # never coalesce — every op is an effect, not a pure read — but a freed
    # dispatch slot drains up to ``write_batch - 1`` additional queued write
    # ops behind a write leader into ONE backend.write_batch commit group:
    # a contiguous revision block, one engine round trip, one event-ring
    # pass, per-op conflict demux. Per-client FIFO through pop_matching
    # keeps same-client ordering identical to sequential submission.)
    def create(self, key: bytes, value: bytes, ttl: int | None = None,
               lease: int = 0, client: str = "") -> Any:
        wexec = self._backend_wexec
        return self.submit(
            lambda: self.backend.create(key, value, ttl=ttl, lease=lease),
            classify_write(key), client, key=None,
            bargs=("create", key, value, ttl, lease) if wexec else None,
            bexec=wexec,
        )

    def update(self, key: bytes, value: bytes, expected_revision: int,
               ttl: int | None = None, lease: int = 0,
               client: str = "") -> Any:
        wexec = self._backend_wexec
        return self.submit(
            lambda: self.backend.update(key, value, expected_revision,
                                        ttl=ttl, lease=lease),
            classify_write(key), client, key=None,
            bargs=("update", key, value, expected_revision, ttl, lease)
            if wexec else None,
            bexec=wexec,
        )

    def delete(self, key: bytes, expected_revision: int = 0,
               client: str = "") -> Any:
        wexec = self._backend_wexec
        return self.submit(
            lambda: self.backend.delete(key, expected_revision),
            classify_write(key), client, key=None,
            bargs=("delete", key, expected_revision) if wexec else None,
            bexec=wexec,
        )

    # ------------------------------------------------------------- dispatch
    def _dispatch_loop(self) -> None:
        while True:
            req = self._next_request()
            if req is None:
                return
            # bound in-flight depth: block until a dispatch slot frees
            if not self._acquire_slot():
                # closing: never strand the popped request in _runq where
                # nothing will finish it
                req.finish(error=SchedClosedError("scheduler closed"))
                return
            try:
                with self._cv:
                    closed = self._closed
                shed = False if closed else self._shed_if_stale(req)
                if not closed and not shed:
                    self._form_batch(req)
                    with self._cv:
                        for r in (req, *req.batch_members):
                            if r.key is not None:
                                self._inflight[r.key] = r
                            self._inflight_count += 1
                    self.dispatched += 1 + len(req.batch_members)
            except BaseException as e:
                # a dispatch-path failure must not shrink scheduler depth
                # for the rest of the process (kblint KB124): give the slot
                # back and fail the request instead of stranding both
                self._release_slot()
                req.finish(error=e)
                raise
            if closed:
                self._release_slot()
                req.finish(error=SchedClosedError("scheduler closed"))
                return
            if shed:
                self._release_slot()
                continue
            with self._run_cv:
                self._runq.append(req)
                self._run_cv.notify()

    def _form_batch(self, req: _Request) -> None:
        """Drain additional compatible ready requests into ``req``'s
        dispatch slot: up to ``batch - 1`` scan requests behind a scan
        leader, or up to ``write_batch - 1`` write ops behind a write
        leader — one mechanism, two executors. Compatible = carries the
        SAME batch executor identity (the backend's ``list_batch`` for
        scans, ``write_batch`` for writes; streamed lists and wire-encoded
        fast paths never set one), so scan batches and write groups can
        never mix. Members drain in strict lane-priority order through the
        per-client round-robin (head-only pops — per-client FIFO is
        preserved, which is what makes same-client write ordering inside a
        group identical to sequential), so a queued SYSTEM op rides the
        very next slot instead of waiting out lower-priority work ahead of
        it."""
        is_write = (self._backend_wexec is not None
                    and req.bexec is self._backend_wexec)
        limit = self.config.write_batch if is_write else self.config.batch
        if req.bexec is None or limit <= 1:
            return
        members: list[_Request] = []
        want = limit - 1
        compatible = lambda r: r.bexec is req.bexec
        while len(members) < want:
            with self._cv:
                m = None
                for lane in Lane:
                    m = self._queues[lane].pop_matching(compatible)
                    if m is not None:
                        break
                if m is None:
                    break
                if m.key is not None and self._pending.get(m.key) is m:
                    del self._pending[m.key]
            if self._shed_if_stale(m):
                continue  # shed members don't occupy a batch position
            members.append(m)
        if not members:
            return
        req.batch_members = members
        for m in members:
            m.joined_batch = True
        if is_write:
            self.write_batched += len(members)
        else:
            self.batched += len(members)
        if self.metrics is not None:
            self.metrics.emit_histogram(
                "kb.sched.write.batch.size" if is_write
                else "kb.sched.batch.size", float(1 + len(members)))

    def _next_request(self) -> _Request | None:
        with self._cv:
            while True:
                if self._closed:
                    return None
                for lane in Lane:  # strict priority order
                    req = self._queues[lane].pop()
                    if req is not None:
                        if req.key is not None and \
                                self._pending.get(req.key) is req:
                            del self._pending[req.key]
                        return req
                self._cv.wait(timeout=0.2)

    def _shed_if_stale(self, req: _Request) -> bool:
        age_ms = (time.monotonic() - req.enqueued) * 1000.0
        if age_ms <= self.config.shed_ms:
            return False
        with self._cv:
            self.shed_counts[req.lane] += 1 + len(req.followers)
        self._emit_counter("kb.sched.shed.total", req.lane, reason="deadline")
        req.finish(error=SchedOverloadError(req.lane, f"queued {age_ms:.0f}ms"))
        return True

    def _work_loop(self) -> None:
        while True:
            with self._run_cv:
                while not self._runq:
                    if self._closed:
                        return
                    self._run_cv.wait(timeout=0.2)
                req = self._runq.popleft()
            if req.batch_members:
                self._run_batch(req)
                continue
            # enqueue -> execution start; recorded on the submitter's span
            TRACER.record_stage("queue_wait", req.enqueued, time.monotonic(),
                                span=req.span)
            try:
                with TRACER.use(req.span):
                    result = req.fn()
                err = None
            except BaseException as e:  # surfaced to the waiting caller
                result, err = None, e
            finally:
                self._release_slot()
                with self._cv:
                    if req.key is not None and \
                            self._inflight.get(req.key) is req:
                        del self._inflight[req.key]
                    self._inflight_count -= 1
            req.finish(result=result, error=err)

    def _run_batch(self, req: _Request) -> None:
        """Execute a batch leader + members as ONE backend call and demux.
        The executor returns one result per descriptor, an Exception
        element failing only its own query (e.g. a compacted revision);
        an executor-level raise fails every member — the same visibility a
        shared single dispatch would have had."""
        batch = [req, *req.batch_members]
        t_exec = time.monotonic()
        for r in batch:
            # enqueue -> execution start, on every rider's own span
            TRACER.record_stage("queue_wait", r.enqueued, t_exec, span=r.span)
        try:
            with TRACER.use(req.span):
                results = req.bexec([r.bargs for r in batch])
            err = None
            if len(results) != len(batch):  # executor contract violation
                raise RuntimeError(
                    f"batch executor returned {len(results)} results "
                    f"for {len(batch)} queries")
        except BaseException as e:
            results, err = None, e
        finally:
            self._release_slot()
            with self._cv:
                for r in batch:
                    if r.key is not None and \
                            self._inflight.get(r.key) is r:
                        del self._inflight[r.key]
                    self._inflight_count -= 1
        t_done = time.monotonic()
        for i, r in enumerate(batch):
            if err is not None:
                r.finish(error=err)
            elif isinstance(results[i], BaseException):
                r.finish(error=results[i])
            else:
                r.finish(result=results[i])
            if r is not req:
                # the member's whole device residency happened inside the
                # leader's execution — one stage, coalesce_join-style
                TRACER.record_stage("batch_join", t_exec, t_done, span=r.span)

    # -------------------------------------------------------------- metrics
    def _emit_counter(self, name: str, lane: Lane, **tags: Any) -> None:
        if self.metrics is not None:
            self.metrics.emit_counter(name, 1, lane=lane.name.lower(), **tags)


_ENSURE_LOCK = threading.Lock()


def ensure_scheduler(backend: Any, config: SchedConfig | None = None,
                     metrics: Any = None) -> RequestScheduler:
    """The process-wide scheduler for ``backend``: every service surface
    (sync etcd, aio, native front, brain) must share one admission queue or
    lanes mean nothing. First caller wins; cli.build_endpoint calls this
    early with the flag-derived config + real metrics."""
    sched = getattr(backend, "_kb_scheduler", None)
    if sched is not None:
        return sched
    with _ENSURE_LOCK:
        sched = getattr(backend, "_kb_scheduler", None)
        if sched is None:
            sched = RequestScheduler(backend, config, metrics)
            backend._kb_scheduler = sched
    return sched
