"""Server composition: gRPC services + info HTTP handlers.

Reference: pkg/server/server.go:70-180 — composes the etcd RPC server, the
brain RPC server, and the HTTP handlers ``/health``, ``/status`` (the
follower→leader revision-sync endpoint, :151-165) and ``/election``.
"""

from __future__ import annotations

import json
import sys
import threading
import traceback

from .. import __version__
from ..backend import Backend
from ..metrics import Metrics, NoopMetrics
from .brain import BrainServer, make_brain_handlers
from .etcd import make_etcd_handlers
from .service import PeerService, SingleNodePeerService


class Server:
    def __init__(
        self,
        backend: Backend,
        peers: PeerService | SingleNodePeerService,
        metrics: Metrics | None = None,
        identity: str = "kubebrain-tpu",
        client_urls: list[str] | None = None,
        compact_interval: float = 60.0,
        replica=None,
    ):
        self.backend = backend
        self.peers = peers
        self.metrics = metrics or NoopMetrics()
        self.identity = identity
        #: follower role (kubebrain_tpu/replica; docs/replication.md)
        self.replica = replica
        self.brain = BrainServer(backend, peers, compact_interval=compact_interval)
        self.grpc_handlers = (
            make_etcd_handlers(backend, peers, identity, client_urls or [],
                               replica=replica)
            + make_brain_handlers(self.brain)
            + [self._health_handler()]
        )

    def _health_handler(self):
        """grpc.health.v1 terminal; the "leader" service reflects leadership
        (reference wires election callbacks into grpc-health, server.go:72-78)."""
        import grpc

        from ..proto import health_pb2

        def check(request, context):
            if request.service in ("", "etcd", "brain"):
                status = health_pb2.HealthCheckResponse.SERVING
            elif request.service == "leader":
                status = (
                    health_pb2.HealthCheckResponse.SERVING
                    if self.peers.is_leader()
                    else health_pb2.HealthCheckResponse.NOT_SERVING
                )
            else:
                context.abort(grpc.StatusCode.NOT_FOUND, "unknown service")
            return health_pb2.HealthCheckResponse(status=status)

        def watch(request, context):
            """Long-lived status stream (grpc.health.v1 contract): emit the
            current status, then only on change, until the client departs."""
            import time as _time

            last = check(request, context)
            yield last
            while context.is_active():
                _time.sleep(0.5)
                cur = check(request, context)
                if cur.status != last.status:
                    last = cur
                    yield cur

        return grpc.method_handlers_generic_handler("grpc.health.v1.Health", {
            "Check": grpc.unary_unary_rpc_method_handler(
                check,
                request_deserializer=health_pb2.HealthCheckRequest.FromString,
                response_serializer=health_pb2.HealthCheckResponse.SerializeToString,
            ),
            "Watch": grpc.unary_stream_rpc_method_handler(
                watch,
                request_deserializer=health_pb2.HealthCheckRequest.FromString,
                response_serializer=health_pb2.HealthCheckResponse.SerializeToString,
            ),
        })

    def start_background(self) -> None:
        self.brain.start_background()

    # ------------------------------------------------------------------ HTTP
    def http_handlers(self) -> dict:
        """path -> fn() -> (content_type, body). The /status payload is the
        revision-sync contract consumed by HttpRevisionSyncer."""
        return {
            "/health": self._health,
            "/status": self._status,
            "/election": self._election,
            "/debug/threads": self._threads,
            "/debug/traces": self._traces,
            "/debug/profile": self._profile,
            "/debug/jax-profile": self._jax_profile,  # legacy fixed-2s alias
            "/tier/failover": self._tier_failover,
        }

    def _traces(self):
        """Recent request span trees + slow-request log + stage EWMAs from
        the process tracer (kubebrain_tpu.trace)."""
        from ..trace import TRACER

        return "application/json", json.dumps(TRACER.snapshot()).encode()

    def _health(self):
        return "application/json", json.dumps({"health": "true"}).encode()

    def _status(self):
        payload = {
            "revision": self.backend.current_revision(),
            "compact_revision": self.backend.compact_revision(),
            "is_leader": self.peers.is_leader(),
            "leader": self.peers.leader_peer_address(),
            "identity": self.identity,
            "watchers": self.backend.watcher_hub.watcher_count(),
            "version": __version__,
        }
        if self.replica is not None:
            # follower: replication watermark/lag + served/forwarded/
            # refused counters (the workload harness's per-replica view)
            payload["replica"] = self.replica.status()
        return "application/json", json.dumps(payload).encode()

    def _election(self):
        return "application/json", json.dumps({
            "leader": self.peers.leader_peer_address(),
            "identity": self.identity,
            "is_leader": self.peers.is_leader(),
        }).encode()

    def _tier_failover(self):
        """Operator-driven storage-tier failover: promote the first
        reachable kbstored follower and repoint this node's pool
        (RemoteKvStorage.failover). Deliberately a manual surface — the tier
        has no raft quorum, so WHEN to flip is the operator's (or the
        election layer's) call; see README 'Tier replication'."""
        from ..storage import unwrap_store

        store = unwrap_store(self.backend.store, "failover")
        if store is None:
            return "application/json", json.dumps(
                {"error": "storage tier has no failover (not --storage=remote?)"}
            ).encode()
        try:
            idx = store.failover()
            return "application/json", json.dumps({"promoted_index": idx}).encode()
        except Exception as exc:  # surfaced to the operator, not swallowed
            return "application/json", json.dumps({"error": str(exc)}).encode()

    def _threads(self):
        """Poor man's pprof: live thread stacks (reference mounts Go pprof,
        pkg/endpoint/pprof.go — the Python analogue is stack dumps; kernel
        profiling goes through jax.profiler instead)."""
        out = []
        for tid, frame in sys._current_frames().items():
            name = next(
                (t.name for t in threading.enumerate() if t.ident == tid), str(tid)
            )
            out.append(f"--- thread {name} ---")
            out.extend(line.rstrip() for line in traceback.format_stack(frame))
        return "text/plain", "\n".join(out).encode()

    _profile_lock = threading.Lock()

    def _profile(self, query=None):
        """``/debug/profile?seconds=N``: capture an on-demand ``jax.profiler``
        device trace of the data plane for N seconds (default 2, clamped to
        [0.05, 60]) — the kernel analogue of the reference's pprof mounts,
        pkg/endpoint/pprof.go; inspect with tensorboard or xprof. One capture
        at a time — an overlapping request would stop the in-flight trace
        mid-capture."""
        import time

        import jax

        try:
            seconds = float((query or {}).get("seconds", 2.0))
        except (TypeError, ValueError):
            return "application/json", json.dumps(
                {"error": "seconds must be a number"}
            ).encode()
        seconds = min(60.0, max(0.05, seconds))
        if not self._profile_lock.acquire(blocking=False):
            return "application/json", json.dumps(
                {"error": "profile capture already in progress"}
            ).encode()
        try:
            out_dir = f"/tmp/kb-jax-profile-{int(time.time())}"
            jax.profiler.start_trace(out_dir)
            try:
                time.sleep(seconds)
            finally:
                jax.profiler.stop_trace()
            return "application/json", json.dumps(
                {"trace_dir": out_dir, "seconds": seconds}
            ).encode()
        finally:
            self._profile_lock.release()

    _profile.kb_query = True  # HTTP layers pass the parsed query string

    def _jax_profile(self):
        return self._profile()

    def start_tier_watchdog(self, interval: float = 1.0, failures: int = 3) -> bool:
        """Auto-failover for the replicated kbstored tier: probe the tier
        primary every ``interval``; after ``failures`` consecutive misses,
        attempt ``failover()``. Split-brain safety does NOT rest on this
        node's view: the FOLLOWER refuses promotion while its replication
        stream from the primary is alive (heartbeat-armed, kbstored
        OP_PROMOTE guard), so a node merely partitioned from a healthy
        primary cannot fork the tier. Returns False when the storage stack
        has no failover surface (not a replicated remote tier)."""
        from ..storage import unwrap_store

        store = unwrap_store(self.backend.store, "failover")
        if store is None or len(getattr(store, "_addresses", [])) < 2:
            return False

        import logging

        log = logging.getLogger("kubebrain.tier")

        def loop():
            misses = 0
            while not self._watchdog_stop.wait(interval):
                try:
                    store.role(timeout=min(2.0, interval))
                    misses = 0
                    continue
                except Exception:
                    misses += 1
                if misses < failures:
                    continue
                # Quorum tier (kbstored --peers): leadership moved by
                # internal election — just find it. Legacy tier: no one
                # self-elects, so promote a follower via failover().
                try:
                    idx = store.find_leader()
                    log.warning("tier primary unreachable %d probes; "
                                "repointed at elected leader %d", misses, idx)
                    misses = 0
                    continue
                except Exception:
                    pass
                try:
                    idx = store.failover()
                    log.warning("tier primary unreachable %d probes; "
                                "promoted follower %d", misses, idx)
                    misses = 0
                except Exception as exc:
                    # follower refused (primary alive from ITS view — we are
                    # the partitioned side) or nothing promotable yet
                    log.warning("tier failover attempt failed: %s", exc)

        from ..util.env import crash_guard

        self._watchdog_stop = threading.Event()
        self._watchdog = threading.Thread(
            target=crash_guard(loop), name="kb-tier-watchdog", daemon=True)
        self._watchdog.start()
        return True

    def close(self) -> None:
        if getattr(self, "_watchdog_stop", None) is not None:
            self._watchdog_stop.set()
        self.brain.close()
        self.peers.close()
