"""Server composition: gRPC services + info HTTP handlers.

Reference: pkg/server/server.go:70-180 — composes the etcd RPC server, the
brain RPC server, and the HTTP handlers ``/health``, ``/status`` (the
follower→leader revision-sync endpoint, :151-165) and ``/election``.
"""

from __future__ import annotations

import json
import sys
import threading
import traceback

from .. import __version__
from ..backend import Backend
from ..metrics import Metrics, NoopMetrics
from .brain import BrainServer, make_brain_handlers
from .etcd import make_etcd_handlers
from .service import PeerService, SingleNodePeerService


class Server:
    def __init__(
        self,
        backend: Backend,
        peers: PeerService | SingleNodePeerService,
        metrics: Metrics | None = None,
        identity: str = "kubebrain-tpu",
        client_urls: list[str] | None = None,
    ):
        self.backend = backend
        self.peers = peers
        self.metrics = metrics or NoopMetrics()
        self.identity = identity
        self.brain = BrainServer(backend, peers)
        self.grpc_handlers = make_etcd_handlers(
            backend, peers, identity, client_urls or []
        ) + make_brain_handlers(self.brain)

    def start_background(self) -> None:
        self.brain.start_background()

    # ------------------------------------------------------------------ HTTP
    def http_handlers(self) -> dict:
        """path -> fn() -> (content_type, body). The /status payload is the
        revision-sync contract consumed by HttpRevisionSyncer."""
        return {
            "/health": self._health,
            "/status": self._status,
            "/election": self._election,
            "/debug/threads": self._threads,
        }

    def _health(self):
        return "application/json", json.dumps({"health": "true"}).encode()

    def _status(self):
        return "application/json", json.dumps({
            "revision": self.backend.current_revision(),
            "compact_revision": self.backend.compact_revision(),
            "is_leader": self.peers.is_leader(),
            "leader": self.peers.leader_peer_address(),
            "identity": self.identity,
            "watchers": self.backend.watcher_hub.watcher_count(),
            "version": __version__,
        }).encode()

    def _election(self):
        return "application/json", json.dumps({
            "leader": self.peers.leader_peer_address(),
            "identity": self.identity,
            "is_leader": self.peers.is_leader(),
        }).encode()

    def _threads(self):
        """Poor man's pprof: live thread stacks (reference mounts Go pprof,
        pkg/endpoint/pprof.go — the Python analogue is stack dumps; kernel
        profiling goes through jax.profiler instead)."""
        out = []
        for tid, frame in sys._current_frames().items():
            name = next(
                (t.name for t in threading.enumerate() if t.ident == tid), str(tid)
            )
            out.append(f"--- thread {name} ---")
            out.extend(line.rstrip() for line in traceback.format_stack(frame))
        return "text/plain", "\n".join(out).encode()

    def close(self) -> None:
        self.brain.close()
        self.peers.close()
