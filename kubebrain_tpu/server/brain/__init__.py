"""Native "brain" protocol server (reference pkg/server/brain)."""

from .server import BrainServer, make_brain_handlers

__all__ = ["BrainServer", "make_brain_handlers"]
