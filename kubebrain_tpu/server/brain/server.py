"""Native protocol terminal + background jobs.

Reference: pkg/server/brain/{server,read,write}.go —

- every read syncs the read revision from the leader first
  (read.go:128,148,168,188,207);
- writes check leadership (write.go:363) and run with a bounded deadline
  (write.go:259);
- the leader runs a 60-second compaction loop compacting to
  ``current_revision - 1000`` (server.go:52,64-74).
"""

from __future__ import annotations

import threading

import grpc

from ...backend import (
    Backend,
    CASRevisionMismatchError,
    CompactedError,
    FutureRevisionError,
    KeyExistsError,
)
from ...sched import SchedOverloadError, SchedResultTimeoutError, client_of
from ...storage.errors import (
    KeyNotFoundError,
    StorageError,
    UncertainResultError,
)
from ...proto import brain_pb2
from ..etcd.server import _bidi, _unary

COMPACT_INTERVAL_SECONDS = 60.0
COMPACT_KEEP_REVISIONS = 1000


class BrainServer:
    def __init__(
        self,
        backend: Backend,
        peers=None,
        compact_interval: float = COMPACT_INTERVAL_SECONDS,
        compact_keep: int = COMPACT_KEEP_REVISIONS,
    ):
        self.backend = backend
        self.peers = peers
        self._compact_interval = compact_interval
        self._compact_keep = compact_keep
        self._stop = threading.Event()
        self._compact_thread: threading.Thread | None = None

    # ------------------------------------------------------------ lifecycle
    def start_background(self) -> None:
        """Leader campaign + compaction loop (reference server.go:51-52)."""
        if self.peers is not None:
            self.peers.campaign()
        self._compact_thread = threading.Thread(
            target=self._compact_loop, name="kb-compactor", daemon=True
        )
        self._compact_thread.start()

    def _compact_loop(self) -> None:
        while not self._stop.wait(self._compact_interval):
            if self.peers is not None and not self.peers.is_leader():
                continue
            target = self.backend.current_revision() - self._compact_keep
            if target > 0:
                try:
                    self.backend.compact(target)
                except Exception:
                    pass  # next tick retries

    def close(self) -> None:
        self._stop.set()

    # ----------------------------------------------------------------- reads
    def _sync_read(self):
        if self.peers is not None:
            self.peers.sync_read_revision()

    def Get(self, request, context) -> brain_pb2.GetResponse:
        self._sync_read()
        try:
            kv = self.backend.get(request.key, request.revision)
        except KeyNotFoundError:
            return brain_pb2.GetResponse(header_revision=self.backend.current_revision())
        except (CompactedError, FutureRevisionError) as e:
            context.abort(grpc.StatusCode.OUT_OF_RANGE, str(e))
        return brain_pb2.GetResponse(
            kv=brain_pb2.BrainKeyValue(key=kv.key, value=kv.value, revision=kv.revision),
            header_revision=self.backend.current_revision(),
        )

    def _sched(self):
        """Range reads share the etcd surface's admission scheduler: both
        protocols drain one device pipeline, so they must share one queue."""
        from ...sched import ensure_scheduler

        return ensure_scheduler(self.backend)

    def Range(self, request, context) -> brain_pb2.BrainRangeResponse:
        self._sync_read()
        try:
            res = self._sched().list_(
                request.start, request.end, request.revision, int(request.limit),
                client=self._client_of(context),
            )
        except SchedOverloadError as e:
            context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, str(e))
        except (CompactedError, FutureRevisionError) as e:
            context.abort(grpc.StatusCode.OUT_OF_RANGE, str(e))
        resp = brain_pb2.BrainRangeResponse(more=res.more, header_revision=res.revision)
        for kv in res.kvs:
            resp.kvs.add(key=kv.key, value=kv.value, revision=kv.revision)
        return resp

    def RangeStream(self, request, context):
        self._sync_read()
        try:
            rev, stream = self._sched().list_by_stream(
                request.start, request.end, request.revision,
                client=self._client_of(context),
            )
        except SchedOverloadError as e:
            context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, str(e))
        for batch in stream:
            resp = brain_pb2.BrainRangeResponse(header_revision=rev)
            for kv in batch:
                resp.kvs.add(key=kv.key, value=kv.value, revision=kv.revision)
            yield resp

    def Count(self, request, context) -> brain_pb2.CountResponse:
        self._sync_read()
        try:
            n, rev = self._sched().count(
                request.start, request.end, client=self._client_of(context)
            )
        except SchedOverloadError as e:
            context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, str(e))
        return brain_pb2.CountResponse(count=n, header_revision=rev)

    _client_of = staticmethod(client_of)  # fair-queuing flow id (sched)

    def ListPartition(self, request, context) -> brain_pb2.ListPartitionResponse:
        self._sync_read()
        parts = self.backend.get_partitions(request.start, request.end)
        resp = brain_pb2.ListPartitionResponse(
            header_revision=self.backend.current_revision()
        )
        resp.borders.append(parts[0].left)
        for p in parts:
            resp.borders.append(p.right)
        return resp

    # ---------------------------------------------------------------- writes
    def _check_leader_write(self, context):
        if self.peers is not None and not self.peers.is_leader():
            context.abort(grpc.StatusCode.UNAVAILABLE, "not leader")  # write.go:363

    def Create(self, request, context) -> brain_pb2.CreateResponse:
        self._check_leader_write(context)
        try:
            # writes ride the scheduler lanes + group commit like the etcd
            # surface (kblint KB106; docs/writes.md)
            rev = self._sched().create(request.key, request.value,
                                       client=self._client_of(context))
            return brain_pb2.CreateResponse(succeeded=True, revision=rev)
        except SchedResultTimeoutError:
            # post-dispatch wait timeout: outcome ambiguous, not a shed
            context.abort(grpc.StatusCode.DEADLINE_EXCEEDED, "request timed out")
        except SchedOverloadError as e:
            context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, str(e))
        except KeyExistsError as e:
            return brain_pb2.CreateResponse(succeeded=False, revision=e.revision)
        except FutureRevisionError:
            # drift-back race (concurrent delete drew a higher revision):
            # definite failure, retry deals a fresh revision (write.go analog
            # of the etcd shim's mapping, server/etcd/kv.py)
            context.abort(grpc.StatusCode.UNAVAILABLE, "revision drift, retry")
        except UncertainResultError:
            # engine cannot know whether the commit landed: the same
            # ambiguous status as a result-wait timeout (docs/faults.md)
            context.abort(grpc.StatusCode.DEADLINE_EXCEEDED, "request timed out")
        except StorageError as e:
            # definite engine refusal, nothing applied: safe to retry
            context.abort(grpc.StatusCode.UNAVAILABLE, f"storage error: {e}")

    def Update(self, request, context) -> brain_pb2.UpdateResponse:
        self._check_leader_write(context)
        try:
            rev = self._sched().update(request.key, request.value,
                                       request.expected_revision,
                                       client=self._client_of(context))
            return brain_pb2.UpdateResponse(succeeded=True, revision=rev)
        except SchedResultTimeoutError:
            context.abort(grpc.StatusCode.DEADLINE_EXCEEDED, "request timed out")
        except SchedOverloadError as e:
            context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, str(e))
        except CASRevisionMismatchError as e:
            resp = brain_pb2.UpdateResponse(succeeded=False, revision=e.revision)
            if e.value is not None:
                resp.latest.key = request.key
                resp.latest.value = e.value
                resp.latest.revision = e.revision
            return resp
        except UncertainResultError:
            # engine cannot know whether the commit landed: the same
            # ambiguous status as a result-wait timeout (docs/faults.md)
            context.abort(grpc.StatusCode.DEADLINE_EXCEEDED, "request timed out")
        except StorageError as e:
            # definite engine refusal, nothing applied: safe to retry
            context.abort(grpc.StatusCode.UNAVAILABLE, f"storage error: {e}")

    def Delete(self, request, context) -> brain_pb2.BrainDeleteResponse:
        self._check_leader_write(context)
        try:
            rev, prev = self._sched().delete(request.key,
                                             request.expected_revision,
                                             client=self._client_of(context))
            return brain_pb2.BrainDeleteResponse(
                succeeded=True,
                revision=rev,
                prev_kv=brain_pb2.BrainKeyValue(
                    key=prev.key, value=prev.value, revision=prev.revision
                ),
            )
        except SchedResultTimeoutError:
            context.abort(grpc.StatusCode.DEADLINE_EXCEEDED, "request timed out")
        except SchedOverloadError as e:
            context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, str(e))
        except (KeyNotFoundError, CASRevisionMismatchError):
            return brain_pb2.BrainDeleteResponse(
                succeeded=False, revision=self.backend.current_revision()
            )
        except UncertainResultError:
            # engine cannot know whether the commit landed: the same
            # ambiguous status as a result-wait timeout (docs/faults.md)
            context.abort(grpc.StatusCode.DEADLINE_EXCEEDED, "request timed out")
        except StorageError as e:
            # definite engine refusal, nothing applied: safe to retry
            context.abort(grpc.StatusCode.UNAVAILABLE, f"storage error: {e}")

    def Compact(self, request, context) -> brain_pb2.BrainCompactResponse:
        self._check_leader_write(context)
        done = self.backend.compact(request.revision)
        return brain_pb2.BrainCompactResponse(compacted_revision=done)

    # ----------------------------------------------------------------- watch
    def Watch(self, request, context):
        from ...backend import WatchExpiredError

        try:
            wid, q = self.backend.watch(request.prefix, request.start_revision)
        except WatchExpiredError:
            yield brain_pb2.BrainWatchResponse(
                expired=True, header_revision=self.backend.current_revision()
            )
            return
        import queue as _q

        try:
            while context.is_active():
                try:
                    batch = q.get(timeout=0.5)
                except _q.Empty:
                    continue
                if batch is None:
                    return
                resp = brain_pb2.BrainWatchResponse(
                    header_revision=self.backend.current_revision()
                )
                for ev in batch:
                    resp.events.add(
                        type=int(ev.verb),
                        revision=ev.revision,
                        prev_revision=ev.prev_revision,
                        kv=brain_pb2.BrainKeyValue(
                            key=ev.key, value=ev.value, revision=ev.revision
                        ),
                    )
                yield resp
        finally:
            self.backend.unwatch(wid)


def make_brain_handlers(server: BrainServer):
    p = brain_pb2
    s = server

    def unary_stream(fn, req_cls, resp_cls):
        return grpc.unary_stream_rpc_method_handler(
            fn, request_deserializer=req_cls.FromString,
            response_serializer=resp_cls.SerializeToString,
        )

    return [
        grpc.method_handlers_generic_handler("brainpb.Brain", {
            "Create": _unary(s.Create, p.CreateRequest, p.CreateResponse),
            "Update": _unary(s.Update, p.UpdateRequest, p.UpdateResponse),
            "Delete": _unary(s.Delete, p.BrainDeleteRequest, p.BrainDeleteResponse),
            "Compact": _unary(s.Compact, p.BrainCompactRequest, p.BrainCompactResponse),
            "Get": _unary(s.Get, p.GetRequest, p.GetResponse),
            "Range": _unary(s.Range, p.BrainRangeRequest, p.BrainRangeResponse),
            "RangeStream": unary_stream(s.RangeStream, p.BrainRangeRequest, p.BrainRangeResponse),
            "Count": _unary(s.Count, p.CountRequest, p.CountResponse),
            "ListPartition": _unary(s.ListPartition, p.ListPartitionRequest, p.ListPartitionResponse),
            "Watch": unary_stream(s.Watch, p.BrainWatchRequest, p.BrainWatchResponse),
        }),
    ]
