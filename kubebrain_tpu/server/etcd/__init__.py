"""etcd3 protocol shim (reference pkg/server/etcd)."""

from .server import make_etcd_handlers

__all__ = ["make_etcd_handlers"]
