"""etcd3 KV service terminal: Range demux + Txn pattern matching + Compact.

Reference: pkg/server/etcd/kv.go. kube-apiserver speaks a tiny, rigid subset
of etcd3 — this service recognizes exactly that subset and rejects the rest:

- ``Range`` demuxes get / count / list / partition-borders (the magic
  revision 1888 returns partition borders for partition-wise listing,
  kv.go:33,54-57);
- ``Txn`` pattern-matches the four transaction shapes the apiserver emits —
  create (mod/version == 0 guard + put), update (mod == rev guard + put),
  delete (mod == rev guard + delete_range), and the compactor's
  coordination txn on the literal ``compact_rev_key`` — which under this
  matcher is just a create/update with a VERSION guard (kv.go:160-230).
  Version guards are honored with mod-revision semantics: the guard value is
  an opaque token the compactor reads back from Get, so any per-update
  changing token satisfies the protocol;
- raw ``Put``/``DeleteRange`` are unsupported (kv.go:142-148);
- errors map to the etcd error strings clients key on (ErrCompacted /
  ErrFutureRev) so kube-apiserver re-lists correctly.
"""

from __future__ import annotations

import grpc

from ...backend import (
    Backend,
    CASRevisionMismatchError,
    CompactedError,
    FutureRevisionError,
    KeyExistsError,
)
from ...lease import LeaseNotFoundError
from ...sched import (
    SchedOverloadError,
    SchedResultTimeoutError,
    client_of,
    ensure_scheduler,
)
from ...storage.errors import (
    KeyNotFoundError,
    StorageError,
    UncertainResultError,
)
from ...proto import rpc_pb2
from ...trace import TRACER, traceparent_of
from . import shim
from .misc import ERR_LEASE_NOT_FOUND

PARTITION_MAGIC_REVISION = 1888  # reference kv.go:33
COMPACT_REV_KEY = b"compact_rev_key"  # the apiserver compactor's coordination key

ERR_COMPACTED = "etcdserver: mvcc: required revision has been compacted"
ERR_FUTURE_REV = "etcdserver: mvcc: required revision is a future revision"


class _RawResponse(bytes):
    """Pre-serialized response body; the native-front backhaul sends it
    verbatim (front.py skips SerializeToString for bytes)."""

    def SerializeToString(self) -> bytes:  # grpc-python serializer hook
        return bytes(self)


class KVService:
    def __init__(self, backend: Backend, peers=None, limiter=None,
                 replica=None):
        self.backend = backend
        self.peers = peers  # PeerService: leader check / proxy / revision sync
        #: follower role (kubebrain_tpu/replica): per-RPC routing — reads
        #: gate on the replication watermark and then ride the SAME
        #: scheduler lanes below; writes/compaction forward to the leader
        self.replica = replica
        # the device-aware request scheduler: every range read goes through
        # its admission lanes (kblint KB106). All services over one backend
        # share one scheduler, or priority lanes mean nothing.
        self.limiter = limiter if limiter is not None else ensure_scheduler(backend)

    _client_of = staticmethod(client_of)  # fair-queuing flow id (sched)

    # ------------------------------------------------------------------ Range
    def Range(self, request: rpc_pb2.RangeRequest, context) -> rpc_pb2.RangeResponse:
        # every Range is one span tree in /debug/traces; the client's W3C
        # traceparent (gRPC metadata) parents it when the transport has one
        with TRACER.span("etcd.KV/Range", traceparent=traceparent_of(context)):
            return self._range(request, context)

    def _range(self, request: rpc_pb2.RangeRequest, context) -> rpc_pb2.RangeResponse:
        with TRACER.stage("endpoint_recv"):
            # the native-front backhaul forwards pre-serialized bytes
            # verbatim; python-grpc listeners reserialize, so the raw path
            # is front-only
            raw_ok = bool(getattr(context, "kb_raw_ok", False))
            if self.peers is not None:
                self.peers.sync_read_revision()
            # etcd range conventions: empty range_end = the single key;
            # range_end == b"\0" = everything >= key ("from key")
            range_end = bytes(request.range_end)
            single_key = not range_end
            if range_end == b"\x00":
                range_end = b""
        if (self.replica is not None
                and request.revision != PARTITION_MAGIC_REVISION):
            # follower read gate (docs/replication.md): explicit revisions
            # <= watermark and bounded-staleness serializable reads serve
            # locally; rev-0 linearizable reads fence on the leader's
            # committed revision first; past-bound lag REFUSES (clients
            # fail over) instead of answering stale
            self._replica_gate(request, context)
        try:
            if request.count_only:
                if not self.backend.config.enable_etcd_compatibility:
                    # Count is an etcd-compat feature (reference range.go:188)
                    context.abort(
                        grpc.StatusCode.UNIMPLEMENTED,
                        "etcdserver: count requires etcd compatibility mode",
                    )
                if single_key:
                    try:
                        self.backend.get(request.key, request.revision)
                        n, rev = 1, self.backend.current_revision()
                    except KeyNotFoundError:
                        n, rev = 0, self.backend.current_revision()
                else:
                    n, rev = self.limiter.count(
                        request.key, range_end, request.revision,
                        client=self._client_of(context),
                    )
                with TRACER.stage("response_encode"):
                    return rpc_pb2.RangeResponse(header=shim.header(rev), count=n)
            if request.revision == PARTITION_MAGIC_REVISION:
                return self._partitions(request)
            if single_key:
                return self._get(request)
            return self._list(request, range_end, raw_ok, self._client_of(context))
        except SchedOverloadError as e:
            # admission control shed this request: the etcd error
            # kube-apiserver's client retries with backoff
            context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, str(e))
        except CompactedError:
            context.abort(grpc.StatusCode.OUT_OF_RANGE, ERR_COMPACTED)
        except FutureRevisionError:
            context.abort(grpc.StatusCode.OUT_OF_RANGE, ERR_FUTURE_REV)

    def _replica_gate(self, request, context) -> None:
        from ...replica import (
            FutureRevisionWaitError,
            ReplicaRefusedError,
        )

        try:
            self.replica.gate_read(int(request.revision),
                                   bool(request.serializable))
        except FutureRevisionWaitError:
            # same wire shape a leader gives for a revision it has not
            # dealt yet: the client's classification (definite) and the
            # apiserver's re-list behavior both already handle it
            context.abort(grpc.StatusCode.OUT_OF_RANGE, ERR_FUTURE_REV)
        except ReplicaRefusedError as e:
            # etcdserver:-prefixed UNAVAILABLE = processed-and-refused,
            # provably nothing served: classify_rpc_error calls it safe,
            # so multi-endpoint clients fail over to the next replica
            context.abort(grpc.StatusCode.UNAVAILABLE,
                          f"etcdserver: replica refused ({e.reason}): {e}")
        self.replica.note_served("range")

    def _get(self, request) -> rpc_pb2.RangeResponse:
        try:
            kv = self.backend.get(request.key, request.revision)
        except KeyNotFoundError:
            return rpc_pb2.RangeResponse(
                header=shim.header(self.backend.current_revision()), count=0
            )
        resp = rpc_pb2.RangeResponse(
            header=shim.header(max(self.backend.current_revision(), kv.revision)), count=1
        )
        if request.keys_only:
            kv = type(kv)(kv.key, b"", kv.revision)
        resp.kvs.append(shim.to_kv(kv))
        self._fix_version_token(resp, request.key)
        return resp

    @staticmethod
    def _fix_version_token(resp, key: bytes) -> None:
        """The apiserver compactor guards its coordination txns with
        Version(compact_rev_key) and treats the value as an opaque token read
        back from Get. The MVCC core doesn't track per-key versions (like the
        reference, backendshim.go maps only revisions), so for this one key
        version := mod_revision — a token that changes on every update, which
        is all the protocol needs (kv.go:211-230)."""
        if key == COMPACT_REV_KEY:
            for kv in resp.kvs:
                kv.version = kv.mod_revision

    def _list(self, request, range_end: bytes, raw_ok: bool = False,
              client: str = "") -> rpc_pb2.RangeResponse:
        # raw fast path: the C engine encodes RangeResponse.kvs wire bytes
        # directly (kb_mvcc_list_wire) and the native frontend forwards them
        # without reserialization — no per-row Python anywhere on the list
        # hot path. Only for the default sort/shape kube-apiserver uses.
        if (raw_ok
                and request.sort_target == rpc_pb2.RangeRequest.KEY
                and request.sort_order == rpc_pb2.RangeRequest.NONE
                and not request.keys_only
                and request.key != COMPACT_REV_KEY):
            fast = self.limiter.list_wire(
                request.key, range_end, request.revision, int(request.limit),
                client=client,
            )
            if fast is not None:
                blob, n, more, read_rev = fast
                with TRACER.stage("response_encode"):
                    scalar = rpc_pb2.RangeResponse(
                        header=shim.header(read_rev), more=more, count=n
                    ).SerializeToString()
                    return _RawResponse(scalar + blob)
        res = self.limiter.list_(
            request.key, range_end, request.revision, int(request.limit),
            client=client,
        )
        with TRACER.stage("response_encode"):
            resp = rpc_pb2.RangeResponse(
                header=shim.header(res.revision), more=res.more, count=len(res.kvs)
            )
            kvs = res.kvs
            # results are produced key-ascending; honor the sort options
            # clients like etcdctl send (kube-apiserver uses the default)
            if request.sort_target == rpc_pb2.RangeRequest.MOD:
                kvs = sorted(kvs, key=lambda kv: kv.revision)
            if request.sort_order == rpc_pb2.RangeRequest.DESCEND:
                kvs = list(reversed(kvs))
            for kv in kvs:
                if request.keys_only:
                    kv = type(kv)(kv.key, b"", kv.revision)
                resp.kvs.append(shim.to_kv(kv))
            return resp

    def _partitions(self, request) -> rpc_pb2.RangeResponse:
        """Partition borders as bare KeyValues (reference kv.go:54-57 +
        range.go:208-244): n+1 border keys for n partitions."""
        parts = self.backend.get_partitions(request.key, request.range_end)
        rev = self.backend.current_revision()
        resp = rpc_pb2.RangeResponse(header=shim.header(rev), count=len(parts) + 1)
        borders = [parts[0].left] + [p.right for p in parts]
        for b in borders:
            resp.kvs.add(key=b, mod_revision=rev)
        return resp

    # -------------------------------------------------------------------- Txn
    def Txn(self, request: rpc_pb2.TxnRequest, context) -> rpc_pb2.TxnResponse:
        with TRACER.span("etcd.KV/Txn", traceparent=traceparent_of(context)):
            return self._txn(request, context)

    def _txn(self, request: rpc_pb2.TxnRequest, context) -> rpc_pb2.TxnResponse:
        with TRACER.stage("endpoint_recv"):
            if self.replica is not None:
                # follower role: every write forwards to the leader with
                # status passthrough — the client's safe-vs-ambiguous
                # classification must see exactly what a direct call would
                # (docs/replication.md)
                return self.replica.forward_unary("txn", request, context)
            if self.peers is not None and not self.peers.is_leader():
                fwd = self.peers.forward_txn(request)
                if fwd is not None:
                    return fwd
                context.abort(grpc.StatusCode.UNAVAILABLE, "etcdserver: not leader")
            m = self._match(request, context)
        kind, key, guard_rev, value, lease = m
        client = self._client_of(context)
        try:
            # writes go through the scheduler like reads (kblint KB106):
            # admission lanes + group commit — a freed slot drains queued
            # compatible writes into ONE backend.write_batch commit group
            # (contiguous revision block, one engine round trip, per-op
            # conflict demux; docs/writes.md)
            with TRACER.stage("backend_write"):
                if kind == "create":
                    rev = self.limiter.create(key, value, lease=lease,
                                              client=client)
                elif kind == "update":
                    rev = self.limiter.update(key, value, guard_rev,
                                              lease=lease, client=client)
                else:  # delete
                    rev, _prev = self.limiter.delete(key, guard_rev,
                                                     client=client)
            with TRACER.stage("response_encode"):
                return self._txn_ok(rev, put=kind != "delete")
        except SchedResultTimeoutError:
            # the result wait timed out AFTER dispatch: the write may yet
            # commit, so signal the ambiguous outcome the way etcd does
            # (ErrTimeout → DeadlineExceeded), never the safe-to-retry
            # RESOURCE_EXHAUSTED an admission shed gets
            context.abort(grpc.StatusCode.DEADLINE_EXCEEDED,
                          "etcdserver: request timed out")
        except SchedOverloadError as e:
            # write shed by admission control BEFORE a revision was dealt:
            # safe to retry, and the etcd error the apiserver's client
            # already backs off on
            context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, str(e))
        except LeaseNotFoundError:
            # a put under an unknown/expired lease is a definite failure
            # (etcd ErrLeaseNotFound) — the apiserver re-grants and retries
            context.abort(grpc.StatusCode.NOT_FOUND, ERR_LEASE_NOT_FOUND)
        except KeyExistsError as e:
            return self._txn_failed(request, e.revision)
        except (CASRevisionMismatchError,) as e:
            return self._txn_failed(request, e.revision)
        except KeyNotFoundError:
            return self._txn_failed(request, 0)
        except FutureRevisionError:
            # drift-back race (a concurrent op drew a higher revision than
            # this txn's dealt one): definite failure, safe to retry —
            # UNAVAILABLE makes clients (apiserver) re-issue the txn, which
            # deals a fresh revision (reference ErrRevisionDriftBack,
            # txn.go:171-175)
            context.abort(grpc.StatusCode.UNAVAILABLE,
                          "etcdserver: revision drift, retry txn")
        except UncertainResultError:
            # the engine cannot know whether the commit landed: the SAME
            # ambiguous status as a post-dispatch result timeout (etcd
            # ErrTimeout → DeadlineExceeded). Clients must NEVER blind-
            # retry a non-idempotent write on this status — the async
            # retry FIFO resolves the outcome server-side (docs/faults.md)
            context.abort(grpc.StatusCode.DEADLINE_EXCEEDED,
                          "etcdserver: request timed out")
        except StorageError as e:
            # definite engine refusal BEFORE anything applied (e.g. an
            # injected storage fault): UNAVAILABLE with the etcdserver:
            # prefix = processed-and-refused, safe to retry
            context.abort(grpc.StatusCode.UNAVAILABLE,
                          f"etcdserver: storage error: {e}")

    def _match(self, request, context):
        """Classify the txn (reference kv.go:160-230). Returns
        (kind, key, guard_revision, value, lease_id)."""
        if len(request.compare) != 1 or len(request.success) != 1:
            context.abort(
                grpc.StatusCode.UNIMPLEMENTED,
                "etcdserver: unsupported transaction shape",
            )
        cmp = request.compare[0]
        if cmp.result != rpc_pb2.Compare.EQUAL or cmp.target not in (
            rpc_pb2.Compare.MOD,
            rpc_pb2.Compare.VERSION,
            rpc_pb2.Compare.CREATE,
        ):
            context.abort(
                grpc.StatusCode.UNIMPLEMENTED, "etcdserver: unsupported compare"
            )
        guard = (
            cmp.mod_revision
            if cmp.target == rpc_pb2.Compare.MOD
            else cmp.version if cmp.target == rpc_pb2.Compare.VERSION else cmp.create_revision
        )
        op = request.success[0]
        which = op.WhichOneof("request")
        if which == "request_put":
            if op.request_put.key != cmp.key:
                context.abort(grpc.StatusCode.UNIMPLEMENTED, "etcdserver: key mismatch")
            kind = "create" if guard == 0 else "update"
            # real lease attachment: PutRequest.lease names a lease granted
            # by LeaseService; the backend write path binds the key to it
            # and the reaper owns expiry (an explicit lease always beats the
            # legacy key-pattern TTL — docs/storage_engine.md precedence)
            lease = int(op.request_put.lease) if op.request_put.lease > 0 else 0
            return kind, bytes(op.request_put.key), int(guard), bytes(op.request_put.value), lease
        if which == "request_delete_range":
            if op.request_delete_range.key != cmp.key:
                context.abort(grpc.StatusCode.UNIMPLEMENTED, "etcdserver: key mismatch")
            return "delete", bytes(op.request_delete_range.key), int(guard), b"", 0
        context.abort(
            grpc.StatusCode.UNIMPLEMENTED, "etcdserver: unsupported transaction op"
        )

    def _txn_ok(self, revision: int, put: bool) -> rpc_pb2.TxnResponse:
        resp = rpc_pb2.TxnResponse(header=shim.header(revision), succeeded=True)
        op = resp.responses.add()
        if put:
            op.response_put.header.revision = revision
        else:
            op.response_delete_range.header.revision = revision
            op.response_delete_range.deleted = 1
        return resp

    def _txn_failed(self, request, current_rev: int) -> rpc_pb2.TxnResponse:
        """Failed guard: run the failure branch (always [OpGet(key)] from
        kube-apiserver) so the client sees the current kv."""
        resp = rpc_pb2.TxnResponse(
            header=shim.header(self.backend.current_revision()), succeeded=False
        )
        for op in request.failure:
            if op.WhichOneof("request") != "request_range":
                continue
            r = op.request_range
            try:
                kv = self.backend.get(r.key, r.revision)
                rr = rpc_pb2.RangeResponse(header=shim.header(kv.revision), count=1)
                rr.kvs.append(shim.to_kv(kv))
                self._fix_version_token(rr, bytes(r.key))
            except (KeyNotFoundError, CompactedError):
                rr = rpc_pb2.RangeResponse(
                    header=shim.header(self.backend.current_revision()), count=0
                )
            resp.responses.add().response_range.CopyFrom(rr)
        return resp

    # ----------------------------------------------------------------- Compact
    def Compact(self, request: rpc_pb2.CompactionRequest, context) -> rpc_pb2.CompactionResponse:
        if self.replica is not None:
            # compaction is the leader's job; the follower adopts the new
            # watermark through the replication stream's compact sync
            return self.replica.forward_unary("compact", request, context)
        if self.peers is not None and not self.peers.is_leader():
            # compaction is the leader's job; accept and no-op on followers
            return rpc_pb2.CompactionResponse(
                header=shim.header(self.backend.current_revision())
            )
        done = self.backend.compact(request.revision)
        return rpc_pb2.CompactionResponse(header=shim.header(done))

    # ------------------------------------------------- unsupported raw writes
    def Put(self, request, context):
        context.abort(
            grpc.StatusCode.UNIMPLEMENTED,
            "etcdserver: raw Put is not supported; use Txn",  # kv.go:142-148
        )

    def DeleteRange(self, request, context):
        context.abort(
            grpc.StatusCode.UNIMPLEMENTED,
            "etcdserver: raw DeleteRange is not supported; use Txn",
        )
