"""Lease / Cluster / Maintenance terminals.

Reference: pkg/server/etcd/lease.go (LeaseGrant returns the TTL as the lease
ID — "fake but truthy"; TTL is enforced by key pattern, not lease state,
lease.go:24-31) and cluster.go (MemberList stub, :25-33).
"""

from __future__ import annotations

import grpc

from ... import __version__
from ...proto import rpc_pb2
from . import shim


class LeaseService:
    def __init__(self, backend):
        self.backend = backend

    def LeaseGrant(self, request, context) -> rpc_pb2.LeaseGrantResponse:
        # kube-apiserver attaches leases to /events/ keys; TTL is honored by
        # key pattern in the write path (creator.ttl_for_key), so the lease
        # object itself is a polite fiction: ID := TTL.
        return rpc_pb2.LeaseGrantResponse(
            header=shim.header(self.backend.current_revision()),
            ID=request.TTL,
            TTL=request.TTL,
        )

    def LeaseRevoke(self, request, context) -> rpc_pb2.LeaseRevokeResponse:
        # nothing to revoke: TTLs live on the keys, not on lease state
        return rpc_pb2.LeaseRevokeResponse(
            header=shim.header(self.backend.current_revision())
        )

    def LeaseKeepAlive(self, request_iterator, context):
        # keepalives are acknowledged verbatim (TTL enforcement is by key
        # pattern; the stream exists so lease-holding clients don't error)
        for req in request_iterator:
            yield rpc_pb2.LeaseKeepAliveResponse(
                header=shim.header(self.backend.current_revision()),
                ID=req.ID,
                TTL=req.ID,
            )


class ClusterService:
    def __init__(self, backend, identity: str = "kubebrain-tpu", client_urls=None):
        self.backend = backend
        self.identity = identity
        self.client_urls = client_urls or []

    def MemberList(self, request, context) -> rpc_pb2.MemberListResponse:
        resp = rpc_pb2.MemberListResponse(
            header=shim.header(self.backend.current_revision())
        )
        resp.members.add(ID=1, name=self.identity, clientURLs=self.client_urls)
        return resp


class MaintenanceService:
    def __init__(self, backend):
        self.backend = backend

    def Status(self, request, context) -> rpc_pb2.StatusResponse:
        return rpc_pb2.StatusResponse(
            header=shim.header(self.backend.current_revision()),
            version=f"3.5.0-kubebrain-tpu-{__version__}",
            leader=1,
            raftIndex=self.backend.current_revision(),
            raftTerm=1,
        )

    def Defragment(self, request, context) -> rpc_pb2.DefragmentResponse:
        """etcd defrag ≈ our checkpoint: rewrite a latest-only snapshot and
        truncate the WAL (no-op for engines without durability)."""
        from ...storage import unwrap_store

        # engines hide behind decorator stacks (metrics → tpu → native)
        store = unwrap_store(self.backend.store, "checkpoint")
        if store is not None:
            store.checkpoint()
        return rpc_pb2.DefragmentResponse(
            header=shim.header(self.backend.current_revision())
        )

    def Snapshot(self, request, context):
        """Stream a consistent backup (etcdctl snapshot save): a
        length-framed dump of the keyspace AT the header revision —
        engine-portable (restorable into any engine by replaying creates),
        streamed batch-by-batch so the keyspace never materializes in full
        (backend.list_by_stream)."""
        from ...sched import ensure_scheduler
        from ...trace import TRACER

        # background lane: a snapshot dump must queue behind serving reads.
        # Only the admission + initial dispatch is spanned — the stream
        # drains across yields, and a span must not straddle a generator's
        # suspension points (the contextvar would leak into the consumer).
        with TRACER.span("etcd.Maintenance/Snapshot"):
            rev, stream = ensure_scheduler(self.backend).list_by_stream(b"", b"")
        pending = b"KBSNAP1" + rev.to_bytes(8, "big")
        for batch in stream:
            frames = [pending]
            for kv in batch:
                frames.append(len(kv.key).to_bytes(4, "big"))
                frames.append(kv.key)
                frames.append(len(kv.value).to_bytes(4, "big"))
                frames.append(kv.value)
                frames.append(kv.revision.to_bytes(8, "big"))
            payload = b"".join(frames)
            pending = b""
            yield rpc_pb2.SnapshotResponse(
                header=shim.header(rev),
                remaining_bytes=1,  # progress hint; exact total unknown while streaming
                blob=payload,
            )
        yield rpc_pb2.SnapshotResponse(
            header=shim.header(rev), remaining_bytes=0, blob=pending
        )
