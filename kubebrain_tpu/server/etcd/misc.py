"""Lease / Cluster / Maintenance terminals.

Reference: pkg/server/etcd/lease.go — which ships the "fake but truthy"
stub (LeaseGrant returns the TTL as the lease ID, TTL enforced by key
pattern, lease.go:24-31). This LeaseService is the real thing instead: a
monotonic-clock TTL state machine (kubebrain_tpu/lease) whose expiry path
is the leader-only reaper issuing revision-stamped deletes through the
sequencer, so kube-apiserver workloads that depend on real lease semantics
(event TTLs, masterleases, lock/election keys) behave as against etcd.
ClusterService mirrors cluster.go (MemberList stub, :25-33).
"""

from __future__ import annotations

import grpc

from ... import __version__
from ...lease import LeaseExistsError, LeaseNotFoundError, ensure_lease
from ...proto import rpc_pb2
from ...sched import Lane, ensure_scheduler
from ...trace import TRACER, traceparent_of
from . import shim

ERR_LEASE_NOT_FOUND = "etcdserver: requested lease not found"
ERR_LEASE_EXISTS = "etcdserver: lease already exists"
ERR_NOT_LEADER = "etcdserver: not leader"


class LeaseNotLeaderError(Exception):
    """Lease RPC reached a follower. Lease state lives on the leader (the
    reaper is leader-only); answering from a follower's stale registry
    would either kill a healthy client's lease (TTL=0) or refresh a shadow
    copy the leader never sees. Transports map this to UNAVAILABLE so
    clients retry the leader."""

#: etcd's minLeaseTTL: sub-second grants flap under keepalive jitter
MIN_LEASE_TTL = 1


class LeaseService:
    """etcd Lease terminal over the shared registry + reaper.

    Keepalives are submitted on the request scheduler's SYSTEM lane: under
    overload the background/normal lanes shed, but a shed keepalive would
    expire a healthy client's lease and delete its keys — exactly the
    cascading failure admission control exists to prevent.
    """

    def __init__(self, backend, peers=None, replica=None):
        self.backend = backend
        self.peers = peers
        #: follower role (kubebrain_tpu/replica): lease state lives on the
        #: leader, so every lease RPC forwards there with status passthrough
        #: — unlike the election-follower refusal below, a replica-role
        #: follower is a full serving endpoint for lease clients
        self.replica = replica
        self.registry = ensure_lease(backend, peers=peers)
        self.reaper = backend._kb_lease_reaper
        self.limiter = ensure_scheduler(backend)

    def _check_leader(self, context) -> None:
        # lease state lives on the leader (the reaper is leader-only);
        # followers don't forward lease RPCs — clients retry the leader
        if self.peers is not None and not self.peers.is_leader():
            context.abort(grpc.StatusCode.UNAVAILABLE, ERR_NOT_LEADER)

    def LeaseGrant(self, request, context) -> rpc_pb2.LeaseGrantResponse:
        if self.replica is not None:
            return self.replica.forward_unary("lease_grant", request, context)
        with TRACER.span("etcd.Lease/LeaseGrant",
                         traceparent=traceparent_of(context)):
            with TRACER.stage("endpoint_recv"):
                self._check_leader(context)
                ttl = max(int(request.TTL), MIN_LEASE_TTL)
            try:
                with TRACER.stage("backend_write"):
                    lease = self.registry.grant(ttl, int(request.ID))
            except LeaseExistsError:
                context.abort(grpc.StatusCode.FAILED_PRECONDITION, ERR_LEASE_EXISTS)
            with TRACER.stage("response_encode"):
                return rpc_pb2.LeaseGrantResponse(
                    header=shim.header(self.backend.current_revision()),
                    ID=lease.id,
                    TTL=int(lease.granted_ttl),
                )

    def LeaseRevoke(self, request, context) -> rpc_pb2.LeaseRevokeResponse:
        if self.replica is not None:
            return self.replica.forward_unary("lease_revoke", request, context)
        with TRACER.span("etcd.Lease/LeaseRevoke",
                         traceparent=traceparent_of(context)):
            with TRACER.stage("endpoint_recv"):
                self._check_leader(context)
            try:
                # keys-first delete discipline (reaper.revoke): every
                # attached key dies as a normal MVCC tombstone through the
                # sequencer before the lease record goes away
                with TRACER.stage("backend_write"):
                    self.reaper.revoke(int(request.ID))
            except LeaseNotFoundError:
                context.abort(grpc.StatusCode.NOT_FOUND, ERR_LEASE_NOT_FOUND)
            with TRACER.stage("response_encode"):
                return rpc_pb2.LeaseRevokeResponse(
                    header=shim.header(self.backend.current_revision())
                )

    def LeaseKeepAlive(self, request_iterator, context):
        if self.replica is not None:
            # the whole stream pipes through the leader (the etcd-proxy
            # watch-piping shape applied to keepalives)
            yield from self.replica.forward_keepalive(request_iterator,
                                                      context)
            return
        tp = traceparent_of(context)
        try:
            for req in request_iterator:
                yield self.keepalive_one(req, traceparent=tp)
        except LeaseNotLeaderError:
            context.abort(grpc.StatusCode.UNAVAILABLE, ERR_NOT_LEADER)

    def keepalive_one(self, req, traceparent=None) -> rpc_pb2.LeaseKeepAliveResponse:
        """One keepalive refresh, admitted on the SYSTEM lane. TTL=0 in the
        response is the etcd encoding of "lease not found/expired" — the
        registry never revives an expired lease. Shared by the sync, aio,
        and native-front keepalive streams; raises LeaseNotLeaderError on
        followers (a follower answering TTL=0 from its stale table would
        make the client abandon a lease that is alive on the leader)."""
        with TRACER.span("etcd.Lease/LeaseKeepAlive", traceparent=traceparent):
            if self.peers is not None and not self.peers.is_leader():
                raise LeaseNotLeaderError(ERR_NOT_LEADER)
            registry = self.registry
            lease_id = int(req.ID)
            ttl = self.limiter.submit(
                lambda: registry.keepalive(lease_id),
                lane=Lane.SYSTEM, client="lease-keepalive",
            )
            with TRACER.stage("response_encode"):
                return rpc_pb2.LeaseKeepAliveResponse(
                    header=shim.header(self.backend.current_revision()),
                    ID=req.ID,
                    TTL=ttl,
                )

    def LeaseTimeToLive(self, request, context) -> rpc_pb2.LeaseTimeToLiveResponse:
        if self.replica is not None:
            return self.replica.forward_unary("lease_ttl", request, context)
        with TRACER.span("etcd.Lease/LeaseTimeToLive",
                         traceparent=traceparent_of(context)):
            self._check_leader(context)  # a follower's table is stale
            ttl, granted, keys = self.registry.time_to_live(int(request.ID))
            with TRACER.stage("response_encode"):
                resp = rpc_pb2.LeaseTimeToLiveResponse(
                    header=shim.header(self.backend.current_revision()),
                    ID=request.ID,
                    TTL=ttl,          # -1 = missing or expired (etcd contract)
                    grantedTTL=granted,
                )
                if request.keys and ttl >= 0:
                    resp.keys.extend(keys)
                return resp

    def LeaseLeases(self, request, context) -> rpc_pb2.LeaseLeasesResponse:
        if self.replica is not None:
            return self.replica.forward_unary("lease_leases", request, context)
        with TRACER.span("etcd.Lease/LeaseLeases",
                         traceparent=traceparent_of(context)):
            self._check_leader(context)  # a follower's table is stale
            resp = rpc_pb2.LeaseLeasesResponse(
                header=shim.header(self.backend.current_revision())
            )
            for lease_id in self.registry.ids():
                resp.leases.add(ID=lease_id)
            return resp


class ClusterService:
    def __init__(self, backend, identity: str = "kubebrain-tpu", client_urls=None):
        self.backend = backend
        self.identity = identity
        self.client_urls = client_urls or []

    def MemberList(self, request, context) -> rpc_pb2.MemberListResponse:
        resp = rpc_pb2.MemberListResponse(
            header=shim.header(self.backend.current_revision())
        )
        resp.members.add(ID=1, name=self.identity, clientURLs=self.client_urls)
        return resp


class MaintenanceService:
    def __init__(self, backend):
        self.backend = backend

    def Status(self, request, context) -> rpc_pb2.StatusResponse:
        return rpc_pb2.StatusResponse(
            header=shim.header(self.backend.current_revision()),
            version=f"3.5.0-kubebrain-tpu-{__version__}",
            leader=1,
            raftIndex=self.backend.current_revision(),
            raftTerm=1,
        )

    def Defragment(self, request, context) -> rpc_pb2.DefragmentResponse:
        """etcd defrag ≈ our checkpoint: rewrite a latest-only snapshot and
        truncate the WAL (no-op for engines without durability)."""
        from ...storage import unwrap_store

        # engines hide behind decorator stacks (metrics → tpu → native)
        store = unwrap_store(self.backend.store, "checkpoint")
        if store is not None:
            store.checkpoint()
        return rpc_pb2.DefragmentResponse(
            header=shim.header(self.backend.current_revision())
        )

    def Snapshot(self, request, context):
        """Stream a consistent backup (etcdctl snapshot save): a
        length-framed dump of the keyspace AT the header revision —
        engine-portable (restorable into any engine by replaying creates),
        streamed batch-by-batch so the keyspace never materializes in full
        (backend.list_by_stream)."""
        from ...sched import ensure_scheduler
        from ...trace import TRACER

        # background lane: a snapshot dump must queue behind serving reads.
        # Only the admission + initial dispatch is spanned — the stream
        # drains across yields, and a span must not straddle a generator's
        # suspension points (the contextvar would leak into the consumer).
        with TRACER.span("etcd.Maintenance/Snapshot"):
            rev, stream = ensure_scheduler(self.backend).list_by_stream(b"", b"")
        pending = b"KBSNAP1" + rev.to_bytes(8, "big")
        for batch in stream:
            frames = [pending]
            for kv in batch:
                frames.append(len(kv.key).to_bytes(4, "big"))
                frames.append(kv.key)
                frames.append(len(kv.value).to_bytes(4, "big"))
                frames.append(kv.value)
                frames.append(kv.revision.to_bytes(8, "big"))
            payload = b"".join(frames)
            pending = b""
            yield rpc_pb2.SnapshotResponse(
                header=shim.header(rev),
                remaining_bytes=1,  # progress hint; exact total unknown while streaming
                blob=payload,
            )
        yield rpc_pb2.SnapshotResponse(
            header=shim.header(rev), remaining_bytes=0, blob=pending
        )
