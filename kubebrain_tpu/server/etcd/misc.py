"""Lease / Cluster / Maintenance terminals.

Reference: pkg/server/etcd/lease.go (LeaseGrant returns the TTL as the lease
ID — "fake but truthy"; TTL is enforced by key pattern, not lease state,
lease.go:24-31) and cluster.go (MemberList stub, :25-33).
"""

from __future__ import annotations

import grpc

from ... import __version__
from ...proto import rpc_pb2
from . import shim


class LeaseService:
    def __init__(self, backend):
        self.backend = backend

    def LeaseGrant(self, request, context) -> rpc_pb2.LeaseGrantResponse:
        # kube-apiserver attaches leases to /events/ keys; TTL is honored by
        # key pattern in the write path (creator.ttl_for_key), so the lease
        # object itself is a polite fiction: ID := TTL.
        return rpc_pb2.LeaseGrantResponse(
            header=shim.header(self.backend.current_revision()),
            ID=request.TTL,
            TTL=request.TTL,
        )


class ClusterService:
    def __init__(self, backend, identity: str = "kubebrain-tpu", client_urls=None):
        self.backend = backend
        self.identity = identity
        self.client_urls = client_urls or []

    def MemberList(self, request, context) -> rpc_pb2.MemberListResponse:
        resp = rpc_pb2.MemberListResponse(
            header=shim.header(self.backend.current_revision())
        )
        resp.members.add(ID=1, name=self.identity, clientURLs=self.client_urls)
        return resp


class MaintenanceService:
    def __init__(self, backend):
        self.backend = backend

    def Status(self, request, context) -> rpc_pb2.StatusResponse:
        return rpc_pb2.StatusResponse(
            header=shim.header(self.backend.current_revision()),
            version=f"3.5.0-kubebrain-tpu-{__version__}",
            leader=1,
            raftIndex=self.backend.current_revision(),
            raftTerm=1,
        )
