"""etcd3 gRPC service registration.

Reference: pkg/server/etcd/server.go:55-60 (registers KV, Watch, Lease,
Cluster). grpc_tools isn't available in this image, so instead of generated
``add_*_servicer_to_server`` glue the services are mounted with
``grpc.method_handlers_generic_handler`` — byte-identical on the wire.
"""

from __future__ import annotations

import grpc

from ...proto import rpc_pb2
from .kv import KVService
from .misc import ClusterService, LeaseService, MaintenanceService
from .watch import WatchService


def _unary(fn, req_cls, resp_cls):
    return grpc.unary_unary_rpc_method_handler(
        fn, request_deserializer=req_cls.FromString,
        response_serializer=resp_cls.SerializeToString,
    )


def _bidi(fn, req_cls, resp_cls):
    return grpc.stream_stream_rpc_method_handler(
        fn, request_deserializer=req_cls.FromString,
        response_serializer=resp_cls.SerializeToString,
    )


def make_etcd_handlers(backend, peers=None, identity="kubebrain-tpu",
                       client_urls=None, replica=None):
    """Generic handlers for the etcd3 surface; mount with
    ``server.add_generic_rpc_handlers``. ``replica`` (a FollowerRole)
    switches the per-RPC routing to follower mode: local/fence/forward
    (docs/replication.md)."""
    kv = KVService(backend, peers, replica=replica)
    watch = WatchService(backend, peers, replica=replica)
    lease = LeaseService(backend, peers, replica=replica)
    cluster = ClusterService(backend, identity, client_urls)
    maint = MaintenanceService(backend)
    p = rpc_pb2
    return [
        grpc.method_handlers_generic_handler("etcdserverpb.KV", {
            "Range": _unary(kv.Range, p.RangeRequest, p.RangeResponse),
            "Txn": _unary(kv.Txn, p.TxnRequest, p.TxnResponse),
            "Compact": _unary(kv.Compact, p.CompactionRequest, p.CompactionResponse),
            "Put": _unary(kv.Put, p.PutRequest, p.PutResponse),
            "DeleteRange": _unary(kv.DeleteRange, p.DeleteRangeRequest, p.DeleteRangeResponse),
        }),
        grpc.method_handlers_generic_handler("etcdserverpb.Watch", {
            "Watch": _bidi(watch.Watch, p.WatchRequest, p.WatchResponse),
        }),
        grpc.method_handlers_generic_handler("etcdserverpb.Lease", {
            "LeaseGrant": _unary(lease.LeaseGrant, p.LeaseGrantRequest, p.LeaseGrantResponse),
            "LeaseRevoke": _unary(lease.LeaseRevoke, p.LeaseRevokeRequest, p.LeaseRevokeResponse),
            "LeaseKeepAlive": _bidi(lease.LeaseKeepAlive, p.LeaseKeepAliveRequest, p.LeaseKeepAliveResponse),
            "LeaseTimeToLive": _unary(lease.LeaseTimeToLive, p.LeaseTimeToLiveRequest, p.LeaseTimeToLiveResponse),
            "LeaseLeases": _unary(lease.LeaseLeases, p.LeaseLeasesRequest, p.LeaseLeasesResponse),
        }),
        grpc.method_handlers_generic_handler("etcdserverpb.Cluster", {
            "MemberList": _unary(cluster.MemberList, p.MemberListRequest, p.MemberListResponse),
        }),
        grpc.method_handlers_generic_handler("etcdserverpb.Maintenance", {
            "Status": _unary(maint.Status, p.StatusRequest, p.StatusResponse),
            "Defragment": _unary(maint.Defragment, p.DefragmentRequest, p.DefragmentResponse),
            "Snapshot": grpc.unary_stream_rpc_method_handler(
                maint.Snapshot,
                request_deserializer=p.SnapshotRequest.FromString,
                response_serializer=p.SnapshotResponse.SerializeToString,
            ),
        }),
    ]
