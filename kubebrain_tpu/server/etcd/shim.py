"""Backend ⇄ etcd protobuf conversion.

Reference: pkg/server/etcd/backendshim.go — maps brain revisions into
``mvccpb.KeyValue{ModRevision, CreateRevision}`` and brain events into
``mvccpb.Event``. Like the reference, per-key create-revision/version
counters are not tracked by the MVCC core, so create_revision mirrors
mod_revision and version is 1 — kube-apiserver keys its optimistic
concurrency entirely off mod_revision.
"""

from __future__ import annotations

from ...backend.common import KeyValue, Verb, WatchEvent
from ...proto import kv_pb2, rpc_pb2


def to_kv(kv: KeyValue) -> kv_pb2.KeyValue:
    return kv_pb2.KeyValue(
        key=kv.key,
        value=kv.value,
        mod_revision=kv.revision,
        create_revision=kv.revision,
        version=1,
    )


def header(revision: int) -> rpc_pb2.ResponseHeader:
    return rpc_pb2.ResponseHeader(revision=revision)


def to_event(ev: WatchEvent, want_prev: bool = False) -> kv_pb2.Event:
    if ev.verb == Verb.DELETE:
        out = kv_pb2.Event(
            type=kv_pb2.Event.DELETE,
            kv=kv_pb2.KeyValue(key=ev.key, mod_revision=ev.revision),
        )
        if want_prev and ev.prev_value is not None:
            out.prev_kv.CopyFrom(
                kv_pb2.KeyValue(
                    key=ev.key, value=ev.prev_value,
                    mod_revision=ev.prev_revision, create_revision=ev.prev_revision,
                    version=1,
                )
            )
        return out
    out = kv_pb2.Event(
        type=kv_pb2.Event.PUT,
        kv=kv_pb2.KeyValue(
            key=ev.key, value=ev.value,
            mod_revision=ev.revision,
            create_revision=ev.revision if ev.verb == Verb.CREATE else ev.prev_revision or ev.revision,
            version=1,
        ),
    )
    if want_prev and ev.prev_value is not None:
        out.prev_kv.CopyFrom(
            kv_pb2.KeyValue(
                key=ev.key, value=ev.prev_value,
                mod_revision=ev.prev_revision, create_revision=ev.prev_revision,
                version=1,
            )
        )
    return out
