"""etcd3 Watch service: one gRPC stream = one watcher session holding many
watches, each pumping from its backend queue into the shared response stream.

Reference: pkg/server/etcd/watch.go. Protocol points kept:

- each WatchCreateRequest spawns an independent watch with its own cancel
  (watch.go:83-117);
- **negative start revision ⇒ "range stream"**: the client is asking for a
  List delivered over the watch channel (batches of PUT events at the list
  revision, then a cancel) — the custom-apiserver partition-listing trick
  (watch.go:101,150-152,204);
- a watcher whose start revision fell out of the history cache is cancelled
  with compact_revision=1, forcing the client to re-list (watch.go:174-186);
- progress requests answer with a bare header (watch_id −1).
"""

from __future__ import annotations

import queue
import threading

from ...backend import Backend, WatchExpiredError
from ...backend.watcherhub import ProgressMarker
from ...proto import rpc_pb2
from ...trace import emit_histogram
from . import shim


def events_response(batch, watch_id, want_prev, no_put, no_delete):
    """Wire WatchResponse for one event batch (None if fully filtered) —
    shared by the sync and aio pumps so the protocol can't drift."""
    from ...proto import kv_pb2

    resp = rpc_pb2.WatchResponse(
        header=shim.header(batch[-1].revision), watch_id=watch_id
    )
    for ev in batch:
        pe = shim.to_event(ev, want_prev)
        if (pe.type == kv_pb2.Event.PUT and no_put) or (
            pe.type == kv_pb2.Event.DELETE and no_delete
        ):
            continue
        resp.events.append(pe)
    return resp if resp.events else None


def dropped_response(current_revision, watch_id):
    return rpc_pb2.WatchResponse(
        header=shim.header(current_revision), watch_id=watch_id, canceled=True,
        cancel_reason="etcdserver: watcher dropped (slow consumer)",
    )


def compacted_response(current_revision, compact_revision, watch_id):
    return rpc_pb2.WatchResponse(
        header=shim.header(current_revision), watch_id=watch_id,
        created=True, canceled=True,
        compact_revision=max(compact_revision, 1),
        cancel_reason="etcdserver: mvcc: required revision has been compacted",
    )


class WatchService:
    def __init__(self, backend: Backend, peers=None, replica=None):
        self.backend = backend
        self.peers = peers
        #: follower role: watches serve from the LOCAL pipeline — the
        #: replication applier feeds the local cache + hub, so follower
        #: watchers ride the same fan-out machinery as on the leader
        self.replica = replica

    def Watch(self, request_iterator, context):
        if self.replica is not None:
            self.replica.note_served("watch")
        if self.peers is not None and not self.peers.is_leader():
            # followers serve watches from the leader's pipeline
            # (reference etcd_proxy.go:239: watch forwarding)
            forwarded = self.peers.forward_watch(request_iterator)
            if forwarded is not None:
                yield from forwarded
                return
        out: queue.Queue = queue.Queue(maxsize=1024)
        session = _WatchSession(self.backend, out, context)
        reader = threading.Thread(
            target=session.read_loop, args=(request_iterator,), daemon=True
        )
        reader.start()
        try:
            while True:
                item = out.get()
                # poisoned (a _send could not deliver in order): stop
                # BEFORE yielding, so the wire sequence stays a strict
                # PREFIX of the enqueued order — truncation is harmless
                # (clients resume from their last delivered revision),
                # an internal gap is not (docs/replication.md)
                if item is None or session.poisoned:
                    return
                yield item
        finally:
            session.close()

    def _sentinel(self):  # pragma: no cover
        pass


class _WatchSession:
    def __init__(self, backend: Backend, out: queue.Queue, context):
        self.backend = backend
        self.out = out
        self.context = context
        self._lock = threading.Lock()
        self._watches: dict[int, tuple[int, threading.Event]] = {}  # watch_id -> (hub wid, stop)
        self._next_id = 0
        self._closed = False
        #: set when a response could not be enqueued in order: the stream
        #: writer truncates at its next pop instead of delivering past an
        #: invisible gap (set-once, read without the lock)
        self.poisoned = False

    # --------------------------------------------------------------- requests
    def read_loop(self, request_iterator) -> None:
        try:
            for req in request_iterator:
                which = req.WhichOneof("request_union")
                if which == "create_request":
                    self._create(req.create_request)
                elif which == "cancel_request":
                    self._cancel(req.cancel_request.watch_id, "watch cancelled by client")
                elif which == "progress_request":
                    self._send(
                        rpc_pb2.WatchResponse(
                            header=shim.header(self.backend.current_revision()),
                            watch_id=-1,
                        )
                    )
                    # ordered per-watch progress marks (docs/replication.md):
                    # the out-of-band -1 header above can overtake event
                    # batches still in the per-watch queues, so replication
                    # watermarks ride markers through those SAME queues —
                    # a mark's revision is sound exactly because every owed
                    # event was enqueued before it
                    self._post_progress()
        except Exception:
            pass  # stream closed / client gone
        self._send(None)

    def _create(self, creq) -> None:
        with self._lock:
            self._next_id += 1
            watch_id = creq.watch_id if creq.watch_id > 0 else self._next_id
        from ..service.revision import is_list_over_watch

        if is_list_over_watch(creq.start_revision):
            # negative revision: list-over-watch range stream (watch.go:150)
            t = threading.Thread(
                target=self._range_stream, args=(creq, watch_id), daemon=True
            )
            t.start()
            return
        end = bytes(creq.range_end)
        if not end:
            end = bytes(creq.key) + b"\x00"  # single-key watch
        elif end == b"\x00":
            end = b""  # etcd convention: range_end "\0" = everything >= key
        # the created ack's header revision is read BEFORE registration: it
        # must lower-bound every event this subscription will deliver, so a
        # resume-from-ack-revision+1 client (WatchMux resume) can never
        # skip an owed event — a post-registration read races the pump
        # (docs/faults.md)
        created_rev = self.backend.current_revision()
        try:
            wid, q = self.backend.watch_range(
                bytes(creq.key), end, int(creq.start_revision)
            )
        except WatchExpiredError:
            self._send(
                compacted_response(
                    self.backend.current_revision(),
                    self.backend.compact_revision(),
                    watch_id,
                )
            )
            return
        stop = threading.Event()
        with self._lock:
            if self._closed:
                self.backend.unwatch(wid)
                return
            self._watches[watch_id] = (wid, stop)
        self._send(
            rpc_pb2.WatchResponse(
                header=shim.header(created_rev),
                watch_id=watch_id,
                created=True,
            )
        )
        no_put = rpc_pb2.WatchCreateRequest.NOPUT in creq.filters
        no_delete = rpc_pb2.WatchCreateRequest.NODELETE in creq.filters
        pump = threading.Thread(
            target=self._pump,
            args=(watch_id, wid, q, stop, bool(creq.prev_kv), no_put, no_delete,
                  bool(creq.progress_notify)),
            daemon=True,
        )
        pump.start()

    def _post_progress(self) -> None:
        """Queue a ProgressMarker for each of this session's watches at the
        sequencer's fully-flushed floor. The floor read returns -1 while
        the drainer is mid-pass — retry briefly; under sustained writes
        the events themselves advance the client's watermark, so giving up
        is only a skipped idle-time mark, never a correctness issue. A
        floor of 0 (fresh store, nothing ever written) is valid but not
        worth a mark — watermarks start at 0."""
        import time as _time

        rev = -1
        for _ in range(50):
            rev = self.backend.flushed_revision()
            if rev >= 0:
                break
            _time.sleep(0.002)
        if rev <= 0:
            return
        with self._lock:
            hub_wids = [wid for wid, _stop in self._watches.values()]
        for hw in hub_wids:
            self.backend.watcher_hub.post_progress(hw, rev)

    # ----------------------------------------------------------------- pumps
    PROGRESS_INTERVAL = 60.0  # etcd sends ~10min; apiserver only needs "periodic"

    def _pump(self, watch_id, wid, q, stop, want_prev, no_put, no_delete,
              progress_notify=False) -> None:
        import time as _time

        # lag gate: only events committed after this pump started count
        # toward the wire-lag histogram — replayed catch-up batches carry
        # their ORIGINAL commit ts (possibly minutes old) and would record
        # bogus multi-second lag on every reconnect-with-replay
        registered = _time.monotonic()
        last_sent = registered
        while not stop.is_set():
            try:
                batch = q.get(timeout=0.5)
            except queue.Empty:
                if (
                    progress_notify
                    and _time.monotonic() - last_sent >= self.PROGRESS_INTERVAL
                ):
                    # watch bookmark: bare header so the client can advance
                    # its resourceVersion without events (apiserver
                    # watchcache progress notify)
                    last_sent = _time.monotonic()
                    self._send(
                        rpc_pb2.WatchResponse(
                            header=shim.header(self.backend.current_revision()),
                            watch_id=watch_id,
                        )
                    )
                continue
            if batch is None or getattr(q, "kb_dropped", False):
                # hub dropped us (slow consumer) or backend closed: cancel
                # so the client re-watches (watcherhub.go:82-90). The
                # dropped flag is checked BEFORE every delivery so batches
                # buffered past the drop point are never sent — the
                # delivered sequence stays a prefix (the drop protocol's
                # no-invisible-gap contract, watcherhub.delete_watcher)
                self._send(dropped_response(self.backend.current_revision(), watch_id))
                self._remove(watch_id)
                return
            if isinstance(batch, ProgressMarker):
                # ordered progress mark: bare header on THIS watch id,
                # after every owed event (queue FIFO carries the proof)
                last_sent = _time.monotonic()
                self._send(
                    rpc_pb2.WatchResponse(
                        header=shim.header(batch.revision),
                        watch_id=watch_id,
                    )
                )
                continue
            resp = events_response(batch, watch_id, want_prev, no_put, no_delete)
            if resp is not None:
                last_sent = _time.monotonic()
                self._send(resp)
                if batch[0].ts >= registered:
                    # commit -> wire handoff for this watcher (the hub emits
                    # the commit -> queue point; the spread between the two
                    # is pump/backlog time)
                    emit_histogram(
                        "kb.watch.lag.seconds", last_sent - batch[0].ts,
                        point="wire",
                    )

    def _range_stream(self, creq, watch_id: int) -> None:
        """List delivered over the watch protocol (reference watcher.List,
        watch.go:204-273): PUT event batches at the snapshot revision, then a
        clean cancel."""
        from ...backend.errors import CompactedError, FutureRevisionError
        from ..service.revision import decode_list_revision

        revision = decode_list_revision(creq.start_revision)
        from ...sched import SchedOverloadError, ensure_scheduler

        try:
            rev, stream = ensure_scheduler(self.backend).list_by_stream(
                bytes(creq.key), bytes(creq.range_end), revision
            )
        except SchedOverloadError as e:
            # shed by admission control: cancel without a compact marker so
            # the client retries the same revision instead of re-listing
            self._send(
                rpc_pb2.WatchResponse(
                    header=shim.header(self.backend.current_revision()),
                    watch_id=watch_id,
                    created=True,
                    canceled=True,
                    cancel_reason=str(e),
                )
            )
            return
        except (CompactedError, FutureRevisionError) as e:
            self._send(
                rpc_pb2.WatchResponse(
                    header=shim.header(self.backend.current_revision()),
                    watch_id=watch_id,
                    created=True,
                    canceled=True,
                    compact_revision=getattr(e, "compacted", 1),
                    cancel_reason=str(e),
                )
            )
            return
        self._send(
            rpc_pb2.WatchResponse(header=shim.header(rev), watch_id=watch_id, created=True)
        )
        from ...proto import kv_pb2

        for batch in stream:
            resp = rpc_pb2.WatchResponse(header=shim.header(rev), watch_id=watch_id)
            for kv in batch:
                resp.events.append(
                    kv_pb2.Event(type=kv_pb2.Event.PUT, kv=shim.to_kv(kv))
                )
            self._send(resp)
        self._send(
            rpc_pb2.WatchResponse(
                header=shim.header(rev), watch_id=watch_id, canceled=True
            )
        )

    # -------------------------------------------------------------- plumbing
    def _cancel(self, watch_id: int, reason: str) -> None:
        self._remove(watch_id)
        self._send(
            rpc_pb2.WatchResponse(
                header=shim.header(self.backend.current_revision()),
                watch_id=watch_id,
                canceled=True,
                cancel_reason=reason,
            )
        )

    def _remove(self, watch_id: int) -> None:
        with self._lock:
            entry = self._watches.pop(watch_id, None)
        if entry:
            wid, stop = entry
            stop.set()
            self.backend.unwatch(wid)

    def _send(self, item) -> None:
        try:
            self.out.put(item, timeout=5.0)
        except queue.Full:
            # Stream writer wedged. Silently dropping one response would
            # open an invisible GAP in a delivered-in-order stream: a
            # later response (an event batch, or worse a progress mark)
            # would vouch for revisions the client never received, and a
            # resume watermark would skip them forever — the replica
            # watermark-corruption shape (docs/replication.md). Evicting
            # queued responses to fit a pill is just as gappy (the
            # consumer races the eviction and can deliver a newer queued
            # response after an older one was discarded). Poison the
            # session instead: the stream writer truncates BEFORE its
            # next delivery, so the wire sequence is a strict prefix of
            # the enqueued order — the client sees the stream end and
            # resumes from its last delivered revision, losing nothing.
            self.poisoned = True
            self.close()

    def close(self) -> None:
        with self._lock:
            self._closed = True
            entries = list(self._watches.values())
            self._watches.clear()
        for wid, stop in entries:
            stop.set()
            self.backend.unwatch(wid)
