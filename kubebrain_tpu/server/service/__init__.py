"""Peer plumbing (reference pkg/server/service): leader election wrapper,
follower→leader revision sync, etcd-proxy write forwarding."""

from .peer import PeerService, SingleNodePeerService

__all__ = ["PeerService", "SingleNodePeerService"]
