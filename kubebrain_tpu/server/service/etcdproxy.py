"""Follower→leader write forwarding over the etcd3 protocol.

Reference: pkg/server/service/etcdproxy/etcd_proxy.go — community
kube-apiserver load-balances writes across all replicas, but only the leader
can write; followers therefore proxy Txn (and Watch) to the leader's client
port. The reference keeps an etcd clientv3 pointed at the leader with a 1s
leader-change check loop (etcd_proxy.go:71-79); here a raw grpc channel
speaks the same etcdserverpb methods, re-dialed when the leader moves.
"""

from __future__ import annotations

import threading
from typing import Callable

import grpc

from ...proto import rpc_pb2

PROXY_TIMEOUT_SECONDS = 5.0


class EtcdProxy:
    def __init__(self, get_leader_client_address: Callable[[], str | None]):
        self._get_leader = get_leader_client_address
        self._lock = threading.Lock()
        self._channel: grpc.Channel | None = None
        self._target: str | None = None

    def _stub(self):
        target = self._get_leader()
        if not target:
            return None
        with self._lock:
            if target != self._target:
                if self._channel is not None:
                    self._channel.close()
                self._channel = grpc.insecure_channel(target)
                self._target = target
            return self._channel.unary_unary(
                "/etcdserverpb.KV/Txn",
                request_serializer=rpc_pb2.TxnRequest.SerializeToString,
                response_deserializer=rpc_pb2.TxnResponse.FromString,
            )

    def forward_txn(self, request: rpc_pb2.TxnRequest) -> rpc_pb2.TxnResponse | None:
        call = self._stub()
        if call is None:
            return None
        try:
            return call(request, timeout=PROXY_TIMEOUT_SECONDS)
        except grpc.RpcError:
            return None

    def forward_watch(self, request_iterator):
        """Pipe a whole Watch stream through the leader (reference
        etcd_proxy.go:239-288); returns a response iterator or None."""
        target = self._get_leader()
        if not target:
            return None
        with self._lock:
            if target != self._target:
                if self._channel is not None:
                    self._channel.close()
                self._channel = grpc.insecure_channel(target)
                self._target = target
            stream = self._channel.stream_stream(
                "/etcdserverpb.Watch/Watch",
                request_serializer=rpc_pb2.WatchRequest.SerializeToString,
                response_deserializer=rpc_pb2.WatchResponse.FromString,
            )
        return stream(request_iterator)

    def close(self) -> None:
        with self._lock:
            if self._channel is not None:
                self._channel.close()
                self._channel = None
                self._target = None


class DisabledEtcdProxy:
    """No-op when --enable-etcd-proxy is off (reference etcdproxy/disabled.go)."""

    def forward_txn(self, request):  # noqa: ARG002
        return None

    def forward_watch(self, request_iterator):  # noqa: ARG002
        return None

    def close(self) -> None:
        pass
