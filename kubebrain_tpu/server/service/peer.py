"""PeerService — the composite the RPC terminals depend on.

Reference: pkg/server/service/peer_service.go:28-68
(PeerService = RevisionSyncer + LeaderElection + EtcdProxy). Identity format
is "host:peerPort" (cmd/option/option.go:234-238); the leader's client port
(for proxying) is derived by swapping the port.
"""

from __future__ import annotations

from ...backend import Backend
from ...backend.election import LeaderElection, ResourceLock, StubLeaderElection
from .etcdproxy import DisabledEtcdProxy, EtcdProxy
from .revision import HttpRevisionSyncer, RevisionSyncError


class PeerService:
    def __init__(
        self,
        backend: Backend,
        identity: str,
        client_port: int,
        enable_proxy: bool = False,
        on_leader_change=None,
    ):
        self.backend = backend
        self.identity = identity
        self._client_port = client_port
        host = identity.rsplit(":", 1)[0]
        self.election = LeaderElection(
            ResourceLock(
                backend.store, identity,
                meta={"client": f"{host}:{client_port}"},
            ),
            on_started_leading=self._on_started_leading,
            # default: reset the term (drop watchers, poison the scan
            # mirror) — the reference's panic-on-leader-loss contract
            on_stopped_leading=on_leader_change or backend.reset_term,
        )
        self.syncer = HttpRevisionSyncer(self.leader_peer_address, backend.set_current_revision)
        self.proxy = EtcdProxy(self.leader_client_address) if enable_proxy else DisabledEtcdProxy()

    REVISION_GUARD = 1000  # headroom for revisions dealt-but-unpersisted by a crashed leader

    def _on_started_leading(self, start_revision: int) -> None:
        """Seed the revision sequencer on taking leadership (reference
        leader.go:96-107 → backend.SetCurrentRevision): the max of the lock
        record's engine clock, the persisted last-committed-revision
        watermark, and our local view — plus a guard so revisions a crashed
        leader dealt to *failed* ops (never persisted anywhere) cannot be
        re-dealt in the new term."""
        seed = max(
            start_revision,
            self.backend.recover_revision(),
            self.backend.current_revision(),
        )
        self.backend.set_current_revision(seed + self.REVISION_GUARD)

    # -------------------------------------------------------------- addresses
    def leader_peer_address(self) -> str | None:
        if self.election.is_leader():
            return self.identity
        return self.election.leader_identity()

    def leader_client_address(self) -> str | None:
        """The leader's client (gRPC) address, published in the election
        record meta; falls back to swapping the peer port for same-config
        deployments."""
        if self.election.is_leader():
            host = self.identity.rsplit(":", 1)[0]
            return f"{host}:{self._client_port}"
        rec = self.election._lock.get()
        import time as _time

        if rec is None or rec.expired(_time.time()):
            return None
        if rec.meta and rec.meta.get("client"):
            return rec.meta["client"]
        host = rec.holder.rsplit(":", 1)[0]
        return f"{host}:{self._client_port}"

    # ------------------------------------------------------------- contract
    def is_leader(self) -> bool:
        return self.election.is_leader()

    def campaign(self) -> None:
        self.election.campaign()

    def sync_read_revision(self) -> None:
        """Followers sync the read revision from the leader before every read
        (reference revision.go:114-128, read.go:128); failure fails the read."""
        if self.election.is_leader():
            return
        self.syncer.sync()

    def forward_txn(self, request):
        return self.proxy.forward_txn(request)

    def forward_watch(self, request_iterator):
        return self.proxy.forward_watch(request_iterator)

    def close(self) -> None:
        self.election.close()
        self.proxy.close()


class SingleNodePeerService:
    """Always-leader, no peers (stub election, reference leader/stub.go)."""

    def __init__(self, backend: Backend, identity: str = "local"):
        self.backend = backend
        self.identity = identity
        self.election = StubLeaderElection(identity)

    def is_leader(self) -> bool:
        return True

    def campaign(self) -> None:
        pass

    def sync_read_revision(self) -> None:
        pass

    def forward_txn(self, request):  # noqa: ARG002
        return None

    def forward_watch(self, request_iterator):  # noqa: ARG002
        return None

    def leader_peer_address(self) -> str:
        return self.identity

    def close(self) -> None:
        pass


__all__ = ["PeerService", "SingleNodePeerService", "RevisionSyncError"]
