"""PeerService — the composite the RPC terminals depend on.

Reference: pkg/server/service/peer_service.go:28-68
(PeerService = RevisionSyncer + LeaderElection + EtcdProxy). Identity format
is "host:peerPort" (cmd/option/option.go:234-238); the leader's client port
(for proxying) is derived by swapping the port.
"""

from __future__ import annotations

from ...backend import Backend
from ...backend.election import LeaderElection, ResourceLock, StubLeaderElection
from .etcdproxy import DisabledEtcdProxy, EtcdProxy
from .revision import HttpRevisionSyncer, RevisionSyncError


class PeerService:
    def __init__(
        self,
        backend: Backend,
        identity: str,
        client_port: int,
        enable_proxy: bool = False,
        on_leader_change=None,
    ):
        self.backend = backend
        self.identity = identity
        self._client_port = client_port
        self.election = LeaderElection(
            ResourceLock(backend.store, identity),
            on_started_leading=self._on_started_leading,
            on_stopped_leading=on_leader_change,
        )
        self.syncer = HttpRevisionSyncer(self.leader_peer_address, backend.set_current_revision)
        self.proxy = EtcdProxy(self.leader_client_address) if enable_proxy else DisabledEtcdProxy()

    def _on_started_leading(self, start_revision: int) -> None:
        """Seed the revision sequencer from the lock record's engine clock
        (reference leader.go:96-107 → backend.SetCurrentRevision)."""
        self.backend.set_current_revision(max(start_revision, self.backend.current_revision()))

    # -------------------------------------------------------------- addresses
    def leader_peer_address(self) -> str | None:
        if self.election.is_leader():
            return self.identity
        return self.election.leader_identity()

    def leader_client_address(self) -> str | None:
        peer = self.leader_peer_address()
        if not peer:
            return None
        host = peer.rsplit(":", 1)[0]
        return f"{host}:{self._client_port}"

    # ------------------------------------------------------------- contract
    def is_leader(self) -> bool:
        return self.election.is_leader()

    def campaign(self) -> None:
        self.election.campaign()

    def sync_read_revision(self) -> None:
        """Followers sync the read revision from the leader before every read
        (reference revision.go:114-128, read.go:128); failure fails the read."""
        if self.election.is_leader():
            return
        self.syncer.sync()

    def forward_txn(self, request):
        return self.proxy.forward_txn(request)

    def close(self) -> None:
        self.election.close()
        self.proxy.close()


class SingleNodePeerService:
    """Always-leader, no peers (stub election, reference leader/stub.go)."""

    def __init__(self, backend: Backend, identity: str = "local"):
        self.backend = backend
        self.identity = identity
        self.election = StubLeaderElection(identity)

    def is_leader(self) -> bool:
        return True

    def campaign(self) -> None:
        pass

    def sync_read_revision(self) -> None:
        pass

    def forward_txn(self, request):  # noqa: ARG002
        return None

    def leader_peer_address(self) -> str:
        return self.identity

    def close(self) -> None:
        pass


__all__ = ["PeerService", "SingleNodePeerService", "RevisionSyncError"]
