"""Follower→leader read-revision sync.

Reference: pkg/server/service/revision/revision.go — a follower cannot serve
reads from its stale local revision: before each read it HTTP-GETs the
leader's ``/status`` endpoint (which reports the committed revision,
server/server.go:151-165), deduplicated through a singleflight so a burst of
reads costs one round-trip (revision.go:114-128), with http/https schema
auto-probing (revision.go:142-209).
"""

from __future__ import annotations

import json
import threading
import urllib.request
from typing import Callable

SYNC_TIMEOUT_SECONDS = 1.0


# ---------------------------------------------------------------------------
# Revision-value helpers. Revisions are opaque monotonic tokens minted by
# the sequencer; every transformation the serving surface needs lives here
# so the etcd shim never invents revisions by raw arithmetic (kblint KB105
# enforces this over server/etcd/).

def is_list_over_watch(start_revision: int) -> bool:
    """Whether a WatchCreateRequest start_revision selects the
    list-over-watch protocol (negative = 'stream me a list')."""
    return int(start_revision) < 0


def decode_list_revision(start_revision: int) -> int:
    """The list revision a negative list-over-watch start_revision encodes
    (the protocol ships ``-rev``; 0 means 'latest')."""
    return -int(start_revision)


class RevisionSyncError(Exception):
    pass


class HttpRevisionSyncer:
    def __init__(
        self,
        get_leader_address: Callable[[], str | None],
        set_revision: Callable[[int], None],
        timeout: float = SYNC_TIMEOUT_SECONDS,
    ):
        self._get_leader_address = get_leader_address
        self._set_revision = set_revision
        self._timeout = timeout
        self._schema_cache: dict[str, str] = {}  # address -> working schema
        # singleflight: one in-flight sync; followers pile onto its result
        self._flight_lock = threading.Lock()
        self._flight: threading.Event | None = None
        self._flight_result: tuple[int | None, BaseException | None] = (None, None)

    def sync(self) -> int:
        """Fetch the leader revision and apply it locally; singleflighted."""
        with self._flight_lock:
            flight = self._flight
            if flight is None:
                flight = self._flight = threading.Event()
                owner = True
            else:
                owner = False
        if not owner:
            flight.wait(self._timeout * 2)
            rev, err = self._flight_result
            if err is not None:
                raise RevisionSyncError(str(err))
            if rev is None:
                raise RevisionSyncError("sync timed out")
            return rev
        try:
            rev = self._fetch()
            self._set_revision(rev)
            self._flight_result = (rev, None)
            return rev
        except BaseException as e:
            self._flight_result = (None, e)
            raise RevisionSyncError(str(e)) from e
        finally:
            with self._flight_lock:
                self._flight = None
            flight.set()

    def _fetch(self) -> int:
        return int(self.fetch_status()["revision"])

    def fetch_status(self) -> dict:
        """The leader's full /status payload, with http/https schema
        auto-probing + per-address caching — the ONE transport for every
        leader-status consumer (the follower fence and the replication
        stream's compact sync share it; docs/replication.md)."""
        address = self._get_leader_address()
        if not address:
            raise RevisionSyncError("no leader")
        schemas = [self._schema_cache.get(address)] if address in self._schema_cache else []
        schemas += [s for s in ("http", "https") if s not in schemas]
        last_err: BaseException | None = None
        for schema in schemas:
            if schema is None:
                continue
            try:
                payload = self._fetch_one(f"{schema}://{address}/status")
                self._schema_cache[address] = schema
                return payload
            except BaseException as e:  # wrong schema / transient: try next
                last_err = e
        raise RevisionSyncError(f"leader /status unreachable: {last_err}")

    def _fetch_one(self, url: str) -> dict:
        import ssl

        ctx = None
        if url.startswith("https"):
            ctx = ssl.create_default_context()
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE  # peer identity comes from the lock record
        with urllib.request.urlopen(url, timeout=self._timeout, context=ctx) as resp:
            return json.loads(resp.read().decode())
