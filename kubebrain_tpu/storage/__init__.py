"""The pluggable key-value engine contract.

Reference: pkg/storage/interface.go:28-156 and docs/storage_engine.md:3-15.
An engine must provide: a logical clock (timestamp oracle), a shard map
(partitions), snapshot point reads, bidirectional snapshot range iteration,
and atomic conditional write batches whose commit can report *uncertainty*.
MVCC (revisions, tombstones, watch) is built entirely above this contract by
``kubebrain_tpu.backend``; the engine only ever sees opaque internal keys.

Engines shipped:

- ``memkv``   — in-memory versioned sorted map, the test fake
                (reference pkg/storage/memkv).
- ``native``  — C++ host block manager via cffi (reference's Badger role).
- ``tpu``     — the ``native``/host engine plus an HBM-mirrored sorted block
                store; bulk scans/counts/compaction masks run as JAX/Pallas
                kernels sharded over the device mesh (reference's TiKV role,
                re-imagined for TPU).
- ``metrics`` — decorator timing every engine op
                (reference pkg/storage/metrics/store.go).
"""

from __future__ import annotations

import abc
from dataclasses import dataclass
from typing import Callable, Iterator

from .errors import (
    CASFailedError,
    Conflict,
    KeyNotFoundError,
    StorageError,
    UncertainResultError,
)

__all__ = [
    "Partition",
    "BatchWrite",
    "Iter",
    "KvStorage",
    "Conflict",
    "CASFailedError",
    "KeyNotFoundError",
    "UncertainResultError",
    "StorageError",
    "new_storage",
]


@dataclass(frozen=True)
class Partition:
    """A contiguous key-range shard [left, right) of the engine's key space.

    Reference: pkg/storage/interface.go:150. For distributed engines these are
    real placement shards (TiKV regions); for the TPU engine they are device
    block ranges, so mesh sharding mirrors storage sharding (SURVEY §2.10).
    An empty ``right`` means "unbounded above".
    """

    left: bytes
    right: bytes


class Iter(abc.ABC):
    """Streaming snapshot iterator over [start, end).

    Reference: pkg/storage/interface.go:125. Iteration is *reverse* when the
    constructor received start > end (used by the point-get path,
    pkg/backend/range.go:83-121).
    """

    @abc.abstractmethod
    def next(self) -> tuple[bytes, bytes]:
        """Return the next (key, value); raise StopIteration when drained."""

    def __iter__(self) -> Iterator[tuple[bytes, bytes]]:
        while True:
            try:
                yield self.next()
            except StopIteration:
                return

    def close(self) -> None:  # pragma: no cover - default no-op
        pass


class BatchWrite(abc.ABC):
    """An atomic conditional write batch.

    Reference: pkg/storage/interface.go:81-123. Ops are recorded in order;
    ``commit`` applies all-or-nothing. Conditional ops that lose raise
    ``CASFailedError`` carrying a ``Conflict`` (index + observed value).
    ``commit`` raises ``UncertainResultError`` when the outcome is unknowable
    (interface.go:104) — the caller must treat the write as *maybe applied*.
    """

    @abc.abstractmethod
    def put_if_not_exist(self, key: bytes, value: bytes, ttl_seconds: int = 0) -> None: ...

    @abc.abstractmethod
    def cas(self, key: bytes, new_value: bytes, old_value: bytes, ttl_seconds: int = 0) -> None: ...

    @abc.abstractmethod
    def put(self, key: bytes, value: bytes, ttl_seconds: int = 0) -> None: ...

    @abc.abstractmethod
    def delete(self, key: bytes) -> None: ...

    @abc.abstractmethod
    def del_current(self, key: bytes, expected_value: bytes) -> None:
        """Delete ``key`` only if its current value equals ``expected_value``
        (reference DelCurrent — delete-if-unchanged)."""

    @abc.abstractmethod
    def commit(self) -> None: ...


class KvStorage(abc.ABC):
    """The engine contract (reference KvStorage, pkg/storage/interface.go:34).

    Requirements (docs/storage_engine.md:3-15): snapshot reads, bidirectional
    traversal, CAS write transactions, an exposed logical clock; snapshot
    isolation and linearizable writes.
    """

    @abc.abstractmethod
    def get_timestamp_oracle(self) -> int:
        """Current logical clock; any snapshot_ts <= this is a valid snapshot."""

    @abc.abstractmethod
    def get_partitions(self, start: bytes, end: bytes) -> list[Partition]:
        """Shard map of [start, end), clamped to the range. Never empty."""

    @abc.abstractmethod
    def get(self, key: bytes, snapshot_ts: int | None = None) -> bytes:
        """Point read at a snapshot (latest when None). KeyNotFoundError on miss."""

    @abc.abstractmethod
    def iter(
        self,
        start: bytes,
        end: bytes,
        snapshot_ts: int | None = None,
        limit: int = 0,
    ) -> Iter:
        """Range iterator at a snapshot; reverse iteration when start > end."""

    @abc.abstractmethod
    def begin_batch_write(self) -> BatchWrite: ...

    def delete(self, key: bytes) -> None:
        """Unconditional single delete (reference KvStorage.Del)."""
        b = self.begin_batch_write()
        b.delete(key)
        b.commit()

    def del_current(self, key: bytes, expected_value: bytes) -> None:
        """Single delete-if-unchanged (reference KvStorage.DelCurrent)."""
        b = self.begin_batch_write()
        b.del_current(key, expected_value)
        b.commit()

    def support_ttl(self) -> bool:
        """Whether the engine expires TTL'd entries natively.

        Reference: badger.go:48 returns True, TiKV/memkv False — when False the
        compaction path expires ``/events/`` keys itself (scanner.go:566-591).
        """
        return False

    def exclusive_client(self) -> "KvStorage":
        """An isolated handle for bulk maintenance (compaction) so GC I/O does
        not contend with serving traffic. Reference: ExclusiveKvStorage,
        pkg/storage/interface.go:28-31. Default: self."""
        return self

    def make_scanner(self, **kwargs):
        """Engines that bring their own scan offload (the ``tpu`` engine)
        return a backend Scanner here; None selects the generic iterator
        scanner. Mirrors how the reference picks partition-parallel scan
        behavior from the engine's GetPartitions shape."""
        return None

    def close(self) -> None:  # pragma: no cover - default no-op
        pass


_FACTORIES: dict[str, Callable[..., KvStorage]] = {}


def unwrap_store(store, attr: str):
    """Walk a decorator stack (metrics → tpu mirror → …) down ``_inner``
    links until a layer offering ``attr`` appears; cycle-safe. Returns None
    when no layer has it. Shared by the admin surfaces (Defragment,
    /tier/failover) so the unwrap rule cannot diverge."""
    seen: set = set()
    while store is not None and id(store) not in seen:
        seen.add(id(store))
        if hasattr(store, attr):
            return store
        store = getattr(store, "_inner", None)
    return None


def register_engine(name: str, factory: Callable[..., KvStorage]) -> None:
    _FACTORIES[name] = factory


def new_storage(name: str, **kwargs) -> KvStorage:
    """Runtime engine selection — replaces the reference's compile-time Go
    build tags (cmd/option/option_badger.go:15 vs option_tikv.go:62)."""
    if name not in _FACTORIES:
        # Lazy-import shipped engines so `new_storage` works without callers
        # importing the adapter modules first.
        if name == "memkv":
            from . import memkv  # noqa: F401
        elif name == "tpu":
            from . import tpu  # noqa: F401
        elif name == "native":
            from . import native  # noqa: F401
        elif name == "remote":
            from . import remote  # noqa: F401
    if name not in _FACTORIES:
        raise ValueError(f"unknown storage engine {name!r}; have {sorted(_FACTORIES)}")
    return _FACTORIES[name](**kwargs)
