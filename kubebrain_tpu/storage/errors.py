"""Storage error taxonomy that drives MVCC control flow.

Reference: pkg/storage/errors.go:23-75. Three errors matter to the layers
above the engine:

- ``KeyNotFoundError`` — point get missed.
- ``CASFailedError`` — a conditional write (PutIfNotExist / CAS / DelCurrent)
  lost a race. It carries a ``Conflict`` with the index of the failing op and
  the value the engine observed, so the caller can skip a re-read (reference
  Conflict{Idx,Key,Val}, errors.go:47-75 — used by the create→update
  conversion in creator/naive.go:62-86).
- ``UncertainResultError`` — the engine cannot know whether the batch
  committed (e.g. a commit-phase timeout in a distributed engine). The write
  path must neither confirm nor deny; the async FIFO retry repairs it later
  (reference pkg/backend/retry/).
"""

from __future__ import annotations

from dataclasses import dataclass


class StorageError(Exception):
    pass


class KeyNotFoundError(StorageError):
    def __init__(self, key: bytes = b""):
        super().__init__(f"key not found: {key!r}")
        self.key = key


@dataclass
class Conflict:
    """Details of a failed conditional op inside a batch.

    ``index`` is the position of the op in the batch; ``value`` is the value
    the engine saw for ``key`` at conflict time (None if the key was absent),
    letting callers avoid a follow-up read.
    """

    index: int
    key: bytes
    value: bytes | None


class CASFailedError(StorageError):
    def __init__(self, conflict: Conflict | None = None):
        super().__init__(f"cas failed: {conflict}")
        self.conflict = conflict


class UncertainResultError(StorageError):
    """Commit outcome unknowable; see reference storage/errors.go:23-45."""

    def __init__(self, cause: BaseException | str = ""):
        super().__init__(f"uncertain result: {cause}")
        self.cause = cause


class RevisionDriftBackError(StorageError):
    """The revision sequencer observed time going backwards: the engine saw
    a record at ``latest`` >= the op's dealt revision (0 = unreported).

    Reference: pkg/backend/backend.go:188-199 (ErrRevisionDriftBack).
    """

    def __init__(self, message: str = "revision drift", latest: int = 0):
        super().__init__(message)
        self.latest = latest


class InvalidArgumentError(StorageError):
    pass


class TimeoutError_(StorageError):
    pass
