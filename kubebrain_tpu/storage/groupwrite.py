"""Shared group-commit executor over an engine's one-call MVCC fast paths.

The group-commit engine contract (``write_batch``; docs/writes.md) wants
one engine round trip per write GROUP. Engines whose primitives already
collapse a whole MVCC write into one call (``mvcc_write`` /
``mvcc_delete`` — the native C store via FFI, the kbstored tier via its
wire protocol) get a correct ``write_batch`` from this module: a loop of
those one-call primitives with the per-op conditional outcomes demuxed
into the shared outcome tuples. The group still wins everything above the
engine (one scheduler dispatch, one contiguous revision block, one ring
pass); the engine round trips stay per-op until the engine grows a native
grouped op (the C/wire framing is future work — the loop IS the
documented fallback shape).

Outcome vocabulary (aligned with ``ops``):

- create/update: ``("ok",)`` | ``("conflict", observed_record)`` |
  ``("drift", latest_rev)``;
- delete: ``("ok", prev, latest)`` | ``("not_found", None, latest)`` |
  ``("mismatch", prev, latest)`` | ``("drift", latest)``;
- any op: ``("uncertain", exc)`` (maybe-applied — the caller poisons the
  mirror / routes to the retry daemon) or ``("error", exc)``.

The create op carries the creator's tombstone-conversion semantics
(backend/creator.py, naive.go:53-98): put-if-not-exist, and on conflict
with a LOWER-revision tombstone a CAS over the observed record — with the
lost-race branches mapped to the same drift/conflict outcomes the
sequential creator raises.
"""

from __future__ import annotations

from .. import coder
from .errors import (
    CASFailedError,
    RevisionDriftBackError,
    StorageError,
    UncertainResultError,
)


def mvcc_write_batch(store, ops: list) -> list:
    """Execute the engine-level write-group ``ops`` via ``store``'s
    ``mvcc_write`` / ``mvcc_delete`` fast paths, one outcome per op.
    Ops apply strictly in order; a failed op never blocks later ones."""
    out: list = []
    for op in ops:
        kind = op[0]
        try:
            if kind == "create":
                out.append(_create(store, op))
            elif kind == "update":
                out.append(_update(store, op))
            elif kind == "delete":
                out.append(store.mvcc_delete(*op[1:]))
            else:
                out.append(("error", ValueError(f"bad op kind {kind!r}")))
        except RevisionDriftBackError as e:
            out.append(("drift", e.latest))
        except UncertainResultError as e:
            out.append(("uncertain", e))
        except StorageError as e:
            out.append(("error", e))
    return out


def _update(store, op) -> tuple:
    _, rev_key, rev_val, expected, obj_key, obj_val, last_key, last_val, ttl = op
    try:
        store.mvcc_write(rev_key, rev_val, expected, obj_key, obj_val,
                         last_key, last_val, ttl)
        return ("ok",)
    except CASFailedError as e:
        return ("conflict", e.conflict.value if e.conflict else None)


def _create(store, op) -> tuple:
    _, rev_key, new_rev, rev_val, obj_key, obj_val, last_key, last_val, ttl = op
    for _attempt in range(2):
        try:
            store.mvcc_write(rev_key, rev_val, None, obj_key, obj_val,
                             last_key, last_val, ttl)
            return ("ok",)
        except CASFailedError as e:
            observed = e.conflict.value if e.conflict else None
            if observed is None:
                continue  # record vanished under us (compacted delete): retry
            try:
                old_rev, deleted = coder.decode_rev_value(observed)
            except coder.CodecError:
                return ("conflict", observed)
            if not deleted:
                return ("conflict", observed)
            if old_rev >= new_rev:
                # tombstone from a racing delete with a same-or-newer
                # revision: drift-back, definite + retryable (creator.py)
                return ("drift", old_rev)
            try:
                # deleted key: create becomes an update over the tombstone
                store.mvcc_write(rev_key, rev_val, observed, obj_key, obj_val,
                                 last_key, last_val, ttl)
                return ("ok",)
            except CASFailedError as e2:
                observed2 = e2.conflict.value if e2.conflict else None
                if observed2 is None:
                    return ("drift", -1)  # unknown winner: watermark fence
                try:
                    rev2, del2 = coder.decode_rev_value(observed2)
                except coder.CodecError:
                    return ("conflict", None)
                if not del2:
                    return ("conflict", observed2)
                return ("drift", rev2)
    return ("conflict", None)
