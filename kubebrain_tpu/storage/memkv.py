"""In-memory versioned sorted-map engine — the deterministic test fake.

Reference: pkg/storage/memkv (skiplist.go:30, batch.go, iter.go). Differences
by design:

- The logical clock is a commit counter, not wall-clock ns (skiplist.go:57) —
  deterministic tests.
- Snapshot isolation is real: every committed batch gets one timestamp and
  every key keeps its version history, so an ``iter`` at snapshot S never
  observes a commit > S (the reference fakes this with a whole-store mutex
  held across the batch, skiplist.go:82-85).
- Partitions are configurable via ``split_points`` so partition-parallel scans
  and border adjustment are testable without a distributed engine — the role
  the mock TiKV cluster plays in the reference tests (backend_test.go:171-178).
"""

from __future__ import annotations

import bisect
import threading
import time

from . import BatchWrite, Iter, KvStorage, Partition, register_engine
from .errors import CASFailedError, Conflict, KeyNotFoundError

_PUT_IF_NOT_EXIST = 0
_CAS = 1
_PUT = 2
_DEL = 3
_DEL_CURRENT = 4


class _Version:
    __slots__ = ("ts", "value", "expire_at")

    def __init__(self, ts: int, value: bytes | None, expire_at: float):
        self.ts = ts
        self.value = value  # None == engine-level deletion
        self.expire_at = expire_at  # 0.0 == no TTL


class MemKv(KvStorage):
    def __init__(
        self,
        split_points: list[bytes] | None = None,
        ttl_supported: bool = True,
    ):
        self._lock = threading.RLock()
        self._keys: list[bytes] = []  # sorted index of every key ever written
        self._versions: dict[bytes, list[_Version]] = {}
        self._ts = 0
        self._split_points = sorted(split_points or [])
        self._ttl_supported = ttl_supported

    # ------------------------------------------------------------- clock/shards
    def get_timestamp_oracle(self) -> int:
        with self._lock:
            return self._ts

    def get_partitions(self, start: bytes, end: bytes) -> list[Partition]:
        borders = [start]
        for sp in self._split_points:
            if start < sp and (not end or sp < end):
                borders.append(sp)
        borders.append(end)
        return [Partition(borders[i], borders[i + 1]) for i in range(len(borders) - 1)]

    # ------------------------------------------------------------------- reads
    def _live_value(self, key: bytes, snapshot_ts: int | None, now: float) -> bytes | None:
        """Latest value at the snapshot, honoring TTL; None if absent/deleted."""
        versions = self._versions.get(key)
        if not versions:
            return None
        ts = self._ts if snapshot_ts is None else snapshot_ts
        for v in reversed(versions):
            if v.ts <= ts:
                if v.value is None:
                    return None
                if self._ttl_supported and v.expire_at and now >= v.expire_at:
                    return None
                return v.value
        return None

    def get(self, key: bytes, snapshot_ts: int | None = None) -> bytes:
        with self._lock:
            val = self._live_value(key, snapshot_ts, time.time())
            if val is None:
                raise KeyNotFoundError(key)
            return val

    def iter(
        self,
        start: bytes,
        end: bytes,
        snapshot_ts: int | None = None,
        limit: int = 0,
    ) -> Iter:
        reverse = bool(end) and start > end
        with self._lock:
            now = time.time()
            ts = self._ts if snapshot_ts is None else snapshot_ts
        return _LazyIter(self, start, end, ts, now, limit, reverse)

    # ------------------------------------------------------------------ writes
    def begin_batch_write(self) -> BatchWrite:
        return _MemBatch(self)

    def write_batch(self, ops: list) -> list:
        """Grouped MVCC commit under ONE store-lock acquisition with per-op
        conditional demux (the group-commit engine contract,
        docs/writes.md). ``ops`` is a list of

        - ``("create", rev_key, new_rev, rev_val, obj_key, obj_val,
          last_key, last_val, ttl)``
        - ``("update", rev_key, rev_val, expected, obj_key, obj_val,
          last_key, last_val, ttl)``
        - ``("delete", rev_key, expected_rev, new_rev, new_record,
          tombstone, last_key, last_val)``

        Each op validates against the state as mutated by earlier ops in
        the SAME group and either applies atomically (its own commit
        timestamp, exactly like a sequential batch commit) or fails alone.
        Outcomes, aligned with ``ops``:

        - create/update: ``("ok",)`` or ``("conflict", observed_record)``
          or ``("drift", latest_rev)`` (create over a same-or-newer
          tombstone);
        - delete: the ``mvcc_delete`` quadruple —
          ``("ok", prev_value, latest_rev)`` / ``("not_found", None,
          latest_rev)`` / ``("mismatch", prev_value, latest_rev)`` /
          ``("drift", latest_rev)``.

        The create op resolves the creator's tombstone-conversion branch
        in-engine (naive.go:83-86): under the store lock there is no
        read-then-CAS race, so the two-attempt loop collapses to a branch.
        Record parsing uses the shared MVCC codec — the same format the
        native engine's C `kb_mvcc_delete` parses."""
        from .. import coder

        out: list = []
        with self._lock:
            now = time.time()
            for op in ops:
                kind = op[0]
                if kind == "create":
                    out.append(self._wb_create(op, now, coder))
                elif kind == "update":
                    out.append(self._wb_update(op, now))
                elif kind == "delete":
                    out.append(self._wb_delete(op, now, coder))
                else:
                    out.append(("error", ValueError(f"bad op kind {kind!r}")))
        return out

    def _wb_apply(self, puts: list[tuple[bytes, bytes, int]], now: float) -> None:
        """One successful group member = one commit timestamp (identical to
        a sequential ``begin_batch_write().commit()``); TTL is per row —
        the record and object rows carry the member's TTL, the watermark
        row never does, exactly like ``Backend._commit_write``."""
        self._ts += 1
        for key, value, ttl in puts:
            expire_at = now + ttl if ttl else 0.0
            self._append(key, _Version(self._ts, value, expire_at))

    def _wb_create(self, op, now: float, coder):
        _, rev_key, new_rev, rev_val, obj_key, obj_val, last_key, last_val, ttl = op
        cur = self._live_value(rev_key, None, now)
        if cur is not None:
            try:
                old_rev, deleted = coder.decode_rev_value(cur)
            except coder.CodecError:
                return ("conflict", cur)
            if not deleted:
                return ("conflict", cur)
            if old_rev >= new_rev:
                return ("drift", old_rev)
            # deleted at a lower revision: create becomes an update over the
            # tombstone (creator conversion, resolved in-engine)
        self._wb_apply([(rev_key, rev_val, ttl), (obj_key, obj_val, ttl),
                        (last_key, last_val, 0)], now)
        return ("ok",)

    def _wb_update(self, op, now: float):
        _, rev_key, rev_val, expected, obj_key, obj_val, last_key, last_val, ttl = op
        cur = self._live_value(rev_key, None, now)
        if cur != expected:
            return ("conflict", cur)
        self._wb_apply([(rev_key, rev_val, ttl), (obj_key, obj_val, ttl),
                        (last_key, last_val, 0)], now)
        return ("ok",)

    def _wb_delete(self, op, now: float, coder):
        _, rev_key, expected_rev, new_rev, new_record, tombstone, last_key, last_val = op
        cur = self._live_value(rev_key, None, now)
        if cur is None:
            return ("not_found", None, 0)
        try:
            latest, deleted = coder.decode_rev_value(cur)
        except coder.CodecError:
            return ("not_found", None, 0)
        if deleted:
            return ("not_found", None, latest)
        ukey, _ = coder.decode(rev_key)
        prev = self._live_value(coder.encode_object_key(ukey, latest), None, now)
        if expected_rev and latest != expected_rev:
            return ("mismatch", prev, latest)
        if new_rev <= latest:
            return ("drift", latest)
        self._wb_apply([(rev_key, new_record, 0),
                        (coder.encode_object_key(ukey, new_rev), tombstone, 0),
                        (last_key, last_val, 0)], now)
        return ("ok", prev, latest)

    def mvcc_delete(self, rev_key: bytes, expected_rev: int, new_rev: int,
                    new_record: bytes, tombstone: bytes, last_key: bytes,
                    last_val: bytes) -> tuple:
        """One-call read-validate-tombstone delete (the native engine's
        ``kb_mvcc_delete`` contract) — the sequential delete then takes
        ``Backend._delete_fast``, where a failed delete consumes its dealt
        revision exactly like a failed group member, so grouped and
        sequential revision streams stay byte-identical on this engine."""
        from .. import coder
        from .errors import RevisionDriftBackError

        with self._lock:
            out = self._wb_delete(
                ("delete", rev_key, expected_rev, new_rev, new_record,
                 tombstone, last_key, last_val), time.time(), coder)
        if out[0] == "drift":
            raise RevisionDriftBackError(
                f"revision drift on delete (latest {out[1]})", latest=out[1])
        return out

    def _commit(self, ops: list[tuple]) -> None:
        with self._lock:
            now = time.time()
            # Validate all conditional ops against latest state first
            # (all-or-nothing; reference memkv serializes batches under the
            # store mutex, batch.go:146-167).
            for idx, op in enumerate(ops):
                kind, key = op[0], op[1]
                cur = self._live_value(key, None, now)
                if kind == _PUT_IF_NOT_EXIST and cur is not None:
                    raise CASFailedError(Conflict(idx, key, cur))
                if kind == _CAS and cur != op[3]:
                    raise CASFailedError(Conflict(idx, key, cur))
                if kind == _DEL_CURRENT and cur != op[2]:
                    raise CASFailedError(Conflict(idx, key, cur))
            self._ts += 1
            ts = self._ts
            for op in ops:
                kind, key = op[0], op[1]
                if kind in (_PUT_IF_NOT_EXIST, _CAS, _PUT):
                    value, ttl = op[2], op[-1]
                    expire_at = now + ttl if ttl else 0.0
                    self._append(key, _Version(ts, value, expire_at))
                else:  # _DEL / _DEL_CURRENT
                    self._append(key, _Version(ts, None, 0.0))

    def _append(self, key: bytes, version: _Version) -> None:
        if key not in self._versions:
            self._versions[key] = []
            bisect.insort(self._keys, key)
        self._versions[key].append(version)

    def bulk_gc(self, vkeys, vlens, vrevs, rkeys, rlens, rrevs, rtomb) -> int:
        """Compaction fast path mirroring the native engine's contract
        (native.py:bulk_gc): delete every victim object row and CAS-guarded
        revision record under ONE lock acquisition with one commit
        timestamp — the same logical deletions the per-victim batch path
        produces (MVCC deletion markers, hidden from iter/get, physically
        freed by prune_versions), without a one-op batch commit per
        revision record. Arrays: uint8[N, W] fixed-width user keys +
        lens + uint64 revs; ``rtomb`` marks records whose expected value
        carries the deletion flag. Returns the number of revision records
        deleted (CAS mismatches skip, exactly like ``del_current``)."""
        import numpy as np

        from .. import coder

        vlens = np.asarray(vlens, dtype=np.int64)
        rlens = np.asarray(rlens, dtype=np.int64)
        deleted = 0
        with self._lock:
            now = time.time()
            self._ts += 1
            marker = _Version(self._ts, None, 0.0)
            for j in range(len(vlens)):
                uk = vkeys[j, : vlens[j]].tobytes()
                self._append(coder.encode_object_key(uk, int(vrevs[j])), marker)
            for j in range(len(rlens)):
                uk = rkeys[j, : rlens[j]].tobytes()
                rkey = coder.encode_revision_key(uk)
                expected = coder.encode_rev_value(
                    int(rrevs[j]), deleted=bool(rtomb[j]))
                if self._live_value(rkey, None, now) != expected:
                    continue  # rewritten since the caller's snapshot
                self._append(rkey, marker)
                deleted += 1
        return deleted

    # --------------------------------------------------------------- lifecycle
    def prune_versions(self, keep_after_ts: int) -> int:
        """Physically free history invisible to snapshots >= keep_after_ts
        (same contract as the native engine's kb_prune)."""
        freed = 0
        with self._lock:
            now = time.time()
            dead_keys: set[bytes] = set()
            for key in list(self._versions):
                versions = self._versions[key]
                last_visible = None
                for i, v in enumerate(versions):
                    if v.ts <= keep_after_ts:
                        last_visible = i
                if last_visible:
                    del versions[:last_visible]
                    freed += last_visible
                dead = all(
                    v.ts <= keep_after_ts
                    and (v.value is None
                         or (self._ttl_supported and v.expire_at and now >= v.expire_at))
                    for v in versions
                )
                if dead and versions:
                    freed += len(versions)
                    del self._versions[key]
                    dead_keys.add(key)
            if dead_keys:
                # ONE filtered rebuild of the sorted key list: a per-key
                # `del self._keys[idx]` is an O(n) memmove each, which a
                # compaction GC'ing ~half a million whole chains turns
                # into minutes of pure list surgery (O(dead · n))
                self._keys = [k for k in self._keys if k not in dead_keys]
        return freed

    def version_count(self) -> int:
        with self._lock:
            return sum(len(v) for v in self._versions.values())

    def support_ttl(self) -> bool:
        return self._ttl_supported

    def close(self) -> None:
        with self._lock:
            self._keys.clear()
            self._versions.clear()


class _LazyIter(Iter):
    """Streaming snapshot iterator: each ``next()`` advances a *key-based*
    cursor under the store lock, so the engine never materializes the whole
    range up front (the reference iterates the skiplist lazily, iter.go).
    The snapshot timestamp pins visibility against concurrent COMMITS; like
    the native engine, ``prune_versions(keep_after_ts)`` only preserves
    history for snapshots >= its watermark — an iterator pinned BELOW a
    later prune watermark may observe pruned keys vanish mid-scan (callers
    hold the compaction fence for exactly this reason, backend/retry.py)."""

    def __init__(self, store: "MemKv", start: bytes, end: bytes, ts: int,
                 now: float, limit: int, reverse: bool):
        self._store = store
        self._start = start
        self._end = end
        self._ts = ts
        self._now = now
        self._limit = limit
        self._reverse = reverse
        self._cursor: bytes | None = None  # last key returned or skipped
        self._emitted = 0

    def _next_pos(self, keys: list[bytes]) -> int | None:
        if self._reverse:
            # reverse contract: end <= k <= start, descending
            if self._cursor is None:
                pos = bisect.bisect_right(keys, self._start) - 1
            else:
                pos = bisect.bisect_left(keys, self._cursor) - 1
            if pos < 0 or keys[pos] < self._end:
                return None
            return pos
        if self._cursor is None:
            pos = bisect.bisect_left(keys, self._start)
        else:
            pos = bisect.bisect_right(keys, self._cursor)
        if pos >= len(keys) or (self._end and keys[pos] >= self._end):
            return None
        return pos

    def next(self) -> tuple[bytes, bytes]:
        if self._limit and self._emitted >= self._limit:
            raise StopIteration
        store = self._store
        with store._lock:
            while True:
                pos = self._next_pos(store._keys)
                if pos is None:
                    raise StopIteration
                k = store._keys[pos]
                self._cursor = k
                val = store._live_value(k, self._ts, self._now)
                if val is not None:
                    self._emitted += 1
                    return (k, val)


class _MemBatch(BatchWrite):
    def __init__(self, store: MemKv):
        self._store = store
        self._ops: list[tuple] = []

    def put_if_not_exist(self, key: bytes, value: bytes, ttl_seconds: int = 0) -> None:
        self._ops.append((_PUT_IF_NOT_EXIST, key, value, ttl_seconds))

    def cas(self, key: bytes, new_value: bytes, old_value: bytes, ttl_seconds: int = 0) -> None:
        self._ops.append((_CAS, key, new_value, old_value, ttl_seconds))

    def put(self, key: bytes, value: bytes, ttl_seconds: int = 0) -> None:
        self._ops.append((_PUT, key, value, ttl_seconds))

    def delete(self, key: bytes) -> None:
        self._ops.append((_DEL, key))

    def del_current(self, key: bytes, expected_value: bytes) -> None:
        self._ops.append((_DEL_CURRENT, key, expected_value))

    def commit(self) -> None:
        self._store._commit(self._ops)
        self._ops = []


register_engine("memkv", MemKv)
