"""In-memory versioned sorted-map engine — the deterministic test fake.

Reference: pkg/storage/memkv (skiplist.go:30, batch.go, iter.go). Differences
by design:

- The logical clock is a commit counter, not wall-clock ns (skiplist.go:57) —
  deterministic tests.
- Snapshot isolation is real: every committed batch gets one timestamp and
  every key keeps its version history, so an ``iter`` at snapshot S never
  observes a commit > S (the reference fakes this with a whole-store mutex
  held across the batch, skiplist.go:82-85).
- Partitions are configurable via ``split_points`` so partition-parallel scans
  and border adjustment are testable without a distributed engine — the role
  the mock TiKV cluster plays in the reference tests (backend_test.go:171-178).
"""

from __future__ import annotations

import bisect
import threading
import time

from . import BatchWrite, Iter, KvStorage, Partition, register_engine
from .errors import CASFailedError, Conflict, KeyNotFoundError

_PUT_IF_NOT_EXIST = 0
_CAS = 1
_PUT = 2
_DEL = 3
_DEL_CURRENT = 4


class _Version:
    __slots__ = ("ts", "value", "expire_at")

    def __init__(self, ts: int, value: bytes | None, expire_at: float):
        self.ts = ts
        self.value = value  # None == engine-level deletion
        self.expire_at = expire_at  # 0.0 == no TTL


class MemKv(KvStorage):
    def __init__(
        self,
        split_points: list[bytes] | None = None,
        ttl_supported: bool = True,
    ):
        self._lock = threading.RLock()
        self._keys: list[bytes] = []  # sorted index of every key ever written
        self._versions: dict[bytes, list[_Version]] = {}
        self._ts = 0
        self._split_points = sorted(split_points or [])
        self._ttl_supported = ttl_supported

    # ------------------------------------------------------------- clock/shards
    def get_timestamp_oracle(self) -> int:
        with self._lock:
            return self._ts

    def get_partitions(self, start: bytes, end: bytes) -> list[Partition]:
        borders = [start]
        for sp in self._split_points:
            if start < sp and (not end or sp < end):
                borders.append(sp)
        borders.append(end)
        return [Partition(borders[i], borders[i + 1]) for i in range(len(borders) - 1)]

    # ------------------------------------------------------------------- reads
    def _live_value(self, key: bytes, snapshot_ts: int | None, now: float) -> bytes | None:
        """Latest value at the snapshot, honoring TTL; None if absent/deleted."""
        versions = self._versions.get(key)
        if not versions:
            return None
        ts = self._ts if snapshot_ts is None else snapshot_ts
        for v in reversed(versions):
            if v.ts <= ts:
                if v.value is None:
                    return None
                if self._ttl_supported and v.expire_at and now >= v.expire_at:
                    return None
                return v.value
        return None

    def get(self, key: bytes, snapshot_ts: int | None = None) -> bytes:
        with self._lock:
            val = self._live_value(key, snapshot_ts, time.time())
            if val is None:
                raise KeyNotFoundError(key)
            return val

    def iter(
        self,
        start: bytes,
        end: bytes,
        snapshot_ts: int | None = None,
        limit: int = 0,
    ) -> Iter:
        reverse = bool(end) and start > end
        with self._lock:
            now = time.time()
            ts = self._ts if snapshot_ts is None else snapshot_ts
        return _LazyIter(self, start, end, ts, now, limit, reverse)

    # ------------------------------------------------------------------ writes
    def begin_batch_write(self) -> BatchWrite:
        return _MemBatch(self)

    def _commit(self, ops: list[tuple]) -> None:
        with self._lock:
            now = time.time()
            # Validate all conditional ops against latest state first
            # (all-or-nothing; reference memkv serializes batches under the
            # store mutex, batch.go:146-167).
            for idx, op in enumerate(ops):
                kind, key = op[0], op[1]
                cur = self._live_value(key, None, now)
                if kind == _PUT_IF_NOT_EXIST and cur is not None:
                    raise CASFailedError(Conflict(idx, key, cur))
                if kind == _CAS and cur != op[3]:
                    raise CASFailedError(Conflict(idx, key, cur))
                if kind == _DEL_CURRENT and cur != op[2]:
                    raise CASFailedError(Conflict(idx, key, cur))
            self._ts += 1
            ts = self._ts
            for op in ops:
                kind, key = op[0], op[1]
                if kind in (_PUT_IF_NOT_EXIST, _CAS, _PUT):
                    value, ttl = op[2], op[-1]
                    expire_at = now + ttl if ttl else 0.0
                    self._append(key, _Version(ts, value, expire_at))
                else:  # _DEL / _DEL_CURRENT
                    self._append(key, _Version(ts, None, 0.0))

    def _append(self, key: bytes, version: _Version) -> None:
        if key not in self._versions:
            self._versions[key] = []
            bisect.insort(self._keys, key)
        self._versions[key].append(version)

    # --------------------------------------------------------------- lifecycle
    def prune_versions(self, keep_after_ts: int) -> int:
        """Physically free history invisible to snapshots >= keep_after_ts
        (same contract as the native engine's kb_prune)."""
        freed = 0
        with self._lock:
            now = time.time()
            for key in list(self._versions):
                versions = self._versions[key]
                last_visible = None
                for i, v in enumerate(versions):
                    if v.ts <= keep_after_ts:
                        last_visible = i
                if last_visible:
                    del versions[:last_visible]
                    freed += last_visible
                dead = all(
                    v.ts <= keep_after_ts
                    and (v.value is None
                         or (self._ttl_supported and v.expire_at and now >= v.expire_at))
                    for v in versions
                )
                if dead and versions:
                    freed += len(versions)
                    del self._versions[key]
                    idx = bisect.bisect_left(self._keys, key)
                    if idx < len(self._keys) and self._keys[idx] == key:
                        del self._keys[idx]
        return freed

    def version_count(self) -> int:
        with self._lock:
            return sum(len(v) for v in self._versions.values())

    def support_ttl(self) -> bool:
        return self._ttl_supported

    def close(self) -> None:
        with self._lock:
            self._keys.clear()
            self._versions.clear()


class _LazyIter(Iter):
    """Streaming snapshot iterator: each ``next()`` advances a *key-based*
    cursor under the store lock, so the engine never materializes the whole
    range up front (the reference iterates the skiplist lazily, iter.go).
    The snapshot timestamp pins visibility against concurrent COMMITS; like
    the native engine, ``prune_versions(keep_after_ts)`` only preserves
    history for snapshots >= its watermark — an iterator pinned BELOW a
    later prune watermark may observe pruned keys vanish mid-scan (callers
    hold the compaction fence for exactly this reason, backend/retry.py)."""

    def __init__(self, store: "MemKv", start: bytes, end: bytes, ts: int,
                 now: float, limit: int, reverse: bool):
        self._store = store
        self._start = start
        self._end = end
        self._ts = ts
        self._now = now
        self._limit = limit
        self._reverse = reverse
        self._cursor: bytes | None = None  # last key returned or skipped
        self._emitted = 0

    def _next_pos(self, keys: list[bytes]) -> int | None:
        if self._reverse:
            # reverse contract: end <= k <= start, descending
            if self._cursor is None:
                pos = bisect.bisect_right(keys, self._start) - 1
            else:
                pos = bisect.bisect_left(keys, self._cursor) - 1
            if pos < 0 or keys[pos] < self._end:
                return None
            return pos
        if self._cursor is None:
            pos = bisect.bisect_left(keys, self._start)
        else:
            pos = bisect.bisect_right(keys, self._cursor)
        if pos >= len(keys) or (self._end and keys[pos] >= self._end):
            return None
        return pos

    def next(self) -> tuple[bytes, bytes]:
        if self._limit and self._emitted >= self._limit:
            raise StopIteration
        store = self._store
        with store._lock:
            while True:
                pos = self._next_pos(store._keys)
                if pos is None:
                    raise StopIteration
                k = store._keys[pos]
                self._cursor = k
                val = store._live_value(k, self._ts, self._now)
                if val is not None:
                    self._emitted += 1
                    return (k, val)


class _MemBatch(BatchWrite):
    def __init__(self, store: MemKv):
        self._store = store
        self._ops: list[tuple] = []

    def put_if_not_exist(self, key: bytes, value: bytes, ttl_seconds: int = 0) -> None:
        self._ops.append((_PUT_IF_NOT_EXIST, key, value, ttl_seconds))

    def cas(self, key: bytes, new_value: bytes, old_value: bytes, ttl_seconds: int = 0) -> None:
        self._ops.append((_CAS, key, new_value, old_value, ttl_seconds))

    def put(self, key: bytes, value: bytes, ttl_seconds: int = 0) -> None:
        self._ops.append((_PUT, key, value, ttl_seconds))

    def delete(self, key: bytes) -> None:
        self._ops.append((_DEL, key))

    def del_current(self, key: bytes, expected_value: bytes) -> None:
        self._ops.append((_DEL_CURRENT, key, expected_value))

    def commit(self) -> None:
        self._store._commit(self._ops)
        self._ops = []


register_engine("memkv", MemKv)
