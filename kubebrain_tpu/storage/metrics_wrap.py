"""Engine decorator timing every storage op.

Reference: pkg/storage/metrics/store.go:30-231 — times Get/Del/DelCurrent/
Iter/Commit and counts batch ops; enabled by --enable-storage-metrics
(cmd/option/option.go:254-256).
"""

from __future__ import annotations

from . import BatchWrite, KvStorage
from ..metrics import Metrics


class MetricsKvStorage(KvStorage):
    def __init__(self, inner: KvStorage, metrics: Metrics):
        self._inner = inner
        self._m = metrics
        if hasattr(inner, "mvcc_write"):
            self.mvcc_write = self._mvcc_write_timed
        if hasattr(inner, "mvcc_delete"):
            self.mvcc_delete = self._mvcc_delete_timed
        if hasattr(inner, "write_batch"):
            self.write_batch = self._write_batch_timed
        if hasattr(inner, "prune_versions"):
            self.prune_versions = inner.prune_versions

    def _mvcc_write_timed(self, *args, **kwargs):
        with self._m.timed("storage.mvcc_write"):
            return self._inner.mvcc_write(*args, **kwargs)

    def _mvcc_delete_timed(self, *args, **kwargs):
        with self._m.timed("storage.mvcc_delete"):
            return self._inner.mvcc_delete(*args, **kwargs)

    def _write_batch_timed(self, ops):
        self._m.emit_counter("storage.write_batch.ops", len(ops))
        with self._m.timed("storage.write_batch"):
            return self._inner.write_batch(ops)

    def get_timestamp_oracle(self) -> int:
        return self._inner.get_timestamp_oracle()

    def get_partitions(self, start, end):
        return self._inner.get_partitions(start, end)

    def get(self, key, snapshot_ts=None):
        with self._m.timed("storage.get"):
            return self._inner.get(key, snapshot_ts)

    def iter(self, start, end, snapshot_ts=None, limit=0):
        with self._m.timed("storage.iter"):
            return self._inner.iter(start, end, snapshot_ts, limit)

    def begin_batch_write(self) -> BatchWrite:
        return _MetricsBatch(self._inner.begin_batch_write(), self._m)

    def delete(self, key):
        with self._m.timed("storage.del"):
            self._inner.delete(key)

    def del_current(self, key, expected_value):
        with self._m.timed("storage.del_current"):
            self._inner.del_current(key, expected_value)

    def support_ttl(self) -> bool:
        return self._inner.support_ttl()

    def exclusive_client(self) -> KvStorage:
        return MetricsKvStorage(self._inner.exclusive_client(), self._m)

    def make_scanner(self, **kwargs):
        return self._inner.make_scanner(**kwargs)

    def close(self) -> None:
        self._inner.close()


class _MetricsBatch(BatchWrite):
    def __init__(self, inner: BatchWrite, metrics: Metrics):
        self._inner = inner
        self._m = metrics
        self._ops = 0

    def put_if_not_exist(self, key, value, ttl_seconds=0):
        self._ops += 1
        self._inner.put_if_not_exist(key, value, ttl_seconds)

    def cas(self, key, new_value, old_value, ttl_seconds=0):
        self._ops += 1
        self._inner.cas(key, new_value, old_value, ttl_seconds)

    def put(self, key, value, ttl_seconds=0):
        self._ops += 1
        self._inner.put(key, value, ttl_seconds)

    def delete(self, key):
        self._ops += 1
        self._inner.delete(key)

    def del_current(self, key, expected_value):
        self._ops += 1
        self._inner.del_current(key, expected_value)

    def commit(self):
        self._m.emit_counter("storage.batch.ops", self._ops)
        with self._m.timed("storage.commit"):
            self._inner.commit()
        self._ops = 0
