"""``native`` engine: ctypes adapter over the C++ kbstore library.

The embedded single-host engine (the role Badger plays for the reference,
pkg/storage/badger) and the default authoritative host store under the TPU
mirror. Build with ``make -C native``; the adapter auto-builds on first use
when the toolchain is present.

Mapping to the engine contract:
- TSO            → kb_tso (commit counter; badger.go:41-46 uses ReadTs)
- snapshot reads → kb_get / kb_iter_open(snap)
- CAS batches    → kb_batch_* with conflict index + observed value
- TTL            → native (support_ttl=True, entries expire server-side,
                   badger.go:48)
- partitions     → kb_split_keys sampling (the PD-region-map analogue)
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

from .. import coder
from ..backend.common import KeyValue
from ..backend.scanner import Scanner
from . import BatchWrite, Iter, KvStorage, Partition, register_engine
from .errors import CASFailedError, Conflict, KeyNotFoundError, StorageError

_LIB_PATH = os.path.join(os.path.dirname(__file__), "..", "..", "native", "libkbstore.so")
_lib = None
_lib_lock = threading.Lock()


def _load_lib() -> ctypes.CDLL:
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        path = os.path.abspath(_LIB_PATH)
        if not os.path.exists(path):
            # first-use auto-build must be single-flight; every caller
            # needs the lib before it can proceed anyway
            # kblint: disable=KB102 -- deliberate build-under-lock
            subprocess.run(
                ["make", "-C", os.path.dirname(path)], check=True, capture_output=True
            )
        lib = ctypes.CDLL(path)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        lib.kb_open.restype = ctypes.c_void_p
        lib.kb_open_at.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.kb_open_at.restype = ctypes.c_void_p
        lib.kb_checkpoint.argtypes = [ctypes.c_void_p]
        lib.kb_close.argtypes = [ctypes.c_void_p]
        lib.kb_tso.argtypes = [ctypes.c_void_p]
        lib.kb_tso.restype = ctypes.c_uint64
        lib.kb_get.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t, ctypes.c_uint64,
            ctypes.POINTER(u8p), ctypes.POINTER(ctypes.c_size_t),
        ]
        lib.kb_free.argtypes = [ctypes.c_void_p]
        lib.kb_batch_begin.argtypes = [ctypes.c_void_p]
        lib.kb_batch_begin.restype = ctypes.c_void_p
        for name, extra in [
            ("kb_batch_put", [ctypes.c_char_p, ctypes.c_size_t, ctypes.c_int64]),
            ("kb_batch_put_if_absent", [ctypes.c_char_p, ctypes.c_size_t, ctypes.c_int64]),
        ]:
            getattr(lib, name).argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t, *extra
            ]
        lib.kb_batch_cas.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t,
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p, ctypes.c_size_t,
            ctypes.c_int64,
        ]
        lib.kb_batch_del.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t]
        lib.kb_batch_del_current.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t,
            ctypes.c_char_p, ctypes.c_size_t,
        ]
        lib.kb_batch_abort.argtypes = [ctypes.c_void_p]
        lib.kb_batch_commit.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(u8p),
            ctypes.POINTER(ctypes.c_size_t), ctypes.POINTER(ctypes.c_int),
        ]
        lib.kb_iter_open.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t,
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_uint64, ctypes.c_uint64,
            ctypes.c_int,
        ]
        lib.kb_iter_open.restype = ctypes.c_void_p
        lib.kb_iter_next.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(u8p), ctypes.POINTER(ctypes.c_size_t),
            ctypes.POINTER(u8p), ctypes.POINTER(ctypes.c_size_t),
        ]
        lib.kb_iter_close.argtypes = [ctypes.c_void_p]
        lib.kb_scan_page.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t,
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_uint64, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_int),
        ]
        lib.kb_scan_page.restype = ctypes.c_uint64
        lib.kb_mvcc_list_page.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t,
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_uint64, ctypes.c_uint64,
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p, ctypes.c_size_t,
            ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_size_t), ctypes.POINTER(ctypes.c_int),
        ]
        lib.kb_mvcc_list_page.restype = ctypes.c_uint64
        lib.kb_mvcc_list_wire.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t,
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_uint64, ctypes.c_uint64,
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p, ctypes.c_size_t,
            ctypes.c_uint64, ctypes.c_uint64,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_size_t),
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_size_t), ctypes.POINTER(ctypes.c_int),
        ]
        lib.kb_mvcc_list_wire.restype = ctypes.c_uint64
        lib.kb_split_keys.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_size_t),
        ]
        lib.kb_key_count.argtypes = [ctypes.c_void_p]
        lib.kb_key_count.restype = ctypes.c_uint64
        lib.kb_version_count.argtypes = [ctypes.c_void_p]
        lib.kb_version_count.restype = ctypes.c_uint64
        lib.kb_prune.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.kb_prune.restype = ctypes.c_uint64
        lib.kb_bulk_gc.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_uint64,  # victims
            ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_uint64,                                   # rev records
            ctypes.c_size_t, ctypes.c_char_p, ctypes.c_size_t,  # width, magic
        ]
        lib.kb_bulk_gc.restype = ctypes.c_uint64
        lib.kb_mvcc_export_stats.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t,
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_uint64,
            ctypes.c_char_p, ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.kb_mvcc_export_fill.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t,
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_uint64,
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p, ctypes.c_size_t,
            ctypes.c_size_t, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.kb_mvcc_export_fill.restype = ctypes.c_uint64
        lib.kb_mvcc_delete.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p, ctypes.c_size_t,  # rev_key
            ctypes.c_uint64, ctypes.c_uint64,  # expected, new rev
            ctypes.c_char_p, ctypes.c_size_t,  # new record
            ctypes.c_char_p, ctypes.c_size_t,  # tombstone value
            ctypes.c_char_p, ctypes.c_size_t,  # last_key
            ctypes.c_char_p, ctypes.c_size_t,  # last_val
            ctypes.POINTER(u8p), ctypes.POINTER(ctypes.c_size_t),
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.kb_mvcc_write.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p, ctypes.c_size_t,  # rev_key
            ctypes.c_char_p, ctypes.c_size_t,  # rev_val
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_int,  # expected
            ctypes.c_char_p, ctypes.c_size_t,  # obj_key
            ctypes.c_char_p, ctypes.c_size_t,  # obj_val
            ctypes.c_char_p, ctypes.c_size_t,  # last_key
            ctypes.c_char_p, ctypes.c_size_t,  # last_val
            ctypes.c_int64,
            ctypes.POINTER(u8p), ctypes.POINTER(ctypes.c_size_t),
            ctypes.POINTER(ctypes.c_int),
        ]
        _lib = lib
        return lib


class NativeKv(KvStorage):
    def __init__(self, partitions: int = 1, data_dir: str = "", fsync: bool = False):
        self._lib = _load_lib()
        if data_dir:
            os.makedirs(data_dir, exist_ok=True)
            self._store = ctypes.c_void_p(
                self._lib.kb_open_at(data_dir.encode(), 1 if fsync else 0)
            )
            if not self._store:
                raise StorageError(f"failed to open/recover store at {data_dir}")
        else:
            self._store = ctypes.c_void_p(self._lib.kb_open())
        self._n_parts = partitions

    def checkpoint(self) -> None:
        """Write a latest-only snapshot and truncate the WAL."""
        if self._lib.kb_checkpoint(self._store) != 0:
            raise StorageError("checkpoint failed (snapshot write or WAL reopen)")

    def get_timestamp_oracle(self) -> int:
        return int(self._lib.kb_tso(self._store))

    def get_partitions(self, start: bytes, end: bytes) -> list[Partition]:
        n = self._n_parts
        if n <= 1:
            return [Partition(start, end)]
        width = 256
        borders_buf = ctypes.create_string_buffer(width * (n - 1))
        lens = (ctypes.c_size_t * (n - 1))()
        got = self._lib.kb_split_keys(self._store, n, borders_buf, width, lens)
        borders = [start]
        for i in range(got):
            b = borders_buf.raw[i * width : i * width + lens[i]]
            if borders[-1] < b and (not end or b < end):
                borders.append(b)
        borders.append(end)
        return [Partition(borders[i], borders[i + 1]) for i in range(len(borders) - 1)]

    def get(self, key: bytes, snapshot_ts: int | None = None) -> bytes:
        out = ctypes.POINTER(ctypes.c_uint8)()
        out_len = ctypes.c_size_t()
        rc = self._lib.kb_get(
            self._store, key, len(key), snapshot_ts or 0,
            ctypes.byref(out), ctypes.byref(out_len),
        )
        if rc != 0:
            raise KeyNotFoundError(key)
        try:
            return ctypes.string_at(out, out_len.value)
        finally:
            self._lib.kb_free(out)

    def iter(self, start: bytes, end: bytes, snapshot_ts: int | None = None, limit: int = 0) -> Iter:
        reverse = 1 if (end and start > end) else 0
        if not reverse:
            # forward scans page through ONE FFI call per 1024 rows instead
            # of 3 calls + 2 copies per row (the etcd list hot path)
            snap = snapshot_ts or self.get_timestamp_oracle()
            return _PagedNativeIter(self._lib, self._store, start, end, snap, limit)
        handle = self._lib.kb_iter_open(
            self._store, start, len(start), end, len(end),
            snapshot_ts or 0, limit, reverse,
        )
        return _NativeIter(self._lib, handle)

    def begin_batch_write(self) -> BatchWrite:
        return _NativeBatch(self._lib, self._lib.kb_batch_begin(self._store))

    def support_ttl(self) -> bool:
        return True

    def key_count(self) -> int:
        return int(self._lib.kb_key_count(self._store))

    def version_count(self) -> int:
        return int(self._lib.kb_version_count(self._store))

    def prune_versions(self, keep_after_ts: int) -> int:
        """Physically free version history invisible to snapshots >=
        keep_after_ts; returns versions freed."""
        return int(self._lib.kb_prune(self._store, keep_after_ts))

    def write_batch(self, ops: list) -> list:
        """Group-commit executor (docs/writes.md): the shared loop over the
        one-FFI-call MVCC fast paths below — each op is already a single C
        round trip; the group's wins live above the engine (one scheduler
        dispatch, one revision block, one ring pass). A native C grouped op
        (one FFI call for the whole group) is the documented next step."""
        from .groupwrite import mvcc_write_batch

        return mvcc_write_batch(self, ops)

    def mvcc_write(
        self,
        rev_key: bytes,
        rev_val: bytes,
        expected: bytes | None,
        obj_key: bytes,
        obj_val: bytes,
        last_key: bytes,
        last_val: bytes,
        ttl_seconds: int = 0,
    ) -> None:
        """One-FFI-call MVCC write: conditional revision record + object row
        + last-revision watermark, atomic. Raises CASFailedError with the
        observed record on conflict."""
        cv = ctypes.POINTER(ctypes.c_uint8)()
        cl = ctypes.c_size_t()
        ch = ctypes.c_int(0)
        rc = self._lib.kb_mvcc_write(
            self._store,
            rev_key, len(rev_key), rev_val, len(rev_val),
            expected or b"", len(expected or b""), 1 if expected is not None else 0,
            obj_key, len(obj_key), obj_val, len(obj_val),
            last_key, len(last_key), last_val, len(last_val),
            ttl_seconds,
            ctypes.byref(cv), ctypes.byref(cl), ctypes.byref(ch),
        )
        if rc == 2:
            raise StorageError("WAL append failed; commit aborted")
        if rc == 1:
            observed = None
            if ch.value:
                observed = ctypes.string_at(cv, cl.value)
                self._lib.kb_free(cv)
            raise CASFailedError(Conflict(0, rev_key, observed))

    def mvcc_delete(
        self,
        rev_key: bytes,
        expected_rev: int,
        new_rev: int,
        new_record: bytes,
        tombstone: bytes,
        last_key: bytes,
        last_val: bytes,
    ) -> tuple[str, bytes | None, int]:
        """One-call read-validate-tombstone delete. Returns
        (outcome, prev_value, latest_rev) with outcome in
        {"ok", "not_found", "mismatch"}; raises on WAL failure/drift."""
        pv = ctypes.POINTER(ctypes.c_uint8)()
        pl = ctypes.c_size_t(0)
        latest = ctypes.c_uint64(0)
        rc = self._lib.kb_mvcc_delete(
            self._store, rev_key, len(rev_key),
            expected_rev, new_rev, new_record, len(new_record),
            tombstone, len(tombstone), last_key, len(last_key),
            last_val, len(last_val),
            ctypes.byref(pv), ctypes.byref(pl), ctypes.byref(latest),
        )
        # free whenever the C side filled the buffer, regardless of rc —
        # rc 4 (revision drift) also mallocs prev_val before its check
        prev = None
        if pl.value:
            prev = ctypes.string_at(pv, pl.value)
            self._lib.kb_free(pv)
        if rc == 0:
            return "ok", prev, int(latest.value)
        if rc == 1:
            # latest = the tombstone's revision (0 when truly absent) — the
            # backend fences its read floor on it (_await_revealed)
            return "not_found", None, int(latest.value)
        if rc == 2:
            return "mismatch", prev, int(latest.value)
        if rc == 3:
            raise StorageError("WAL append failed; delete aborted")
        from .errors import RevisionDriftBackError

        raise RevisionDriftBackError(
            f"revision drift on delete (latest {latest.value})",
            latest=int(latest.value))

    def export_mvcc(
        self,
        start: bytes,
        end: bytes,
        snapshot_ts: int,
        key_width: int,
        magic: bytes,
        tombstone: bytes,
    ):
        """Bulk-export version rows as numpy arrays (the TPU-mirror rebuild
        fast path): (keys uint8[N, W], lens int32[N], revs uint64[N],
        tomb bool[N], value_arena bytes, offsets uint64[N+1])."""
        import numpy as np

        n_rows = ctypes.c_uint64()
        val_bytes = ctypes.c_uint64()
        self._lib.kb_mvcc_export_stats(
            self._store, start, len(start), end, len(end), snapshot_ts,
            magic, len(magic), ctypes.byref(n_rows), ctypes.byref(val_bytes),
        )
        n = int(n_rows.value)
        keys = np.zeros((n, key_width), dtype=np.uint8)
        lens = np.zeros(n, dtype=np.int32)
        revs = np.zeros(n, dtype=np.uint64)
        tomb = np.zeros(n, dtype=np.uint8)
        arena = np.zeros(int(val_bytes.value), dtype=np.uint8)
        offsets = np.zeros(n + 1, dtype=np.uint64)
        if n:
            got = self._lib.kb_mvcc_export_fill(
                self._store, start, len(start), end, len(end), snapshot_ts,
                magic, len(magic), tombstone, len(tombstone),
                key_width, n,
                keys.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                revs.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
                tomb.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                arena.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            )
            if got == 2**64 - 1:
                raise StorageError("export overflow (key wider than key_width?)")
            if got < n:  # rows vanished between the two passes: trim
                keys, lens, revs, tomb = keys[:got], lens[:got], revs[:got], tomb[:got]
                offsets = offsets[: got + 1]
        return keys, lens, revs, tomb.astype(bool), arena, offsets

    def bulk_gc(self, vkeys, vlens, vrevs, rkeys, rlens, rrevs, rtomb) -> int:
        """Compaction fast path: delete all victim object rows and
        CAS-guarded revision records in ONE engine call (one lock, one WAL
        record) — no per-victim Python (reference hot loop
        scanner.go:465-491, vectorized). Arrays: fixed-width uint8[N, W]
        user keys + int32 lens + uint64 revs; rtomb uint8[M] marks records
        whose expected value carries the deletion flag. Returns the number
        of revision records deleted."""
        import numpy as np

        from .. import coder

        vkeys = np.ascontiguousarray(vkeys, dtype=np.uint8)
        rkeys = np.ascontiguousarray(rkeys, dtype=np.uint8)
        vlens = np.ascontiguousarray(vlens, dtype=np.int32)
        rlens = np.ascontiguousarray(rlens, dtype=np.int32)
        vrevs = np.ascontiguousarray(vrevs, dtype=np.uint64)
        rrevs = np.ascontiguousarray(rrevs, dtype=np.uint64)
        rtomb = np.ascontiguousarray(rtomb, dtype=np.uint8)
        width = vkeys.shape[1] if len(vkeys) else (rkeys.shape[1] if len(rkeys) else 1)
        u8 = ctypes.POINTER(ctypes.c_uint8)
        i32 = ctypes.POINTER(ctypes.c_int32)
        u64 = ctypes.POINTER(ctypes.c_uint64)
        got = self._lib.kb_bulk_gc(
            self._store,
            vkeys.ctypes.data_as(u8), vlens.ctypes.data_as(i32),
            vrevs.ctypes.data_as(u64), len(vlens),
            rkeys.ctypes.data_as(u8), rlens.ctypes.data_as(i32),
            rrevs.ctypes.data_as(u64), rtomb.ctypes.data_as(u8), len(rlens),
            width, coder.MAGIC, len(coder.MAGIC),
        )
        if got == 2**64 - 1:
            raise StorageError("WAL append failed; bulk GC aborted")
        return int(got)

    def mvcc_list_page(self, start: bytes, end: bytes, snapshot_ts: int,
                       read_rev: int, max_rows: int = 4096,
                       val_cap: int = 4 << 20):
        """One page of MVCC-visible (user_key, value, revision) rows — the
        whole visibility rule runs in C (kb_mvcc_list_page). Returns
        (rows, more, next_start)."""
        import numpy as np

        from .. import coder
        from ..backend.common import TOMBSTONE

        u8 = ctypes.POINTER(ctypes.c_uint8)
        u64 = ctypes.POINTER(ctypes.c_uint64)
        key_cap = 1 << 18
        next_cap = 4096
        while True:
            if key_cap > (1 << 30) or val_cap > (1 << 30):
                raise StorageError("mvcc list row exceeds 1GB arena cap")
            karena = np.empty(key_cap, dtype=np.uint8)
            varena = np.empty(val_cap, dtype=np.uint8)
            koffs = np.empty(max_rows + 1, dtype=np.uint64)
            voffs = np.empty(max_rows + 1, dtype=np.uint64)
            revs = np.empty(max_rows, dtype=np.uint64)
            nxt = np.empty(next_cap, dtype=np.uint8)
            nxt_len = ctypes.c_size_t()
            more = ctypes.c_int()
            n = int(self._lib.kb_mvcc_list_page(
                self._store, start, len(start), end, len(end),
                snapshot_ts, read_rev,
                coder.MAGIC, len(coder.MAGIC), TOMBSTONE, len(TOMBSTONE),
                max_rows,
                karena.ctypes.data_as(u8), key_cap, koffs.ctypes.data_as(u64),
                varena.ctypes.data_as(u8), val_cap, voffs.ctypes.data_as(u64),
                revs.ctypes.data_as(u64),
                nxt.ctypes.data_as(u8), next_cap, ctypes.byref(nxt_len),
                ctypes.byref(more),
            ))
            if more.value == 2:
                next_cap = int(nxt_len.value) + 64
                continue
            if n == 0 and more.value:
                # a single row larger than an arena; C can't say which, so
                # grow both (bounded above)
                val_cap *= 4
                key_cap *= 4
                continue
            break
        ko = koffs[: n + 1].astype(np.int64)
        vo = voffs[: n + 1].astype(np.int64)
        kb = karena[: int(ko[-1]) if n else 0].tobytes()
        vb = varena[: int(vo[-1]) if n else 0].tobytes()
        rows = [
            (kb[ko[i]:ko[i + 1]], vb[vo[i]:vo[i + 1]], int(revs[i]))
            for i in range(n)
        ]
        return rows, bool(more.value), bytes(nxt[: nxt_len.value])

    def mvcc_list_wire(self, start: bytes, end: bytes, snapshot_ts: int,
                       read_rev: int, max_rows: int = 65536,
                       byte_cap: int = 32 << 20):
        """One MVCC list page as ready RangeResponse.kvs protobuf bytes —
        the entire list hot path (visibility + wire encoding) in one C call.
        Returns (blob, rows, more, next_start)."""
        from .. import coder
        from ..backend.common import TOMBSTONE

        out = ctypes.POINTER(ctypes.c_uint8)()
        out_len = ctypes.c_size_t()
        nxt_len = ctypes.c_size_t()
        more = ctypes.c_int()
        next_cap = 4096
        while True:
            nxt = (ctypes.c_uint8 * next_cap)()
            rows = int(self._lib.kb_mvcc_list_wire(
                self._store, start, len(start), end, len(end),
                snapshot_ts, read_rev,
                coder.MAGIC, len(coder.MAGIC), TOMBSTONE, len(TOMBSTONE),
                max_rows, byte_cap,
                ctypes.byref(out), ctypes.byref(out_len),
                nxt, next_cap, ctypes.byref(nxt_len), ctypes.byref(more),
            ))
            blob = ctypes.string_at(out, out_len.value)
            self._lib.kb_free(out)
            if more.value == 2:
                next_cap = int(nxt_len.value) + 64
                continue
            return blob, rows, bool(more.value), bytes(nxt[: nxt_len.value])

    def make_scanner(self, **kwargs):
        return NativeScanner(self, **kwargs)

    def close(self) -> None:
        if self._store:
            self._lib.kb_close(self._store)
            self._store = None


class NativeScanner(Scanner):
    """Generic scanner with the list hot paths served by the engine's C
    MVCC pass (kb_mvcc_list_page) — one FFI call per page instead of a
    per-row Python loop. Compact keeps the generic (partition-parallel)
    implementation. Reference analogue: the scan worker loop
    (scanner.go:389-516) running inside the Badger-role engine."""

    PAGE_ROWS = 4096

    def _list_pages(self, lo: bytes, hi: bytes, snapshot: int, read_rev: int,
                    max_rows: int):
        cursor = lo
        while True:
            rows, more, nxt = self._store.mvcc_list_page(
                cursor, hi, snapshot, read_rev, max_rows
            )
            yield rows
            if not more or not nxt:
                return
            cursor = nxt

    def range_(self, start: bytes, end: bytes, read_revision: int, limit: int = 0):
        lo, hi = coder.internal_range(start, end)
        snapshot = self._snapshot_checked(read_revision)
        kvs: list[KeyValue] = []
        want = min(limit + 1, self.PAGE_ROWS) if limit else self.PAGE_ROWS
        for rows in self._list_pages(lo, hi, snapshot, read_revision, want):
            kvs.extend(KeyValue(k, v, r) for k, v, r in rows)
            if limit and len(kvs) > limit:
                break
        if limit:
            return kvs[:limit], len(kvs) > limit
        return kvs, False

    def count(self, start: bytes, end: bytes, read_revision: int) -> int:
        lo, hi = coder.internal_range(start, end)
        snapshot = self._snapshot_checked(read_revision)
        total = 0
        for rows in self._list_pages(lo, hi, snapshot, read_revision, self.PAGE_ROWS):
            total += len(rows)
        return total

    def list_wire(self, start: bytes, end: bytes, read_revision: int,
                  limit: int = 0) -> tuple[bytes, int, bool]:
        """Visible range as ready RangeResponse.kvs wire bytes (C encoder).
        Returns (kvs_blob, n_rows, more)."""
        lo, hi = coder.internal_range(start, end)
        snapshot = self._snapshot_checked(read_revision)
        blobs: list[bytes] = []
        total = 0
        cursor = lo
        while True:
            want = min(limit - total, self.PAGE_ROWS) if limit else self.PAGE_ROWS
            blob, n, more, nxt = self._store.mvcc_list_wire(
                cursor, hi, snapshot, read_revision, want
            )
            blobs.append(blob)
            total += n
            if limit and total >= limit:
                # the C more flag is exact: set only when a further visible
                # non-tombstone row exists — etcd's More semantics directly
                return b"".join(blobs), total, more
            if not more or not nxt:
                return b"".join(blobs), total, False
            cursor = nxt

    def range_stream(self, start: bytes, end: bytes, read_revision: int,
                     batch_size: int = 300):
        lo, hi = coder.internal_range(start, end)
        snapshot = self._snapshot_checked(read_revision)

        def generate():
            batch: list[KeyValue] = []
            for rows in self._list_pages(lo, hi, snapshot, read_revision,
                                         self.PAGE_ROWS):
                for k, v, r in rows:
                    batch.append(KeyValue(k, v, r))
                    if len(batch) >= batch_size:
                        out, b2 = batch[:], []
                        batch = b2
                        yield out
            if batch:
                yield batch

        return generate()


class _PagedNativeIter(Iter):
    """Forward scan over kb_scan_page: bulk pages, zero per-row FFI."""

    PAGE_ROWS = 1024
    KEY_CAP = 1 << 18
    VAL_CAP = 4 << 20

    def __init__(self, lib, store, start, end, snap, limit):
        self._lib = lib
        self._store = store
        self._cursor = start
        self._end = end
        self._snap = snap
        self._limit = limit
        self._served = 0
        self._rows: list[tuple[bytes, bytes]] = []
        self._pos = 0
        self._more = True
        self._val_cap = self.VAL_CAP

    def _fetch(self) -> None:
        import numpy as np

        want = self.PAGE_ROWS
        if self._limit:
            want = min(want, self._limit - self._served)
        while True:
            if getattr(self, "_karena", None) is None or len(self._varena) < self._val_cap:
                self._karena = np.empty(self.KEY_CAP, dtype=np.uint8)
                self._varena = np.empty(self._val_cap, dtype=np.uint8)
                self._koffs = np.empty(self.PAGE_ROWS + 1, dtype=np.uint64)
                self._voffs = np.empty(self.PAGE_ROWS + 1, dtype=np.uint64)
            karena, varena = self._karena, self._varena
            koffs, voffs = self._koffs, self._voffs
            more = ctypes.c_int()
            u8 = ctypes.POINTER(ctypes.c_uint8)
            u64 = ctypes.POINTER(ctypes.c_uint64)
            n = int(self._lib.kb_scan_page(
                self._store, self._cursor, len(self._cursor),
                self._end, len(self._end), self._snap, want,
                karena.ctypes.data_as(u8), self.KEY_CAP,
                koffs.ctypes.data_as(u64),
                varena.ctypes.data_as(u8), self._val_cap,
                voffs.ctypes.data_as(u64),
                ctypes.byref(more),
            ))
            if n == 0 and more.value:
                # single row larger than the value arena: grow and retry
                self._val_cap *= 4
                continue
            break
        ko = koffs[: n + 1].astype(np.int64)
        vo = voffs[: n + 1].astype(np.int64)
        kb = karena[: int(ko[-1]) if n else 0].tobytes()
        vb = varena[: int(vo[-1]) if n else 0].tobytes()
        self._rows = [
            (kb[ko[i]:ko[i + 1]], vb[vo[i]:vo[i + 1]]) for i in range(n)
        ]
        self._pos = 0
        self._more = bool(more.value)
        if n:
            self._cursor = self._rows[-1][0] + b"\x00"

    def next(self) -> tuple[bytes, bytes]:
        if self._limit and self._served >= self._limit:
            raise StopIteration
        if self._pos >= len(self._rows):
            if not self._more:
                raise StopIteration
            self._fetch()
            if not self._rows:
                raise StopIteration
        kv = self._rows[self._pos]
        self._pos += 1
        self._served += 1
        return kv

    def close(self) -> None:
        self._rows = []
        self._more = False


class _NativeIter(Iter):
    def __init__(self, lib, handle):
        self._lib = lib
        self._h = handle

    def next(self) -> tuple[bytes, bytes]:
        if self._h is None:
            raise StopIteration
        k = ctypes.POINTER(ctypes.c_uint8)()
        kl = ctypes.c_size_t()
        v = ctypes.POINTER(ctypes.c_uint8)()
        vl = ctypes.c_size_t()
        rc = self._lib.kb_iter_next(
            self._h, ctypes.byref(k), ctypes.byref(kl), ctypes.byref(v), ctypes.byref(vl)
        )
        if rc != 0:
            self.close()
            raise StopIteration
        return ctypes.string_at(k, kl.value), ctypes.string_at(v, vl.value)

    def close(self) -> None:
        if self._h is not None:
            self._lib.kb_iter_close(self._h)
            self._h = None

    def __del__(self):
        self.close()


class _NativeBatch(BatchWrite):
    def __init__(self, lib, handle):
        self._lib = lib
        self._h = handle
        self._keys: list[bytes] = []

    def put_if_not_exist(self, key, value, ttl_seconds=0):
        self._keys.append(key)
        self._lib.kb_batch_put_if_absent(self._h, key, len(key), value, len(value), ttl_seconds)

    def cas(self, key, new_value, old_value, ttl_seconds=0):
        self._keys.append(key)
        self._lib.kb_batch_cas(
            self._h, key, len(key), new_value, len(new_value),
            old_value, len(old_value), ttl_seconds,
        )

    def put(self, key, value, ttl_seconds=0):
        self._keys.append(key)
        self._lib.kb_batch_put(self._h, key, len(key), value, len(value), ttl_seconds)

    def delete(self, key):
        self._keys.append(key)
        self._lib.kb_batch_del(self._h, key, len(key))

    def del_current(self, key, expected_value):
        self._keys.append(key)
        self._lib.kb_batch_del_current(self._h, key, len(key), expected_value, len(expected_value))

    def commit(self):
        idx = ctypes.c_int64(-1)
        val = ctypes.POINTER(ctypes.c_uint8)()
        vlen = ctypes.c_size_t()
        has_val = ctypes.c_int(0)
        rc = self._lib.kb_batch_commit(
            self._h, ctypes.byref(idx), ctypes.byref(val),
            ctypes.byref(vlen), ctypes.byref(has_val),
        )
        self._h = None  # commit consumes the batch
        if rc == 2:
            raise StorageError("WAL append failed; commit aborted")
        if rc != 0:
            observed = None
            if has_val.value:
                observed = ctypes.string_at(val, vlen.value)
                self._lib.kb_free(val)
            i = int(idx.value)
            key = self._keys[i] if 0 <= i < len(self._keys) else b""
            raise CASFailedError(Conflict(i, key, observed))

    def __del__(self):
        if self._h is not None:
            self._lib.kb_batch_abort(self._h)
            self._h = None


register_engine("native", NativeKv)
