"""Network-attached storage adapter (the reference's TiKV client role).

Talks to ``kbstored`` (native/kvrpc/kbstored.cc) over a pipelined binary TCP
protocol, so N separate kubebrain-tpu server processes — on this host or
others — share one storage truth. Mirrors pkg/storage/tikv/tikv.go:38-153:

- a **round-robin connection pool** spreads request load (the reference
  keeps 200 gRPC clients to TiKV, tikv.go:36-82; parallelism P5);
- ``commit`` classifies transport failures: a batch whose outcome is
  unknowable (timeout / connection death after send) raises
  ``UncertainResultError`` — the caller treats the write as *maybe applied*
  and the async retry repairs it (reference batch.go:125-146);
- CAS conflicts carry the observed value back (``Conflict``) so callers
  skip a re-read (reference errors.go:47-75);
- the engine's one-call MVCC fast paths (mvcc_write / mvcc_delete) are
  forwarded as single frames, keeping the backend's write path at one
  network round trip per transaction.

Scans are client-paged (stateless server): forward scans re-issue from
``last_key + b"\\x00"`` while the server reports truncation; reverse scans
(the point-get path) page by moving the exclusive upper bound down to the
smallest key served, so version chains longer than a server page stay
correct.
"""

from __future__ import annotations

import random
import socket
import struct
import threading
import time

from . import BatchWrite, Iter, KvStorage, Partition, register_engine
from .errors import (
    CASFailedError,
    Conflict,
    KeyNotFoundError,
    StorageError,
    UncertainResultError,
)

OP_GET, OP_TSO, OP_BATCH, OP_SCAN, OP_PARTITIONS = 1, 2, 3, 4, 5
OP_MVCC_WRITE, OP_MVCC_DELETE, OP_CHECKPOINT, OP_INFO = 6, 7, 8, 9
OP_EXPORT = 10
OP_REPL_HELLO, OP_REPL_ACK, OP_PROMOTE, OP_ROLE, OP_VOTE = 11, 12, 13, 14, 15
ST_OK, ST_NOT_FOUND, ST_CONFLICT, ST_WAL, ST_DRIFT, ST_ERROR = 0, 1, 2, 3, 4, 5
# quorum-mode tier: the write was applied on the (now deposed or
# quorum-less) leader but never reached a majority — outcome unknown
ST_UNCERTAIN = 6
# definite pre-apply refusals that are safe to retry on the real leader
_REDIRECTABLE = (b"read-only follower", b"no quorum")

_REQ = struct.Struct("<IQB")
SCAN_PAGE_CAP = 2048


def _bytes_field(buf: bytearray, b: bytes) -> None:
    buf += struct.pack("<I", len(b))
    buf += b


class _Reader:
    __slots__ = ("b", "off")

    def __init__(self, b: bytes):
        self.b = b
        self.off = 0

    def u8(self) -> int:
        v = self.b[self.off]
        self.off += 1
        return v

    def u32(self) -> int:
        (v,) = struct.unpack_from("<I", self.b, self.off)
        self.off += 4
        return v

    def u64(self) -> int:
        (v,) = struct.unpack_from("<Q", self.b, self.off)
        self.off += 8
        return v

    def i64(self) -> int:
        (v,) = struct.unpack_from("<q", self.b, self.off)
        self.off += 8
        return v

    def bytes_(self) -> bytes:
        n = self.u32()
        v = self.b[self.off:self.off + n]
        self.off += n
        return v


class _PooledConn:
    """One TCP connection; a lock serializes request/response pairs on it."""

    def __init__(self, address: tuple[str, int], timeout: float):
        self.lock = threading.Lock()
        self.sock = socket.create_connection(address, timeout=timeout)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._rfile = self.sock.makefile("rb")
        self._req_id = 0

    def call(self, op: int, body: bytes) -> tuple[int, bytes]:
        """One request/response; raises OSError/EOFError on transport death."""
        with self.lock:
            self._req_id += 1
            rid = self._req_id
            self.sock.sendall(_REQ.pack(len(body), rid, op) + body)
            hdr = self._rfile.read(13)
            if len(hdr) != 13:
                raise EOFError("kbstored connection closed")
            blen, got_rid, status = _REQ.unpack(hdr)
            payload = self._rfile.read(blen) if blen else b""
            if blen and len(payload) != blen:
                raise EOFError("kbstored connection closed mid-frame")
            if got_rid != rid:
                raise StorageError("kbstored response out of sync")
            return status, payload

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


class RemoteBatchWrite(BatchWrite):
    def __init__(self, store: "RemoteKvStorage"):
        self._store = store
        self._ops: list[tuple[int, int, bytes, bytes, bytes]] = []

    def put(self, key, value, ttl_seconds=0):
        self._ops.append((0, ttl_seconds, key, value, b""))

    def put_if_not_exist(self, key, value, ttl_seconds=0):
        self._ops.append((1, ttl_seconds, key, value, b""))

    def cas(self, key, new_value, old_value, ttl_seconds=0):
        self._ops.append((2, ttl_seconds, key, new_value, old_value))

    def delete(self, key):
        self._ops.append((3, 0, key, b"", b""))

    def del_current(self, key, expected_value):
        self._ops.append((4, 0, key, b"", expected_value))

    def commit(self) -> None:
        body = bytearray(struct.pack("<I", len(self._ops)))
        for typ, ttl, key, val, old in self._ops:
            body += struct.pack("<Bq", typ, ttl)
            _bytes_field(body, key)
            _bytes_field(body, val)
            _bytes_field(body, old)
        ops = self._ops
        self._ops = []
        # capture the epoch BEFORE the call: a failover completing while this
        # response is in flight must not tag the OLD primary's (possibly
        # far-ahead, standalone-acked) clock with the NEW epoch — that would
        # poison _max_seen above anything the new lineage produces and make
        # later failovers refuse healthy primaries
        epoch_at_send = self._store._epoch_snapshot()
        # transport death / quorum loss -> UncertainResultError inside
        # (reference batch.go:125-146); leader moved -> transparent retry
        status, payload = self._store._write_frame(
            OP_BATCH, bytes(body), "batch commit")
        if status == ST_OK:
            if len(payload) >= 8:  # commit clock: feeds lineage adoption
                ts = struct.unpack_from("<Q", payload)[0]
                self._store._observe(ts, epoch_at_send)
            return
        if status == ST_CONFLICT:
            r = _Reader(payload)
            idx = r.i64()
            has = r.u8()
            val = r.bytes_()
            conflict_key = ops[idx][2] if 0 <= idx < len(ops) else b""
            raise CASFailedError(Conflict(int(idx), conflict_key, val if has else None))
        raise StorageError(f"batch commit failed (status {status}): {payload!r}")


class _PagedIter(Iter):
    """Client-paged forward scan / single-page reverse scan."""

    def __init__(self, store, start, end, snapshot_ts, limit, reverse):
        self._store = store
        self._start = start
        self._end = end
        # pin the snapshot NOW when the caller passed none: pages must all
        # read the same version of the world (Iter contract — the in-process
        # engines get this by buffering at open)
        self._snap = snapshot_ts or store.get_timestamp_oracle()
        self._limit = limit
        self._reverse = reverse
        self._rows: list[tuple[bytes, bytes]] = []
        self._pos = 0
        self._served = 0
        self._more = True
        self._fetch()

    def _fetch(self) -> None:
        continuing = self._reverse and self._served > 0
        want = 0
        if self._limit:
            want = self._limit - self._served
            if continuing and want:
                want += 1  # the anchor row comes back once more (dropped below)
        body = bytearray()
        body += struct.pack("<Q", self._snap)
        body += struct.pack("<B", 1 if self._reverse else 0)
        body += struct.pack("<I", want)
        _bytes_field(body, self._start)
        _bytes_field(body, self._end)
        status, payload = self._store._read_call(OP_SCAN, bytes(body), self._snap)
        if status != ST_OK:
            raise StorageError(f"scan failed (status {status}): {payload!r}")
        r = _Reader(payload)
        n = r.u32()
        self._rows = [(r.bytes_(), r.bytes_()) for _ in range(n)]
        self._pos = 0
        more = bool(r.u8())
        if continuing and self._rows and self._rows[0][0] == self._start:
            # reverse continuation re-anchors on the previous page's smallest
            # key (the engine's reverse start bound is inclusive); drop it
            self._pos = 1
        self._more = more
        if self._rows:
            if self._reverse:
                # rows arrive descending; the next reverse page continues
                # from the smallest key served (a user key with more live
                # versions than one server page must not silently truncate
                # the point-get path — VERDICT r2 weak #6)
                self._start = self._rows[-1][0]
            else:
                # next forward page starts just after the last returned key
                self._start = self._rows[-1][0] + b"\x00"

    def next(self) -> tuple[bytes, bytes]:
        if self._limit and self._served >= self._limit:
            raise StopIteration
        while self._pos >= len(self._rows):
            if not self._more:
                raise StopIteration
            self._fetch()  # may yield pages holding only the dropped anchor
        kv = self._rows[self._pos]
        self._pos += 1
        self._served += 1
        return kv


class RemoteKvStorage(KvStorage):
    """KvStorage over a kbstored server (reference tikv.NewKvStorage)."""

    def __init__(self, address: str = "127.0.0.1:2389", pool: int = 8,
                 timeout: float = 30.0, partitions: int = 4,
                 read_followers: bool = False):
        # 30s default: kbstored serves ops from one reactor thread, so a
        # checkpoint or big scan page briefly stalls other connections — a
        # tight timeout would misclassify those stalls as uncertain writes.
        # ``address`` may be a comma-separated list: the first entry is the
        # primary, the rest are WAL-shipping followers (kbstored --follow) —
        # see failover(). Mirrors the reference's PD endpoints list
        # (tikv.go:38-82).
        self._addresses = []
        for a in address.split(","):
            host, _, port = a.strip().rpartition(":")
            self._addresses.append((host or "127.0.0.1", int(port)))
        self._primary = 0
        self._address = self._addresses[0]
        self._timeout = timeout
        self._n_partitions = max(1, partitions)
        self._pool = [_PooledConn(self._address, timeout) for _ in range(pool)]
        self._rr = 0
        self._rr_lock = threading.Lock()
        # follower read routing (tier-level read scaling, the storage-side
        # analogue of the `wat` mesh axis): snapshot-PINNED reads can go to
        # any replica that has applied the snapshot — the follower answers
        # ST_DRIFT when asked for a snap beyond its clock and the read falls
        # back to the primary. Lazy one-conn-per-follower pools.
        self._read_followers = read_followers and len(self._addresses) > 1
        # per-follower conn lists sized like the primary pool so routed
        # reads keep the same in-flight parallelism (each _PooledConn
        # serializes one request/response at a time)
        self._fpool_size = max(1, pool)
        self._fpools: dict[int, list[_PooledConn]] = {}
        self._frole: dict[int, tuple[float, bool]] = {}  # idx -> (probed_at, is_follower)
        self._fdown: dict[int, float] = {}               # idx -> cooldown deadline
        self._fprobing: set[int] = set()                 # single-flight role probes
        # highest (epoch, clock) observed anywhere in the tier — epochs are
        # bumped on promotion and inherited by followers, so lexicographic
        # comparison distinguishes lineages where raw clocks cannot (a
        # detached primary's standalone acks can push its clock PAST the
        # promoted follower's)
        self._max_seen = (0, 0)
        self._cur_epoch = 0  # epoch of the member the pool points at
        self._frr = 0
        # probe + cache engine facts
        status, payload = self._call(OP_INFO, b"")
        if status != ST_OK:
            raise StorageError("kbstored INFO failed")
        self._support_ttl = bool(payload[0])
        # Probe ROLE up front so _cur_epoch/_max_seen are epoch-tagged BEFORE
        # any adoption decision: without this, commit/TSO observations are
        # tagged (0, ts) and the very first failover() could adopt a
        # restarted stale primary whose persisted epoch >= 1 (r3 advisor,
        # medium). Best-effort: pre-epoch daemons simply report epoch 0.
        # On a quorum tier the configured first address may well be a
        # follower (leadership lands wherever the election put it) — chase
        # the leader once; write paths re-resolve on demand after that.
        try:
            is_f, *_ = self.member_info()
            if is_f and len(self._addresses) > 1:
                try:
                    self.find_leader()
                except StorageError:
                    pass  # tier still electing; resolved at first write
        except (OSError, EOFError, StorageError):
            pass

    # ------------------------------------------------------------- plumbing
    def _observe(self, ts: int, epoch: int) -> None:
        """Fold a lineage observation into the (epoch, ts) watermark under
        the lock: these are read-modify-writes from many threads (commit,
        TSO, role probes) and a lost update would lower the watermark the
        split-brain adoption guard depends on (r3 advisor, low). Callers on
        the commit/TSO paths must pass the epoch snapshotted BEFORE the
        request went out (_epoch_snapshot), never the live _cur_epoch — see
        RemoteBatchWrite.commit."""
        with self._rr_lock:
            if (epoch, ts) > self._max_seen:
                self._max_seen = (epoch, ts)

    def _epoch_snapshot(self) -> int:
        with self._rr_lock:
            return self._cur_epoch

    def _conn(self) -> tuple[int, _PooledConn]:
        with self._rr_lock:
            self._rr = (self._rr + 1) % len(self._pool)
            return self._rr, self._pool[self._rr]

    def _heal(self, slot: int, dead: _PooledConn) -> _PooledConn:
        """Replace a dead pooled connection, slot-addressed so concurrent
        failures on the same conn never close a healthy replacement (each
        loser sees pool[slot] is no longer `dead` and just uses the new
        one). Raises OSError if the server is still unreachable."""
        with self._rr_lock:
            current = self._pool[slot]
            if current is not dead:
                return current  # another thread already healed this slot
        new = _PooledConn(self._address, self._timeout)
        with self._rr_lock:
            if self._pool[slot] is dead:
                self._pool[slot] = new
                dead.close()
                return new
        new.close()
        return self._pool[slot]

    def _call(self, op: int, body: bytes) -> tuple[int, bytes]:
        slot, conn = self._conn()
        try:
            return conn.call(op, body)
        except (OSError, EOFError):
            # reads are idempotent: heal the slot and retry once. Writes
            # (BATCH / MVCC_*) never come through here — their callers
            # classify transport death as UncertainResultError instead.
            try:
                new = self._heal(slot, conn)
                return new.call(op, body)
            except (OSError, EOFError):
                # the member itself is gone — leadership may have moved
                # (quorum election / external failover); chase it once
                if not self._maybe_repoint():
                    raise
                _, conn2 = self._conn()
                return conn2.call(op, body)

    def _maybe_repoint(self) -> bool:
        """Best-effort leader chase after a dead-member transport failure;
        True when the pool now points at a different member."""
        if len(self._addresses) < 2:
            return False
        old = self._primary
        try:
            return self.find_leader(probe_timeout=0.5) != old
        except (OSError, EOFError, StorageError):
            return False

    def _candidate_is_follower(self, idx: int) -> bool:
        """Role-gate a read candidate (cached, ~5s TTL; unreachable nodes
        sit out a 5s cooldown). A non-follower candidate is NOT a routing
        target: a restarted old primary answers reads from an ABANDONED
        lineage and — being a primary — bypasses the server-side drift
        check, so routing to it would serve silently-stale data."""
        now = time.monotonic()
        with self._rr_lock:
            down_until = self._fdown.get(idx, 0.0)
            probed_at, is_f = self._frole.get(idx, (0.0, False))
            if now < down_until:
                return False
            if now - probed_at < 5.0:
                return is_f
            if idx in self._fprobing:
                # single-flight: someone else is probing — don't pile more
                # blocked readers on a possibly-wedged candidate; fall back
                return False
            self._fprobing.add(idx)
        try:
            # short dedicated probe timeout: a wedged candidate must not
            # stall the read for the full transport timeout
            is_f, _, _ = self.role(idx, timeout=min(self._timeout, 1.0))
        except Exception:
            with self._rr_lock:
                self._fdown[idx] = now + 5.0
                self._fprobing.discard(idx)
            return False
        with self._rr_lock:
            self._frole[idx] = (now, is_f)
            self._fprobing.discard(idx)
        return is_f

    def _read_call(self, op: int, body: bytes, snapshot_ts: int) -> tuple[int, bytes]:
        """Snapshot-pinned read: try a follower first (when enabled), fall
        back to the primary on drift/any transport trouble. Reads without a
        pinned snapshot go straight to the primary (read-your-writes)."""
        if self._read_followers and snapshot_ts:
            with self._rr_lock:
                self._frr += 1
                rr = self._frr
                candidates = [i for i in range(len(self._addresses))
                              if i != self._primary]
                idx = candidates[rr % len(candidates)] if candidates else None
            if idx is not None and not self._candidate_is_follower(idx):
                idx = None
            if idx is not None:
                conn = None
                try:
                    conn = self._follower_conn(idx, rr)
                    status, payload = conn.call(op, body)
                    if status != ST_DRIFT:
                        return status, payload
                except (OSError, EOFError, StorageError):
                    if conn is not None:
                        with self._rr_lock:
                            conns = self._fpools.get(idx)
                            if conns and conn in conns:
                                conns.remove(conn)
                            self._fdown[idx] = time.monotonic() + 5.0
                        conn.close()
        return self._call(op, body)

    def _follower_conn(self, idx: int, rr: int) -> _PooledConn:
        """Pick (or lazily grow, up to the primary pool's size) a follower
        connection; all list mutations happen under the lock so racing
        growers never leak a socket."""
        with self._rr_lock:
            conns = self._fpools.setdefault(idx, [])
            if len(conns) >= self._fpool_size:
                return conns[rr % len(conns)]
        new = _PooledConn(self._addresses[idx], self._timeout)
        with self._rr_lock:
            conns = self._fpools.setdefault(idx, [])
            if len(conns) < self._fpool_size:
                conns.append(new)
                return new
            keep = conns[rr % len(conns)]
        new.close()
        return keep

    def _write_call(self, op: int, body: bytes) -> tuple[int, bytes]:
        """Write-path transport: on failure the outcome is unknowable, but
        the dead socket must still be healed or a single server restart
        leaves permanently-dead pool slots on write-heavy workloads."""
        slot, conn = self._conn()
        try:
            return conn.call(op, body)
        except (OSError, EOFError):
            try:
                self._heal(slot, conn)
            except OSError:
                # server still down; chase a moved leadership so the
                # CALLER'S retry (after its UncertainResultError repair)
                # lands on the new leader instead of this corpse
                self._maybe_repoint()
            raise

    def _write_frame(self, op: int, body: bytes, what: str) -> tuple[int, bytes]:
        """One write round trip with the tier's failure classification:

        - transport death  -> UncertainResultError (maybe applied);
        - ST_UNCERTAIN     -> UncertainResultError (quorum tier: applied on
          a leader that lost quorum/stepped down before majority ack);
        - definite pre-apply refusals ("read-only follower", "no quorum")
          -> find the real leader and retry ONCE — nothing was applied, so
          the retry cannot double-apply."""
        deadline = None
        while True:
            try:
                status, payload = self._write_call(op, body)
            except (OSError, EOFError) as exc:
                raise UncertainResultError(
                    f"{what} outcome unknown: {exc}") from exc
            if status != ST_ERROR or not any(m in payload
                                             for m in _REDIRECTABLE):
                break
            # wait out an in-flight election / follower attachment window
            # (bounded): leadership is usually seconds away, and nothing
            # was applied, so re-issuing cannot double-apply
            if deadline is None:
                deadline = time.monotonic() + 5.0
            elif time.monotonic() >= deadline:
                raise StorageError(f"{what} refused: {payload!r}")
            try:
                self.find_leader()
            except StorageError:
                pass  # nobody claims leadership yet; retry until deadline
            # jittered: a fleet of refused writers probing an in-flight
            # election must not re-collide on the same beat (kblint KB118)
            time.sleep(0.25 * random.uniform(0.6, 1.4))
        if status == ST_UNCERTAIN:
            raise UncertainResultError(f"{what}: {payload!r}")
        return status, payload

    # ------------------------------------------------------------- contract
    def get_timestamp_oracle(self) -> int:
        epoch_at_send = self._epoch_snapshot()  # see _observe docstring
        status, payload = self._call(OP_TSO, b"")
        if status != ST_OK:
            raise StorageError("TSO failed")
        ts = struct.unpack("<Q", payload)[0]
        self._observe(ts, epoch_at_send)
        return ts

    def get_partitions(self, start: bytes, end: bytes) -> list[Partition]:
        status, payload = self._call(
            OP_PARTITIONS, struct.pack("<I", self._n_partitions))
        if status != ST_OK:
            return [Partition(start, end)]
        r = _Reader(payload)
        borders = [r.bytes_() for _ in range(r.u32())]
        borders = [b for b in borders if (not start or b > start) and (not end or b < end)]
        edges = [start, *borders, end]
        return [Partition(edges[i], edges[i + 1]) for i in range(len(edges) - 1)]

    def get(self, key: bytes, snapshot_ts: int | None = None) -> bytes:
        status, payload = self._read_call(
            OP_GET, struct.pack("<Q", snapshot_ts or 0) + key, snapshot_ts or 0)
        if status == ST_NOT_FOUND:
            raise KeyNotFoundError(key)
        if status != ST_OK:
            raise StorageError(f"get failed (status {status})")
        return payload

    def iter(self, start, end, snapshot_ts=None, limit=0) -> Iter:
        reverse = bool(end) and start > end
        return _PagedIter(self, start, end, snapshot_ts, limit, reverse)

    def begin_batch_write(self) -> BatchWrite:
        return RemoteBatchWrite(self)

    def support_ttl(self) -> bool:
        return self._support_ttl

    def checkpoint(self) -> None:
        status, payload = self._call(OP_CHECKPOINT, b"")
        if status != ST_OK:
            raise StorageError(
                f"checkpoint failed on kbstored (status {status}): {payload!r}")

    # ---------------------------------------------------------- replication
    def _call_addr(self, addr: tuple[str, int], op: int, body: bytes,
                   timeout: float | None = None):
        """One-off request to a specific tier member (control-plane ops)."""
        conn = _PooledConn(addr, timeout if timeout is not None else self._timeout)
        try:
            return conn.call(op, body)
        finally:
            conn.close()

    def member_info(self, idx: int | None = None,
                    timeout: float | None = None):
        """(is_follower, clock, attached_replicas, upstream_alive, epoch) of
        a tier member — the ONE decoder of the ROLE payload. Every
        observation feeds the (epoch, ts) lineage tracker; pre-epoch
        daemons report epoch 0."""
        # snapshot the primary index under the lock: _repoint swaps it from
        # failover threads, and an unguarded read here has no common guard
        # with that write (kblint KB120)
        with self._rr_lock:
            primary = self._primary
        addr = self._addresses[primary if idx is None else idx]
        status, payload = self._call_addr(addr, OP_ROLE, b"", timeout=timeout)
        if status != ST_OK:
            raise StorageError(f"ROLE failed (status {status})")
        r = _Reader(payload)
        is_f, ts, n_rep = bool(r.u8()), r.u64(), r.u32()
        alive = bool(r.u8()) if len(payload) >= 14 else False
        epoch = r.u64() if len(payload) >= 22 else 0
        self._observe(ts, epoch)
        with self._rr_lock:
            if idx is None or idx == self._primary:
                self._cur_epoch = max(self._cur_epoch, epoch)
        return is_f, ts, n_rep, alive, epoch

    def role(self, idx: int | None = None,
             timeout: float | None = None) -> tuple[bool, int, int]:
        """(is_follower, clock, attached_replicas) of a tier member."""
        is_f, ts, n_rep, _, _ = self.member_info(idx, timeout=timeout)
        return is_f, ts, n_rep

    def upstream_alive(self, idx: int, timeout: float | None = None) -> bool:
        """Does the follower at ``idx`` still receive its primary's stream
        (heartbeats included)? The client side of the split-brain guard."""
        try:
            return self.member_info(idx, timeout=timeout)[3]
        except (OSError, EOFError, StorageError):
            return False

    def promote(self, idx: int, force: bool = False) -> None:
        """Promote the follower at ``idx`` to primary (idempotent). The
        follower REFUSES while its replication stream from the primary is
        alive unless ``force`` — the tier's split-brain guard."""
        body = struct.pack("<B", 1) if force else b""
        status, payload = self._call_addr(self._addresses[idx], OP_PROMOTE, body)
        if status != ST_OK:
            raise StorageError(f"PROMOTE failed (status {status}): {payload!r}")

    def failover(self, force: bool = False) -> int:
        """Promote the first reachable follower and repoint the pool at it.

        Deliberately NOT automatic on transport blips: the CALLER decides
        when the primary is dead (election layer / operator) — auto-flipping
        here would risk split-brain, the problem raft solves for the
        reference's TiKV (tikv.go:123-153). Returns the new primary index.
        In-flight requests on old pool conns surface as
        UncertainResultError and repair through the retry path as usual.
        """
        last_exc: Exception | None = None
        with self._rr_lock:
            primary0 = self._primary
        for idx, addr in enumerate(self._addresses):
            if idx == primary0:
                continue
            try:
                # only promote actual FOLLOWERS: a restarted old primary
                # answers PROMOTE with an idempotent OK, and repointing at
                # it would silently abandon every write acked since the
                # first failover (stale-lineage guard)
                is_follower, cand_ts, _, _, cand_epoch = self.member_info(idx)
                if not is_follower:
                    # already a primary. Adopt it ONLY when its lineage is
                    # at least everything this client ever observed —
                    # lexicographic (epoch, ts): a freshly-promoted
                    # follower carries a HIGHER epoch; a restarted old
                    # primary carries an older epoch no matter how far its
                    # standalone-acked clock ran ahead.
                    with self._rr_lock:
                        observed = self._max_seen
                    adoptable = (cand_epoch, cand_ts) >= observed
                    if adoptable:
                        # _repoint updates _cur_epoch inside its locked
                        # swap; setting it here-and-early would tag acks
                        # from the OLD primary with the new epoch if the
                        # repoint fails or is refused
                        self._repoint(idx, addr,
                                      lineage=(cand_epoch, cand_ts))
                        return idx
                    last_exc = StorageError(
                        f"{addr} is a primary of a stale lineage "
                        f"((epoch, ts) ({cand_epoch}, {cand_ts}) < observed "
                        f"{observed}); refusing")
                    continue
                self.promote(idx, force=force)
            except (OSError, EOFError, StorageError) as exc:
                last_exc = exc
                continue
            # learn the bumped epoch BEFORE repointing so the swap carries
            # the promoted member's lineage — without it a concurrent
            # adoption of an even newer leader during the (seconds-wide)
            # connect window could be silently overwritten with this one
            lineage = None
            try:
                _, new_ts, _, _, new_epoch = self.member_info(idx)
                lineage = (new_epoch, new_ts)
            except Exception:
                pass  # degrade to an unvalidated swap rather than fail over
            self._repoint(idx, addr, lineage=lineage)
            if lineage is None:
                try:
                    self.member_info(idx)  # learn the bumped epoch
                except Exception:
                    pass
            return idx
        raise StorageError(f"no promotable follower reachable: {last_exc}")

    def find_leader(self, probe_timeout: float = 1.0) -> int:
        """Quorum-tier leader discovery: probe every member's ROLE, pick the
        reachable non-follower with the highest (epoch, ts) lineage, and
        repoint the pool at it. Unlike failover() this never PROMOTEs —
        quorum tiers elect internally (kbstored --peers); the client only
        has to find where leadership landed. The stale-lineage watermark
        guard still applies: a leader below everything this client has
        observed is a split-brain artifact, not a target."""
        best = None  # (epoch, ts, idx, addr)
        for idx, addr in enumerate(self._addresses):
            try:
                is_f, ts, _, _, epoch = self.member_info(
                    idx, timeout=probe_timeout)
            except (OSError, EOFError, StorageError):
                continue
            if is_f:
                continue
            if best is None or (epoch, ts) > (best[0], best[1]):
                best = (epoch, ts, idx, addr)
        if best is None:
            raise StorageError("no leader reachable in the tier")
        epoch, ts, idx, addr = best
        with self._rr_lock:
            if (epoch, ts) < self._max_seen:
                stale = self._max_seen
                already = True  # unused on the raise path
            else:
                stale = None
                already = idx == self._primary
                if already:
                    # already pointed there: just refresh the snapshot.
                    # The repoint case defers to _repoint's locked swap so
                    # a refused/failed swap can't leave _cur_epoch
                    # claiming a leader that was never adopted.
                    self._cur_epoch = epoch
        if stale is not None:
            raise StorageError(
                f"best reachable leader {addr} has lineage ({epoch}, {ts}) "
                f"< observed {stale}; refusing to adopt")
        if not already:
            self._repoint(idx, addr, lineage=(epoch, ts))
        return idx

    def _repoint(self, idx: int, addr: tuple[str, int],
                 lineage: tuple[int, int] | None = None) -> None:
        """Swing the pool to a new primary; old conns surface as
        UncertainResultError to in-flight callers and repair as usual.

        ``lineage`` is the (epoch, ts) the caller's adoption decision was
        based on; it is RE-VALIDATED against ``_max_seen`` inside the swap
        lock, because between the caller's guard and this swap another
        thread can adopt a newer leader (and the connect loop below makes
        that window seconds wide) — losing that race must abandon the
        fresh pool, not overwrite the newer adoption with a stale one."""
        # Connect the replacement pool BEFORE taking _rr_lock: a TCP
        # connect can block for seconds on an unreachable host, and doing
        # it under the lock convoys every reader thread through failover
        # (kblint KB112). It also means a failed connect leaves the OLD
        # primary/pool intact instead of a repointed primary with stale
        # connections.
        with self._rr_lock:
            pool_size = len(self._pool)
        fresh: list[_PooledConn] = []
        try:
            for _ in range(pool_size):
                fresh.append(_PooledConn(addr, self._timeout))
        except OSError:
            for c in fresh:
                c.close()
            raise
        with self._rr_lock:
            if lineage is not None and lineage < self._max_seen:
                stale = self._max_seen
            else:
                stale = None
                self._primary = idx
                self._address = addr
                if lineage is not None:
                    # the epoch snapshot must advance WITH the adoption —
                    # updating it before the swap (or not at all) leaves
                    # acks tagged with the wrong lineage when the swap
                    # fails or when another thread raced us here
                    self._cur_epoch = lineage[0]
                old, self._pool = self._pool, fresh
                old_f, self._fpools = self._fpools, {}
                self._frole.clear()
                self._fdown.clear()
        if stale is not None:
            for c in fresh:
                c.close()
            raise StorageError(
                f"leader {addr} lineage {lineage} fell behind observed "
                f"{stale} while repointing; refusing to adopt")
        for c in old:
            c.close()
        for conns in old_f.values():
            for c in conns:
                c.close()

    def close(self) -> None:
        for c in self._pool:
            c.close()
        for conns in self._fpools.values():
            for c in conns:
                c.close()
        self._fpools.clear()

    def export_mvcc(self, start: bytes, end: bytes, snapshot_ts: int,
                    key_width: int, magic: bytes, tombstone: bytes):
        """Bulk-export version rows as numpy arrays — the TPU-mirror rebuild
        fast path over the wire (kbstored OP_EXPORT → kb_mvcc_export_wire).
        The server parses the MVCC rows; the client only reinterprets the
        columnar page buffers, so a multi-million-row mirror rebuild costs
        O(pages) Python instead of O(rows). Same contract as the embedded
        engine's export (storage/native.py export_mvcc): returns
        (keys uint8[N, W], lens int32[N], revs uint64[N], tomb bool[N],
        value_arena uint8[...], offsets uint64[N+1])."""
        import numpy as np

        snap = snapshot_ts or self.get_timestamp_oracle()
        pages: list[tuple] = []
        cursor = start
        while True:
            body = bytearray(struct.pack("<QQI", snap, key_width, 0))
            for f in (magic, tombstone, cursor, end):
                _bytes_field(body, f)
            status, payload = self._call(OP_EXPORT, bytes(body))
            if status != ST_OK:
                raise StorageError(f"export failed (status {status}): {payload!r}")
            r = _Reader(payload)
            n = r.u32()
            more = bool(r.u8())
            next_start = r.bytes_()
            buf = payload
            off = r.off

            def take(count, dtype, shape=None):
                nonlocal off
                arr = np.frombuffer(buf, dtype=dtype, count=count, offset=off)
                off += arr.nbytes
                return arr.reshape(shape) if shape else arr

            keys = take(n * key_width, np.uint8, (n, key_width))
            lens = take(n, np.int32)
            revs = take(n, np.uint64)
            tomb = take(n, np.uint8)
            (alen,) = struct.unpack_from("<Q", buf, off)
            off += 8
            arena = np.frombuffer(buf, dtype=np.uint8, count=alen, offset=off)
            off += alen
            offsets = take(n + 1, np.uint64)
            if n:
                pages.append((keys, lens, revs, tomb, arena, offsets))
            if not more:
                break
            cursor = next_start

        if not pages:
            return (np.zeros((0, key_width), np.uint8), np.zeros(0, np.int32),
                    np.zeros(0, np.uint64), np.zeros(0, bool),
                    np.zeros(0, np.uint8), np.zeros(1, np.uint64))
        keys = np.concatenate([p[0] for p in pages])
        lens = np.concatenate([p[1] for p in pages])
        revs = np.concatenate([p[2] for p in pages])
        tomb = np.concatenate([p[3] for p in pages]).astype(bool)
        arena = np.concatenate([p[4] for p in pages])
        # per-page offsets are arena-relative; rebase by each page's start
        bases = np.cumsum([0] + [len(p[4]) for p in pages[:-1]]).astype(np.uint64)
        offsets = np.concatenate(
            [pages[0][5]] + [p[5][1:] + b for p, b in zip(pages[1:], bases[1:])]
        )
        return keys, lens, revs, tomb, arena, offsets

    # ------------------------------------------- MVCC one-round-trip paths
    def write_batch(self, ops: list) -> list:
        """Group-commit executor (docs/writes.md): the shared loop over the
        one-round-trip MVCC frames below. The wire round trips stay per-op
        until kbstored grows an OP_WRITE_BATCH frame (documented future
        work); the group still pays one scheduler dispatch, one contiguous
        revision block, and one ring pass above the engine."""
        from .groupwrite import mvcc_write_batch

        return mvcc_write_batch(self, ops)

    def mvcc_write(self, rev_key, rev_val, expected, obj_key, obj_val,
                   last_key, last_val, ttl_seconds=0) -> None:
        body = bytearray(struct.pack(
            "<Bq", 1 if expected is not None else 0, ttl_seconds))
        for f in (rev_key, rev_val, expected or b"", obj_key, obj_val,
                  last_key, last_val):
            _bytes_field(body, f)
        status, payload = self._write_frame(OP_MVCC_WRITE, bytes(body),
                                            "mvcc write")
        if status == ST_OK:
            return
        if status == ST_CONFLICT:
            r = _Reader(payload)
            has = r.u8()
            val = r.bytes_()
            raise CASFailedError(Conflict(0, rev_key, val if has else None))
        raise StorageError(f"mvcc write failed (status {status}): {payload!r}")

    def mvcc_delete(self, rev_key, expected_rev, new_rev, new_record,
                    tombstone, last_key, last_val):
        body = bytearray(struct.pack("<QQ", expected_rev, new_rev))
        for f in (rev_key, new_record, tombstone, last_key, last_val):
            _bytes_field(body, f)
        status, payload = self._write_frame(OP_MVCC_DELETE, bytes(body),
                                            "mvcc delete")
        if status == ST_NOT_FOUND:
            latest = struct.unpack("<Q", payload)[0] if len(payload) >= 8 else 0
            return "not_found", None, latest
        if status in (ST_OK, ST_CONFLICT):
            r = _Reader(payload)
            has = r.u8()
            prev = r.bytes_()
            latest = r.u64()
            return ("ok" if status == ST_OK else "mismatch",
                    prev if has else None, latest)
        if status == ST_WAL:
            raise StorageError("WAL append failed; delete aborted")
        if status == ST_DRIFT:
            latest = struct.unpack("<Q", payload)[0]
            from .errors import RevisionDriftBackError

            raise RevisionDriftBackError(
                f"revision drift on delete (latest {latest})", latest=latest)
        raise StorageError(f"mvcc delete failed (status {status}): {payload!r}")


def _factory(**kwargs) -> RemoteKvStorage:
    return RemoteKvStorage(**kwargs)


register_engine("remote", _factory)
