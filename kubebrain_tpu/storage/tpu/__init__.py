"""TPU block-store engine (``--storage=tpu``)."""

from .blocks import Mirror, build_mirror
from .engine import TpuKvStorage, TpuScanner

__all__ = ["Mirror", "build_mirror", "TpuKvStorage", "TpuScanner"]
