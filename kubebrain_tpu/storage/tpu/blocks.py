"""HBM-resident sorted block mirror of the MVCC keyspace.

The TiKV-role engine re-imagined for TPU (SURVEY §2.8): the authoritative
store stays on host (writes are pointwise and CAS-heavy — wrong for TPU);
the *scan-hot columns* (packed user key, revision, tombstone flag) are
mirrored into device HBM as P sorted partitions, padded to a common row
count and sharded over the mesh's ``part`` axis. Values never leave the
host — kernels decide *which* rows are visible; the host materializes bytes
by row index (the same division of labor as reference workers streaming
KVs out of engine iterators, scanner.go:395-427).

Partition borders are always user-key-aligned (adjustPartitionBorders,
scanner.go:202-225) so no version chain straddles devices and shard-local
kernels need no cross-device carry.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from ...ops import keys as keyops


@dataclass
class Mirror:
    # device (sharded over "part" on axis 0)
    keys_dev: jax.Array     # uint32[P, N, C]
    rh_dev: jax.Array       # uint32[P, N]
    rl_dev: jax.Array       # uint32[P, N]
    tomb_dev: jax.Array     # bool[P, N]
    ttl_dev: jax.Array      # bool[P, N]
    n_valid_dev: jax.Array  # int32[P]
    # host copies (row-aligned with device arrays)
    keys_host: np.ndarray   # uint32[P, N, C]
    revs_host: np.ndarray   # uint64[P, N]
    tomb_host: np.ndarray   # bool[P, N]
    n_valid: np.ndarray     # int32[P]
    user_keys: list[list[bytes]]   # per partition, per row
    values: list[list[bytes]]      # per partition, per row
    snapshot_ts: int
    max_rev: int

    @property
    def partitions(self) -> int:
        return self.keys_host.shape[0]

    @property
    def rows(self) -> int:
        return int(self.n_valid.sum())

    def partition_first_keys(self) -> list[bytes]:
        out = []
        for p in range(self.partitions):
            out.append(self.user_keys[p][0] if self.n_valid[p] > 0 else b"")
        return out


TTL_PREFIX = b"/events/"


def build_mirror(
    rows: list[tuple[bytes, int, bytes]],
    mesh,
    key_width: int,
    snapshot_ts: int,
) -> Mirror:
    """Build a Mirror from sorted (user_key, revision, value) version rows.

    Splits into P = mesh-size partitions balanced by row count, never
    splitting a user key's version chain across partitions.
    """
    n_parts = int(np.prod(mesh.devices.shape)) if mesh is not None else 1
    n = len(rows)
    # choose user-key-aligned split offsets
    offsets = [0]
    target = max(1, (n + n_parts - 1) // n_parts)
    for p in range(1, n_parts):
        pos = min(p * target, n)
        while 0 < pos < n and rows[pos][0] == rows[pos - 1][0]:
            pos += 1  # don't split a version chain
        pos = max(pos, offsets[-1])
        offsets.append(pos)
    offsets.append(n)
    counts = [offsets[i + 1] - offsets[i] for i in range(n_parts)]
    n_max = max(max(counts), 8)

    c = key_width // 4
    keys_h = np.zeros((n_parts, n_max, c), dtype=np.uint32)
    revs_h = np.zeros((n_parts, n_max), dtype=np.uint64)
    tomb_h = np.zeros((n_parts, n_max), dtype=bool)
    ttl_h = np.zeros((n_parts, n_max), dtype=bool)
    user_keys: list[list[bytes]] = []
    values: list[list[bytes]] = []
    max_rev = 0

    from ...backend.common import TOMBSTONE

    for p in range(n_parts):
        part_rows = rows[offsets[p] : offsets[p + 1]]
        uks = [r[0] for r in part_rows]
        if part_rows:
            packed, _ = keyops.pack_keys(uks, key_width)
            keys_h[p, : len(part_rows)] = packed
            revs = np.array([r[1] for r in part_rows], dtype=np.uint64)
            revs_h[p, : len(part_rows)] = revs
            tomb_h[p, : len(part_rows)] = [r[2] == TOMBSTONE for r in part_rows]
            ttl_h[p, : len(part_rows)] = [uk.startswith(TTL_PREFIX) for uk in uks]
            max_rev = max(max_rev, int(revs.max()))
        user_keys.append(uks)
        values.append([r[2] for r in part_rows])

    rh, rl = keyops.split_revs(revs_h.reshape(-1))
    rh = rh.reshape(n_parts, n_max)
    rl = rl.reshape(n_parts, n_max)
    n_valid = np.array(counts, dtype=np.int32)

    def put(arr, *trailing_none):
        if mesh is None:
            return jax.device_put(arr)
        spec = PartitionSpec("part", *(None,) * (arr.ndim - 1))
        return jax.device_put(arr, NamedSharding(mesh, spec))

    return Mirror(
        keys_dev=put(keys_h),
        rh_dev=put(rh),
        rl_dev=put(rl),
        tomb_dev=put(tomb_h),
        ttl_dev=put(ttl_h),
        n_valid_dev=put(n_valid),
        keys_host=keys_h,
        revs_host=revs_h,
        tomb_host=tomb_h,
        n_valid=n_valid,
        user_keys=user_keys,
        values=values,
        snapshot_ts=snapshot_ts,
        max_rev=max_rev,
    )
