"""HBM-resident sorted block mirror of the MVCC keyspace.

The TiKV-role engine re-imagined for TPU (SURVEY §2.8): the authoritative
store stays on host (writes are pointwise and CAS-heavy — wrong for TPU);
the *scan-hot columns* (packed user key, revision, tombstone flag) are
mirrored into device HBM as P sorted partitions, padded to a common row
count and sharded over the mesh's ``part`` axis. Values never leave the
host — kernels decide *which* rows are visible; the host materializes bytes
by row index from per-partition byte arenas (no per-row Python objects, so
a million-row mirror rebuild is numpy memcpy, not object churn).

Partition borders are always user-key-aligned (adjustPartitionBorders,
scanner.go:202-225) so no version chain straddles devices and shard-local
kernels need no cross-device carry.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from ...ops import keys as keyops
from .encode import EncodeOverflow, KeyEncoding, build_encoding

TTL_PREFIX = b"/events/"


@dataclass
class Mirror:
    # device (sharded over "part" on axis 0). With a live ``encoding`` the
    # key columns hold ENCODED rows (storage/tpu/encode.py: code chunk +
    # stripped suffix, C' << C chunks) whose lexicographic order equals
    # raw byte order — the kernels compare them unchanged; ``lens_host``
    # then holds encoded-suffix byte lengths.
    keys_dev: jax.Array     # uint32[P, N, C]
    rh_dev: jax.Array       # uint32[P, N]
    rl_dev: jax.Array       # uint32[P, N]
    tomb_dev: jax.Array     # bool[P, N]
    ttl_dev: jax.Array      # bool[P, N]
    n_valid_dev: jax.Array  # int32[P]
    # host copies (row-aligned with device arrays)
    keys_host: np.ndarray   # uint32[P, N, C]
    lens_host: np.ndarray   # int32[P, N]
    revs_host: np.ndarray   # uint64[P, N]
    tomb_host: np.ndarray   # bool[P, N]
    n_valid: np.ndarray     # int32[P]
    # values: one byte arena + offsets per partition
    val_arena: list[np.ndarray]    # uint8[...]
    val_offsets: list[np.ndarray]  # uint64[nv+1]
    snapshot_ts: int
    max_rev: int
    key_width: int = 0              # RAW packed key width (bytes)
    encoding: KeyEncoding | None = None
    # host TTL flag column (row-aligned with ttl_dev): lets the incremental
    # stored-domain merge and the pallas TTL layout run without a device
    # pull, and lets merged TTL flags ride the delta instead of being
    # recomputed from (undecodable) encoded keys
    ttl_host: np.ndarray | None = None  # bool[P, N]

    @property
    def partitions(self) -> int:
        return self.keys_host.shape[0]

    @property
    def rows(self) -> int:
        return int(self.n_valid.sum())

    @property
    def raw_key_width(self) -> int:
        """RAW packed key width in bytes (the width decoded keys pad to);
        falls back to the stored chunk width for pre-encoding mirrors."""
        return self.key_width or self.keys_host.shape[2] * 4

    def user_key(self, p: int, i: int) -> bytes:
        if self.encoding is not None:
            return self.encoding.decode_one(
                self.keys_host[p, i], int(self.lens_host[p, i]))
        row = keyops.chunks_to_u8(self.keys_host[p, i : i + 1])[0]
        return row[: int(self.lens_host[p, i])].tobytes()

    def value(self, p: int, i: int) -> bytes:
        o = self.val_offsets[p]
        return self.val_arena[p][int(o[i]) : int(o[i + 1])].tobytes()

    def decoded_keys(self, p: int, rows: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """(raw_u8, raw_lens) for row indices of one partition — the ONE
        decode funnel (kblint KB116): encoded key bytes only turn back into
        raw bytes here, sized by the caller's visible-row set."""
        if self.encoding is not None:
            return self.encoding.decode_rows(
                self.keys_host[p][rows], self.lens_host[p][rows])
        return (keyops.chunks_to_u8(self.keys_host[p][rows]),
                self.lens_host[p][rows])

    def materialize(self, p: int, rows: np.ndarray):
        """Bulk (keys, values, revisions) for sorted row indices of one
        partition — one vectorized unpack instead of per-row slicing.
        Decoding (when the mirror is encoded) happens here, for exactly the
        visible rows — never for the whole mirror."""
        k_u8, k_lens = self.decoded_keys(p, rows)
        keys = [k_u8[i, : int(k_lens[i])].tobytes() for i in range(len(k_u8))]
        o = self.val_offsets[p].astype(np.int64)
        arena = self.val_arena[p]
        values = [arena[o[i] : o[i + 1]].tobytes() for i in map(int, rows)]
        revs = self.revs_host[p][rows]
        return keys, values, revs

    def partition_first_keys(self) -> list[bytes]:
        return [
            self.user_key(p, 0) if self.n_valid[p] > 0 else b""
            for p in range(self.partitions)
        ]

    def flat_arrays(self):
        """Valid rows of every partition, concatenated in order:
        (keys_u8[N, W], lens, revs, tomb, arena, offsets). Always RAW-domain
        keys — an encoded mirror decodes every valid row here, which is why
        this path only backs full-rebuild maintenance, never serving."""
        parts_u8, parts_lens, parts_revs, parts_tomb = [], [], [], []
        arenas, lens_list = [], []
        for p in range(self.partitions):
            nv = int(self.n_valid[p])
            k_u8, k_lens = self.decoded_keys(p, np.arange(nv))
            parts_u8.append(k_u8)
            parts_lens.append(np.asarray(k_lens, np.int32))
            parts_revs.append(self.revs_host[p, :nv])
            parts_tomb.append(self.tomb_host[p, :nv])
            arenas.append(self.val_arena[p][: int(self.val_offsets[p][nv])])
            o = self.val_offsets[p].astype(np.int64)
            lens_list.append(o[1 : nv + 1] - o[:nv])
        # empty-mirror fallback: the RAW key width the caller will merge
        # against, never a hardcoded 4 (a non-default --key-width mirror
        # used to come back as uint8[0, 4] and poison the rebuild concat)
        keys_u8 = (np.concatenate(parts_u8) if parts_u8
                   else np.zeros((0, self.raw_key_width), np.uint8))
        arena = np.concatenate(arenas) if arenas else np.zeros(0, np.uint8)
        row_lens = np.concatenate(lens_list) if lens_list else np.zeros(0, np.int64)
        offsets = np.zeros(len(row_lens) + 1, dtype=np.uint64)
        offsets[1:] = np.cumsum(row_lens).astype(np.uint64)
        return (
            keys_u8,
            np.concatenate(parts_lens) if parts_lens else np.zeros(0, np.int32),
            np.concatenate(parts_revs) if parts_revs else np.zeros(0, np.uint64),
            np.concatenate(parts_tomb) if parts_tomb else np.zeros(0, bool),
            arena,
            offsets,
        )


def rows_to_arrays(rows: list[tuple[bytes, int, bytes]], width: int):
    """Python (user_key, rev, value) rows → the array quintuple."""
    n = len(rows)
    keys_u8 = np.zeros((n, width), dtype=np.uint8)
    lens = np.zeros(n, dtype=np.int32)
    revs = np.zeros(n, dtype=np.uint64)
    from ...backend.common import TOMBSTONE

    tomb = np.zeros(n, dtype=bool)
    offsets = np.zeros(n + 1, dtype=np.uint64)
    chunks_vals = []
    off = 0
    for i, (k, rev, v) in enumerate(rows):
        keys_u8[i, : len(k)] = np.frombuffer(k, dtype=np.uint8)
        lens[i] = len(k)
        revs[i] = rev
        tomb[i] = v == TOMBSTONE
        chunks_vals.append(v)
        off += len(v)
        offsets[i + 1] = off
    arena = np.frombuffer(b"".join(chunks_vals), dtype=np.uint8).copy() if rows else np.zeros(0, np.uint8)
    return keys_u8, lens, revs, tomb, arena, offsets


def _merge_sorted_blocks(blocks: list[tuple]) -> tuple:
    """k-way merge of row-array tuples sorted by (key, revision).

    Each block is ``(keys_u8[n, W], *columns, arena, offsets)`` — any
    number of row-aligned 1-D columns between the key matrix and the
    value arena. Sort key = key bytes + big-endian revision (the column
    right after the keys), compared as a void scalar (memcmp) — a single
    numpy argsort, no Python comparisons. Shared by the raw-domain
    :func:`merge_sorted_arrays` and the stored-domain
    :func:`merge_sorted_stored` so the two merge paths cannot diverge."""
    ncols = len(blocks[0]) - 3  # columns between keys and arena
    keys_u8 = np.concatenate([b[0] for b in blocks])
    cols = [np.concatenate([b[1 + c] for b in blocks]) for c in range(ncols)]
    revs = cols[1]  # (keys, lens, revs, ...) in every caller
    n, w = keys_u8.shape
    rev_be = revs[:, None].astype(">u8").view(np.uint8).reshape(n, 8)
    sort_rows = np.ascontiguousarray(np.concatenate([keys_u8, rev_be], axis=1))
    void = sort_rows.view([("v", f"V{w + 8}")]).reshape(n)
    perm = np.argsort(void, kind="stable")
    # merge arenas (rebase each block's offsets), then reorder by perm
    arena = np.concatenate([b[-2] for b in blocks])
    bases = np.cumsum([0] + [len(b[-2]) for b in blocks[:-1]]).astype(np.int64)
    offsets = np.concatenate(
        [b[-1].astype(np.int64)[:-1] + base
         for b, base in zip(blocks, bases)]
        + [np.array([len(arena)], dtype=np.int64)]
    ).astype(np.uint64)
    new_arena, new_offsets = keyops.gather_arena(arena, offsets, perm)
    return (keys_u8[perm], *(c[perm] for c in cols), new_arena, new_offsets)


def merge_sorted_arrays(a, b):
    """Merge two RAW row-array sextuples ``(keys, lens, revs, tomb,
    arena, offsets)`` into one, sorted by (key, revision)."""
    return _merge_sorted_blocks([a, b])


def padded_capacity(count: int) -> int:
    """Row capacity for a partition holding ``count`` rows: the next power
    of two past 1.25x headroom. Headroom lets incremental delta merges land
    in place without reshaping every shard; the power-of-two bucket keeps
    kernel shapes stable across rebuilds (bounded recompiles)."""
    want = max(256, int(count * 1.25) + 1)
    cap = 256
    while cap < want:
        cap *= 2
    return cap


def compute_ttl_flags(keys_u8: np.ndarray, lens: np.ndarray) -> np.ndarray:
    ttl_pref = np.frombuffer(TTL_PREFIX, dtype=np.uint8)
    if len(keys_u8) == 0:
        return np.zeros(0, dtype=bool)
    pref = keys_u8[:, : len(ttl_pref)]
    return (pref == ttl_pref).all(axis=1) & (lens >= len(ttl_pref))


def build_mirror_from_arrays(
    keys_u8: np.ndarray,
    lens: np.ndarray,
    revs: np.ndarray,
    tomb: np.ndarray,
    arena: np.ndarray,
    offsets: np.ndarray,
    mesh,
    key_width: int,
    snapshot_ts: int,
    n_parts: int | None = None,
    encode: bool = False,
) -> Mirror:
    """Sorted RAW row arrays → partitioned, padded, device-resident Mirror.

    ``n_parts`` decouples the partition count from the mesh size
    (--scan-partitions): P must be a multiple of the mesh's ``part`` axis so
    ``PartitionSpec("part")`` places P//N contiguous partitions per device.
    Default: one partition per mesh device.

    ``encode=True`` builds an order-preserving prefix dictionary from the
    snapshot keys (storage/tpu/encode.py) and stores ENCODED rows — the
    device key column shrinks from ``key_width`` to ``encoding.width``
    bytes per row while every kernel compare stays byte-order-exact.
    Partition borders, TTL flags, and the user-key-aligned split are
    computed from the RAW keys (encoded order equals raw order, so the
    split is identical either way)."""
    if n_parts is None:
        n_parts = int(np.prod(mesh.devices.shape)) if mesh is not None else 1
    n = len(keys_u8)
    if keys_u8.shape[1] != key_width:
        padded = np.zeros((n, key_width), dtype=np.uint8)
        padded[:, : keys_u8.shape[1]] = keys_u8[:, :key_width]
        keys_u8 = padded

    encoding = build_encoding(keys_u8, lens, raw_width=key_width) \
        if (encode and n) else None
    if encoding is not None:
        # cannot overflow: the dictionary was built from these very keys
        store_u8, store_lens = encoding.encode_keys(keys_u8, lens)
        store_width = encoding.width
    else:
        store_u8, store_lens, store_width = keys_u8, lens, key_width

    # user-key-aligned balanced split offsets (vectorized boundary detect)
    if n:
        same_prev = np.zeros(n, dtype=bool)
        same_prev[1:] = (keys_u8[1:] == keys_u8[:-1]).all(axis=1)
    splits = [0]
    target = max(1, (n + n_parts - 1) // n_parts)
    for p in range(1, n_parts):
        pos = min(p * target, n)
        while 0 < pos < n and same_prev[pos]:
            pos += 1
        splits.append(max(pos, splits[-1]))
    splits.append(n)
    counts = [splits[i + 1] - splits[i] for i in range(n_parts)]
    n_max = padded_capacity(max(counts) if counts else 0)

    c = store_width // 4
    keys_h = np.zeros((n_parts, n_max, c), dtype=np.uint32)
    lens_h = np.zeros((n_parts, n_max), dtype=np.int32)
    revs_h = np.zeros((n_parts, n_max), dtype=np.uint64)
    tomb_h = np.zeros((n_parts, n_max), dtype=bool)
    ttl_h = np.zeros((n_parts, n_max), dtype=bool)
    arenas, offs = [], []
    ttl_pref = np.frombuffer(TTL_PREFIX, dtype=np.uint8)

    off64 = offsets.astype(np.int64)
    for p in range(n_parts):
        lo, hi = splits[p], splits[p + 1]
        nv = hi - lo
        if nv:
            keys_h[p, :nv] = keyops.bytes_to_chunks(store_u8[lo:hi])
            lens_h[p, :nv] = store_lens[lo:hi]
            revs_h[p, :nv] = revs[lo:hi]
            tomb_h[p, :nv] = tomb[lo:hi]
            pref = keys_u8[lo:hi, : len(ttl_pref)]  # TTL flag: RAW prefix
            ttl_h[p, :nv] = (pref == ttl_pref).all(axis=1) & (lens[lo:hi] >= len(ttl_pref))
        arenas.append(arena[off64[lo] : off64[hi]].copy())
        offs.append((off64[lo : hi + 1] - off64[lo]).astype(np.uint64))

    rh, rl = keyops.split_revs(revs_h.reshape(-1))
    rh = rh.reshape(n_parts, n_max)
    rl = rl.reshape(n_parts, n_max)
    n_valid = np.array(counts, dtype=np.int32)

    def put(arr):
        if mesh is None:
            return jax.device_put(arr)
        spec = PartitionSpec("part", *(None,) * (arr.ndim - 1))
        return jax.device_put(arr, NamedSharding(mesh, spec))

    return Mirror(
        keys_dev=put(keys_h), rh_dev=put(rh), rl_dev=put(rl),
        tomb_dev=put(tomb_h), ttl_dev=put(ttl_h), n_valid_dev=put(n_valid),
        keys_host=keys_h, lens_host=lens_h, revs_host=revs_h, tomb_host=tomb_h,
        n_valid=n_valid, val_arena=arenas, val_offsets=offs,
        snapshot_ts=snapshot_ts,
        max_rev=int(revs.max()) if n else 0,
        key_width=key_width, encoding=encoding, ttl_host=ttl_h,
    )


def build_mirror(
    rows: list[tuple[bytes, int, bytes]],
    mesh,
    key_width: int,
    snapshot_ts: int,
    n_parts: int | None = None,
    encode: bool = False,
) -> Mirror:
    """Python-row convenience path (tests / generic engines)."""
    return build_mirror_from_arrays(
        *rows_to_arrays(rows, key_width), mesh, key_width, snapshot_ts,
        n_parts=n_parts, encode=encode,
    )


def _assemble_sharded(mesh, host_arr: np.ndarray, old_dev, dirty: set[int]):
    """Rebuild a [P, ...]-sharded device array, re-uploading ONLY the device
    shards holding dirty partitions when the layout places P//N contiguous
    partitions per device (any single-axis mesh with P a multiple of the
    device count — one-per-device is the k=1 case); clean shards reuse the
    existing device buffers. Falls back to a full device_put for
    replicated / multi-axis layouts."""
    if mesh is None:
        return jax.device_put(host_arr)
    spec = PartitionSpec("part", *(None,) * (host_arr.ndim - 1))
    sharding = NamedSharding(mesh, spec)
    P = host_arr.shape[0]
    axis_sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    n_dev = axis_sizes.get("part", 0)
    blocked = (
        old_dev is not None
        and len(mesh.axis_names) == 1
        and n_dev > 0
        and P % n_dev == 0
        and tuple(old_dev.shape) == tuple(host_arr.shape)
    )
    if not blocked:
        return jax.device_put(host_arr, sharding)
    k = P // n_dev  # contiguous partitions per device shard
    by_dev = {s.device: s.data for s in old_dev.addressable_shards}
    shards = []
    for i, d in enumerate(mesh.devices.flat):
        lo = i * k
        if d not in by_dev or any(p in dirty for p in range(lo, lo + k)):
            shards.append(jax.device_put(host_arr[lo : lo + k], d))
        else:
            shards.append(by_dev[d])
    return jax.make_array_from_single_device_arrays(host_arr.shape, sharding, shards)


def merge_sorted_stored(blocks: list[tuple]) -> tuple:
    """Merge k sorted STORED-domain row blocks into one.

    A stored block is a septuple ``(keys_u8[n, W], lens, revs, tomb, ttl,
    arena, offsets)`` whose key bytes live in the mirror's compare domain —
    raw packed bytes for a raw mirror, dictionary-encoded rows for an
    encoded one. Encoded lexicographic order equals raw byte order
    (storage/tpu/encode.py order preservation) and the encoding is
    injective, so ONE void argsort over ``key || rev_be`` merges encoded
    blocks as exactly as raw ones — the k-way merge of the write-path
    delta blocks (docs/writes.md). Shares :func:`_merge_sorted_blocks`
    with the raw-domain :func:`merge_sorted_arrays` so the two merge
    paths cannot diverge."""
    if len(blocks) == 1:
        return blocks[0]
    return _merge_sorted_blocks(blocks)


def merge_partitions_stored(
    mirror: Mirror,
    delta: tuple,  # sorted stored-domain septuple (see merge_sorted_stored)
    mesh,
    snapshot_ts: int,
) -> Mirror | None:
    """Incremental merge of a STORED-domain delta into the mirror — the
    write-path successor to :func:`merge_partitions_incremental`.

    The delta rows arrive already encoded against the published dictionary
    (sealed at write time, PR 9's incremental re-encode moved off the merge
    path), so a dirty partition merges by pure byte interleave: no
    partition decode, no raw-domain merge, no re-encode — per-merge host
    work is O(delta + dirty-partition memcpy). TTL flags ride the delta
    column and the mirror's host TTL column, so the merge never touches the
    device except for the dirty-shard-only republish
    (:func:`_assemble_sharded`, PR 7 machinery).

    A partition outgrowing its padded capacity does NOT force the full
    decode → re-dictionary → re-partition host rebuild: the stored-domain
    arrays grow to the next padded capacity by pure memcpy (every shard
    republishes — the device pays, the host never re-sorts or re-encodes),
    which is what keeps a sustained write storm on the incremental path
    between compactions (compaction re-partitions and re-fits capacity).
    Returns None only when the mirror predates the host TTL column or the
    delta's stored width no longer matches (a re-dictionaried mirror) —
    the true full-rebuild cases."""
    d_keys, d_lens, d_revs, d_tomb, d_ttl, d_arena, d_offsets = delta
    dn = len(d_keys)
    if dn == 0:
        return mirror
    if mirror.ttl_host is None:
        return None  # pre-ttl_host mirror: full rebuild re-derives everything
    P = mirror.partitions
    cap = mirror.keys_host.shape[1]
    W = mirror.keys_host.shape[2] * 4
    if d_keys.shape[1] != W:
        return None  # stored-width drift (re-dictionaried mirror): rebuild

    # route delta rows to non-empty partitions by the partitions' FIRST
    # STORED rows — stored order == raw order, so the stored compare routes
    # identically to the raw-domain routing of merge_partitions_incremental
    nonempty = [p for p in range(P) if mirror.n_valid[p] > 0]
    if not nonempty:
        return None  # nothing to merge into; full rebuild re-partitions
    firsts = np.stack([mirror.keys_host[p, 0] for p in nonempty])
    firsts_u8 = keyops.chunks_to_u8(firsts)
    firsts_void = keyops.u8_void(np.ascontiguousarray(firsts_u8))
    d_void = keyops.u8_void(np.ascontiguousarray(d_keys))
    # last non-empty partition whose first key <= row key (rows below the
    # first partition's floor route to it)
    pos = np.maximum(np.searchsorted(firsts_void, d_void, side="right") - 1, 0)
    row_part = np.asarray(nonempty, dtype=np.int64)[pos]
    # row_part is non-decreasing (sorted delta routed through sorted
    # firsts), so each dirty partition owns ONE contiguous delta slice —
    # locate every slice with two binary searches instead of a full-delta
    # boolean scan per partition (this runs in the merge critical section)
    dirty = np.unique(row_part).tolist()
    part_lo = np.searchsorted(row_part, np.asarray(dirty), side="left")
    part_hi = np.searchsorted(row_part, np.asarray(dirty), side="right")

    # capacity check up front: if any dirty partition outgrows the padded
    # cap, grow EVERY partition's stored arrays to the next padded
    # capacity (memcpy, no decode/re-encode/re-sort) and republish all
    # shards — the write-storm path that must never fall back to the full
    # host rebuild between compactions
    need = int(max(
        int(mirror.n_valid[p]) + int(hi - lo)
        for p, lo, hi in zip(dirty, part_lo, part_hi)))
    grew = need > cap
    if grew:
        new_cap = padded_capacity(need)
        keys_h = np.zeros((P, new_cap, mirror.keys_host.shape[2]),
                          dtype=mirror.keys_host.dtype)
        lens_h = np.zeros((P, new_cap), dtype=mirror.lens_host.dtype)
        revs_h = np.zeros((P, new_cap), dtype=mirror.revs_host.dtype)
        tomb_h = np.zeros((P, new_cap), dtype=mirror.tomb_host.dtype)
        ttl_h = np.zeros((P, new_cap), dtype=mirror.ttl_host.dtype)
        for p in range(P):
            nv = int(mirror.n_valid[p])
            keys_h[p, :nv] = mirror.keys_host[p, :nv]
            lens_h[p, :nv] = mirror.lens_host[p, :nv]
            revs_h[p, :nv] = mirror.revs_host[p, :nv]
            tomb_h[p, :nv] = mirror.tomb_host[p, :nv]
            ttl_h[p, :nv] = mirror.ttl_host[p, :nv]
        cap = new_cap
    else:
        # copy-on-write: readers hold the old Mirror object
        keys_h = mirror.keys_host.copy()
        lens_h = mirror.lens_host.copy()
        revs_h = mirror.revs_host.copy()
        tomb_h = mirror.tomb_host.copy()
        ttl_h = mirror.ttl_host.copy()
    n_valid = mirror.n_valid.copy()
    arenas = list(mirror.val_arena)
    offs = list(mirror.val_offsets)

    d_off64 = d_offsets.astype(np.int64)
    for p, lo, hi in zip(dirty, part_lo, part_hi):
        lo, hi = int(lo), int(hi)
        nv = int(n_valid[p])
        mn = nv + (hi - lo)
        part = (
            keyops.chunks_to_u8(mirror.keys_host[p, :nv]),
            mirror.lens_host[p, :nv], mirror.revs_host[p, :nv],
            mirror.tomb_host[p, :nv], mirror.ttl_host[p, :nv],
            mirror.val_arena[p][: int(mirror.val_offsets[p][nv])],
            mirror.val_offsets[p][: nv + 1],
        )
        dslice = (
            d_keys[lo:hi], d_lens[lo:hi], d_revs[lo:hi], d_tomb[lo:hi],
            d_ttl[lo:hi],
            d_arena[d_off64[lo] : d_off64[hi]],
            (d_off64[lo : hi + 1] - d_off64[lo]).astype(np.uint64),
        )
        mk, ml, mr, mt, mttl, ma, mo = merge_sorted_stored([part, dslice])
        keys_h[p, :mn] = keyops.bytes_to_chunks(np.ascontiguousarray(mk))
        lens_h[p, :mn] = ml
        revs_h[p, :mn] = mr
        tomb_h[p, :mn] = mt
        ttl_h[p, :mn] = mttl
        ttl_h[p, mn:] = False
        n_valid[p] = mn
        arenas[p] = ma
        offs[p] = mo

    rh_all, rl_all = keyops.split_revs(revs_h.reshape(-1))
    rh_all = rh_all.reshape(P, cap)
    rl_all = rl_all.reshape(P, cap)

    ds = set(dirty)
    return Mirror(
        keys_dev=_assemble_sharded(mesh, keys_h, mirror.keys_dev, ds),
        rh_dev=_assemble_sharded(mesh, rh_all, mirror.rh_dev, ds),
        rl_dev=_assemble_sharded(mesh, rl_all, mirror.rl_dev, ds),
        tomb_dev=_assemble_sharded(mesh, tomb_h, mirror.tomb_dev, ds),
        ttl_dev=_assemble_sharded(mesh, ttl_h, mirror.ttl_dev, ds),
        n_valid_dev=(
            jax.device_put(n_valid) if mesh is None
            else jax.device_put(
                n_valid, NamedSharding(mesh, PartitionSpec("part")))
        ),
        keys_host=keys_h, lens_host=lens_h, revs_host=revs_h, tomb_host=tomb_h,
        n_valid=n_valid, val_arena=arenas, val_offsets=offs,
        snapshot_ts=snapshot_ts,
        max_rev=max(mirror.max_rev, int(d_revs.max())),
        key_width=mirror.key_width, encoding=mirror.encoding, ttl_host=ttl_h,
    )


def compact_partitions_stored(
    mirror: Mirror,
    keep_idx: dict[int, np.ndarray],  # dirty partition -> sorted survivor rows
    mesh,
    snapshot_ts: int,
) -> Mirror | None:
    """Shrink the mirror to the compaction survivors WITHOUT leaving the
    stored domain — the mirror half of the device-side compaction pipeline
    (docs/compaction.md).

    ``keep_idx`` names only the DIRTY partitions (those with >= 1 victim);
    each maps to the ascending row indices that survive. Survivors are
    gathered as stored rows — ``(code, suffix)`` key bytes, host TTL
    column, value-arena gather — so the steady compaction path performs no
    key decode, no re-encode, and no re-dictionary: partition borders and
    the published :class:`~.encode.KeyEncoding` are carried over unchanged,
    and only dirty shards republish (:func:`_assemble_sharded`). A pending
    write delta then lands through the ordinary
    :func:`merge_partitions_stored` against the compacted mirror.

    Returns None only for a pre-``ttl_host`` mirror (nothing to gather the
    TTL flags from) — the caller falls back to the full host rebuild.
    Shrinking can never overflow a partition's padded capacity."""
    if not keep_idx:
        return mirror
    if mirror.ttl_host is None:
        return None
    P = mirror.partitions
    cap = mirror.keys_host.shape[1]

    # copy-on-write: readers hold the old Mirror object
    keys_h = mirror.keys_host.copy()
    lens_h = mirror.lens_host.copy()
    revs_h = mirror.revs_host.copy()
    tomb_h = mirror.tomb_host.copy()
    ttl_h = mirror.ttl_host.copy()
    n_valid = mirror.n_valid.copy()
    arenas = list(mirror.val_arena)
    offs = list(mirror.val_offsets)

    for p, keep in keep_idx.items():
        nv = int(n_valid[p])
        keep = np.asarray(keep, dtype=np.int64)
        mn = len(keep)
        keys_h[p, :mn] = mirror.keys_host[p][keep]
        lens_h[p, :mn] = mirror.lens_host[p][keep]
        revs_h[p, :mn] = mirror.revs_host[p][keep]
        tomb_h[p, :mn] = mirror.tomb_host[p][keep]
        ttl_h[p, :mn] = mirror.ttl_host[p][keep]
        # zero the vacated tail: stale rows beyond n_valid are kernel-masked
        # but must not survive as garbage into later capacity-grow memcpys
        keys_h[p, mn:nv] = 0
        lens_h[p, mn:nv] = 0
        revs_h[p, mn:nv] = 0
        tomb_h[p, mn:nv] = False
        ttl_h[p, mn:nv] = False
        n_valid[p] = mn
        arenas[p], offs[p] = keyops.gather_arena(
            mirror.val_arena[p], mirror.val_offsets[p][: nv + 1], keep)

    rh_all, rl_all = keyops.split_revs(revs_h.reshape(-1))
    rh_all = rh_all.reshape(P, cap)
    rl_all = rl_all.reshape(P, cap)

    ds = set(keep_idx)
    return Mirror(
        keys_dev=_assemble_sharded(mesh, keys_h, mirror.keys_dev, ds),
        rh_dev=_assemble_sharded(mesh, rh_all, mirror.rh_dev, ds),
        rl_dev=_assemble_sharded(mesh, rl_all, mirror.rl_dev, ds),
        tomb_dev=_assemble_sharded(mesh, tomb_h, mirror.tomb_dev, ds),
        ttl_dev=_assemble_sharded(mesh, ttl_h, mirror.ttl_dev, ds),
        n_valid_dev=(
            jax.device_put(n_valid) if mesh is None
            else jax.device_put(
                n_valid, NamedSharding(mesh, PartitionSpec("part")))
        ),
        keys_host=keys_h, lens_host=lens_h, revs_host=revs_h, tomb_host=tomb_h,
        n_valid=n_valid, val_arena=arenas, val_offsets=offs,
        snapshot_ts=snapshot_ts,
        max_rev=mirror.max_rev,
        key_width=mirror.key_width, encoding=mirror.encoding, ttl_host=ttl_h,
    )


def merge_partitions_incremental(
    mirror: Mirror,
    delta,  # sorted row-array sextuple (keys_u8, lens, revs, tomb, arena, offsets)
    mesh,
    key_width: int,
    snapshot_ts: int,
) -> Mirror | None:
    """Merge a (small, sorted) delta into the mirror touching ONLY the
    partitions the delta lands in: per-partition two-way merge on host,
    dirty-shard-only re-upload on device. Returns None when any partition
    overflows its padded capacity — the caller falls back to the full
    rebuild (which re-balances and re-pads).

    This is the incremental answer to VERDICT r1 weak #4 (all-or-nothing
    mirror maintenance): merge cost scales with delta size + dirty-partition
    size, not dataset size."""
    d_keys, d_lens, d_revs, d_tomb, d_arena, d_offsets = delta
    dn = len(d_keys)
    if dn == 0:
        return mirror
    P = mirror.partitions
    cap = mirror.keys_host.shape[1]

    # route delta rows to partitions by the partition lower bounds. Only
    # NON-EMPTY partitions are routing targets — routing into an empty
    # partition sandwiched between populated ones would break the global
    # cross-partition sort order that range_stream/compact rely on.
    firsts = mirror.partition_first_keys()
    nonempty = [p for p in range(P) if mirror.n_valid[p] > 0]
    if not nonempty:
        return None  # nothing to merge into; full rebuild re-partitions
    ne_bounds = [firsts[p] for p in nonempty]
    import bisect as _bisect

    d_key_bytes = [d_keys[i, : d_lens[i]].tobytes() for i in range(dn)]
    row_part = np.empty(dn, dtype=np.int64)
    for i, kb in enumerate(d_key_bytes):
        # last non-empty partition whose first key <= kb (earlier keys go to
        # the first non-empty partition — everything left of it is empty)
        row_part[i] = nonempty[max(0, _bisect.bisect_right(ne_bounds, kb) - 1)]
    dirty = sorted(set(int(p) for p in row_part))

    # copy-on-write: readers hold the old Mirror object; stacked-array copies
    # are memcpy (fast), the expensive work below is per-dirty-partition only
    keys_h = mirror.keys_host.copy()
    lens_h = mirror.lens_host.copy()
    revs_h = mirror.revs_host.copy()
    tomb_h = mirror.tomb_host.copy()
    n_valid = mirror.n_valid.copy()
    arenas = list(mirror.val_arena)
    offs = list(mirror.val_offsets)

    ttl_dirty: dict[int, np.ndarray] = {}
    d_off64 = d_offsets.astype(np.int64)
    for p in dirty:
        rows_p = np.nonzero(row_part == p)[0]
        lo, hi = rows_p[0], rows_p[-1] + 1  # contiguous: delta is sorted
        nv = int(n_valid[p])
        # the merge runs in the RAW domain: decode the dirty partition (it
        # is the only one paying the cost), merge with the raw delta, then
        # re-encode against the PUBLISHED dictionary — a delta key that no
        # longer fits (wrong bucket strip / suffix past the width budget)
        # falls back to the full re-dictionary rebuild
        part_u8, part_lens = mirror.decoded_keys(p, np.arange(nv))
        o = mirror.val_offsets[p].astype(np.int64)
        part = (
            part_u8, np.asarray(part_lens, np.int32), mirror.revs_host[p, :nv],
            mirror.tomb_host[p, :nv],
            mirror.val_arena[p][: o[nv]], mirror.val_offsets[p][: nv + 1],
        )
        dslice = (
            d_keys[lo:hi], d_lens[lo:hi], d_revs[lo:hi], d_tomb[lo:hi],
            d_arena[d_off64[lo] : d_off64[hi]],
            (d_off64[lo : hi + 1] - d_off64[lo]).astype(np.uint64),
        )
        mk, ml, mr, mt, ma, mo = merge_sorted_arrays(part, dslice)
        mn = len(mk)
        if mn > cap:
            return None  # overflow: rebalance via full rebuild
        if mirror.encoding is not None:
            try:
                enc_u8, enc_lens = mirror.encoding.encode_keys(mk, ml)
            except EncodeOverflow:
                return None  # suffix-width budget overflow: re-dictionary
            keys_h[p, :mn] = keyops.bytes_to_chunks(enc_u8)
            lens_h[p, :mn] = enc_lens
        else:
            keys_h[p, :mn] = keyops.bytes_to_chunks(
                np.ascontiguousarray(mk[:, :key_width])
            )
            lens_h[p, :mn] = ml
        revs_h[p, :mn] = mr
        tomb_h[p, :mn] = mt
        n_valid[p] = mn
        arenas[p] = ma
        offs[p] = mo
        ttl_row = np.zeros(cap, dtype=bool)
        ttl_row[:mn] = compute_ttl_flags(mk, ml)
        ttl_dirty[p] = ttl_row

    rh_all, rl_all = keyops.split_revs(revs_h.reshape(-1))
    rh_all = rh_all.reshape(P, cap)
    rl_all = rl_all.reshape(P, cap)
    ttl_h = None
    if ttl_dirty:
        ttl_h = (mirror.ttl_host.copy() if mirror.ttl_host is not None
                 else np.array(jax.device_get(mirror.ttl_dev)))
        for p, row in ttl_dirty.items():
            ttl_h[p] = row

    ds = set(dirty)
    return Mirror(
        keys_dev=_assemble_sharded(mesh, keys_h, mirror.keys_dev, ds),
        rh_dev=_assemble_sharded(mesh, rh_all, mirror.rh_dev, ds),
        rl_dev=_assemble_sharded(mesh, rl_all, mirror.rl_dev, ds),
        tomb_dev=_assemble_sharded(mesh, tomb_h, mirror.tomb_dev, ds),
        ttl_dev=_assemble_sharded(mesh, ttl_h, mirror.ttl_dev, ds)
        if ttl_h is not None else mirror.ttl_dev,
        n_valid_dev=(
            jax.device_put(n_valid) if mesh is None
            else jax.device_put(
                n_valid, NamedSharding(mesh, PartitionSpec("part")))
        ),
        keys_host=keys_h, lens_host=lens_h, revs_host=revs_h, tomb_host=tomb_h,
        n_valid=n_valid, val_arena=arenas, val_offsets=offs,
        snapshot_ts=snapshot_ts,
        max_rev=max(mirror.max_rev, int(d_revs.max())),
        key_width=mirror.key_width, encoding=mirror.encoding,
        ttl_host=ttl_h if ttl_h is not None else mirror.ttl_host,
    )
