"""Order-preserving prefix/dictionary encoding for mirror keys.

HBM is the binding constraint on dataset size: the raw mirror spends
``KEY_WIDTH`` (128) bytes per row on the packed user key, yet kube-style
keys (``/registry/pods/<ns>/<name>``) are hierarchically redundant — long
shared prefixes are the norm (FOCUS, arxiv 2505.24221). Following LSM-OPD
(arxiv 2508.11862), the scan kernels execute directly on the compressed
rows: keys are stored as ``(code, suffix)`` where numeric code order equals
prefix byte order, so lexicographic order of ENCODED rows equals byte order
of RAW keys and ``_lex_less`` works unchanged on the narrower chunk arrays.
Only visible rows are ever decoded, at host materialization.

The scheme (interval front coding):

- the dictionary is a sorted list of m **boundary** strings; key ``k``
  belongs to bucket ``j = bisect_right(boundaries, k)`` (m+1 buckets, so
  bucket index is monotone in ``k`` by construction);
- each bucket carries a **strip** string — a certified common prefix of
  every mirror key routed to it (computed from the data: keys are sorted,
  so the bucket's lcp is ``lcp(first, last)``);
- ``enc(k) = code(j) || k[len(strip_j):] || zero padding`` with the code a
  big-endian uint32 occupying chunk 0. Within a bucket the shared strip is
  gone, so suffix order == key order; across buckets the code decides; the
  map is injective. Stored keys are NUL-free, so zero-padded fixed-width
  compare equals true byte-string compare — the same invariant the raw
  packed layout relies on (ops/keys.py).

Query bounds are encoded host-side through the same dictionary
(:meth:`KeyEncoding.encode_start_bound` / :meth:`encode_end_bound`) with
explicit handling of bounds that fall between or outside dictionary
entries; the docstrings there carry the case analysis, and
tests/test_encode.py carries the machine-checked proof that visibility is
never widened or narrowed.

Delta overlays and the dirty-shard republish path re-encode incrementally
against the published dictionary (:meth:`encode_keys` on the merged rows);
a key that no longer fits — wrong bucket strip, or a suffix past the width
budget — raises :class:`EncodeOverflow` and the caller falls back to the
full re-dictionary rebuild.
"""

from __future__ import annotations

import bisect
from dataclasses import dataclass, field

import numpy as np

from ...ops import keys as keyops

#: bytes of fixed-width bucket code at the head of every encoded key —
#: one uint32 chunk, so codes ride the existing big-endian chunk compare
CODE_BYTES = 4
#: suffix-width headroom past the build-time max, so routine new keys
#: (a pod name one digit longer) don't force a re-dictionary rebuild
SUFFIX_SLACK = 8
#: dictionary size cap; past it boundaries are decimated (strips shorten,
#: compression degrades gracefully, correctness is untouched)
MAX_DICT = 1 << 20


class EncodeOverflow(Exception):
    """A key cannot be encoded against this dictionary (wrong bucket strip
    or suffix past the width budget) — the mirror needs a re-dictionary
    rebuild."""


def _group_by_code(codes: np.ndarray):
    """Yield ``(code, row-index array)`` groups — one stable argsort plus
    run-length slicing, O(n log n) total instead of a full-array scan per
    distinct code (a 20M-row rebuild over tens of thousands of directory
    buckets must not be O(rows × buckets)). Callers pass sorted rows, but
    correctness does not depend on it."""
    if len(codes) == 0:
        return
    order = np.argsort(codes, kind="stable")
    sc = codes[order]
    starts = np.flatnonzero(np.r_[True, sc[1:] != sc[:-1]])
    ends = np.r_[starts[1:], len(order)]
    for s, e in zip(starts, ends):
        yield int(sc[s]), order[s:e]


def _last_slash_len(keys_u8: np.ndarray, lens: np.ndarray) -> np.ndarray:
    """Per row: length of the directory prefix (through the last ``/``),
    0 when the key has no ``/`` — vectorized."""
    n, w = keys_u8.shape
    pos = np.arange(1, w + 1, dtype=np.int64)[None, :]
    is_slash = (keys_u8 == ord("/")) & (pos <= np.asarray(lens)[:, None])
    return (is_slash * pos).max(axis=1)


def _succ(prefix: bytes) -> bytes:
    """Smallest string greater than every extension of ``prefix`` (etcd's
    prefix_end); prefixes here never end in 0xff (they end in ``/``)."""
    return prefix[:-1] + bytes([prefix[-1] + 1])


@dataclass
class KeyEncoding:
    """The published dictionary: immutable once a Mirror references it
    (copy-on-write like the mirror arrays themselves)."""

    boundaries: list[bytes]          # sorted, m entries
    strips: list[bytes]              # m+1 entries; strips[j] for bucket j
    suffix_width: int                # encoded suffix bytes, % 4 == 0
    raw_width: int                   # the raw packed key width this replaces
    strip_lens: np.ndarray = field(init=False)   # int64[m+1]
    _strips_mat: np.ndarray = field(init=False)  # uint8[m+1, max_strip]
    _bounds_width: int = field(init=False)       # boundary pad width
    _bounds_void: np.ndarray = field(init=False)  # void[m] sorted view

    def __post_init__(self):
        m1 = len(self.strips)
        self.strip_lens = np.array([len(s) for s in self.strips], np.int64)
        w = max(1, int(self.strip_lens.max()) if m1 else 1)
        self._strips_mat = np.zeros((m1, w), dtype=np.uint8)
        for j, s in enumerate(self.strips):
            if s:
                self._strips_mat[j, : len(s)] = np.frombuffer(s, np.uint8)
        # boundary matrix/void view cached once per (immutable) dictionary:
        # every incremental republish routes its dirty partition through
        # _buckets_np, which must not re-pad the boundary list per call
        wb = max(1, self.raw_width,
                 max((len(b) for b in self.boundaries), default=0))
        self._bounds_width = wb
        b_mat = np.zeros((len(self.boundaries), wb), dtype=np.uint8)
        for i, b in enumerate(self.boundaries):
            b_mat[i, : len(b)] = np.frombuffer(b, np.uint8)
        self._bounds_void = b_mat.view(f"V{wb}").reshape(-1)

    # ------------------------------------------------------------- geometry
    @property
    def width(self) -> int:
        """Encoded key bytes: code chunk + suffix."""
        return CODE_BYTES + self.suffix_width

    @property
    def chunks(self) -> int:
        return self.width // 4

    @property
    def n_codes(self) -> int:
        return len(self.boundaries) + 1

    # -------------------------------------------------------------- routing
    def bucket_of(self, key: bytes) -> int:
        return bisect.bisect_right(self.boundaries, key)

    def _buckets_np(self, keys_u8: np.ndarray, lens: np.ndarray) -> np.ndarray:
        """Vectorized bucket assignment: one searchsorted over zero-padded
        void views (keys and boundaries are NUL-free, so the padded compare
        is the true byte compare)."""
        if not self.boundaries:
            return np.zeros(len(keys_u8), dtype=np.int64)
        w = self._bounds_width
        if keys_u8.shape[1] > w:  # wider than any key this dict was built
            w = keys_u8.shape[1]  # for — pad the boundaries up instead
            b_mat = np.zeros((len(self.boundaries), w), dtype=np.uint8)
            for i, b in enumerate(self.boundaries):
                b_mat[i, : len(b)] = np.frombuffer(b, np.uint8)
            bv = keyops.u8_void(b_mat)
        else:
            bv = self._bounds_void
        k_mat = keys_u8
        if keys_u8.shape[1] < w:
            k_mat = np.zeros((len(keys_u8), w), dtype=np.uint8)
            k_mat[:, : keys_u8.shape[1]] = keys_u8
        kv = keyops.u8_void(k_mat)
        return np.searchsorted(bv, kv, side="right").astype(np.int64)

    # ------------------------------------------------------------- encoding
    def encode_keys(self, keys_u8: np.ndarray,
                    lens: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """Raw zero-padded keys → (enc_u8[n, width], suffix_lens[n]).

        Raises :class:`EncodeOverflow` when any key does not start with its
        bucket's strip or its suffix exceeds the width budget — the caller
        (incremental delta merge) then falls back to a full re-dictionary
        rebuild. Build-time callers can't overflow by construction.
        """
        n = len(keys_u8)
        lens = np.asarray(lens, dtype=np.int64)
        enc = np.zeros((n, self.width), dtype=np.uint8)
        sfx_lens = np.zeros(n, dtype=np.int32)
        if n == 0:
            return enc, sfx_lens
        codes = self._buckets_np(keys_u8, lens)
        enc[:, 0] = (codes >> 24) & 0xFF
        enc[:, 1] = (codes >> 16) & 0xFF
        enc[:, 2] = (codes >> 8) & 0xFF
        enc[:, 3] = codes & 0xFF
        sl = self.strip_lens[codes]
        if (lens < sl).any() or (lens - sl > self.suffix_width).any():
            raise EncodeOverflow("suffix outside the width budget")
        sfx_lens[:] = lens - sl
        # group rows by bucket (at most #distinct codes python iterations;
        # rows of one bucket need one shared shift, which numpy slices do)
        for code, rows in _group_by_code(codes):
            s = int(self.strip_lens[code])
            if s:
                strip = self._strips_mat[code, :s]
                if (keys_u8[rows, :s] != strip).any():
                    raise EncodeOverflow(
                        f"key outside bucket {int(code)} strip")
            take = min(self.suffix_width, keys_u8.shape[1] - s)
            if take > 0:
                enc[np.ix_(rows, np.arange(CODE_BYTES, CODE_BYTES + take))] = \
                    keys_u8[np.ix_(rows, np.arange(s, s + take))]
        return enc, sfx_lens

    def decode_rows(self, enc_chunks: np.ndarray,
                    suffix_lens: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """Encoded chunk rows → (raw_u8[n, raw_width], raw_lens[n]) — the
        inverse of :meth:`encode_keys`, used only at the named host
        materialization funnels (kblint KB116)."""
        enc_u8 = keyops.chunks_to_u8(enc_chunks)
        n = len(enc_u8)
        suffix_lens = np.asarray(suffix_lens, dtype=np.int64)
        codes = (
            (enc_u8[:, 0].astype(np.int64) << 24)
            | (enc_u8[:, 1].astype(np.int64) << 16)
            | (enc_u8[:, 2].astype(np.int64) << 8)
            | enc_u8[:, 3].astype(np.int64)
        )
        raw = np.zeros((n, self.raw_width), dtype=np.uint8)
        raw_lens = (self.strip_lens[codes] + suffix_lens).astype(np.int32)
        for code, rows in _group_by_code(codes):
            s = int(self.strip_lens[code])
            if s:
                raw[np.ix_(rows, np.arange(s))] = self._strips_mat[code, :s]
            take = min(self.suffix_width, self.raw_width - s)
            if take > 0:
                raw[np.ix_(rows, np.arange(s, s + take))] = \
                    enc_u8[np.ix_(rows, np.arange(CODE_BYTES, CODE_BYTES + take))]
        return raw, raw_lens

    def decode_one(self, enc_chunk_row: np.ndarray, suffix_len: int) -> bytes:
        raw, lens = self.decode_rows(enc_chunk_row[None, :],
                                     np.array([suffix_len]))
        return raw[0, : int(lens[0])].tobytes()

    # ---------------------------------------------------------- probes
    def encode_probe(self, key: bytes) -> bytes | None:
        """Exact-match probe: the encoded form of ``key``, or None when no
        mirror row can equal ``key`` under this dictionary (key outside its
        bucket's strip, or suffix past the width — every MIRROR key starts
        with its bucket's strip and fits the width by construction)."""
        j = self.bucket_of(key)
        strip = self.strips[j]
        if not key.startswith(strip) or len(key) - len(strip) > self.suffix_width:
            return None
        out = np.zeros(self.width, dtype=np.uint8)
        out[0] = (j >> 24) & 0xFF
        out[1] = (j >> 16) & 0xFF
        out[2] = (j >> 8) & 0xFF
        out[3] = j & 0xFF
        sfx = key[len(strip):]
        if sfx:
            out[CODE_BYTES : CODE_BYTES + len(sfx)] = np.frombuffer(sfx, np.uint8)
        return out.tobytes()

    # ---------------------------------------------------------- query bounds
    def _code_floor(self, j: int) -> np.ndarray:
        out = np.zeros(self.width, dtype=np.uint8)
        out[0] = (j >> 24) & 0xFF
        out[1] = (j >> 16) & 0xFF
        out[2] = (j >> 8) & 0xFF
        out[3] = j & 0xFF
        return out

    def _encode_bound(self, bound: bytes) -> np.ndarray:
        """The shared exact bound mapping — one uint8[width] value ``v``
        such that for EVERY mirror key ``k``:  ``k >= bound  ⇔  enc(k) >= v``
        (equivalently ``k < bound ⇔ enc(k) < v``), so one mapping serves the
        inclusive start and the exclusive end alike.

        Case analysis (proof test: tests/test_encode.py):

        - ``bound`` starts with its bucket's strip → ``code || suffix``;
          a suffix past the width budget is truncated and the whole value
          incremented by one: the only row the truncation could confuse is
          ``enc == code||trunc`` i.e. key == strip+trunc, which is < bound
          (bound is longer), and +1 classifies it below the bound — exact;
        - bound sorts below every possible key of its bucket (it is a
          proper prefix of the strip, or diverges below it) →
          ``code || zeros``: the whole bucket and everything after is
          >= bound, everything before is < bound;
        - bound sorts above every possible key of its bucket (diverges
          above the strip) → ``code+1 || zeros``.

        Bucket index is monotone in the bound, and every mirror key starts
        with its bucket's strip, so cross-bucket classification is exact by
        the code compare alone.
        """
        j = self.bucket_of(bound)
        strip = self.strips[j]
        if bound.startswith(strip):
            sfx = bound[len(strip):]
            v = self._code_floor(j)
            take = min(len(sfx), self.suffix_width)
            if take:
                v[CODE_BYTES : CODE_BYTES + take] = np.frombuffer(
                    sfx[:take], np.uint8)
            if len(sfx) > self.suffix_width:
                _increment_u8(v)
            return v
        if bound < strip:
            # proper prefix of the strip, or diverging below it: every key
            # of this bucket (all start with strip) is > bound
            return self._code_floor(j)
        # diverging above the strip: every key of this bucket is < bound
        return self._code_floor(j + 1)

    def encode_start_bound(self, start: bytes) -> np.ndarray:
        """Inclusive start bound → uint8[width] encoded bound for the
        unchanged ``lex_geq`` kernel compare. Exact: never widens or
        narrows visibility (see :meth:`_encode_bound`)."""
        return self._encode_bound(start)

    def encode_end_bound(self, end: bytes) -> np.ndarray:
        """Exclusive end bound → uint8[width] encoded bound for the
        unchanged ``lex_less`` kernel compare. The same mapping as the
        start bound: ``k < end ⇔ enc(k) < v`` is the complement of
        ``k >= end ⇔ enc(k) >= v``."""
        return self._encode_bound(end)


def _increment_u8(v: np.ndarray) -> None:
    """v += 1 as a big-endian integer, in place. Cannot overflow here: the
    code chunk never reaches 2^32-1 (dictionaries are capped at MAX_DICT)."""
    for i in range(len(v) - 1, -1, -1):
        if v[i] != 0xFF:
            v[i] += 1
            return
        v[i] = 0
    raise AssertionError("encoded bound overflow")


def build_encoding(keys_u8: np.ndarray, lens: np.ndarray, raw_width: int,
                   max_dict: int = MAX_DICT,
                   suffix_slack: int = SUFFIX_SLACK) -> KeyEncoding | None:
    """Derive a dictionary from the snapshot's (sorted) raw keys, or None
    when encoding would not beat the raw layout.

    Boundaries are the distinct directory prefixes (through the last
    ``/``) plus each directory's successor string, so a directory's files
    occupy their own buckets and keep the full directory as strip even when
    a shorter sibling directory follows. Strips are computed from the data
    (lcp of the bucket's first and last key — rows are sorted), so they are
    certified common prefixes no matter how the boundaries interleave.
    """
    n = len(keys_u8)
    if n == 0:
        return None
    lens = np.asarray(lens, dtype=np.int64)
    dir_lens = _last_slash_len(keys_u8, lens)
    # distinct directories, preserving sort order (keys are sorted but
    # their directories interleave; void-unique keeps it cheap)
    w = keys_u8.shape[1]
    dirs_u8 = np.where(
        np.arange(w)[None, :] < dir_lens[:, None], keys_u8, 0
    ).astype(np.uint8)
    uniq = np.unique(keyops.u8_void(np.ascontiguousarray(dirs_u8)))
    dir_list = []
    for v in uniq:
        b = v.tobytes().rstrip(b"\x00")
        if b:
            dir_list.append(b)
    if len(dir_list) > max_dict // 2:
        stride = (2 * len(dir_list) + max_dict - 1) // max_dict
        dir_list = dir_list[::stride]
    boundaries = sorted({d for d in dir_list} | {_succ(d) for d in dir_list})
    if not boundaries:
        return None

    enc = KeyEncoding(boundaries=boundaries,
                      strips=[b""] * (len(boundaries) + 1),
                      suffix_width=0, raw_width=raw_width)
    codes = enc._buckets_np(keys_u8, lens)
    strips: list[bytes] = [b""] * (len(boundaries) + 1)
    max_sfx = 0
    for code, rows in _group_by_code(codes):
        first, last = rows[0], rows[-1]
        fl, ll = int(lens[first]), int(lens[last])
        limit = min(fl, ll)
        diff = np.nonzero(
            keys_u8[first, :limit] != keys_u8[last, :limit])[0]
        strip_len = int(diff[0]) if len(diff) else limit
        # truncate the strip to the last ``/`` inside it: a raw-lcp strip
        # over-fits (lcp of pod-00000..pod-00049 includes "pod-000", so
        # pod-00150 would force a full re-dictionary rebuild); a
        # directory-aligned strip keeps routine key growth incremental
        slashes = np.nonzero(keys_u8[first, :strip_len] == ord("/"))[0]
        if len(slashes):
            strip_len = int(slashes[-1]) + 1
        strips[int(code)] = keys_u8[first, :strip_len].tobytes()
        max_sfx = max(max_sfx, int((lens[rows] - strip_len).max()))

    suffix_width = -(-(max_sfx + suffix_slack) // 4) * 4
    if CODE_BYTES + suffix_width >= raw_width:
        return None  # no gain — serve the raw layout
    return KeyEncoding(boundaries=boundaries, strips=strips,
                       suffix_width=suffix_width, raw_width=raw_width)
